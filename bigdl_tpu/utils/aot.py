"""Persistent AOT executable cache: cold start is a cache read, not a compile.

The compile-time war chest (ROADMAP item 1): XLA compiles of some models are
pathologically slow on the tunneled backend (LeNet's train step: 809s
measured, vs 27s for ResNet-50 — docs/benchmarking.md), and rounds 3-5 lost
whole bench windows to recompiles.  The XLA persistent cache
(utils/platform.enable_compilation_cache) already warms the *compiler*; this
module goes one level up and caches the **serialized executable** itself
(`jax.jit(...).lower(...).compile()` via
`jax.experimental.serialize_executable`), so a warm process performs zero
XLA work at all: startup becomes IO.

Three compile choke points route through here:

- the Optimizer's pjit train step (optim/optimizer._build_step) — keyed by
  the **HLO hash** (plus versions/backend/mesh/avals), so any model or
  lowering change is automatically a miss;
- Evaluator/Predictor/serve forward (optim.optimizer._ShardedForward) —
  keyed by a **structural module fingerprint** (no tracing needed), so a
  warm `InferenceServer.warmup()` performs zero fresh lowers: the serve
  bucket ladder's N compiles become N cache reads;
- bench.py's timed configs — the measured `compile_seconds` collapses on a
  warm run and the per-config record carries the hit/miss delta.

Entries are CRC-framed pickles written through :mod:`.file_io` (the PR-1
checkpoint framing — local, ``memory://`` and fsspec schemes all work, so a
remote cache dir warms a whole pod).  A corrupt or undeserializable entry is
**quarantined** (renamed ``*.corrupt``) and silently recompiled — the cache
can never make a run fail.

Keying / invalidation: every key fingerprints (jax, jaxlib, bigdl_tpu
versions; backend + device kind + device/process count; mesh shape+axes;
arg avals incl. shardings; an optional ``BIGDL_TPU_AOT_CACHE_TAG``), plus
the HLO hash (train/bench) or the module fingerprint (forward).  Change any
of them and the entry simply misses; stale entries are never served.

Knobs:

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_AOT_CACHE`` | cache directory (any file_io scheme); empty/0 = disabled | off |
| ``BIGDL_TPU_AOT_CACHE_TAG`` | free-form fingerprint salt (bump to invalidate en masse) | "" |

Telemetry: ``aot.load`` / ``aot.store`` / ``compile`` spans, plus an ``aot``
counter track (hits / misses / stores) so a trace proves whether a run was
warm.  Multi-process (multi-host) runs disable the cache: a serialized SPMD
executable embeds the global topology and per-host deserialize ordering is
not worth the risk — each host still benefits from the XLA persistent cache.
"""

from __future__ import annotations

import hashlib
import json
import logging
import pickle
import threading
import time
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger("bigdl_tpu")

__all__ = ["enabled", "cache_dir", "get_cache", "reset", "stats",
           "AOTCache", "fingerprint", "base_fingerprint",
           "aval_fingerprint", "module_fingerprint", "hlo_hash",
           "cached_compile", "get_or_compile"]

_FORMAT = "bigdl_tpu-aot-v1"
_SUFFIX = ".aotx"

# process-wide counters: the "did this run compile anything?" ledger that
# tests, bench records, and the telemetry counter track all read
_lock = threading.Lock()
_STATS_KEYS = ("hits", "misses", "stores", "lowers", "compiles",
               "corrupt", "errors", "compile_s", "load_s")
_stats: Dict[str, float] = {k: 0 for k in _STATS_KEYS}
_cache_singleton: Dict[str, Any] = {}


_TRACK_KEYS = ("hits", "misses", "stores", "lowers", "compiles")


def _bump(key: str, amount: float = 1) -> None:
    from . import telemetry
    with _lock:
        _stats[key] += amount
        snap = {k: _stats[k] for k in _TRACK_KEYS}
    if key in _TRACK_KEYS:
        # the full warm-start ledger rides the `aot` counter track so
        # trace_report's aot section (and Perfetto) can prove whether a
        # run compiled anything, not just whether the cache hit
        telemetry.counter("aot", **snap)


def stats() -> Dict[str, float]:
    """Snapshot of the process-wide cache counters (hits/misses/stores/
    lowers/compiles/corrupt/errors + cumulative compile_s/load_s)."""
    with _lock:
        return dict(_stats)


def reset() -> None:
    """Zero the counters and drop the cache singleton (tests)."""
    with _lock:
        for k in _STATS_KEYS:
            _stats[k] = 0
        _cache_singleton.clear()


def cache_dir() -> Optional[str]:
    """The configured cache directory, or None when disabled."""
    from . import config
    d = config.get_str("AOT_CACHE", "").strip()
    if not d or d == "0":
        return None
    return d


def enabled() -> bool:
    """True when a cache dir is configured AND this is a single-process
    run (serialized SPMD executables embed the global topology; multi-host
    replay is disabled by design — the XLA persistent cache still warms
    those)."""
    if cache_dir() is None:
        return False
    try:
        import jax
        return jax.process_count() == 1
    except Exception:  # noqa: BLE001 — backend not up yet
        return False


def get_cache() -> Optional["AOTCache"]:
    """The process AOTCache for the configured dir (singleton per dir)."""
    d = cache_dir()
    if d is None or not enabled():
        return None
    cache = _cache_singleton.get(d)
    if cache is None:
        cache = AOTCache(d)
        _cache_singleton.clear()
        _cache_singleton[d] = cache
    return cache


# ----------------------------------------------------------------------
# fingerprinting
# ----------------------------------------------------------------------

def fingerprint(fields: Dict[str, Any]) -> str:
    """Stable sha256 over a canonical-JSON rendering of the key fields."""
    blob = json.dumps(fields, sort_keys=True, default=str,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def base_fingerprint(mesh=None) -> Dict[str, Any]:
    """The environment half of every key: versions, backend, device kind,
    topology, mesh, and the free-form cache tag."""
    import jax
    import jaxlib

    from . import config
    dev = jax.devices()[0]
    fields = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "bigdl_tpu": _pkg_version(),
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", "?"),
        "n_devices": len(jax.devices()),
        "processes": jax.process_count(),
        "tag": config.get_str("AOT_CACHE_TAG", ""),
    }
    if mesh is not None:
        fields["mesh"] = {"shape": dict(mesh.shape),
                          "axes": list(mesh.axis_names)}
    return fields


def _pkg_version() -> str:
    try:
        import bigdl_tpu
        return getattr(bigdl_tpu, "__version__", "0")
    except Exception:  # noqa: BLE001
        return "0"


def aval_fingerprint(tree) -> list:
    """Flattened (shape, dtype, sharding) triples for an arg pytree —
    concrete arrays, ShapeDtypeStructs and avals all work; no tracing."""
    import jax
    out = []
    for leaf in jax.tree.leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        sh = getattr(leaf, "sharding", None)
        spec = str(getattr(sh, "spec", "")) if sh is not None else ""
        out.append([list(shape), dtype, spec])
    return out


def module_fingerprint(module) -> str:
    """Structural hash of an nn.Module tree: class names + primitive
    config attributes + children, recursively.  Deliberately excludes the
    uid-bearing ``name`` and all array state (weights enter the key via
    :func:`aval_fingerprint` of the placed params).  No tracing, no
    lowering — this is what lets a warm serve ladder skip lowering
    entirely."""
    _VOLATILE = {"name", "params", "state", "grads", "output", "grad_input",
                 "_last_rng", "modules", "weight_initializer",
                 "bias_initializer", "training_mode"}

    def walk(m):
        d: Dict[str, Any] = {
            "cls": f"{type(m).__module__}.{type(m).__qualname__}"}
        attrs = {}
        for k, v in sorted(vars(m).items()):
            if k in _VOLATILE:
                continue
            if isinstance(v, (bool, int, float, str, type(None))):
                attrs[k] = v
            elif isinstance(v, (tuple, list)) and all(
                    isinstance(x, (bool, int, float, str, type(None)))
                    for x in v):
                attrs[k] = list(v)
        if attrs:
            d["attrs"] = attrs
        children = getattr(m, "modules", None)
        if isinstance(children, (list, tuple)) and children:
            d["children"] = [walk(c) for c in children]
        return d

    return fingerprint(walk(module))


def hlo_hash(lowered) -> str:
    """sha256 of the lowered StableHLO text — the strongest possible key
    component: any change to the computation (model edit, donation,
    sharding, env-dependent lowering like the tiny-channel conv pad)
    changes it."""
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()


# ----------------------------------------------------------------------
# the cache proper
# ----------------------------------------------------------------------

class AOTCache:
    """One cache directory of CRC-framed serialized executables.

    All IO goes through :mod:`.file_io` (local / ``memory://`` / fsspec,
    retried remote writes) and every entry carries the PR-1 integrity
    frame; a CRC mismatch or a deserialize failure quarantines the entry
    (``*.corrupt``) and reports a miss — the caller recompiles and the
    fresh store overwrites nothing (new entries are written to a temp name
    and renamed into place)."""

    def __init__(self, root: str):
        from . import file_io
        self.root = file_io._strip_file_scheme(str(root))
        self._fs = file_io.get_filesystem(self.root)
        try:
            self._fs.makedirs(self.root)
        except Exception:  # noqa: BLE001 — unwritable root = every op misses
            logger.warning("aot: cache dir %s not creatable", self.root)

    def _path(self, key: str) -> str:
        from . import file_io
        return file_io._join(self.root, key + _SUFFIX)

    def load(self, key: str):
        """Deserialize the executable stored under ``key``; None on miss.
        Corrupt/stale entries are quarantined and count as misses."""
        from . import file_io, telemetry
        path = self._path(key)
        t0 = time.perf_counter()
        with telemetry.span("aot.load", cat="aot", key=key[:16]):
            try:
                if not self._fs.exists(path):
                    _bump("misses")
                    return None
            except Exception as e:  # noqa: BLE001 — cache must never raise
                logger.warning("aot: exists(%s) failed: %s", path, e)
                _bump("errors")
                _bump("misses")
                return None
            try:
                entry = file_io.load(path)
                if not (isinstance(entry, dict)
                        and entry.get("format") == _FORMAT):
                    raise ValueError(f"not a {_FORMAT} entry")
                from jax.experimental.serialize_executable import \
                    deserialize_and_load
                compiled = deserialize_and_load(
                    entry["exe"], entry["in_tree"], entry["out_tree"])
            except Exception as e:  # noqa: BLE001 — corrupt OR stale
                # (CRC mismatch, truncated pickle, executable rejected by
                # this jaxlib): quarantine so the next process does not
                # trip over it again, then silently recompile
                self._quarantine(path, e, key=key)
                _bump("corrupt")
                _bump("misses")
                return None
        _bump("load_s", time.perf_counter() - t0)
        _bump("hits")
        return compiled

    def store(self, key: str, compiled, meta: Optional[dict] = None) -> bool:
        """Serialize + frame + write ``compiled`` under ``key`` (temp name
        then rename: concurrent writers race benignly).  Returns False —
        never raises — when the executable does not support serialization
        or the write fails."""
        from . import file_io, telemetry
        path = self._path(key)
        with telemetry.span("aot.store", cat="aot", key=key[:16]):
            try:
                from jax.experimental.serialize_executable import serialize
                exe, in_tree, out_tree = serialize(compiled)
                entry = {"format": _FORMAT, "exe": exe, "in_tree": in_tree,
                         "out_tree": out_tree, "meta": meta or {}}
                tmp = f"{path}.tmp.{_token()}"
                file_io.save(entry, tmp)
                try:
                    self._fs.rename(tmp, path)
                except Exception:  # noqa: BLE001 — loser of a store race
                    try:
                        self._fs.remove(tmp)
                    except Exception:  # noqa: BLE001
                        pass
            except Exception as e:  # noqa: BLE001 — cache must never raise
                logger.warning("aot: store(%s) failed: %s: %s", key[:16],
                               type(e).__name__, e)
                _bump("errors")
                return False
        _bump("stores")
        return True

    def _quarantine(self, path: str, err: Exception,
                    key: Optional[str] = None) -> None:
        # the full fingerprint in the log line: corrupt-entry forensics
        # (which env/model/avals produced this key?) can start from the
        # entry's meta without attaching a debugger
        logger.warning("aot: quarantining %s (fingerprint %s; %s: %s); "
                       "recompiling", path, key or "?",
                       type(err).__name__, err)
        try:
            self._fs.rename(path, path + ".corrupt")
        except Exception:  # noqa: BLE001 — e.g. a concurrent quarantine
            try:
                self._fs.remove(path)
            except Exception:  # noqa: BLE001
                pass

    def entries(self) -> list:
        """Keys currently stored (diagnostics/tests)."""
        try:
            return sorted(n[:-len(_SUFFIX)] for n in
                          self._fs.listdir(self.root)
                          if n.endswith(_SUFFIX))
        except Exception:  # noqa: BLE001
            return []


def _token() -> str:
    import os
    return f"{os.getpid()}.{threading.get_ident()}"


# ----------------------------------------------------------------------
# the two compile-site entry points
# ----------------------------------------------------------------------

def _compile_timed(lowered, label: str):
    from . import telemetry
    t0 = time.perf_counter()
    with telemetry.span("compile", cat="aot", label=label):
        compiled = lowered.compile()
    _bump("compiles")
    _bump("compile_s", time.perf_counter() - t0)
    return compiled


def cached_compile(lowered, *, label: str, mesh=None,
                   example_args=None, extra: Optional[dict] = None,
                   card_extra: Optional[dict] = None):
    """HLO-hash-keyed compile of an already-lowered computation (the train
    step / bench path: tracing+lowering is cheap, the XLA compile is the
    800s part).  Cache disabled -> plain ``lowered.compile()``.

    Every executable leaving here — freshly compiled OR deserialized from
    the cache — emits a compile card (utils/hlostats.py) when cards are
    armed; ``card_extra`` rides in the card (NOT the cache key): the train
    step's knob/bucket/buffer self-description."""
    from . import hlostats
    _bump("lowers")
    cache = get_cache()
    key = None
    if cache is not None:
        fields = dict(base_fingerprint(mesh))
        fields["label"] = label
        fields["hlo"] = hlo_hash(lowered)
        if example_args is not None:
            fields["args"] = aval_fingerprint(example_args)
        if extra:
            fields.update(extra)
        key = fingerprint(fields)
        compiled = cache.load(key)
        if compiled is not None:
            logger.info("aot: %s warm-started from cache (%s)", label,
                        key[:16])
            hlostats.capture(compiled, lowered, label=label, key=key,
                             example_args=example_args, extra=card_extra,
                             source="aot-hit")
            return compiled
    compiled = _compile_timed(lowered, label)
    if cache is not None:
        cache.store(key, compiled, meta={"label": label,
                                         "fields": _meta_fields(fields)})
    hlostats.capture(compiled, lowered, label=label, key=key,
                     example_args=example_args, extra=card_extra,
                     source="compile")
    return compiled


def get_or_compile(key_fields: Dict[str, Any], lower_fn: Callable[[], Any],
                   *, label: str, card_extra: Optional[dict] = None):
    """Logical-key lookup that skips lowering entirely on a hit (the serve
    bucket-ladder path: ``key_fields`` must identify the computation
    without tracing — module fingerprint + avals + base fingerprint).
    On miss, ``lower_fn()`` is invoked once and the compile is stored.
    Hit or miss, the executable emits a compile card when armed (a hit's
    card has no StableHLO section — nothing was lowered, by design)."""
    from . import hlostats
    cache = get_cache()
    fields = dict(key_fields)
    fields["label"] = label
    key = fingerprint(fields)
    if cache is None:
        _bump("lowers")
        lowered = lower_fn()
        compiled = _compile_timed(lowered, label)
        hlostats.capture(compiled, lowered, label=label, key=key,
                         extra=card_extra, source="compile")
        return compiled
    compiled = cache.load(key)
    if compiled is not None:
        logger.info("aot: %s warm-started from cache (%s)", label, key[:16])
        hlostats.capture(compiled, None, label=label, key=key,
                         extra=card_extra, source="aot-hit")
        return compiled
    _bump("lowers")
    lowered = lower_fn()
    compiled = _compile_timed(lowered, label)
    cache.store(key, compiled, meta={"label": label,
                                     "fields": _meta_fields(fields)})
    hlostats.capture(compiled, lowered, label=label, key=key,
                     extra=card_extra, source="compile")
    return compiled


def _meta_fields(fields: Dict[str, Any]) -> Dict[str, Any]:
    """Human-inspectable copy of the key fields for the entry's meta
    (avals can be long; everything else is small and invaluable when
    debugging why a key missed)."""
    out = {k: v for k, v in fields.items() if k != "args"}
    out["n_args"] = len(fields.get("args", []))
    return out
