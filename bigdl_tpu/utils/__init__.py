from .table import Table, T
from .engine import Engine
from .rng import RandomGenerator, RNG
from .util import kth_largest
from .thread_pool import ThreadPool

__all__ = ["Table", "T", "Engine", "RandomGenerator", "RNG", "kth_largest", "ThreadPool"]
