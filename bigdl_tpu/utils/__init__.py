from .table import Table, T
from .engine import Engine
from .rng import RandomGenerator, RNG

__all__ = ["Table", "T", "Engine", "RandomGenerator", "RNG"]
