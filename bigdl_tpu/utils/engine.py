"""Engine: device-topology discovery and execution configuration.

Reference: BigDL `utils/Engine.scala:36` — `Engine.init` (:93) discovers cluster
topology (node count x cores per node) from the Spark master URL
(`parseExecutorAndCore`, :353-418) and builds two thread pools (`Engine.default`,
`Engine.model`, :241-257) that all layers and the optimizer use.

TPU-native re-design: topology discovery is `jax.devices()` / `jax.process_count()`;
the "thread pools" collapse into XLA — a single compiled train step uses every core of
every chip it is sharded over.  `Engine.init()` builds the global `jax.sharding.Mesh`
that the rest of the framework (Optimizer, DataSet sharding, parallel strategies)
consumes.  Node-count-as-a-parameter is preserved: like BigDL's
`Engine.setNodeAndCore` trick that lets tests simulate an N-node cluster in one JVM
(utils/Engine.scala:313, used by DistriOptimizerSpec), `Engine.init(mesh_shape=...)`
can build any mesh over however many (possibly virtual CPU) devices exist.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["Engine"]

logger = logging.getLogger("bigdl_tpu")


class Engine:
    """Process-wide singleton holding the device mesh (BigDL: utils/Engine.scala:36)."""

    _mesh: Optional[Mesh] = None
    _initialized = False
    #: outstanding device-discovery probe (thread, result box) after a
    #: timeout — reused by the next _discover_devices call (see there)
    _probe = None

    #: canonical mesh axis names, in order: data, pipeline(stage), tensor(model),
    #: sequence(context), expert
    DATA_AXIS = "data"
    PIPE_AXIS = "pipe"
    MODEL_AXIS = "model"
    SEQ_AXIS = "seq"
    EXPERT_AXIS = "expert"

    #: True once jax.distributed.initialize has run in this process
    _distributed_initialized = False

    #: elastic logical topology (parallel/elastic): None, or a dict
    #: {"rank": original rank id, "survivors": sorted tuple of surviving
    #: original rank ids}.  Ranks keep their ORIGINAL ids across shrinks
    #: (heartbeat/intent files stay addressable); the world SIZE and a
    #: rank's data-shard index derive from the survivor set.  Installed
    #: by reform(); the pre-fault logical topology of a simulated
    #: multi-host run comes from BIGDL_TPU_ELASTIC_WORLD/_ELASTIC_RANK.
    _elastic = None

    @classmethod
    def init_distributed(cls, coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         local_device_ids: Optional[Sequence[int]] = None
                         ) -> None:
        """Join the multi-host runtime (jax.distributed.initialize).

        The reference discovers cluster topology from the Spark master URL
        (`Engine.parseExecutorAndCore`, utils/Engine.scala:353-418); here the
        coordination contract is environment variables — set by the launcher
        on every host, mirroring how spark-submit seeds each executor:

          BIGDL_TPU_COORDINATOR    host:port of process 0
          BIGDL_TPU_NUM_PROCESSES  world size
          BIGDL_TPU_PROCESS_ID     this process's rank

        On TPU pods all three may be omitted: jax auto-detects them from the
        TPU metadata service.  After this call `jax.devices()` is GLOBAL
        (every chip of every host) and `Engine.init()` builds the global mesh;
        each process addresses only its local chips and feeds them its data
        shard via `make_array_from_process_local_data`
        (Optimizer._put_batch — SURVEY.md §5.8).
        """
        if cls._distributed_initialized:
            return
        from . import config
        kwargs = {}
        coord = coordinator_address or config.get_str("COORDINATOR", "")
        if coord:
            kwargs["coordinator_address"] = coord
        nproc = (num_processes if num_processes is not None
                 else config.get_int("NUM_PROCESSES", 0))
        if nproc:
            kwargs["num_processes"] = int(nproc)
        pid = (process_id if process_id is not None
               else config.get_int("PROCESS_ID", -1))
        if pid >= 0:
            kwargs["process_id"] = int(pid)
        if local_device_ids is not None:
            kwargs["local_device_ids"] = list(local_device_ids)
        jax.distributed.initialize(**kwargs)
        cls._distributed_initialized = True
        logger.info(
            "Engine.init_distributed: process %d/%d, %d local / %d global "
            "devices", jax.process_index(), jax.process_count(),
            jax.local_device_count(), jax.device_count())

    @classmethod
    def init(cls, mesh_shape: Optional[dict] = None,
             devices: Optional[Sequence] = None,
             distributed: Optional[bool] = None) -> Mesh:
        """Discover devices and build the global mesh.

        mesh_shape: dict axis_name -> size, e.g. {"data": 4, "model": 2}.
          Defaults to pure data parallelism over every visible device — the
          reference's only inter-node strategy (SURVEY.md §2.5: sync data-parallel
          SGD is BigDL's sole distribution mode, optim/DistriOptimizer.scala).
        devices: explicit device list (tests pass virtual CPU devices here).
        distributed: join the multi-host runtime first (init_distributed).
          Defaults to True when BIGDL_TPU_COORDINATOR is set, so launcher
          scripts only need to export the env contract.
        """
        if distributed is None:
            from . import config
            distributed = bool(config.get_str("COORDINATOR", ""))
        if distributed:
            cls.init_distributed()
        devs = (list(devices) if devices is not None
                else cls._discover_devices())
        if mesh_shape is None:
            mesh_shape = {cls.DATA_AXIS: len(devs)}
        sizes = list(mesh_shape.values())
        total = int(np.prod(sizes))
        if total != len(devs):
            raise ValueError(
                f"mesh_shape {mesh_shape} needs {total} devices, have {len(devs)}")
        dev_array = np.array(devs).reshape(sizes)
        cls._mesh = Mesh(dev_array, tuple(mesh_shape.keys()))
        cls._initialized = True
        # host-kernel thread count (reference: Engine.init pins MKL threads
        # via MKL.setNumThreads, utils/Engine.scala:241-257)
        from . import config, native
        native.set_num_threads(config.num_threads())
        logger.info("Engine.init: mesh %s over %d %s device(s)",
                    dict(zip(cls._mesh.axis_names, cls._mesh.devices.shape)),
                    len(devs), devs[0].platform)
        return cls._mesh

    @classmethod
    def _discover_devices(cls):
        """jax.devices() with an OPT-IN watchdog: on a tunneled/remote TPU
        backend, backend init blocks forever when the accelerator service
        is unreachable (observed on this image's axon tunnel).  Set
        BIGDL_TPU_DEVICE_TIMEOUT=<seconds> to turn the silent hang into an
        actionable error.  Off by default: multi-host runs legitimately
        block in init until every process joins, and a default timeout
        would break that wait."""
        import os
        raw = os.environ.get("BIGDL_TPU_DEVICE_TIMEOUT")
        if raw is None or not raw.strip():
            return list(jax.devices())
        try:
            timeout = float(raw)
        except ValueError:
            # this knob exists to prevent a silent hang — silently
            # disabling it on a typo ('60s', '1m') would reproduce exactly
            # the failure it guards against
            raise ValueError(
                f"BIGDL_TPU_DEVICE_TIMEOUT={raw!r} is not a number of "
                "seconds (e.g. '60')") from None
        if timeout <= 0:
            return list(jax.devices())
        import threading
        # a timed-out probe thread cannot be killed (it is parked inside
        # native backend init) — but it must not be LEAKED once per call:
        # keep the outstanding (thread, box) and re-join it on the next
        # attempt, so at most one probe ever exists and a late-resolving
        # backend is still harvested instead of racing a second probe
        prior = cls._probe
        if prior is not None and prior[0].is_alive():
            t, box = prior
        else:
            box = {}

            def probe():
                try:
                    box["devices"] = list(jax.devices())
                except Exception as e:  # noqa: BLE001 — surfaced below
                    box["error"] = e

            t = threading.Thread(target=probe, daemon=True,
                                 name="bigdl-device-probe")
            t.start()
        t.join(timeout)
        if "devices" in box:
            cls._probe = None
            return box["devices"]
        if "error" in box:
            cls._probe = None
            raise box["error"]
        cls._probe = (t, box)
        raise TimeoutError(
            f"jax.devices() did not return within {timeout:.0f}s "
            "(BIGDL_TPU_DEVICE_TIMEOUT) — the accelerator backend is "
            "unreachable (tunneled TPU service down?). Restart the "
            "process with JAX_PLATFORMS=cpu (the backend is already "
            "mid-init here, so an in-process jax.config update cannot "
            "take effect) or restore the accelerator service.")

    @classmethod
    def mesh(cls) -> Mesh:
        if cls._mesh is None:
            cls.init()
        return cls._mesh

    @classmethod
    def set_mesh(cls, mesh: Mesh) -> None:
        cls._mesh = mesh
        cls._initialized = True

    @classmethod
    def reset(cls) -> None:
        cls._mesh = None
        cls._initialized = False
        cls._probe = None
        cls._elastic = None

    # -- elastic topology (parallel/elastic) ----------------------------

    @classmethod
    def _env_elastic_world(cls) -> int:
        from . import config
        return config.get_int("ELASTIC_WORLD", 0)

    @classmethod
    def world(cls) -> int:
        """Logical world size: survivor count after a reform(), the
        BIGDL_TPU_ELASTIC_WORLD simulated topology, else
        jax.process_count() (the physical truth)."""
        if cls._elastic is not None:
            return len(cls._elastic["survivors"])
        w = cls._env_elastic_world()
        return w if w > 1 else jax.process_count()

    @classmethod
    def rank(cls) -> int:
        """This process's logical rank (ORIGINAL id — stable across
        shrinks); falls back to jax.process_index()."""
        if cls._elastic is not None:
            return cls._elastic["rank"]
        if cls._env_elastic_world() > 1:
            from . import config
            return config.get_int("ELASTIC_RANK", jax.process_index())
        return jax.process_index()

    @classmethod
    def survivors(cls) -> tuple:
        """Surviving original rank ids, sorted (all ranks pre-fault)."""
        if cls._elastic is not None:
            return cls._elastic["survivors"]
        return tuple(range(cls.world()))

    @classmethod
    def elastic_active(cls) -> bool:
        """True when a logical (elastic/simulated) topology overrides the
        physical jax process view."""
        return cls._elastic is not None or cls._env_elastic_world() > 1

    @classmethod
    def is_writer(cls) -> bool:
        """True on the rank that owns shared-store writes (checkpoints):
        the lowest surviving rank.  Identical to process_index()==0 until
        a reform() removes rank 0."""
        return cls.rank() == min(cls.survivors() or (0,))

    @classmethod
    def reform(cls, world: Optional[int] = None, rank: Optional[int] = None,
               survivors: Optional[Sequence[int]] = None,
               devices: Optional[Sequence] = None) -> Mesh:
        """Re-form the topology over a new rank set — SHRINK after a host
        loss (parallel/elastic step 3) or GROW when a returning host is
        admitted (step 4): the data axis resizes in either direction.

        `survivors` are ORIGINAL rank ids (default: the first `world`
        current survivors — a shrink-only shorthand; growing must name
        the widened set explicitly since ranks keep their original ids);
        `rank` is this process's original id (default: unchanged).  With
        `devices` given, the mesh itself is rebuilt over that device
        subset (the in-process simulated-host path: "losing a host" =
        losing its devices, "regaining" = its devices coming back); only
        1-D data-parallel meshes re-form this way — multi-axis layouts
        resize their data axis via :meth:`_reform_data_axis`.  Without
        `devices` the mesh keeps its current (local) devices and only
        the logical topology changes — the simulated-multi-host path,
        where each rank's devices were local all along.  The caller
        (Optimizer._elastic_recover / _elastic_grow) owns tearing down
        compiled steps and re-placing state."""
        cur = cls.survivors()
        if survivors is None:
            if world is None:
                raise ValueError("Engine.reform: need world or survivors")
            if int(world) > len(cur):
                raise ValueError(
                    f"Engine.reform: world={world} > current "
                    f"{len(cur)} — growing needs an explicit survivor "
                    "set (original rank ids cannot be invented)")
            survivors = cur[:int(world)]
        survivors = tuple(sorted(int(r) for r in survivors))
        if not survivors:
            raise ValueError("Engine.reform: empty survivor set")
        if world is not None and int(world) != len(survivors):
            raise ValueError(f"Engine.reform: world={world} disagrees with "
                             f"survivors {survivors}")
        if rank is None:
            rank = cls.rank()
        rank = int(rank)
        if rank not in survivors:
            raise ValueError(f"Engine.reform: rank {rank} not in survivors "
                             f"{survivors}")
        if devices is not None:
            devs = list(devices)
            if cls._mesh is not None and len(cls._mesh.axis_names) > 1:
                cls.set_mesh(cls._reform_data_axis(cls._mesh, devs))
            else:
                cls.set_mesh(Mesh(np.array(devs), (cls.DATA_AXIS,)))
        cls._elastic = {"rank": rank, "survivors": survivors}
        logger.warning("Engine.reform: world -> %d (rank %d, survivors %s)",
                       len(survivors), rank, list(survivors))
        return cls.mesh()

    @classmethod
    def _reform_data_axis(cls, mesh: Mesh, devs) -> Mesh:
        """Re-form a MULTI-AXIS mesh over a new device set by resizing
        the 'data' axis — in EITHER direction — and keeping every other
        axis (the fsdp x tp x pipe x expert block of a MeshLayout)
        intact.  When the device count is not a multiple of the non-data
        block — the shard groups cannot be preserved — this raises the
        typed MeshReformError instead of silently re-laying-out sharded
        parameters (parallel/layout; drilled by tests/test_layout.py and
        tests/test_elastic.py for the widen direction)."""
        from ..parallel.layout import MeshReformError
        names = tuple(mesh.axis_names)
        if cls.DATA_AXIS not in names:
            raise MeshReformError(
                f"cannot re-form mesh {dict(mesh.shape)} over "
                f"{len(devs)} device(s): no '{cls.DATA_AXIS}' "
                "axis to resize — rebuild the layout via Engine.init")
        sizes = [int(mesh.shape[a]) for a in names]
        di = names.index(cls.DATA_AXIS)
        block = int(np.prod([s for i, s in enumerate(sizes) if i != di]))
        if len(devs) < block or len(devs) % block:
            raise MeshReformError(
                f"cannot re-form mesh {dict(mesh.shape)} over "
                f"{len(devs)} device(s): the non-data block "
                f"({ {a: s for i, (a, s) in enumerate(zip(names, sizes)) if i != di} }"
                f" = {block} devices) must divide the device count to "
                "keep the fsdp/tp/pipe/expert shard groups intact; "
                f"re-form to a multiple of {block} devices or re-init a "
                "different layout")
        sizes[di] = len(devs) // block
        logger.warning("Engine.reform: mesh %s -> %s over %d device(s)",
                       dict(mesh.shape), dict(zip(names, sizes)), len(devs))
        return Mesh(np.array(devs).reshape(sizes), names)

    # kept as an alias: external drills/tests referenced the shrink name
    _shrink_data_axis = _reform_data_axis

    # -- topology accessors (BigDL: Engine.nodeNumber / Engine.coreNumber) --

    @classmethod
    def data_shard_info(cls, axis: str = None) -> tuple:
        """(shard_index, shard_count) for PER-PROCESS input sharding,
        derived from how the mesh's data axis maps onto processes (the
        locality role of ZippedPartitionsWithLocalityRDD, SURVEY.md §5.8).

        A process must feed exactly the batch rows its devices will hold:
        when the data axis spans processes, each process feeds its slice
        (shard_count > 1); when the data axis is intra-process (e.g. a
        'model'-first mesh where TP spans hosts and the batch is replicated
        across them), every process must feed the FULL batch
        (shard_count == 1).  Feeding a blind per-process slice in the
        latter layout silently trains each host on different data."""
        axis = axis or cls.DATA_AXIS
        if cls._elastic is not None or cls._env_elastic_world() > 1:
            # elastic logical topology (simulated multi-host / post-shrink):
            # each surviving rank feeds its index-th stride of the data
            surv = cls.survivors()
            return surv.index(cls.rank()), len(surv)
        if jax.process_count() == 1:
            return 0, 1
        mesh = cls.mesh()
        if axis not in mesh.axis_names:
            # no data axis -> batch_sharding replicates the batch: every
            # process must feed the identical full dataset
            return 0, 1
        devs = np.asarray(mesh.devices)
        ax = mesh.axis_names.index(axis)
        size = devs.shape[ax]
        rows = np.moveaxis(devs, ax, 0).reshape(size, -1)
        def coverage(pid):
            return tuple(i for i in range(size)
                         if any(d.process_index == pid for d in rows[i]))
        unique = sorted({coverage(p) for p in range(jax.process_count())})
        return unique.index(coverage(jax.process_index())), len(unique)

    @classmethod
    def node_number(cls) -> int:
        """Number of host processes (BigDL: Engine.nodeNumber, utils/Engine.scala)."""
        return jax.process_count()

    @classmethod
    def core_number(cls) -> int:
        """Devices attached to this process (BigDL: Engine.coreNumber)."""
        return jax.local_device_count()

    @classmethod
    def device_count(cls) -> int:
        return len(cls.mesh().devices.reshape(-1))

    @classmethod
    def data_parallel_size(cls) -> int:
        m = cls.mesh()
        return m.shape[cls.DATA_AXIS] if cls.DATA_AXIS in m.axis_names else 1
