"""Trustworthy device timing for benchmarks.

On this image's tunneled TPU backend, `jax.block_until_ready` returns WITHOUT
waiting for device execution — only a host fetch of result bytes actually
synchronizes (measured: an 8192^3 bf16 matmul "completed" in 22us = 50
PFLOP/s under block_until_ready; fetching the result took the physically
sensible ~7ms).  Every timing helper here therefore synchronizes by fetching
a scalar derived from the result, and the per-step measurement DIFFERENCES
two chained-run lengths to cancel the constant fetch/tunnel round-trip:

    dt = (T(n2) - T(n1)) / (n2 - n1)

Role in the reference: DistriOptimizer's per-iteration wall timing
(optim/DistriOptimizer.scala:293-297) is host-side around a synchronous Spark
job, so it never had this problem; a compiled async backend needs explicit
sync discipline.  Shared by `bench.py` and `bigdl_tpu/tools/perf.py`.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["fetch_scalar", "measure_chain", "measure_sync",
           "measure_step_seconds", "measure_roofline", "is_tpu_like"]


def fetch_scalar(x) -> float:
    """Force completion of everything `x` depends on via a host byte fetch."""
    while isinstance(x, (list, tuple)):
        x = x[0]
    flat = x.ravel() if getattr(x, "ndim", 0) else x
    return float(np.asarray(flat[0] if getattr(flat, "ndim", 0) else flat))


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _progress(progress) -> None:
    """One measurement heartbeat: the caller's callback (if any) PLUS the
    process-default supervisor (utils/supervisor.notify) — tunneled-TPU
    benches get stall coverage for free, with no handle threading."""
    if progress:
        progress()
    from . import supervisor
    supervisor.notify()


def measure_chain(run, n1=4, n2=16, reps=3, progress=None):
    """Differenced chained timing of `run()` (must return a device value that
    depends on all prior `run()` calls, e.g. the loss of a step that threads
    its params).  Returns (seconds_per_run, details dict).  `progress` (no
    args, no output) is called after every rep so a caller's stall watchdog
    sees a heartbeat at least once per chain instead of one long silence;
    the active supervisor (utils/supervisor) is beaten either way."""
    fetch_scalar(run())  # drain queue + any lazy backend state
    _progress(progress)
    times = {}
    for n in (n1, n2):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = None
            for _ in range(n):
                out = run()
            fetch_scalar(out)
            best = min(best, time.perf_counter() - t0)
            _progress(progress)
        times[n] = best
    dt = (times[n2] - times[n1]) / (n2 - n1)
    overhead = max(times[n1] - n1 * dt, 0.0)
    return dt, {"n1": n1, "n2": n2, "t_n1": round(times[n1], 6),
                "t_n2": round(times[n2], 6),
                "fixed_overhead_seconds": round(overhead, 6)}


def measure_sync(run, iters=6, progress=None) -> float:
    """Median per-call timing with a host fetch per call (upper-bounds the
    true step time by one tunnel round-trip).  Heartbeats like
    measure_chain: per-rep callback + active-supervisor notify."""
    fetch_scalar(run())
    _progress(progress)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fetch_scalar(run())
        ts.append(time.perf_counter() - t0)
        _progress(progress)
    ts.sort()
    return ts[len(ts) // 2]


def measure_step_seconds(run, n1=4, n2=16, reps=3, log=None, progress=None):
    """Best-effort step time: differenced chain, falling back to the synced
    median when the differencing is inconsistent (noise/backlog)."""
    dt, detail = measure_chain(run, n1=n1, n2=n2, reps=reps,
                               progress=progress)
    dt_sync = measure_sync(run, progress=progress)
    detail["step_seconds_sync"] = round(dt_sync, 6)
    if dt <= 0 or dt > dt_sync * 1.5:
        if log:
            log(f"chained dt={dt:.6f}s inconsistent with sync="
                f"{dt_sync:.6f}s; using sync timing")
        detail["fallback"] = "sync"
        dt = dt_sync
    return dt, detail


def measure_roofline(n=8192, reps=2, tolerance=1.25):
    """Measured bf16 matmul FLOP/s on the default device — the empirical
    peak used to calibrate MFU denominators.  Runs the measurement `reps`
    times; returns None (inconclusive) unless all agree within `tolerance`x,
    so a single differencing glitch cannot silently deflate every MFU."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    a = jax.random.normal(jax.random.key(0), (n, n), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (n, n), jnp.bfloat16)
    scale = jnp.bfloat16(1.0 / (n ** 0.5))

    @partial(jax.jit, static_argnums=2)
    def chain(x, w, length):
        def body(c, _):
            return (c @ w) * scale, ()
        y, _ = jax.lax.scan(body, x, None, length=length)
        return y

    # compile both lengths before timing
    fetch_scalar(chain(a, b, 2))
    fetch_scalar(chain(a, b, 8))

    estimates = []
    for _ in range(reps):
        t2 = min(_timed(lambda: fetch_scalar(chain(a, b, 2)))
                 for _ in range(3))
        t8 = min(_timed(lambda: fetch_scalar(chain(a, b, 8)))
                 for _ in range(3))
        per_mm = (t8 - t2) / 6.0
        if per_mm <= 0:
            return None
        estimates.append(2.0 * (n ** 3) / per_mm)
    if max(estimates) > tolerance * min(estimates):
        return None  # irreproducible — refuse rather than mis-calibrate
    return sum(estimates) / len(estimates)


def is_tpu_like(device) -> bool:
    """True for real TPUs however the platform registers itself (the tunneled
    backend on this image reports platform 'tpu' but other plugin builds may
    expose the plugin name, e.g. 'axon'; device_kind stays 'TPU ...')."""
    kind = getattr(device, "device_kind", "").lower()
    platform = getattr(device, "platform", "").lower()
    return "tpu" in kind or platform in ("tpu", "axon")
