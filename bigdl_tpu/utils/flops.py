"""Analytic FLOP counting by walking a jaxpr.

Role in the reference: the perf harness `DistriOptimizerPerf.scala:91-95`
reports only records/s; MFU accounting is net-new for the TPU rebuild
(BASELINE.md: ResNet-50 >= 45% MFU on v5e).  XLA's `compiled.cost_analysis()`
is the primary FLOPs source, but it can fail on experimental backends — this
module is the deterministic fallback: trace the function with
`jax.make_jaxpr` (no compile, no device) and count matmul/conv FLOPs
directly from the equations, recursing into scan/cond/while/pjit/custom-vjp
sub-jaxprs.

Conventions: a dot_general counts 2*M*N*K (multiply+add); a conv counts
2 * prod(out_shape) * (in_features / feature_group_count) * prod(kernel_spatial).
Elementwise ops are ignored (matmul/conv dominate on the MXU).  `scan` bodies
are multiplied by trip count; `while_loop` bodies are counted once (trip count
is data-dependent) — callers that need exact totals should avoid while_loop in
the hot path anyway (it also blocks XLA pipelining).
"""

from __future__ import annotations

import math

import jax

__all__ = ["jaxpr_flops", "fn_flops", "device_peak_flops",
           "CPU_NOMINAL_PEAK"]

# bf16 peak FLOP/s per *jax device* (v2/v3 devices are single cores) —
# the MFU denominator bench.py and the Optimizer's per-step mfu counter
# share.  Ordering matters: "v5p" must match before "v5" (lite/e).
_TPU_PEAK_BF16 = (
    ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),  # v5 lite / v5e
    ("v4", 275e12), ("v3", 61.5e12), ("v2", 22.5e12),
)

# Nominal CPU denominator: there is no honest single peak for a shared
# host CPU, but a FIXED nominal one still makes the per-step mfu counter
# a usable *regression* signal in CPU traces (the absolute value is
# meaningless; the trend is not).  Override with BIGDL_TPU_PEAK_FLOPS.
CPU_NOMINAL_PEAK = 1e12


def device_peak_flops(device=None):
    """(peak_flops, source) for the MFU denominator.

    source is ``"env"`` (BIGDL_TPU_PEAK_FLOPS override), ``"table"`` (TPU
    device-kind match), or ``"nominal"`` (CPU/unknown fallback,
    :data:`CPU_NOMINAL_PEAK`).  Callers that refuse to report MFU against
    a made-up denominator (bench.py) gate on ``source != "nominal"``."""
    from . import config
    env = config.get_float("PEAK_FLOPS", 0.0)
    if env > 0:
        return env, "env"
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" in kind or "tpu" in getattr(device, "platform", ""):
        for key, val in _TPU_PEAK_BF16:
            if key in kind:
                return val, "table"
    return CPU_NOMINAL_PEAK, "nominal"


def _prod(xs):
    return math.prod(int(x) for x in xs)


def _eqn_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        (lc, _rc), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval.shape
        out = eqn.outvars[0].aval.shape
        k = _prod(lhs[d] for d in lc)
        return 2.0 * _prod(out) * k
    if name == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval.shape
        out = eqn.outvars[0].aval.shape
        # rhs_spec = (out_f, in_f, *spatial); the in_f dim of the kernel is
        # already per-group (in_features / feature_group_count), so no extra
        # group division is needed
        in_f = rhs[dn.rhs_spec[1]]
        k_spatial = _prod(rhs[d] for d in dn.rhs_spec[2:])
        return 2.0 * _prod(out) * in_f * k_spatial
    return 0.0


def _sub_jaxprs(eqn):
    """Yield (jaxpr, multiplier) for every sub-jaxpr in an equation."""
    name = eqn.primitive.name
    for pname, val in eqn.params.items():
        mult = 1.0
        if name == "scan" and pname == "jaxpr":
            mult = float(eqn.params.get("length", 1))
        for j in _iter_jaxprs(val):
            yield j, mult


def _iter_jaxprs(val):
    if hasattr(val, "eqns") or hasattr(val, "jaxpr"):  # Jaxpr / ClosedJaxpr
        yield val
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from _iter_jaxprs(v)


def jaxpr_flops(jaxpr) -> float:
    """Total matmul+conv FLOPs in a (Closed)Jaxpr, recursing into sub-jaxprs."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    total = 0.0
    for eqn in inner.eqns:
        total += _eqn_flops(eqn)
        if eqn.primitive.name == "cond":
            # conservative: cost of the most expensive branch, counted once
            branches = eqn.params.get("branches", ())
            if branches:
                total += max(jaxpr_flops(b) for b in branches)
            continue
        for sub, mult in _sub_jaxprs(eqn):
            total += mult * jaxpr_flops(sub)
    return total


def fn_flops(fn, *args, **kwargs) -> float:
    """FLOPs of one call of `fn(*args)` — traced, never compiled or executed."""
    closed = jax.make_jaxpr(fn, **kwargs)(*args)
    return jaxpr_flops(closed)
