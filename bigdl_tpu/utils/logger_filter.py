"""LoggerFilter: route framework/dependency log noise to a file.

Reference: utils/LoggerFilter.scala:34 — redirects Spark/akka/breeze INFO
chatter to `bigdl.log` so the driver console shows only BigDL's own
progress lines; controlled by `bigdl.utils.LoggerFilter.{disable,logFile,
enableSparkLog}` properties.  TPU re-design: the noisy dependencies are
jax/absl/etc.; control via BIGDL_TPU_DISABLE_LOGGER_FILTER and
BIGDL_TPU_LOG_FILE (utils/config.py)."""

from __future__ import annotations

import logging
import os
from typing import Iterable, Optional

from . import config

__all__ = ["redirect"]

_NOISY = ("jax", "jax._src", "absl", "orbax", "flax")

# one handler per log path for the process — repeat redirect() calls reuse
# it instead of leaking file descriptors
_handlers: dict = {}


def redirect(loggers: Optional[Iterable[str]] = None,
             log_file: Optional[str] = None) -> Optional[str]:
    """Send the given loggers' records (default: jax/absl and friends) to
    BIGDL_TPU_LOG_FILE instead of the console.  Returns the log path, or
    None when disabled (reference: LoggerFilter.redirectSparkInfoLogs)."""
    if config.get_bool("DISABLE_LOGGER_FILTER"):
        return None
    path = log_file or config.get_str("LOG_FILE",
                                      os.path.abspath("bigdl_tpu.log"))
    handler = _handlers.get(path)
    if handler is None:
        handler = logging.FileHandler(path)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s - %(message)s"))
        _handlers[path] = handler
    for name in (loggers or _NOISY):
        lg = logging.getLogger(name)
        # handlers are cached per path (bounded), so detach without closing
        # — another logger may still share the old handler
        for old in list(lg.handlers):
            lg.removeHandler(old)
        lg.addHandler(handler)
        lg.propagate = False
        lg.setLevel(logging.INFO)
    return path
