"""Table: Torch-style heterogeneous container used for multi-input/output activities
and optimizer state.

Reference: BigDL `utils/Table.scala:34` (int-or-any keyed table, used as the `Activity`
union's non-tensor half) and the `T()` constructor (`utils/Table.scala:299`).

TPU-native re-design: a Table is just a Python dict registered as a JAX pytree, so it
flows through `jax.jit` / `jax.grad` / shardings like any other container.  Integer
keys (Torch's 1-based convention) are supported for parity, but idiomatic code should
use lists/tuples, which JAX already treats as pytrees.
"""

from __future__ import annotations

import jax

__all__ = ["Table", "T"]


class Table(dict):
    """A dict that tolerates Torch-style `table[1]`, `table[2]` integer keys."""

    def insert(self, value):
        """Append with the next free 1-based integer key (Torch semantics)."""
        i = 1
        while i in self:
            i += 1
        self[i] = value
        return self

    def __repr__(self):
        inner = ", ".join(f"{k!r}: {v!r}" for k, v in self.items())
        return "T{" + inner + "}"


def _table_flatten(t: Table):
    keys = sorted(t.keys(), key=lambda k: (str(type(k)), k))
    return [t[k] for k in keys], tuple(keys)


def _table_unflatten(keys, values):
    return Table(zip(keys, values))


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)


def T(*args, **kwargs) -> Table:
    """`T(a, b, c)` -> Table with 1-based integer keys; `T(k=v)` -> named entries."""
    t = Table()
    for i, a in enumerate(args):
        t[i + 1] = a
    t.update(kwargs)
    return t
