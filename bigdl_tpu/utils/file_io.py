"""Checkpoint save/load with URL-scheme storage dispatch.

Reference: BigDL `utils/File.scala:25` — java-serialization save/load with
HDFS/S3 support (`saveToHdfs:106`, `loadFromHdfs:139`: the path's scheme
selects the Hadoop filesystem); checkpoint file contract `model.<neval>` /
`optimMethod.<neval>` written by `optim/Optimizer.scala:284-322` and
`DistriOptimizer.scala:394-416`, resumed via `getLatestFile`
(DistriOptimizer.scala:828-845).

TPU-native re-design: params/state pytrees are pulled to host numpy and
written as a single pickle blob (portable, no JVM serialization); the
`model.<neval>` / `optimMethod.<neval>` naming contract is preserved so
resume-by-latest works identically.  Storage dispatch mirrors the
reference's scheme-based routing: plain paths use the local FS fast path
(atomic tmp+rename); `gs://`, `s3://`, `hdfs://`, ... routes through fsspec
(the TPU-native stack's HDFS: GCS is the storage actually attached to TPU
pods).  Custom backends register with `register_filesystem` (tests register
a `mem://` store).
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

__all__ = ["save", "load", "save_checkpoint", "latest_checkpoint", "File",
           "register_filesystem", "get_filesystem"]

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")


class LocalFileSystem:
    """Local fast path with atomic writes (tmp + rename)."""

    def write_pickle(self, path: str, obj) -> None:
        """Stream-pickle straight to disk (no whole-blob bytes object —
        matters for multi-GB checkpoints)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)

    def read_pickle(self, path: str):
        with open(path, "rb") as f:
            return pickle.load(f)

    def write_bytes(self, path: str, data: bytes) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str):
        return os.listdir(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)


class FsspecFileSystem:
    """Remote store via fsspec (gs://, s3://, hdfs://, memory://, ...)."""

    def __init__(self, scheme: str):
        import fsspec
        self.scheme = scheme
        self._fs = fsspec.filesystem(scheme)

    def write_bytes(self, path: str, data: bytes) -> None:
        parent = path.rsplit("/", 1)[0]
        if parent and parent != path:
            try:
                self._fs.makedirs(self._strip(parent), exist_ok=True)
            except Exception:  # noqa: BLE001 — flat stores have no dirs
                pass
        with self._fs.open(self._strip(path), "wb") as f:
            f.write(data)

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open(self._strip(path), "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def isdir(self, path: str) -> bool:
        try:
            return self._fs.isdir(self._strip(path))
        except Exception:  # noqa: BLE001
            return False

    def listdir(self, path: str):
        return [p.rsplit("/", 1)[-1]
                for p in self._fs.ls(self._strip(path), detail=False)]

    def makedirs(self, path: str) -> None:
        try:
            self._fs.makedirs(self._strip(path), exist_ok=True)
        except Exception:  # noqa: BLE001 — flat stores have no dirs
            pass

    def _strip(self, path: str) -> str:
        # fsspec accepts scheme-qualified paths; keep them as-is
        return path


_REGISTRY: Dict[str, Any] = {}
_LOCAL = LocalFileSystem()


def register_filesystem(scheme: str, fs) -> None:
    """Install a filesystem for a URL scheme (tests: an in-memory store)."""
    _REGISTRY[scheme] = fs


def get_filesystem(path: str):
    """Route a path to its filesystem by scheme (File.scala:106 role)."""
    m = _SCHEME_RE.match(path)
    if not m:
        return _LOCAL
    scheme = m.group(1)
    if scheme == "file":
        return _LOCAL
    if scheme not in _REGISTRY:
        _REGISTRY[scheme] = FsspecFileSystem(scheme)
    return _REGISTRY[scheme]


def _join(base: str, name: str) -> str:
    if _SCHEME_RE.match(base):
        return base.rstrip("/") + "/" + name
    return os.path.join(base, name)


def _strip_file_scheme(path: str) -> str:
    return path[len("file://"):] if path.startswith("file://") else path


def _to_numpy(tree):
    # only coerce device arrays — other leaves (strings, modules, None)
    # must survive pickling untouched
    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """(File.scala:25 `save`; remote schemes = saveToHdfs:106 role)."""
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    # check order matters: exists() can be a remote round-trip, skip it
    # entirely in the default overwrite=True case
    if not overwrite and fs.exists(path):
        raise FileExistsError(path)
    obj = _to_numpy(obj)
    if hasattr(fs, "write_pickle"):  # local: stream, no whole-blob copy
        fs.write_pickle(path, obj)
    else:
        fs.write_bytes(path, pickle.dumps(obj,
                                          protocol=pickle.HIGHEST_PROTOCOL))


def load(path: str) -> Any:
    """(File.scala `load`; remote schemes = loadFromHdfs:139 role)."""
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    if hasattr(fs, "read_pickle"):
        return fs.read_pickle(path)
    return pickle.loads(fs.read_bytes(path))


def save_checkpoint(path: str, neval: int, model_blob: Any,
                    optim_blob: Any, overwrite: bool = True) -> Tuple[str, str]:
    """Write model.<neval> + optimMethod.<neval>
    (DistriOptimizer.scala:394-416)."""
    path = _strip_file_scheme(path)
    get_filesystem(path).makedirs(path)
    mp = _join(path, f"model.{neval}")
    op = _join(path, f"optimMethod.{neval}")
    save(model_blob, mp, overwrite)
    save(optim_blob, op, overwrite)
    return mp, op


_ASYNC_EXECUTOR = None
_ASYNC_FUTURES: list = []


def save_checkpoint_async(path: str, neval: int, model_blob: Any,
                          optim_blob: Any, overwrite: bool = True):
    """Non-blocking save_checkpoint (net-new vs the reference — large
    snapshots would otherwise stall the train loop for seconds).

    The device→host copy happens SYNCHRONOUSLY here (the caller's arrays
    are about to be donated back into the compiled step; a background
    np.asarray would read freed buffers); only pickling + filesystem IO
    run on the single background writer thread.  Local writes stay atomic
    (LocalFileSystem tmp+rename).  Errors surface on the next
    `wait_for_async_checkpoints()`/`join_checkpoints` call — or HERE at
    submission when backpressure joins an older write.

    Backpressure: at most 2 snapshots may be pending; a faster checkpoint
    cadence than the storage can absorb blocks on the oldest write instead
    of accumulating full host copies until OOM.  Returns the future."""
    global _ASYNC_EXECUTOR
    model_blob = _to_numpy(model_blob)
    optim_blob = _to_numpy(optim_blob)
    if _ASYNC_EXECUTOR is None:
        from concurrent.futures import ThreadPoolExecutor
        # one worker: checkpoints must land in submission order
        _ASYNC_EXECUTOR = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="bigdl-ckpt")
    _ASYNC_FUTURES[:] = [f for f in _ASYNC_FUTURES if not f.done()]
    while len(_ASYNC_FUTURES) >= 2:
        oldest = _ASYNC_FUTURES.pop(0)
        oldest.result()  # raises in the train loop, like a sync write
    fut = _ASYNC_EXECUTOR.submit(
        save_checkpoint, path, neval, model_blob, optim_blob, overwrite)
    _ASYNC_FUTURES.append(fut)
    return fut


def join_checkpoints(futures) -> None:
    """Join EVERY future, then re-raise the first error (a first-error
    early return would leave later writes in flight with errors lost)."""
    first_err = None
    for f in futures:
        try:
            f.result()
        except Exception as e:  # noqa: BLE001 — collected, re-raised below
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


def wait_for_async_checkpoints() -> None:
    """Block until every pending async checkpoint is on disk; re-raises
    the first write error (after all have been joined)."""
    global _ASYNC_FUTURES
    futs, _ASYNC_FUTURES = _ASYNC_FUTURES, []
    join_checkpoints(futs)


def latest_checkpoint(path: str) -> Optional[Tuple[str, str, int]]:
    """Find the newest (model, optimMethod, neval) triple
    (getLatestFile, DistriOptimizer.scala:828-845)."""
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    if not fs.isdir(path):
        return None
    best = -1
    for name in fs.listdir(path):
        m = re.fullmatch(r"model\.(\d+)", name)
        if m:
            n = int(m.group(1))
            if n > best and fs.exists(_join(path, f"optimMethod.{n}")):
                best = n
    if best < 0:
        return None
    return (_join(path, f"model.{best}"),
            _join(path, f"optimMethod.{best}"), best)


class File:
    """Namespace parity with the reference's `File` object."""

    save = staticmethod(save)
    load = staticmethod(load)
