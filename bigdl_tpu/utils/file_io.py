"""Checkpoint save/load with URL-scheme storage dispatch.

Reference: BigDL `utils/File.scala:25` — java-serialization save/load with
HDFS/S3 support (`saveToHdfs:106`, `loadFromHdfs:139`: the path's scheme
selects the Hadoop filesystem); checkpoint file contract `model.<neval>` /
`optimMethod.<neval>` written by `optim/Optimizer.scala:284-322` and
`DistriOptimizer.scala:394-416`, resumed via `getLatestFile`
(DistriOptimizer.scala:828-845).

TPU-native re-design: params/state pytrees are pulled to host numpy and
written as a single pickle blob (portable, no JVM serialization); the
`model.<neval>` / `optimMethod.<neval>` naming contract is preserved so
resume-by-latest works identically.  Storage dispatch mirrors the
reference's scheme-based routing: plain paths use the local FS fast path
(atomic tmp+rename); `gs://`, `s3://`, `hdfs://`, ... routes through fsspec
(the TPU-native stack's HDFS: GCS is the storage actually attached to TPU
pods).  Custom backends register with `register_filesystem` (tests register
a `mem://` store).

Durability guarantees the reference inherited from Spark's block manager
and this rebuild must provide itself (docs/robustness.md):

- **Integrity frame**: every `save()` payload carries a footer
  ``<u64 payload length> <u32 masked CRC32C> <8-byte magic>`` — the same
  TFRecord-style masked CRC32C as csrc/crc32c.cc / utils/recordio.py
  (native-accelerated when the extension is built, pure-Python fallback).
  `load()` verifies the frame and raises the typed
  :class:`CorruptCheckpoint`; files without the magic load as legacy
  unframed pickles.
- **Atomicity**: local writes stay tmp+rename; remote (fsspec) writes are
  write-then-verify-readback — a torn remote write is retried, never left
  as the newest snapshot.
- **Retry/backoff**: every non-local filesystem op runs under exponential
  backoff with deterministic jitter and a deadline
  (``BIGDL_TPU_IO_RETRIES`` / ``_IO_BACKOFF_BASE`` / ``_IO_BACKOFF_MAX`` /
  ``_IO_DEADLINE``; clock and sleep injectable for tests), so a transient
  fsspec error never reaches — and never burns — the optimizer's scarce
  ``bigdl.failure.retryTimes`` budget.
- **Lineage**: `checkpoint_lineage` lists valid-looking snapshots
  newest-first; `quarantine_checkpoint` renames corrupt ones aside
  (``.corrupt`` suffix — kept for forensics, invisible to resume);
  `prune_checkpoints` enforces keep-last-K (+ explicit keeper set).

Fault points (utils/chaos.py): ``ckpt.write`` / ``ckpt.read`` around every
blob, ``fs.remote`` around every remote op attempt.
"""

from __future__ import annotations

import logging
import os
import pickle
import re
import struct
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from . import chaos, config, telemetry
from .recordio import crc32c_update

logger = logging.getLogger("bigdl_tpu")

__all__ = ["save", "load", "verify", "save_checkpoint", "latest_checkpoint",
           "File", "register_filesystem", "get_filesystem",
           "CorruptCheckpoint", "checkpoint_lineage", "quarantine_checkpoint",
           "prune_checkpoints", "RetryPolicy", "set_retry_timebase",
           "watch_lineage", "frame_fingerprint"]

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")


class CorruptCheckpoint(IOError):
    """A checkpoint whose integrity frame (or payload) failed verification.

    Lineage-walking recovery (optim/Optimizer._recover_from_checkpoint)
    catches exactly this type: it quarantines the file and falls back to
    the next-newest snapshot instead of crashing the run on it."""


# ---------------------------------------------------------------------------
# integrity frame: <payload> <u64 length> <u32 masked crc32c> <magic>
# ---------------------------------------------------------------------------

_FRAME_MAGIC = b"BGLNCKP1"  # 8 bytes, last in the file
_FOOTER = struct.Struct("<QI")
_FOOTER_LEN = _FOOTER.size + len(_FRAME_MAGIC)
_CRC_CHUNK = 4 << 20


def _mask(crc: int) -> int:
    """TFRecord CRC mask (csrc/crc32c.h MaskedCrc32c)."""
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def _footer(length: int, masked_crc: int) -> bytes:
    return _FOOTER.pack(length, masked_crc) + _FRAME_MAGIC


def frame_bytes(payload: bytes) -> bytes:
    """Payload + integrity footer (length + masked CRC32C + magic)."""
    return payload + _footer(len(payload), _mask(crc32c_update(0, payload)))


def unframe_bytes(data: bytes, path: str = "<bytes>") -> bytes:
    """Verify and strip the integrity footer; raises CorruptCheckpoint on
    any mismatch.  Data without the trailing magic passes through as-is
    (legacy unframed pickle — pre-frame checkpoints stay loadable)."""
    if len(data) < _FOOTER_LEN or data[-len(_FRAME_MAGIC):] != _FRAME_MAGIC:
        return data
    length, crc = _FOOTER.unpack(data[-_FOOTER_LEN:-len(_FRAME_MAGIC)])
    payload = data[:-_FOOTER_LEN]
    if length != len(payload):
        raise CorruptCheckpoint(
            f"{path}: truncated checkpoint (frame declares {length} payload "
            f"bytes, file holds {len(payload)})")
    got = _mask(crc32c_update(0, payload))
    if got != crc:
        raise CorruptCheckpoint(
            f"{path}: checkpoint CRC mismatch (stored {crc:#010x}, "
            f"computed {got:#010x})")
    return payload


class _CrcTee:
    """File-object shim: streams pickle.dump output to `f` while keeping a
    running CRC32C and byte count (no whole-blob copy for multi-GB
    checkpoints; native `bigdl_crc32c_extend` when built)."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.nbytes = 0

    def write(self, data):
        # protocol-5 pickling hands buffer-protocol objects (PickleBuffer,
        # memoryview) to write(); normalize once for crc + length
        data = bytes(data)
        self._f.write(data)
        self.crc = crc32c_update(self.crc, data)
        self.nbytes += len(data)


def _loads_payload(payload: bytes, path: str):
    try:
        return pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 — any unpickle error = corrupt
        raise CorruptCheckpoint(f"{path}: unreadable payload "
                                f"({type(e).__name__}: {e})") from e


# ---------------------------------------------------------------------------
# retry/backoff for remote IO
# ---------------------------------------------------------------------------

# injectable time base so tests (and the chaos suite) run deterministic
# backoff schedules with zero wall-clock sleeping
_TIMEBASE = {"clock": time.monotonic, "sleep": time.sleep}


def set_retry_timebase(clock=None, sleep=None):
    """Swap the clock/sleep the retry layer uses (tests); None = real time.
    Returns the previous (clock, sleep) pair."""
    prev = (_TIMEBASE["clock"], _TIMEBASE["sleep"])
    _TIMEBASE["clock"] = clock or time.monotonic
    _TIMEBASE["sleep"] = sleep or time.sleep
    return prev


class RetryPolicy:
    """Exponential backoff with deterministic jitter and a deadline.

    Jitter is a pure function of the attempt number (golden-ratio hash into
    [0.5, 1.0]) — retries de-synchronize across workers without any RNG, so
    chaos runs stay exactly reproducible."""

    def __init__(self, retries: Optional[int] = None,
                 base: Optional[float] = None,
                 max_delay: Optional[float] = None,
                 deadline: Optional[float] = None,
                 clock=None, sleep=None):
        self.retries = (config.get_int("IO_RETRIES", 3)
                        if retries is None else retries)
        self.base = (config.get_float("IO_BACKOFF_BASE", 0.05)
                     if base is None else base)
        self.max_delay = (config.get_float("IO_BACKOFF_MAX", 2.0)
                          if max_delay is None else max_delay)
        self.deadline = (config.get_float("IO_DEADLINE", 60.0)
                         if deadline is None else deadline)
        self.clock = clock or _TIMEBASE["clock"]
        self.sleep = sleep or _TIMEBASE["sleep"]

    def delay(self, attempt: int) -> float:
        """Backoff before retry `attempt` (1-based): exponential, capped,
        deterministically jittered."""
        d = min(self.base * (2 ** (attempt - 1)), self.max_delay)
        frac = (attempt * 0.6180339887498949) % 1.0
        return d * (0.5 + 0.5 * frac)

    def run(self, fn, describe: str = "", retriable=None):
        """Call `fn()` with retries; `retriable(exc) -> bool` gates which
        errors are worth another attempt (default: any Exception that is
        not a CorruptCheckpoint — integrity failures need a rewrite, not a
        reread, so callers opt in explicitly where that applies)."""
        start = self.clock()
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — filtered below
                ok = (retriable(e) if retriable is not None
                      else not isinstance(e, CorruptCheckpoint))
                attempt += 1
                if not ok or attempt > self.retries:
                    raise
                d = self.delay(attempt)
                # the retry is visible on the run timeline next to the
                # checkpoint/data spans it delays (telemetry no-ops when
                # tracing is off)
                telemetry.instant("io.retry", cat="io", op=describe,
                                  attempt=attempt,
                                  error=f"{type(e).__name__}: {e}")
                if self.clock() - start + d > self.deadline:
                    logger.warning("remote IO %s: deadline %.1fs exhausted "
                                   "after %d attempts", describe,
                                   self.deadline, attempt)
                    raise
                logger.warning("remote IO %s failed (%s: %s); retry %d/%d "
                               "in %.2fs", describe, type(e).__name__, e,
                               attempt, self.retries, d)
                self.sleep(d)


# ---------------------------------------------------------------------------
# filesystems
# ---------------------------------------------------------------------------

class LocalFileSystem:
    """Local fast path with atomic writes (tmp + rename)."""

    def write_pickle(self, path: str, obj) -> None:
        """Stream-pickle straight to disk (no whole-blob bytes object —
        matters for multi-GB checkpoints), CRC32C running alongside, then
        footer + atomic rename."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            tee = _CrcTee(f)
            pickle.dump(obj, tee, protocol=pickle.HIGHEST_PROTOCOL)
            f.write(_footer(tee.nbytes, _mask(tee.crc)))
        os.replace(tmp, path)

    def read_pickle(self, path: str):
        with open(path, "rb") as f:
            self._verify_frame(f, path)
            f.seek(0)
            try:
                # pickle.load stops at the STOP opcode, so the trailing
                # footer bytes are never consumed
                return pickle.load(f)
            except Exception as e:  # noqa: BLE001
                raise CorruptCheckpoint(f"{path}: unreadable payload "
                                        f"({type(e).__name__}: {e})") from e

    @staticmethod
    def _verify_frame(f, path: str) -> None:
        """Chunked CRC verify of a framed file (legacy unframed: no-op)."""
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size < _FOOTER_LEN:
            return
        f.seek(size - len(_FRAME_MAGIC))
        if f.read(len(_FRAME_MAGIC)) != _FRAME_MAGIC:
            return
        f.seek(size - _FOOTER_LEN)
        length, crc = _FOOTER.unpack(f.read(_FOOTER.size))
        payload_len = size - _FOOTER_LEN
        if length != payload_len:
            raise CorruptCheckpoint(
                f"{path}: truncated checkpoint (frame declares {length} "
                f"payload bytes, file holds {payload_len})")
        f.seek(0)
        got, left = 0, payload_len
        while left:
            chunk = f.read(min(_CRC_CHUNK, left))
            if not chunk:
                raise CorruptCheckpoint(f"{path}: short read during "
                                        "CRC verification")
            got = crc32c_update(got, chunk)
            left -= len(chunk)
        if _mask(got) != crc:
            raise CorruptCheckpoint(
                f"{path}: checkpoint CRC mismatch (stored {crc:#010x}, "
                f"computed {_mask(got):#010x})")

    def write_bytes(self, path: str, data: bytes) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str):
        return os.listdir(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def remove(self, path: str) -> None:
        os.remove(path)


class FsspecFileSystem:
    """Remote store via fsspec (gs://, s3://, hdfs://, memory://, ...)."""

    def __init__(self, scheme: str):
        import fsspec
        self.scheme = scheme
        self._fs = fsspec.filesystem(scheme)

    def write_bytes(self, path: str, data: bytes) -> None:
        parent = path.rsplit("/", 1)[0]
        if parent and parent != path:
            try:
                self._fs.makedirs(self._strip(parent), exist_ok=True)
            except Exception:  # noqa: BLE001 — flat stores have no dirs
                pass
        with self._fs.open(self._strip(path), "wb") as f:
            f.write(data)

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open(self._strip(path), "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def isdir(self, path: str) -> bool:
        try:
            return self._fs.isdir(self._strip(path))
        except Exception:  # noqa: BLE001
            return False

    def listdir(self, path: str):
        return [p.rsplit("/", 1)[-1]
                for p in self._fs.ls(self._strip(path), detail=False)]

    def makedirs(self, path: str) -> None:
        try:
            self._fs.makedirs(self._strip(path), exist_ok=True)
        except Exception:  # noqa: BLE001 — flat stores have no dirs
            pass

    def rename(self, src: str, dst: str) -> None:
        try:
            self._fs.mv(self._strip(src), self._strip(dst))
        except (AttributeError, NotImplementedError):
            # flat stores without a rename primitive: copy + delete
            data = self.read_bytes(src)
            self.write_bytes(dst, data)
            self._fs.rm(self._strip(src))

    def remove(self, path: str) -> None:
        self._fs.rm(self._strip(path))

    def _strip(self, path: str) -> str:
        # fsspec accepts scheme-qualified paths; keep them as-is
        return path


class RetryingFileSystem:
    """Backoff wrapper for non-local filesystems: every op attempt runs
    under RetryPolicy and fires the ``fs.remote`` chaos point — transient
    remote faults are absorbed HERE, below the optimizer's retry loop, so
    they never consume `bigdl.failure.retryTimes` budget."""

    _OPS = ("write_bytes", "read_bytes", "exists", "isdir", "listdir",
            "makedirs", "rename", "remove")

    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, name):
        target = getattr(self.inner, name)
        if name not in self._OPS:
            return target

        def op(*args, **kwargs):
            def once():
                chaos.fire("fs.remote")
                return target(*args, **kwargs)
            describe = f"{name}({args[0] if args else ''!s:.120})"
            return RetryPolicy().run(once, describe=describe)
        return op


_REGISTRY: Dict[str, Any] = {}
_LOCAL = LocalFileSystem()


def register_filesystem(scheme: str, fs) -> None:
    """Install a filesystem for a URL scheme (tests: an in-memory store).
    Non-local backends are wrapped in the retry/backoff layer."""
    _REGISTRY[scheme] = RetryingFileSystem(fs) if not isinstance(
        fs, (LocalFileSystem, RetryingFileSystem)) else fs


def get_filesystem(path: str):
    """Route a path to its filesystem by scheme (File.scala:106 role)."""
    m = _SCHEME_RE.match(path)
    if not m:
        return _LOCAL
    scheme = m.group(1)
    if scheme == "file":
        return _LOCAL
    if scheme not in _REGISTRY:
        _REGISTRY[scheme] = RetryingFileSystem(FsspecFileSystem(scheme))
    return _REGISTRY[scheme]


def _join(base: str, name: str) -> str:
    if _SCHEME_RE.match(base):
        return base.rstrip("/") + "/" + name
    return os.path.join(base, name)


def _strip_file_scheme(path: str) -> str:
    return path[len("file://"):] if path.startswith("file://") else path


def _to_numpy(tree):
    # only coerce device arrays — other leaves (strings, modules, None)
    # must survive pickling untouched
    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """(File.scala:25 `save`; remote schemes = saveToHdfs:106 role).

    The written file is integrity-framed (footer: length + masked CRC32C).
    Remote writes verify by reading the bytes back; a mismatch (torn
    write) retries the write under the IO RetryPolicy."""
    path = _strip_file_scheme(path)
    with telemetry.span("ckpt.write", cat="io", path=path):
        fs = get_filesystem(path)
        # check order matters: exists() can be a remote round-trip, skip it
        # entirely in the default overwrite=True case
        if not overwrite and fs.exists(path):
            raise FileExistsError(path)
        obj = _to_numpy(obj)
        if hasattr(fs, "write_pickle") and not chaos.armed("ckpt.write"):
            fs.write_pickle(path, obj)  # local: stream, no whole-blob copy
            return
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        # chaos mutates the FRAMED bytes: a corrupt@ schedule lands a file
        # whose CRC verification must fail at read time
        data = chaos.transform("ckpt.write", frame_bytes(payload))
        if hasattr(fs, "write_pickle"):  # local path with chaos armed
            fs.write_bytes(path, data)
            return

        def write_and_verify():
            fs.write_bytes(path, data)
            back = fs.read_bytes(path)
            if back != data:
                raise CorruptCheckpoint(
                    f"{path}: remote readback mismatch (wrote {len(data)} "
                    f"bytes, read {len(back)} back)")
        # readback mismatch IS retriable here — the fix is another write
        RetryPolicy().run(write_and_verify, describe=f"save({path})",
                          retriable=lambda e: True)


def load(path: str) -> Any:
    """(File.scala `load`; remote schemes = loadFromHdfs:139 role).

    Verifies the integrity frame; raises :class:`CorruptCheckpoint` on CRC
    mismatch, truncation, or an unreadable payload.  Files without the
    frame magic (pre-frame snapshots) load as plain pickles."""
    path = _strip_file_scheme(path)
    with telemetry.span("ckpt.read", cat="io", path=path):
        fs = get_filesystem(path)
        if hasattr(fs, "read_pickle") and not chaos.armed("ckpt.read"):
            return fs.read_pickle(path)
        data = chaos.transform("ckpt.read", fs.read_bytes(path))
        return _loads_payload(unframe_bytes(data, path), path)


def verify(path: str) -> None:
    """Integrity-check one blob WITHOUT unpickling it: raises
    :class:`CorruptCheckpoint` on CRC mismatch or truncation, returns
    None on success (legacy unframed files pass, matching `load`).  The
    elastic lineage negotiation (parallel/elastic.survey) uses this to
    build each rank's verified view — a cheap frame walk, not a load."""
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    if isinstance(fs, LocalFileSystem):
        # chunked streaming verify: no whole-blob copy for multi-GB files
        with open(path, "rb") as f:
            LocalFileSystem._verify_frame(f, path)
        return
    unframe_bytes(fs.read_bytes(path), path)


def save_checkpoint(path: str, neval: int, model_blob: Any,
                    optim_blob: Any, overwrite: bool = True) -> Tuple[str, str]:
    """Write model.<neval> + optimMethod.<neval>
    (DistriOptimizer.scala:394-416)."""
    path = _strip_file_scheme(path)
    get_filesystem(path).makedirs(path)
    mp = _join(path, f"model.{neval}")
    op = _join(path, f"optimMethod.{neval}")
    save(model_blob, mp, overwrite)
    save(optim_blob, op, overwrite)
    return mp, op


_ASYNC_EXECUTOR = None
_ASYNC_FUTURES: list = []


def save_checkpoint_async(path: str, neval: int, model_blob: Any,
                          optim_blob: Any, overwrite: bool = True):
    """Non-blocking save_checkpoint (net-new vs the reference — large
    snapshots would otherwise stall the train loop for seconds).

    The device→host copy happens SYNCHRONOUSLY here (the caller's arrays
    are about to be donated back into the compiled step; a background
    np.asarray would read freed buffers); only pickling + filesystem IO
    run on the single background writer thread.  Local writes stay atomic
    (LocalFileSystem tmp+rename).  Errors surface on the next
    `wait_for_async_checkpoints()`/`join_checkpoints` call — or HERE at
    submission when backpressure joins an older write.

    Backpressure: at most 2 snapshots may be pending; a faster checkpoint
    cadence than the storage can absorb blocks on the oldest write instead
    of accumulating full host copies until OOM.  Returns the future."""
    global _ASYNC_EXECUTOR
    model_blob = _to_numpy(model_blob)
    optim_blob = _to_numpy(optim_blob)
    if _ASYNC_EXECUTOR is None:
        from concurrent.futures import ThreadPoolExecutor
        # one worker: checkpoints must land in submission order
        _ASYNC_EXECUTOR = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="bigdl-ckpt")
    _ASYNC_FUTURES[:] = [f for f in _ASYNC_FUTURES if not f.done()]
    while len(_ASYNC_FUTURES) >= 2:
        oldest = _ASYNC_FUTURES.pop(0)
        oldest.result()  # raises in the train loop, like a sync write
    fut = _ASYNC_EXECUTOR.submit(
        save_checkpoint, path, neval, model_blob, optim_blob, overwrite)
    _ASYNC_FUTURES.append(fut)
    return fut


def join_checkpoints(futures) -> None:
    """Join EVERY future, then re-raise the first error (a first-error
    early return would leave later writes in flight with errors lost)."""
    first_err = None
    for f in futures:
        try:
            f.result()
        except Exception as e:  # noqa: BLE001 — collected, re-raised below
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


def wait_for_async_checkpoints() -> None:
    """Block until every pending async checkpoint is on disk; re-raises
    the first write error (after all have been joined)."""
    global _ASYNC_FUTURES
    futs, _ASYNC_FUTURES = _ASYNC_FUTURES, []
    join_checkpoints(futs)


# ---------------------------------------------------------------------------
# lineage: list / resume-by-latest / quarantine / retention
# ---------------------------------------------------------------------------

def checkpoint_lineage(path: str):
    """All complete snapshot triples (model, optimMethod, neval) in `path`,
    NEWEST FIRST — the fall-back order for lineage-walking recovery.
    Quarantined files (``.corrupt``) and half-written pairs (model without
    optimMethod) are excluded; one listdir, no per-file round-trips."""
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    if not fs.isdir(path):
        return []
    names = set(fs.listdir(path))
    nevals = sorted((int(m.group(1)) for m in
                     (re.fullmatch(r"model\.(\d+)", n) for n in names) if m),
                    reverse=True)
    return [(_join(path, f"model.{n}"), _join(path, f"optimMethod.{n}"), n)
            for n in nevals if f"optimMethod.{n}" in names]


def latest_checkpoint(path: str) -> Optional[Tuple[str, str, int]]:
    """Find the newest (model, optimMethod, neval) triple
    (getLatestFile, DistriOptimizer.scala:828-845)."""
    lineage = checkpoint_lineage(path)
    return lineage[0] if lineage else None


def frame_fingerprint(path: str) -> Optional[Tuple[int, int]]:
    """Read one framed blob's ``(payload_length, masked_crc32c)`` from its
    integrity footer WITHOUT reading (or verifying) the payload; None for
    legacy unframed files.  The continuous-deployment publisher
    (serve/continuous.py) records this pair in every release entry and the
    deploy controller compares it against the snapshot it is about to
    serve — a snapshot rewritten after publication (elastic recovery
    re-training over the same nevals) no longer matches and the release is
    rejected typed instead of served."""
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    if isinstance(fs, LocalFileSystem):
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size < _FOOTER_LEN:
                return None
            f.seek(size - _FOOTER_LEN)
            tail = f.read(_FOOTER_LEN)
    else:
        data = fs.read_bytes(path)
        if len(data) < _FOOTER_LEN:
            return None
        tail = data[-_FOOTER_LEN:]
    if tail[-len(_FRAME_MAGIC):] != _FRAME_MAGIC:
        return None
    length, crc = _FOOTER.unpack(tail[:_FOOTER.size])
    return int(length), int(crc)


def watch_lineage(path: str, since: int = -1, *,
                  pattern: str = r"model\.(\d+)",
                  poll: Optional[float] = None,
                  clock=None, sleep=None, stop=None,
                  idle_timeout: Optional[float] = None):
    """Scheme-agnostic lineage watch: a generator yielding ``(n, path)``
    for every file under `path` whose NAME fullmatches `pattern` (group 1
    = the monotonic integer id), in id order, ids > `since` only — the
    poll loop the deployment controller (serve/continuous.py) runs so it
    contains zero ad-hoc IO code, usable against any file_io scheme
    (local, ``memory://``, fsspec remotes; remote listdirs already run
    under the retry/backoff layer).

    Quarantined (``*.corrupt``) and half-written (``*.tmp``) files never
    fullmatch the pattern, so the watch can never hand out an entry the
    writer or a previous consumer has disowned; each id is yielded at
    most once per generator (a file quarantined AFTER being yielded is
    simply never seen again).

    Pacing: ``poll`` fixes the idle delay; None backs off exponentially
    from the ``BIGDL_TPU_IO_BACKOFF_BASE`` knob up to
    ``_IO_BACKOFF_MAX`` with the RetryPolicy's deterministic jitter,
    resetting whenever something new appears.  `clock`/`sleep` are
    injectable (tests run wall-clock-free); `stop` is a callable checked
    every turn (and between yields) to end the generator; `idle_timeout`
    ends it after that many seconds without a new entry."""
    path = _strip_file_scheme(path)
    matcher = re.compile(pattern)
    clk = clock or _TIMEBASE["clock"]
    slp = sleep or _TIMEBASE["sleep"]
    policy = RetryPolicy(clock=clk, sleep=slp)
    last = int(since)
    idle_since = None
    attempt = 0
    while True:
        if stop is not None and stop():
            return
        fs = get_filesystem(path)
        try:
            names = fs.listdir(path) if fs.isdir(path) else []
        except Exception as e:  # noqa: BLE001 — a transient listing
            # failure must not kill the watch (remote ops are already
            # retried below this; a dir that does not exist YET is the
            # normal trainer-not-started case)
            logger.warning("watch_lineage(%s): listing failed (%s: %s); "
                           "treating as empty this poll", path,
                           type(e).__name__, e)
            names = []
        found = {}
        for name in names:
            m = matcher.fullmatch(name)
            if m:
                found[int(m.group(1))] = name
        fresh = sorted(n for n in found if n > last)
        if fresh:
            attempt = 0
            idle_since = None
            for n in fresh:
                last = n
                yield n, _join(path, found[n])
                if stop is not None and stop():
                    return
            continue
        now = clk()
        if idle_since is None:
            idle_since = now
        if idle_timeout is not None and now - idle_since >= idle_timeout:
            return
        attempt = min(attempt + 1, 12)  # cap the exponent, not the wait
        slp(poll if poll is not None else policy.delay(attempt))


def quarantine_checkpoint(model_path: str,
                          optim_path: Optional[str] = None) -> None:
    """Rename a corrupt snapshot aside (``.corrupt`` suffix): it drops out
    of the lineage (resume-by-latest skips it) but stays on disk for
    forensics — quarantined, not deleted."""
    for p in (model_path, optim_path):
        if not p:
            continue
        p = _strip_file_scheme(p)
        fs = get_filesystem(p)
        try:
            if fs.exists(p):
                fs.rename(p, p + ".corrupt")
                logger.warning("quarantined corrupt checkpoint file %s -> "
                               "%s.corrupt", p, p)
        except Exception as e:  # noqa: BLE001 — best-effort: recovery must
            # proceed on older snapshots even if the rename fails
            logger.warning("could not quarantine %s: %s", p, e)


def prune_checkpoints(path: str, keep_last: int, keep=()) -> list:
    """Retention: delete snapshot pairs beyond the newest `keep_last`,
    except nevals in `keep` (the keep-every-N-epochs keepers the optimizer
    marks).  Quarantined ``.corrupt`` files are never touched.  Returns the
    pruned nevals."""
    if keep_last <= 0:
        return []
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    keep = set(keep)
    pruned = []
    with telemetry.span("ckpt.retention", cat="io", keep_last=keep_last):
        for i, (mp, op, n) in enumerate(checkpoint_lineage(path)):
            if i < keep_last or n in keep:
                continue
            try:
                fs.remove(mp)
                fs.remove(op)
                pruned.append(n)
            except Exception as e:  # noqa: BLE001 — retention is
                # best-effort: a failed delete must never take down training
                logger.warning("retention: could not prune snapshot %d in "
                               "%s: %s", n, path, e)
    if pruned:
        logger.info("retention: pruned snapshots %s from %s (keep_last=%d, "
                    "keepers=%s)", sorted(pruned), path, keep_last,
                    sorted(keep))
    return pruned


def sweep_numbered(path: str, pattern: str, keep: int) -> list:
    """Writer-side retention for numbered protocol files: delete every
    file under `path` whose NAME fullmatches `pattern` (group 1 = the
    monotonic integer id) beyond the newest `keep` ids.

    The heartbeat/registry protocols (parallel/elastic grow offers,
    serve/fleet member records) stamp a new id per round/generation and
    never delete — without a sweep a long-lived dir accumulates one file
    per restart forever.  The WRITER sweeps right after publishing (it
    owns the names it stamps); readers only ever want the newest few, so
    keeping `keep` generations leaves every concurrent reader a
    consistent window.  Quarantined ``.corrupt`` files never fullmatch
    and are never touched.  Best-effort: a failed delete is logged, not
    raised.  Returns the removed names."""
    if keep <= 0:
        return []
    path = _strip_file_scheme(path)
    fs = get_filesystem(path)
    matcher = re.compile(pattern)
    try:
        names = fs.listdir(path) if fs.isdir(path) else []
    except Exception:  # noqa: BLE001 — nothing to sweep in an
        # unreachable/absent dir; the next publish retries
        return []
    found = {}
    for name in names:
        m = matcher.fullmatch(name)
        if m:
            found[int(m.group(1))] = name
    removed = []
    for n in sorted(found, reverse=True)[keep:]:
        target = _join(path, found[n])
        try:
            fs.remove(target)
            removed.append(found[n])
        except Exception as e:  # noqa: BLE001 — retention is best-effort
            logger.warning("retention: could not sweep %s: %s", target, e)
    if removed:
        logger.info("retention: swept %d stale protocol file(s) from %s "
                    "(keep=%d): %s", len(removed), path, keep,
                    sorted(removed))
    return removed


class File:
    """Namespace parity with the reference's `File` object."""

    save = staticmethod(save)
    load = staticmethod(load)
