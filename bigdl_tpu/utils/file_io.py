"""Checkpoint save/load.

Reference: BigDL `utils/File.scala:25` — java-serialization save/load with
HDFS/S3 support (saveToHdfs:106); checkpoint file contract `model.<neval>` /
`optimMethod.<neval>` written by `optim/Optimizer.scala:284-322` and
`DistriOptimizer.scala:394-416`, resumed via `getLatestFile`
(DistriOptimizer.scala:828-845).

TPU-native re-design: params/state pytrees are pulled to host numpy and written
as a single .npz-in-pickle blob (portable, no JVM serialization); the
`model.<neval>` / `optimMethod.<neval>` naming contract is preserved so
resume-by-latest works identically.  Remote stores (HDFS/S3/GCS) are out of
scope for this image (zero egress) — the API takes any local path.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Optional, Tuple

import jax
import numpy as np

__all__ = ["save", "load", "save_checkpoint", "latest_checkpoint", "File"]


def _to_numpy(tree):
    # only coerce device arrays — other leaves (strings, modules, None)
    # must survive pickling untouched
    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """(File.scala:25 `save`)."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(_to_numpy(obj), f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)


def load(path: str) -> Any:
    """(File.scala `load`)."""
    with open(path, "rb") as f:
        return pickle.load(f)


def save_checkpoint(path: str, neval: int, model_blob: Any,
                    optim_blob: Any, overwrite: bool = True) -> Tuple[str, str]:
    """Write model.<neval> + optimMethod.<neval>
    (DistriOptimizer.scala:394-416)."""
    os.makedirs(path, exist_ok=True)
    mp = os.path.join(path, f"model.{neval}")
    op = os.path.join(path, f"optimMethod.{neval}")
    save(model_blob, mp, overwrite)
    save(optim_blob, op, overwrite)
    return mp, op


def latest_checkpoint(path: str) -> Optional[Tuple[str, str, int]]:
    """Find the newest (model, optimMethod, neval) triple
    (getLatestFile, DistriOptimizer.scala:828-845)."""
    if not os.path.isdir(path):
        return None
    best = -1
    for name in os.listdir(path):
        m = re.fullmatch(r"model\.(\d+)", name)
        if m:
            n = int(m.group(1))
            if n > best and os.path.exists(
                    os.path.join(path, f"optimMethod.{n}")):
                best = n
    if best < 0:
        return None
    return (os.path.join(path, f"model.{best}"),
            os.path.join(path, f"optimMethod.{best}"), best)


class File:
    """Namespace parity with the reference's `File` object."""

    save = staticmethod(save)
    load = staticmethod(load)
