"""Per-module profiling + compiled-step tracing.

Reference: `nn/abstractnn/AbstractModule.scala:193-217` — every module
accumulates `forwardTime`/`backwardTime` inside the `forward`/`backward`
wrappers and `getTimes()` returns (module, forwardTime, backwardTime)
triples; conv layers additionally track im2col/col2im time
(SpatialConvolution.scala:108-113).

TPU-native re-design: always-on per-layer timers are impossible inside one
fused XLA program (and would defeat the fusion that makes the step fast), so
profiling splits into two tools matching the two execution modes:

1. `ModuleProfiler` — EAGER per-module wall times.  Wraps every submodule's
   `apply` on the instance tree, synchronizing on each output (host fetch —
   `block_until_ready` does not synchronize on this image's tunneled
   backend, see utils/timing.py), and measures per-leaf backward via
   `jax.vjp` on the captured inputs.  `model.get_times()` then mirrors the
   reference's `getTimes()` contract.

2. `trace_steps` — the compiled path: wraps N executions of the real train
   step in `jax.profiler.trace`, producing a TensorBoard-loadable xplane
   trace where XLA's own per-op breakdown lives (SURVEY.md §7.6).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

import jax

from .timing import fetch_scalar

__all__ = ["ModuleProfiler", "trace_steps"]


def _sync(x) -> None:
    leaves = jax.tree.leaves(x)
    if not leaves or isinstance(leaves[0], jax.core.Tracer):
        return  # under a jax trace (e.g. facade backward's vjp): no-op
    try:
        fetch_scalar(leaves[0])
    except Exception:  # noqa: BLE001 — non-array leaves
        pass


class ModuleProfiler:
    """Eager per-module wall-time profiler (AbstractModule.getTimes role).

    Usage:
        with ModuleProfiler(model) as prof:
            model.forward(x)
        for mod, fwd_s, bwd_s in prof.get_times():
            ...

    Forward times are recorded live (each submodule's apply is wrapped and
    synced).  Backward times are measured on demand from the captured
    (params, state, input) of each call via jax.vjp — the facade's whole-
    model vjp cannot attribute time to submodules, exactly like the
    reference cannot attribute MKL time across JNI calls without its
    per-layer wrappers.
    """

    def __init__(self, model, measure_backward: bool = True):
        self.model = model
        self.measure_backward = measure_backward
        self.fwd: Dict[int, float] = {}
        self.bwd: Dict[int, float] = {}
        self.calls: Dict[int, Tuple] = {}
        self._mods: List = []
        self._saved: List[Tuple] = []

    def __enter__(self):
        # identity-deduped walk: a shared module instance (weight sharing)
        # is wrapped and restored exactly once (Module.unique_modules)
        self._mods = list(self.model.unique_modules())
        for m in self._mods:
            orig = m.apply
            # remember whether apply was already an instance attribute
            # (nested profiler / custom wrapper) so __exit__ restores it
            self._saved.append((m, m.__dict__.get("apply")))

            def timed(params, state, input, *, training=False, rng=None,
                      _m=m, _orig=orig):
                leaves = jax.tree.leaves((params, input))
                if any(isinstance(l, jax.core.Tracer) for l in leaves):
                    # under a jax trace (facade backward's vjp, jit):
                    # timing is meaningless and captured tracers would leak
                    return _orig(params, state, input, training=training,
                                 rng=rng)
                t0 = time.perf_counter()
                out, ns = _orig(params, state, input, training=training,
                                rng=rng)
                _sync(out)
                key = id(_m)
                self.fwd[key] = self.fwd.get(key, 0.0) + \
                    (time.perf_counter() - t0)
                self.calls[key] = (params, state, input, training, rng)
                return out, ns

            m.apply = timed
        return self

    def __exit__(self, *exc):
        for m, prev_instance_apply in self._saved:
            if prev_instance_apply is not None:
                m.apply = prev_instance_apply  # restore outer wrapper
            else:
                # deleting the instance attr re-exposes the class method
                m.__dict__.pop("apply", None)
        self._saved = []
        if self.measure_backward and not any(exc):
            self._measure_backward()
        # publish on the model for the get_times() parity accessor
        for m in self._mods:
            m._profile_times = (self.fwd.get(id(m), 0.0),
                                self.bwd.get(id(m), 0.0))
        return False

    def _measure_backward(self):
        import jax.numpy as jnp
        for m in self._mods:
            rec = self.calls.get(id(m))
            if rec is None or getattr(m, "modules", None):
                continue  # containers: reported as sum of leaves
            params, state, input, training, rng = rec

            def f(p, x, _m=m, _s=state, _t=training, _r=rng):
                out, _ = _m.apply(p, _s, x, training=_t, rng=_r)
                return out

            try:
                out, vjp = jax.vjp(f, params, input)
                ct = jax.tree.map(lambda o: jnp.ones_like(o), out)
                t0 = time.perf_counter()
                grads = vjp(ct)
                _sync(grads)
                self.bwd[id(m)] = time.perf_counter() - t0
            except Exception:  # noqa: BLE001 — non-differentiable layers
                continue
        # containers: sum of their leaves (reference reports the wrapper
        # time, which includes children)
        for m in self._mods:
            if getattr(m, "modules", None):
                self.bwd[id(m)] = sum(
                    self.bwd.get(id(c), 0.0) for c in m.unique_modules()
                    if c is not m)

    def get_times(self) -> List[Tuple[Any, float, float]]:
        """(module, forward_seconds, backward_seconds) per submodule —
        the reference's getTimes() shape (AbstractModule.scala:197)."""
        return [(m, self.fwd.get(id(m), 0.0), self.bwd.get(id(m), 0.0))
                for m in self._mods]

    def summary(self, top: int = 20) -> str:
        rows = sorted(self.get_times(), key=lambda r: -(r[1] + r[2]))[:top]
        lines = [f"{'module':40s} {'fwd_ms':>9s} {'bwd_ms':>9s}"]
        for m, f, b in rows:
            lines.append(f"{m.name[:40]:40s} {f*1e3:9.3f} {b*1e3:9.3f}")
        return "\n".join(lines)


def trace_steps(run, n: int, logdir: str):
    """Run `run()` n times under jax.profiler.trace (SURVEY.md §7.6).

    `run` must return a device value; the last output is host-fetched so the
    trace covers real execution.  View with TensorBoard's profile plugin or
    xprof on `logdir`.
    """
    out = None
    with jax.profiler.trace(logdir):
        for _ in range(n):
            out = run()
        if out is not None:
            _sync(out)
    return logdir
