"""Misc utilities (reference: utils/Util.scala)."""

from __future__ import annotations

import random
from typing import List, Sequence

__all__ = ["kth_largest"]


def kth_largest(values: Sequence[float], k: int) -> float:
    """k-th largest element (1-based k) via quickselect
    (reference: utils/Util.scala:20 `kthLargest` — the straggler-threshold
    primitive used by DistriOptimizer.scala:302-330)."""
    if not 1 <= k <= len(values):
        raise ValueError(f"k={k} out of range for {len(values)} values")
    vals: List[float] = list(values)
    target = k - 1  # index in descending order

    lo, hi = 0, len(vals) - 1
    while True:
        if lo == hi:
            return vals[lo]
        pivot = vals[random.randint(lo, hi)]
        i, j = lo, hi
        while i <= j:
            while vals[i] > pivot:
                i += 1
            while vals[j] < pivot:
                j -= 1
            if i <= j:
                vals[i], vals[j] = vals[j], vals[i]
                i += 1
                j -= 1
        if target <= j:
            hi = j
        elif target >= i:
            lo = i
        else:
            return vals[target]
