"""Version-compat shims shared across the package.

`shard_map` moved from jax.experimental to the jax namespace, and its
replication-check kwarg was renamed check_rep -> check_vma along the way;
this is the one place that knows both spellings (previously copy-pasted
per module).
"""

import inspect

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

_CHECK_KW = ("check_vma" if "check_vma" in
             inspect.signature(shard_map).parameters else "check_rep")

# jax < 0.5 has neither lax.pcast nor lax.pvary: a shard_map body that mixes
# replicated and device-varying values (cond branches, ppermute rings) cannot
# annotate its replication for the checker and must run unchecked there
def has_vma_marking() -> bool:
    import jax
    return hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")


def shard_map_unchecked(f, *, mesh, in_specs, out_specs):
    """shard_map with the replication/VMA check disabled, under whichever
    keyword this jax version spells it (custom_vjp + psum bodies trip the
    checker on some versions)."""
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **{_CHECK_KW: False})


__all__ = ["shard_map", "shard_map_unchecked", "has_vma_marking"]
