"""Fault-injection subsystem: named fault points with deterministic schedules.

Reference: the reference's only fault-injection device is the
`ExceptionTest` layer scheduled by invocation count
(test/.../utils/TestUtils.scala:103, DistriOptimizerSpec.scala:89-97).
This module generalizes that count-scheduled determinism into a first-class
chaos layer the whole runtime shares: production code declares *fault
points* (one `fire`/`transform` call per operation), tests and `bench.py
--chaos` attach *schedules* to them.  Everything is counter-driven — no
wall clock, no RNG — so every chaos run is exactly reproducible.

Fault points wired into the runtime:

| point           | where it fires                                | kind      |
|-----------------|-----------------------------------------------|-----------|
| ``ckpt.write``  | once per checkpoint blob written (file_io)    | fail/corrupt |
| ``ckpt.read``   | once per checkpoint blob read (file_io)       | fail/corrupt |
| ``fs.remote``   | once per remote filesystem op *attempt*       | fail      |
| ``data.batch``  | once per training minibatch (driver loop)     | fail      |
| ``step.loss_nan``| once per host loss observation (driver loop) | nan       |

Schedules (1-based counts):

- ``FailAt(3, 5)`` — raise on exactly those invocation counts
- ``FailN(2, start=4)`` — raise on counts 4 and 5 (fail-n-times)
- ``CorruptAt(2)`` / ``CorruptAt(2, mode="truncate")`` — mutate the
  payload passing through ``transform`` (bytes: flip/truncate; floats:
  NaN) on those counts

Env/config spec (``BIGDL_TPU_CHAOS``), `;`-separated points::

    ckpt.write=corrupt@3;fs.remote=fail*2@1;data.batch=fail@6

`fail` raises :class:`ChaosFault` (a RuntimeError: the optimizer retry
loop and the IO retry layer treat it like any transient failure).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional

__all__ = ["ChaosFault", "FailAt", "FailN", "CorruptAt", "register",
           "install", "clear", "reset", "armed", "fire", "transform",
           "scoped", "counts", "FAULT_POINTS"]

FAULT_POINTS = ("ckpt.write", "ckpt.read", "fs.remote", "data.batch",
                "step.loss_nan")


class ChaosFault(RuntimeError):
    """An injected failure (point + invocation count in the message)."""


class FailAt:
    """Raise on exactly the given 1-based invocation counts."""

    def __init__(self, *counts: int):
        self.counts = frozenset(int(c) for c in counts)

    def fires(self, count: int) -> bool:
        return count in self.counts

    def mutate(self, value):  # fail schedules never mutate
        raise AssertionError("FailAt has no payload mutation")

    is_fail = True

    def __repr__(self):
        return f"FailAt({sorted(self.counts)})"


class FailN:
    """Raise on `n` consecutive counts starting at `start` (fail-n-times:
    the reference's transient-fault shape — down, then back up)."""

    def __init__(self, n: int, start: int = 1):
        self.n, self.start = int(n), int(start)

    def fires(self, count: int) -> bool:
        return self.start <= count < self.start + self.n

    def mutate(self, value):
        raise AssertionError("FailN has no payload mutation")

    is_fail = True

    def __repr__(self):
        return f"FailN({self.n}, start={self.start})"


class CorruptAt:
    """Mutate the payload at the given counts instead of raising.

    bytes payloads: ``mode="flip"`` XORs a span in the middle (same length
    — a bit-rot tear the CRC frame must catch), ``mode="truncate"`` drops
    the tail (a torn write).  float payloads become NaN regardless of mode
    (the ``step.loss_nan`` sentinel)."""

    def __init__(self, *counts: int, mode: str = "flip"):
        if mode not in ("flip", "truncate"):
            raise ValueError(f"CorruptAt: unknown mode {mode!r}")
        self.counts = frozenset(int(c) for c in counts)
        self.mode = mode

    def fires(self, count: int) -> bool:
        return count in self.counts

    def mutate(self, value):
        if isinstance(value, (bytes, bytearray)):
            data = bytes(value)
            if self.mode == "truncate":
                return data[:max(len(data) // 2, 0)]
            if not data:
                return data
            mid = len(data) // 2
            span = min(8, len(data) - mid) or 1
            return (data[:mid] +
                    bytes(b ^ 0xFF for b in data[mid:mid + span]) +
                    data[mid + span:])
        if isinstance(value, (int, float)):
            return float("nan")
        raise TypeError(
            f"CorruptAt cannot mutate {type(value).__name__} payloads")

    is_fail = False

    def __repr__(self):
        return f"CorruptAt({sorted(self.counts)}, mode={self.mode!r})"


class _Point:
    __slots__ = ("schedules", "count")

    def __init__(self):
        self.schedules: List = []
        self.count = 0


_LOCK = threading.Lock()
_POINTS: Dict[str, _Point] = {}
_ENV_LOADED = False


def register(point: str, schedule) -> None:
    """Attach a schedule to a fault point (additive)."""
    with _LOCK:
        _POINTS.setdefault(point, _Point()).schedules.append(schedule)


def clear(point: Optional[str] = None) -> None:
    """Remove schedules (and counters) for one point, or everything."""
    global _ENV_LOADED
    with _LOCK:
        if point is None:
            _POINTS.clear()
            _ENV_LOADED = False
        else:
            _POINTS.pop(point, None)


def reset(point: Optional[str] = None) -> None:
    """Zero invocation counters, keeping schedules (re-run a scenario)."""
    with _LOCK:
        for name, p in _POINTS.items():
            if point is None or name == point:
                p.count = 0


def counts() -> Dict[str, int]:
    """Current invocation counters (diagnostics / test assertions)."""
    with _LOCK:
        return {name: p.count for name, p in _POINTS.items()}


def armed(point: str) -> bool:
    """True when any schedule is attached to `point` — production code may
    branch to a chaos-compatible (e.g. non-streaming) path only then."""
    _load_env()
    with _LOCK:
        return point in _POINTS and bool(_POINTS[point].schedules)


def _bump(point: str):
    """count++ and return (count, matching schedules) — one counted
    invocation per fire()/transform() call."""
    _load_env()
    with _LOCK:
        p = _POINTS.get(point)
        if p is None or not p.schedules:
            return 0, []
        p.count += 1
        return p.count, [s for s in p.schedules if s.fires(p.count)]


def fire(point: str) -> None:
    """Count one invocation; raise ChaosFault if a fail schedule matches.
    Corrupt schedules are ignored here (no payload to mutate)."""
    count, hits = _bump(point)
    for s in hits:
        if s.is_fail:
            raise ChaosFault(f"chaos[{point}] injected failure "
                             f"(invocation {count}, {s!r})")


def transform(point: str, value):
    """Count one invocation; raise on fail schedules, else pipe the payload
    through every matching corrupt schedule."""
    count, hits = _bump(point)
    for s in hits:
        if s.is_fail:
            raise ChaosFault(f"chaos[{point}] injected failure "
                             f"(invocation {count}, {s!r})")
        value = s.mutate(value)
    return value


# ---------------------------------------------------------------------------
# spec parsing (env var / --chaos CLI)
# ---------------------------------------------------------------------------

def _parse_action(action: str):
    """One schedule from ``fail@3,5`` / ``fail*2@4`` / ``corrupt@2`` /
    ``truncate@2`` / ``nan@7``."""
    if "@" not in action:
        raise ValueError(f"chaos spec: missing '@counts' in {action!r}")
    kind, _, at = action.partition("@")
    counts_ = [int(c) for c in at.split(",") if c]
    if not counts_:
        raise ValueError(f"chaos spec: empty counts in {action!r}")
    if kind.startswith("fail"):
        if "*" in kind:  # fail*N@start
            n = int(kind.split("*", 1)[1])
            if len(counts_) != 1:
                raise ValueError(
                    f"chaos spec: fail*N takes one start count: {action!r}")
            return FailN(n, start=counts_[0])
        return FailAt(*counts_)
    if kind in ("corrupt", "flip"):
        return CorruptAt(*counts_, mode="flip")
    if kind == "truncate":
        return CorruptAt(*counts_, mode="truncate")
    if kind == "nan":
        return CorruptAt(*counts_)  # float payloads NaN under any mode
    raise ValueError(f"chaos spec: unknown action {kind!r} in {action!r}")


def install(spec: str) -> None:
    """Install schedules from a spec string:
    ``point=action@counts[;point=action@counts...]``."""
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"chaos spec: expected point=action, got "
                             f"{part!r}")
        point, _, action = part.partition("=")
        register(point.strip(), _parse_action(action.strip()))


def _load_env() -> None:
    """One-shot pickup of BIGDL_TPU_CHAOS (config tier; see utils/config).
    Loaded lazily on the first armed()/fire()/transform() so importing this
    module never reads the environment."""
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    with _LOCK:
        if _ENV_LOADED:
            return
        _ENV_LOADED = True
    from . import config
    spec = config.get_str("CHAOS", "")
    if spec:
        install(spec)


class scoped:
    """Context manager for tests: install a spec (or programmatic
    (point, schedule) pairs), clear everything on exit."""

    def __init__(self, spec: str = "", schedules:
                 Optional[Iterable] = None):
        self.spec = spec
        self.schedules = list(schedules or [])

    def __enter__(self):
        clear()
        global _ENV_LOADED
        _ENV_LOADED = True  # scoped runs ignore the ambient env spec
        if self.spec:
            install(self.spec)
        for point, schedule in self.schedules:
            register(point, schedule)
        import sys
        return sys.modules[__name__]

    def __exit__(self, *exc):
        clear()
        return False
