"""Fault-injection subsystem: named fault points with deterministic schedules.

Reference: the reference's only fault-injection device is the
`ExceptionTest` layer scheduled by invocation count
(test/.../utils/TestUtils.scala:103, DistriOptimizerSpec.scala:89-97).
This module generalizes that count-scheduled determinism into a first-class
chaos layer the whole runtime shares: production code declares *fault
points* (one `fire`/`transform` call per operation), tests and `bench.py
--chaos` attach *schedules* to them.  Everything is counter-driven — no
wall clock, no RNG — so every chaos run is exactly reproducible.

Fault points wired into the runtime:

| point           | where it fires                                | kind      |
|-----------------|-----------------------------------------------|-----------|
| ``ckpt.write``  | once per checkpoint blob written (file_io)    | fail/corrupt |
| ``ckpt.read``   | once per checkpoint blob read (file_io)       | fail/corrupt |
| ``fs.remote``   | once per remote filesystem op *attempt*       | fail      |
| ``data.batch``  | once per training minibatch (driver loop)     | fail/corrupt |
| ``step.loss_nan``| once per host loss observation (driver loop) | nan       |
| ``data.record`` | once per record decoded (recordio/seqfile)    | fail/corrupt |
| ``data.stall``  | once per minibatch fetch (driver loop)        | stall     |
| ``step.stall``  | once per device step dispatch (driver loop)   | stall     |
| ``serve.request``| once per request admitted (serve/batcher)    | fail      |
| ``serve.batch`` | once per online device batch (serve/server)   | fail/stall |
| ``serve.replica@<idx>`` | once per non-empty batch on replica `<idx>` (serve/server) | wedge/exit (thread-scoped) |
| ``serve.canary`` | once per canary-routed batch (serve/server)  | fail/stall |
| ``host.lost@<rank>`` | once per train iteration on rank `<rank>` (driver loop) | exit/wedge |
| ``host.return@<rank>`` | once per announce poll in rank `<rank>`'s joiner loop (parallel/elastic grow) | join (gate) |
| ``deploy.publish`` | once per release-entry write (serve/continuous) | corrupt   |
| ``fleet.member@<idx>`` | once per heartbeat loop turn in fleet worker `<idx>`'s process (tools/serve_worker) | exit/wedge (process-scoped) |

Schedules (1-based counts):

- ``FailAt(3, 5)`` — raise on exactly those invocation counts
- ``FailN(2, start=4)`` — raise on counts 4 and 5 (fail-n-times)
- ``CorruptAt(2)`` / ``CorruptAt(2, mode="truncate")`` — mutate the
  payload passing through ``transform`` (bytes: flip/truncate; floats
  and float arrays/minibatches: NaN) on those counts
- ``StallAt(2, seconds=30)`` — BLOCK at those counts (interruptible
  50ms-sliced sleep, so the supervisor's async ``StallError`` can land;
  a real wedged C call is the supervisor's hard-exit policy case)
- ``ExitAt(2)`` / ``WedgeAt(2, seconds=30)`` — the host-loss drill
  (parallel/elastic): stop publishing liveness heartbeats, then die
  (``os._exit(117)``) or wedge UNINTERRUPTIBLY (the sliced sleep
  swallows async-raised exceptions — a lost host cannot be recovered by
  a StallError, which is the point)
- ``ReturnAt(2)`` — the host-RETURN drill (the grow half of
  parallel/elastic): an OBSERVATION GATE, not a fault.  Checked via
  :func:`gate` from the joiner's announce loop; when it fires the
  joiner announces itself and rejoins — nothing raises, blocks, or
  exits

Env/config spec (``BIGDL_TPU_CHAOS``), `;`-separated points::

    ckpt.write=corrupt@3;fs.remote=fail*2@1;data.batch=fail@6;step.stall=stall*30@5
    host.lost@1=exit@1:4;step.stall=stall*30@2:5

`fail` raises :class:`ChaosFault` (a RuntimeError: the optimizer retry
loop and the IO retry layer treat it like any transient failure).
``stall`` blocks for 3600s by default; ``stall*N`` blocks N seconds —
the deterministic hang the supervision subsystem (utils/supervisor)
exists to catch.

Addressing extensions (net-new with the elastic subsystem):

- **rank-addressed points** — ``host.lost@<rank>`` is an ordinary point
  NAME: the driver loop on rank r fires ``host.lost@r`` once per
  iteration, so a spec shared through the env across every rank only
  engages on the addressed one.  Actions: ``exit`` (the process dies
  instantly with code 117) and ``wedge``/``lost`` (stops beating and
  blocks, default 3600s, ``wedge*N`` for N seconds).
  ``host.return@<rank>`` is the grow counterpart: the JOINER's announce
  loop polls it via :func:`gate` (actions ``join``/``return``, or the
  bare ``@epoch:iteration`` shorthand — ``host.return@1=@2:2``).  The
  joiner publishes the CLUSTER position (read from the newest
  snapshot's driver_state) via :func:`at_position` before each poll;
  because a polling observer may never sample the exact coordinate,
  gate position addresses fire AT-OR-AFTER the addressed ``(epoch,
  iteration)`` (tuple order) — fault position addresses stay
  exact-match.
- **thread-scoped exit/wedge** — a fire site may pass ``thread_exc``
  (serve/server.py's replica loop does, with
  ``serve.replica@<replica idx>`` points): an ``exit`` schedule then
  raises that exception class in the CALLING THREAD instead of killing
  the process, and ``wedge`` blocks uninterruptibly without touching
  process liveness — the replica-loss drill the serving control plane
  (serve/control.py) must restart around.
- **``@epoch:iteration`` addressing** — any schedule's ``@`` list may
  mix plain invocation counts with ``epoch:neval`` pairs
  (``stall*30@2:5`` = hang at epoch 2, iteration 5).  The driver
  publishes its position via :func:`at_position` once per iteration;
  position addressing therefore targets per-iteration points
  (``host.lost@r``, ``step.stall``, ``step.loss_nan``, the synchronous
  ``data.*`` path) — multi-fire points (``fs.remote``) and the
  prefetch worker's read-ahead ``data.batch`` see skewed positions.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Iterable, List, Optional

logger = logging.getLogger("bigdl_tpu")

__all__ = ["ChaosFault", "FailAt", "FailN", "CorruptAt", "StallAt",
           "ExitAt", "WedgeAt", "ReturnAt", "register", "install", "clear",
           "reset", "armed", "fire", "gate", "transform", "scoped",
           "counts", "at_position", "FAULT_POINTS"]

FAULT_POINTS = ("ckpt.write", "ckpt.read", "fs.remote", "data.batch",
                "step.loss_nan", "data.record", "data.stall", "step.stall",
                "serve.request", "serve.batch", "serve.replica",
                "serve.canary", "host.lost", "host.return",
                "fleet.member")

#: the driver loop's current (epoch, neval), published once per iteration
#: via at_position() — the coordinate ``@epoch:iteration`` addresses match
_POSITION = {"at": None}


def at_position(epoch: int, neval: int) -> None:
    """Publish the driver's position for ``@epoch:iteration``-addressed
    schedules (one dict store; free when no such schedule exists)."""
    _POSITION["at"] = (int(epoch), int(neval))


class ChaosFault(RuntimeError):
    """An injected failure (point + invocation count in the message)."""


class FailAt:
    """Raise on exactly the given 1-based invocation counts."""

    def __init__(self, *counts: int):
        self.counts = frozenset(int(c) for c in counts)

    def fires(self, count: int) -> bool:
        return count in self.counts

    def mutate(self, value):  # fail schedules never mutate
        raise AssertionError("FailAt has no payload mutation")

    is_fail = True

    def __repr__(self):
        return f"FailAt({sorted(self.counts)})"


class FailN:
    """Raise on `n` consecutive counts starting at `start` (fail-n-times:
    the reference's transient-fault shape — down, then back up)."""

    def __init__(self, n: int, start: int = 1):
        self.n, self.start = int(n), int(start)

    def fires(self, count: int) -> bool:
        return self.start <= count < self.start + self.n

    def mutate(self, value):
        raise AssertionError("FailN has no payload mutation")

    is_fail = True

    def __repr__(self):
        return f"FailN({self.n}, start={self.start})"


class CorruptAt:
    """Mutate the payload at the given counts instead of raising.

    bytes payloads: ``mode="flip"`` XORs a span in the middle (same length
    — a bit-rot tear the CRC frame must catch), ``mode="truncate"`` drops
    the tail (a torn write).  float payloads become NaN regardless of mode
    (the ``step.loss_nan`` sentinel).  Float ndarrays and MiniBatch-like
    objects (``get_input``/``get_target``) get their float features
    NaN-poisoned — the ``data.batch`` corruption the non-finite-loss
    sentinel must catch."""

    def __init__(self, *counts: int, mode: str = "flip"):
        if mode not in ("flip", "truncate"):
            raise ValueError(f"CorruptAt: unknown mode {mode!r}")
        self.counts = frozenset(int(c) for c in counts)
        self.mode = mode

    def fires(self, count: int) -> bool:
        return count in self.counts

    @staticmethod
    def _poison_floats(x):
        """NaN-fill every float array in a (possibly nested) structure;
        integer arrays pass through (labels stay valid indices)."""
        import numpy as np
        if isinstance(x, (list, tuple)):
            return [CorruptAt._poison_floats(e) for e in x]
        arr = np.asarray(x)
        if arr.dtype.kind == "f":
            return np.full_like(arr, np.nan)
        return x

    def mutate(self, value):
        if isinstance(value, (bytes, bytearray)):
            data = bytes(value)
            if self.mode == "truncate":
                return data[:max(len(data) // 2, 0)]
            if not data:
                return data
            mid = len(data) // 2
            span = min(8, len(data) - mid) or 1
            return (data[:mid] +
                    bytes(b ^ 0xFF for b in data[mid:mid + span]) +
                    data[mid + span:])
        if isinstance(value, (int, float)):
            return float("nan")
        if hasattr(value, "get_input") and hasattr(value, "get_target"):
            # MiniBatch-like: poison the float features, keep targets —
            # the loss goes NaN and the host sentinel must catch it
            return type(value)(self._poison_floats(value.get_input()),
                               value.get_target())
        if hasattr(value, "dtype") or hasattr(value, "__array__"):
            return self._poison_floats(value)
        raise TypeError(
            f"CorruptAt cannot mutate {type(value).__name__} payloads")

    is_fail = False

    def __repr__(self):
        return f"CorruptAt({sorted(self.counts)}, mode={self.mode!r})"


class StallAt:
    """BLOCK at the given counts — the silent-hang failure mode (a lost
    backend RPC, a wedged collective) the supervision subsystem exists to
    catch.  The sleep runs in 50ms slices so Python bytecode executes
    between them and the supervisor's async-raised ``StallError`` can
    land; a genuinely wedged C call (no bytecode) is exactly the
    supervisor's hard-exit policy case."""

    def __init__(self, *counts: int, seconds: float = 3600.0):
        self.counts = frozenset(int(c) for c in counts)
        self.seconds = float(seconds)

    def fires(self, count: int) -> bool:
        return count in self.counts

    def mutate(self, value):  # stall schedules never mutate
        raise AssertionError("StallAt has no payload mutation")

    def block(self) -> None:
        end = time.monotonic() + self.seconds
        while time.monotonic() < end:
            time.sleep(min(0.05, max(end - time.monotonic(), 0.001)))

    is_fail = False
    is_stall = True

    def __repr__(self):
        return f"StallAt({sorted(self.counts)}, seconds={self.seconds})"


def _suspend_liveness():
    """Host-loss drill: this rank must go publication-silent on its peers
    (the signal parallel/elastic promotes to PeerLostError).  Lazy import:
    supervisor imports chaos at module level."""
    from . import supervisor as supervision
    sup = supervision.get_active()
    if sup is not None:
        sup.suspend_heartbeat()


class ExitAt:
    """Host-loss drill, hard mode: at the given counts the process stops
    publishing heartbeats and dies instantly (``os._exit(117)``) — the
    deterministic stand-in for a host falling out of the pod.  The
    SURVIVORS' behavior is what the drill measures."""

    EXIT_CODE = 117

    def __init__(self, *counts: int):
        self.counts = frozenset(int(c) for c in counts)

    def fires(self, count: int) -> bool:
        return count in self.counts

    def mutate(self, value):  # exit schedules never mutate
        raise AssertionError("ExitAt has no payload mutation")

    def engage(self) -> None:
        import os as _os
        _suspend_liveness()
        logger.error("chaos[host.lost]: exiting this rank (drill)")
        _os._exit(self.EXIT_CODE)

    is_fail = False
    is_exit = True

    def __repr__(self):
        return f"ExitAt({sorted(self.counts)})"


class WedgeAt:
    """Host-loss drill, zombie mode: stop publishing heartbeats and block
    UNINTERRUPTIBLY (async-raised exceptions are swallowed — a lost host
    cannot be rescued by a StallError, which is exactly what makes it a
    host loss rather than a stall)."""

    def __init__(self, *counts: int, seconds: float = 3600.0):
        self.counts = frozenset(int(c) for c in counts)
        self.seconds = float(seconds)

    def fires(self, count: int) -> bool:
        return count in self.counts

    def mutate(self, value):  # wedge schedules never mutate
        raise AssertionError("WedgeAt has no payload mutation")

    def engage(self) -> None:
        _suspend_liveness()
        self.block_uninterruptible()

    def block_uninterruptible(self) -> None:
        """The wedge itself, without the liveness side effect — the
        thread-scoped variant (``serve.replica`` drills) reuses it."""
        end = time.monotonic() + self.seconds
        while time.monotonic() < end:
            try:
                time.sleep(min(0.05, max(end - time.monotonic(), 0.001)))
            except BaseException:  # noqa: BLE001 — swallow async raises:
                # the wedged host must stay wedged
                pass

    is_fail = False
    is_exit = True  # engage() like ExitAt; never returns control normally

    def __repr__(self):
        return f"WedgeAt({sorted(self.counts)}, seconds={self.seconds})"


class ReturnAt:
    """Host-return drill (the grow half of parallel/elastic): an
    observation GATE with fault-schedule addressing but NO fault
    semantics — :func:`fire`/:func:`transform` ignore it entirely; only
    :func:`gate` reports it.  The elastic joiner polls its
    ``host.return@<rank>`` point once per announce loop and announces
    itself when the gate is reached (by invocation count, or at-or-after
    an ``@epoch:iteration`` position — see the module docstring)."""

    def __init__(self, *counts: int):
        self.counts = frozenset(int(c) for c in counts)

    def fires(self, count: int) -> bool:
        return count in self.counts

    def mutate(self, value):  # gate schedules never mutate
        raise AssertionError("ReturnAt has no payload mutation")

    is_fail = False
    is_gate = True

    def __repr__(self):
        return f"ReturnAt({sorted(self.counts)})"


class _Point:
    __slots__ = ("schedules", "count")

    def __init__(self):
        self.schedules: List = []
        self.count = 0


_LOCK = threading.Lock()
_POINTS: Dict[str, _Point] = {}
_ENV_LOADED = False


def register(point: str, schedule) -> None:
    """Attach a schedule to a fault point (additive)."""
    with _LOCK:
        _POINTS.setdefault(point, _Point()).schedules.append(schedule)


def clear(point: Optional[str] = None) -> None:
    """Remove schedules (and counters) for one point, or everything."""
    global _ENV_LOADED
    with _LOCK:
        if point is None:
            _POINTS.clear()
            _ENV_LOADED = False
            _POSITION["at"] = None
        else:
            _POINTS.pop(point, None)


def reset(point: Optional[str] = None) -> None:
    """Zero invocation counters, keeping schedules (re-run a scenario)."""
    with _LOCK:
        for name, p in _POINTS.items():
            if point is None or name == point:
                p.count = 0


def counts() -> Dict[str, int]:
    """Current invocation counters (diagnostics / test assertions)."""
    with _LOCK:
        return {name: p.count for name, p in _POINTS.items()}


def armed(point: str) -> bool:
    """True when any schedule is attached to `point` — production code may
    branch to a chaos-compatible (e.g. non-streaming) path only then."""
    _load_env()
    with _LOCK:
        return point in _POINTS and bool(_POINTS[point].schedules)


def _matches(s, count: int) -> bool:
    """Plain invocation-count match OR ``@epoch:iteration`` position match
    (positions attached by the spec parser; see at_position)."""
    if s.fires(count):
        return True
    at = _POSITION["at"]
    return at is not None and at in getattr(s, "positions", ())


def _bump(point: str):
    """count++ and return (count, matching schedules) — one counted
    invocation per fire()/transform() call."""
    _load_env()
    with _LOCK:
        p = _POINTS.get(point)
        if p is None or not p.schedules:
            return 0, []
        p.count += 1
        return p.count, [s for s in p.schedules if _matches(s, p.count)]


def _trace_hits(point: str, count: int, hits) -> None:
    """Mark each schedule hit as an instant event on the run timeline
    (utils/telemetry) — injected faults become visible right next to the
    retries/stalls/NaNs they cause.  Only runs when a schedule actually
    fired, so unarmed points stay free."""
    from . import telemetry
    telemetry.instant(f"chaos:{point}", cat="chaos", count=count,
                      schedules=[repr(s) for s in hits])


def fire(point: str, thread_exc=None) -> None:
    """Count one invocation; raise ChaosFault if a fail schedule matches,
    block if a stall schedule matches.  Corrupt schedules are ignored here
    (no payload to mutate).

    ``thread_exc`` (an exception class) scopes exit/wedge schedules to
    the CALLING THREAD: ``exit`` raises ``thread_exc`` instead of
    ``os._exit`` and ``wedge`` blocks uninterruptibly without suspending
    process liveness — the serve replica-loss drill
    (``serve.replica@<idx>``, serve/control.py)."""
    count, hits = _bump(point)
    if hits:
        _trace_hits(point, count, hits)
    for s in hits:
        if getattr(s, "is_exit", False):
            if thread_exc is not None:
                if isinstance(s, WedgeAt):
                    s.block_uninterruptible()
                else:
                    raise thread_exc(
                        f"chaos[{point}] thread exit "
                        f"(invocation {count}, {s!r})")
            else:
                s.engage()
        elif getattr(s, "is_stall", False):
            s.block()
        elif s.is_fail:
            raise ChaosFault(f"chaos[{point}] injected failure "
                             f"(invocation {count}, {s!r})")


def gate(point: str) -> bool:
    """Count one invocation and report whether an OBSERVATION GATE at
    `point` is reached — nothing raises, blocks, or exits (the
    difference from :func:`fire`).  The elastic joiner's announce loop
    polls its ``host.return@<rank>`` point with this.

    Matching: plain invocation counts are exact (like every schedule);
    ``@epoch:iteration`` positions fire AT-OR-AFTER the addressed
    coordinate (tuple order on ``(epoch, neval)``) — the gate's caller
    POLLS positions sampled from the checkpoint stream and may never
    observe the exact coordinate, so exact-match would be a silent
    never-fire."""
    _load_env()
    with _LOCK:
        p = _POINTS.get(point)
        if p is None or not p.schedules:
            return False
        p.count += 1
        count = p.count
        at = _POSITION["at"]
        hits = [s for s in p.schedules
                if s.fires(count) or
                (at is not None and
                 any(at >= pos for pos in getattr(s, "positions", ())))]
    if hits:
        _trace_hits(point, count, hits)
    return bool(hits)


def transform(point: str, value):
    """Count one invocation; raise on fail schedules, block on stall
    schedules, else pipe the payload through every matching corrupt
    schedule."""
    count, hits = _bump(point)
    if hits:
        _trace_hits(point, count, hits)
    for s in hits:
        if getattr(s, "is_exit", False):
            s.engage()
        elif getattr(s, "is_stall", False):
            s.block()
        elif s.is_fail:
            raise ChaosFault(f"chaos[{point}] injected failure "
                             f"(invocation {count}, {s!r})")
        elif not getattr(s, "is_gate", False):
            value = s.mutate(value)
    return value


# ---------------------------------------------------------------------------
# spec parsing (env var / --chaos CLI)
# ---------------------------------------------------------------------------

def _parse_counts(at: str, action: str):
    """``@`` operand -> (plain counts, (epoch, neval) positions).  Each
    comma-separated entry is a 1-based invocation count or an
    ``epoch:iteration`` pair (the net-new position addressing)."""
    counts_, positions = [], []
    for c in at.split(","):
        if not c:
            continue
        if ":" in c:
            e, _, s = c.partition(":")
            positions.append((int(e), int(s)))
        else:
            counts_.append(int(c))
    if not counts_ and not positions:
        raise ValueError(f"chaos spec: empty counts in {action!r}")
    return counts_, frozenset(positions)


def _parse_action(action: str):
    """One schedule from ``fail@3,5`` / ``fail*2@4`` / ``corrupt@2`` /
    ``truncate@2`` / ``nan@7`` / ``stall@5`` / ``stall*30@5`` (for stall,
    ``*N`` is the block duration in SECONDS, not a repeat count) /
    ``exit@4`` / ``wedge*30@4`` / ``lost@4`` (= wedge; the host-loss
    drill actions) / ``join@2:2`` / ``return@2:2`` or the bare ``@2:2``
    shorthand (= ReturnAt, the host-return gate).  Counts may be
    ``epoch:iteration`` pairs (``stall*30@2:5``)."""
    if "@" not in action:
        raise ValueError(f"chaos spec: missing '@counts' in {action!r}")
    kind, _, at = action.partition("@")
    counts_, positions = _parse_counts(at, action)

    def place(sched):
        if positions:
            sched.positions = positions
        return sched

    if kind.startswith("stall"):
        seconds = 3600.0
        if "*" in kind:  # stall*SECONDS@counts
            seconds = float(kind.split("*", 1)[1])
        return place(StallAt(*counts_, seconds=seconds))
    if kind == "exit":
        return place(ExitAt(*counts_))
    if kind.startswith(("wedge", "lost")):
        seconds = 3600.0
        if "*" in kind:  # wedge*SECONDS@counts
            seconds = float(kind.split("*", 1)[1])
        return place(WedgeAt(*counts_, seconds=seconds))
    if kind.startswith("fail"):
        if "*" in kind:  # fail*N@start
            n = int(kind.split("*", 1)[1])
            if len(counts_) != 1 or positions:
                raise ValueError(
                    f"chaos spec: fail*N takes one start count: {action!r}")
            return FailN(n, start=counts_[0])
        return place(FailAt(*counts_))
    if kind in ("corrupt", "flip"):
        return place(CorruptAt(*counts_, mode="flip"))
    if kind == "truncate":
        return place(CorruptAt(*counts_, mode="truncate"))
    if kind == "nan":
        return place(CorruptAt(*counts_))  # float payloads NaN any mode
    if kind in ("join", "return", ""):
        # host-return gate: ``host.return@1=join@2:2`` — or the bare
        # ``host.return@1=@2:2`` the drill specs read most naturally
        return place(ReturnAt(*counts_))
    raise ValueError(f"chaos spec: unknown action {kind!r} in {action!r}")


def install(spec: str) -> None:
    """Install schedules from a spec string:
    ``point=action@counts[;point=action@counts...]``."""
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"chaos spec: expected point=action, got "
                             f"{part!r}")
        point, _, action = part.partition("=")
        register(point.strip(), _parse_action(action.strip()))


def _load_env() -> None:
    """One-shot pickup of BIGDL_TPU_CHAOS (config tier; see utils/config).
    Loaded lazily on the first armed()/fire()/transform() so importing this
    module never reads the environment."""
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    with _LOCK:
        if _ENV_LOADED:
            return
        _ENV_LOADED = True
    from . import config
    spec = config.get_str("CHAOS", "")
    if spec:
        install(spec)


class scoped:
    """Context manager for tests: install a spec (or programmatic
    (point, schedule) pairs), clear everything on exit."""

    def __init__(self, spec: str = "", schedules:
                 Optional[Iterable] = None):
        self.spec = spec
        self.schedules = list(schedules or [])

    def __enter__(self):
        clear()
        global _ENV_LOADED
        _ENV_LOADED = True  # scoped runs ignore the ambient env spec
        if self.spec:
            install(self.spec)
        for point, schedule in self.schedules:
            register(point, schedule)
        import sys
        return sys.modules[__name__]

    def __exit__(self, *exc):
        clear()
        return False
