"""Unified run telemetry: span tracer + Chrome-trace JSON + cross-host merge.

Reference gap this closes: the reference's driver printed ``Metrics.summary``
every iteration (DistriOptimizer.scala:298 — BigDL, arXiv:1804.05839 §3)
because a synchronous Spark job made every phase visible in the driver log.
Our compiled async pipeline hides everything between host dispatch and result
fetch, and the MLPerf TPU-pod work (arXiv:1909.09756) shows input-pipeline
and straggler diagnosis at scale needs a per-step, per-host timeline — not a
scrolling log.

Core pieces
-----------
- :class:`Tracer`: a process-wide tracer producing **nested spans**
  ("X" complete events), **instant events** ("i" — chaos fault injections
  land here) and **counter tracks** ("C" — data_wait / step seconds /
  records/s / prefetch queue depth) in Chrome trace-event JSON, loadable
  directly in Perfetto / ``chrome://tracing``.  Events live in a bounded
  in-memory ring (oldest dropped, drop count recorded) and flush
  periodically through ``file_io`` — local dirs, ``memory://`` and any
  fsspec remote scheme all work — to ``trace.<rank>.json`` (one file per
  process, ``pid`` = rank, so multi-host traces merge by concatenation).
- Module-level ``span()/complete()/instant()/counter()/thread_name()``
  helpers that no-op against a shared singleton when no tracer is active:
  instrumented code pays one attribute load + ``is None`` check when
  tracing is off — no events, no allocation, and the tracer has **no
  thread at all** (flushing is inline, count-triggered).
- Timestamps are wall-clock-anchored (epoch micros, advanced by the
  monotonic clock) so traces from different hosts line up on one timeline
  after :func:`merge_traces`; the clock pair is injectable for tests.
- :func:`merge_traces` + :func:`phase_breakdown` + :func:`format_report`
  are the analysis core behind ``tools/trace_report.py``: merge
  ``trace.*.json`` of all ranks, compute per-phase p50/p95/max, the
  ``data_wait_fraction`` (input-bound vs compute-bound diagnosis, same
  definition as bench.py's e2e stage) and straggler ranks.

Who emits what (all through the module-level helpers, so everything is
inert until a tracer is active):

- the Optimizer train loop: ``data``/``step``/``checkpoint``/
  ``validation`` spans + a per-step counter track;
- the prefetch worker (dataset/prefetch.py): its own named thread track
  with per-item ``prefetch.item`` spans;
- file_io: ``ckpt.write``/``ckpt.read`` spans (write+verify),
  ``ckpt.retention`` spans, and an ``io.retry`` instant per remote-IO
  retry attempt;
- chaos (utils/chaos.py): one ``chaos:<point>`` instant per schedule hit,
  so injected faults are visible on the same timeline as their fallout;
- the supervisor (utils/supervisor.py): embeds the active tracer's
  recent-event tail in stall crash reports and flushes the trace file
  before writing the report (flush-on-crash).

Knobs (utils/config tier):

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_TRACE`` | trace output dir (any file_io scheme); empty = tracing off | off |
| ``BIGDL_TPU_TRACE_RING`` | max buffered events (ring; oldest dropped) | 65536 |
| ``BIGDL_TPU_TRACE_FLUSH_EVERY`` | events between automatic flushes | 4096 |
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional

from . import config
from . import metrics_export

logger = logging.getLogger("bigdl_tpu")

__all__ = ["Tracer", "enabled", "trace_dir", "maybe_start", "set_active",
           "get_active", "span", "complete", "instant", "counter",
           "thread_name", "merge_traces", "phase_breakdown",
           "format_report", "diff_breakdowns", "format_diff",
           "flow_start", "flow_step", "flow_finish", "mint_request_id",
           "request_breakdown", "format_requests",
           "REQUEST_ID_HEADER", "TRACE_FILE_RE"]

#: the train loop's phase spans — the names phase_breakdown() ranks first
PHASE_NAMES = ("data", "step", "checkpoint", "validation")

TRACE_FILE_RE = r"trace\.(\d+)\.json"

#: every flow event of one request shares this name+cat — Chrome links
#: s/t/f phases into one arrow chain only when (name, cat, id) all match
FLOW_NAME = "request"
FLOW_CAT = "req"

#: the HTTP header the fleet front uses to propagate a request id to the
#: member that serves it (and that members echo back in every response)
REQUEST_ID_HEADER = "X-BigDL-Request-Id"


class _NullSpan:
    """Shared no-op context manager: what ``span()`` hands out when no
    tracer is active — one singleton, zero allocation per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "name", "cat", "args", "_t0")

    def __init__(self, tr: "Tracer", name: str, cat: str, args):
        self._tr = tr
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = self._tr._now_us()
        return self

    def __exit__(self, *exc):
        self._tr._emit_complete(self.name, self.cat, self._t0,
                                self._tr._now_us() - self._t0, self.args)
        return False


class Tracer:
    """Chrome-trace-event tracer with a bounded ring and file_io flush.

    ``out_dir`` accepts any file_io scheme (local path, ``memory://``,
    ``gs://``); each flush rewrites ``trace.<rank>.json`` with the current
    ring contents, so the newest events are always on storage — a crashed
    or stalled run's trace survives up to its last flush (the supervisor
    forces one before writing a crash report).  No background thread:
    flushing happens inline every ``flush_every`` appended events and on
    ``flush()``/``close()``."""

    def __init__(self, out_dir: str, rank: int = 0, *,
                 ring: Optional[int] = None,
                 flush_every: Optional[int] = None,
                 clock=None, wall_clock=None):
        self.out_dir = str(out_dir)
        self.rank = int(rank)
        self.ring = (config.get_int("TRACE_RING", 65536)
                     if ring is None else int(ring))
        self.flush_every = (config.get_int("TRACE_FLUSH_EVERY", 4096)
                            if flush_every is None else int(flush_every))
        self._clock = clock or time.perf_counter
        wall = wall_clock or time.time
        # wall-anchored monotonic micros: cross-host merge needs a shared
        # timebase (epoch), in-process ordering needs monotonicity
        self._base_us = wall() * 1e6
        self._base_perf = self._clock()
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._meta: List[dict] = []   # process/thread names: never evicted
        self._tids: Dict[int, int] = {}
        self.dropped = 0
        self._since_flush = 0
        self._rid_seq = 0
        self._closed = False
        import socket
        self._host = socket.gethostname()
        self._meta.append({"ph": "M", "name": "process_name",
                           "pid": self.rank, "tid": 0,
                           "args": {"name": f"rank {self.rank} "
                                            f"({self._host})"}})

    # -- clocks / ids ---------------------------------------------------

    def _now_us(self) -> float:
        return self._base_us + (self._clock() - self._base_perf) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
            self._emit_meta("thread_name", tid,
                            threading.current_thread().name)
        return tid

    def _emit_meta(self, kind: str, tid: int, name: str) -> None:
        with self._lock:
            self._meta.append({"ph": "M", "name": kind, "pid": self.rank,
                               "tid": tid, "args": {"name": name}})

    def thread_name(self, name: str) -> None:
        """(Re)label the calling thread's track (the prefetch worker names
        itself at startup)."""
        self._emit_meta("thread_name", self._tid(), name)

    # -- event emission -------------------------------------------------

    def _append(self, ev: dict) -> None:
        flush_now = False
        with self._lock:
            if self._closed:
                return
            self._events.append(ev)
            if len(self._events) > self.ring:
                del self._events[0]
                self.dropped += 1
            self._since_flush += 1
            if self.flush_every > 0 and \
                    self._since_flush >= self.flush_every:
                self._since_flush = 0
                flush_now = True
        if flush_now:
            self.flush()

    def span(self, name: str, cat: str = "phase", **args) -> _Span:
        """Context manager emitting one "X" complete event on exit; nested
        ``with`` blocks nest in Perfetto by time containment."""
        return _Span(self, name, cat, args or None)

    def _emit_complete(self, name, cat, ts_us, dur_us, args) -> None:
        ev = {"name": name, "cat": cat, "ph": "X", "ts": round(ts_us, 1),
              "dur": round(max(dur_us, 0.0), 1), "pid": self.rank,
              "tid": self._tid()}
        if args:
            ev["args"] = args
        self._append(ev)

    def complete(self, name: str, dur_s: float, cat: str = "phase",
                 **args) -> None:
        """Record a span that just ENDED and lasted ``dur_s`` seconds —
        for code that already measured a duration (the train loop's
        data_wait) without restructuring it into a ``with`` block."""
        now = self._now_us()
        self._emit_complete(name, cat, now - dur_s * 1e6, dur_s * 1e6, args)

    def instant(self, name: str, cat: str = "event", **args) -> None:
        ev = {"name": name, "cat": cat, "ph": "i", "ts":
              round(self._now_us(), 1), "s": "t", "pid": self.rank,
              "tid": self._tid()}
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, track: str, **values) -> None:
        """One sample on counter track ``track`` (Perfetto renders each
        arg key as a series)."""
        self._append({"name": track, "ph": "C",
                      "ts": round(self._now_us(), 1), "pid": self.rank,
                      "tid": 0, "args": {k: round(float(v), 6)
                                         for k, v in values.items()}})

    # -- request flows ("s"/"t"/"f" — the cross-process arrow chain) -----

    def mint_request_id(self) -> str:
        """A process-unique request id (pid-rank-seq hex).  Minted at
        admission (FleetFront.submit / InferenceServer.submit /
        DecodeEngine.submit) and carried on the PendingRequest + the
        ``X-BigDL-Request-Id`` header so every process's flow events for
        one request share one Chrome flow ``id``."""
        import os
        with self._lock:
            self._rid_seq += 1
            n = self._rid_seq
        return f"{os.getpid():x}-{self.rank:x}-{n:x}"

    def _emit_flow(self, ph: str, flow_id: str, args) -> None:
        ev = {"name": FLOW_NAME, "cat": FLOW_CAT, "ph": ph,
              "id": str(flow_id), "ts": round(self._now_us(), 1),
              "pid": self.rank, "tid": self._tid()}
        if ph == "f":
            # bind the arrow head to the ENCLOSING slice, not the next one
            ev["bp"] = "e"
        if args:
            ev["args"] = args
        self._append(ev)

    def flow_start(self, flow_id: str, **args) -> None:
        """Open a request flow ("s"): the admission point of the process
        that MINTED the id.  ``args`` should carry ``hop`` — the
        request_breakdown() segment attribution is keyed on hop names."""
        self._emit_flow("s", flow_id, args or None)

    def flow_step(self, flow_id: str, **args) -> None:
        """A "t" flow phase: every later hop the request passes through
        (front send, member enqueue, batch assembly, decode ticks,
        retries, failovers) on whichever process observes it."""
        self._emit_flow("t", flow_id, args or None)

    def flow_finish(self, flow_id: str, **args) -> None:
        """Close the flow ("f", bp="e"): emitted by the id's minter when
        the request resolves (the front's dispatch return, or the
        server's _resolve for locally-minted ids)."""
        self._emit_flow("f", flow_id, args or None)

    # -- inspection / persistence --------------------------------------

    def events_tail(self, n: int = 64) -> List[dict]:
        """The newest n events (the supervisor embeds this in stall crash
        reports so the timeline leading into a hang is preserved even if
        the trace file itself is lost)."""
        with self._lock:
            return [dict(e) for e in self._events[-n:]]

    @property
    def path(self) -> str:
        from . import file_io
        base = file_io._strip_file_scheme(self.out_dir)
        return file_io._join(base, f"trace.{self.rank}.json")

    def flush(self) -> str:
        """Rewrite ``trace.<rank>.json`` with the current ring contents.
        Returns the path; a broken trace store must never take down the
        traced run (logged, not raised)."""
        from . import file_io
        with self._lock:
            payload = {"traceEvents": self._meta + self._events,
                       "displayTimeUnit": "ms",
                       "otherData": {"rank": self.rank, "host": self._host,
                                     "dropped_events": self.dropped}}
            self._since_flush = 0
        path = self.path
        try:
            base = file_io._strip_file_scheme(self.out_dir)
            fs = file_io.get_filesystem(base)
            fs.makedirs(base)
            fs.write_bytes(path, json.dumps(payload).encode())
        except Exception as e:  # noqa: BLE001 — telemetry is best-effort
            logger.warning("telemetry: trace flush to %s failed: %s",
                           path, e)
        return path

    def close(self) -> None:
        """Final flush + detach (idempotent); clears the active slot if
        this tracer holds it."""
        if not self._closed:
            self.flush()
            self._closed = True
        if get_active() is self:
            set_active(None)


# ---------------------------------------------------------------------------
# process-wide active tracer + zero-overhead module helpers
# ---------------------------------------------------------------------------

_ACTIVE: Optional[Tracer] = None


def set_active(tr: Optional[Tracer]) -> None:
    global _ACTIVE
    _ACTIVE = tr


def get_active() -> Optional[Tracer]:
    return _ACTIVE


def trace_dir() -> str:
    """The ``BIGDL_TPU_TRACE`` knob: the trace output dir ('' = off)."""
    return config.get_str("TRACE", "").strip()


def enabled() -> bool:
    return bool(trace_dir())


def maybe_start(rank: int = 0) -> Optional[Tracer]:
    """Start (and make active) a Tracer per the env knobs.  Returns the
    NEW tracer only when this call created one — None when tracing is off
    or another tracer already owns the process slot — so the caller that
    gets a handle back is the one that must ``close()`` it."""
    if _ACTIVE is not None or not enabled():
        return None
    tr = Tracer(trace_dir(), rank=rank)
    set_active(tr)
    return tr


def span(name: str, cat: str = "phase", **args):
    """Module-level span against the active tracer; the shared no-op
    singleton when tracing is off (no allocation, no event)."""
    tr = _ACTIVE
    if tr is None:
        return _NULL_SPAN
    return tr.span(name, cat, **args)


def complete(name: str, dur_s: float, cat: str = "phase", **args) -> None:
    tr = _ACTIVE
    if tr is not None:
        tr.complete(name, dur_s, cat, **args)


def instant(name: str, cat: str = "event", **args) -> None:
    tr = _ACTIVE
    if tr is not None:
        tr.instant(name, cat, **args)


def counter(track: str, **values) -> None:
    tr = _ACTIVE
    if tr is not None:
        tr.counter(track, **values)
    # the live-metrics plane rides the same call sites: every counter
    # track doubles as a Prometheus gauge when a registry is armed (and
    # costs one module-attribute load + None check when it is not)
    reg = metrics_export._REGISTRY
    if reg is not None:
        reg.feed_counter(track, values)


def thread_name(name: str) -> None:
    tr = _ACTIVE
    if tr is not None:
        tr.thread_name(name)


def mint_request_id() -> Optional[str]:
    """Mint a request id against the active tracer — None when tracing is
    off, so untraced admission paths carry (and allocate) nothing."""
    tr = _ACTIVE
    if tr is None:
        return None
    return tr.mint_request_id()


def flow_start(flow_id: Optional[str], **args) -> None:
    tr = _ACTIVE
    if tr is not None and flow_id:
        tr.flow_start(flow_id, **args)


def flow_step(flow_id: Optional[str], **args) -> None:
    tr = _ACTIVE
    if tr is not None and flow_id:
        tr.flow_step(flow_id, **args)


def flow_finish(flow_id: Optional[str], **args) -> None:
    tr = _ACTIVE
    if tr is not None and flow_id:
        tr.flow_finish(flow_id, **args)


# ---------------------------------------------------------------------------
# cross-host merge + phase breakdown (the trace_report core)
# ---------------------------------------------------------------------------

def merge_traces(trace_dir_: str) -> dict:
    """Merge every ``trace.<rank>.json`` under ``trace_dir_`` (any file_io
    scheme) into one Chrome-trace object on a shared timeline: events are
    already wall-clock-anchored and pid-tagged by rank, so the merge is a
    concatenation + time sort.  Raises FileNotFoundError when no trace
    files exist."""
    import re
    from . import file_io
    base = file_io._strip_file_scheme(str(trace_dir_))
    fs = file_io.get_filesystem(base)
    try:
        names = fs.listdir(base)
    except Exception as e:  # noqa: BLE001 — uniform error for a bad dir
        raise FileNotFoundError(f"{trace_dir_}: cannot list trace dir "
                                f"({type(e).__name__}: {e})") from e
    ranks, events, other = [], [], {}
    for name in sorted(names):
        m = re.fullmatch(TRACE_FILE_RE, name)
        if not m:
            continue
        blob = json.loads(fs.read_bytes(file_io._join(base, name)))
        ranks.append(int(m.group(1)))
        events.extend(blob.get("traceEvents", []))
        other[m.group(1)] = blob.get("otherData", {})
    if not ranks:
        raise FileNotFoundError(
            f"{trace_dir_}: no trace.<rank>.json files found")
    # metadata events (ph=M) first, then time order — Perfetto wants names
    # declared before use and meta events carry no ts
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"ranks": sorted(ranks), "per_rank": other}}


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(q * (len(sorted_vals) - 1) + 0.5),
                           len(sorted_vals) - 1)]


def phase_breakdown(merged: dict) -> dict:
    """Per-phase stats + the input-bound-vs-compute-bound diagnosis from a
    merged trace.

    - ``phases``: per span name — count, total seconds, p50/p95/max ms
      (the optimizer's ``data``/``step``/``checkpoint``/``validation``
      first, then every other span name seen);
    - ``ranks``: per rank — wall seconds (first span start to last span
      end), ``data_wait_fraction`` (sum of ``data`` span time / wall: the
      same numerator/denominator bench.py's e2e stage reports), mean step
      seconds;
    - ``data_wait_fraction`` overall + ``diagnosis``;
    - ``straggler_ranks``: ranks whose mean ``step`` span runs > 1.5x the
      median rank's (the one-slow-host signal);
    - ``instants``: count per instant-event name (chaos injections show up
      here);
    - ``elastic``: the ``elastic.*`` instants keyed by suffix
      (join/agree/reform/resume/…) plus ``joined`` — the last value of
      the ``peers`` counter track, i.e. the world size after the most
      recent shrink/grow (parallel/elastic.py)."""
    spans = [e for e in merged.get("traceEvents", [])
             if e.get("ph") == "X" and "dur" in e]
    by_name: Dict[str, List[float]] = {}
    per_rank: Dict[int, dict] = {}
    for e in spans:
        dur_s = e["dur"] / 1e6
        by_name.setdefault(e["name"], []).append(dur_s)
        r = per_rank.setdefault(int(e.get("pid", 0)),
                                {"start": e["ts"], "end": e["ts"] + e["dur"],
                                 "data": 0.0, "step": [], "spans": 0})
        r["start"] = min(r["start"], e["ts"])
        r["end"] = max(r["end"], e["ts"] + e["dur"])
        r["spans"] += 1
        if e["name"] == "data":
            r["data"] += dur_s
        elif e["name"] == "step":
            r["step"].append(dur_s)
    phases = {}
    order = [n for n in PHASE_NAMES if n in by_name] + \
        sorted(n for n in by_name if n not in PHASE_NAMES)
    for name in order:
        vals = sorted(by_name[name])
        phases[name] = {"count": len(vals),
                        "total_s": round(sum(vals), 6),
                        "p50_ms": round(_pct(vals, 0.50) * 1e3, 3),
                        "p95_ms": round(_pct(vals, 0.95) * 1e3, 3),
                        "max_ms": round(vals[-1] * 1e3, 3)}
    ranks = {}
    total_data = total_wall = 0.0
    step_means = {}
    for rank, r in sorted(per_rank.items()):
        wall = max((r["end"] - r["start"]) / 1e6, 1e-9)
        frac = min(r["data"] / wall, 1.0)
        total_data += r["data"]
        total_wall += wall
        mean_step = (sum(r["step"]) / len(r["step"])) if r["step"] else None
        if mean_step is not None:
            step_means[rank] = mean_step
        ranks[str(rank)] = {"wall_s": round(wall, 6),
                            "spans": r["spans"],
                            "data_wait_fraction": round(frac, 4),
                            "step_mean_s": (round(mean_step, 6)
                                            if mean_step is not None
                                            else None)}
    stragglers = []
    if len(step_means) > 1:
        means = sorted(step_means.values())
        # lower median: with an even rank count the SLOWER of the middle
        # pair must not become the yardstick (2 ranks would never flag)
        median = means[(len(means) - 1) // 2]
        stragglers = [{"rank": rk, "step_mean_s": round(v, 6),
                       "x_median": round(v / max(median, 1e-12), 2)}
                      for rk, v in sorted(step_means.items())
                      if v > 1.5 * median]
    frac = min(total_data / total_wall, 1.0) if total_wall > 0 else 0.0
    instants: Dict[str, int] = {}
    for e in merged.get("traceEvents", []):
        if e.get("ph") == "i":
            instants[e["name"]] = instants.get(e["name"], 0) + 1
    # counter tracks ("C" events): per track.series — count/mean/max/last.
    # This is where the optimizer's per-step mfu and the aot hit/miss
    # ledger become part of the printed report (regressions show up in
    # `trace_report` output, not just inside Perfetto).
    counter_vals: Dict[str, List[float]] = {}
    for e in merged.get("traceEvents", []):
        if e.get("ph") == "C":
            for k, v in (e.get("args") or {}).items():
                counter_vals.setdefault(f"{e['name']}.{k}", []).append(
                    float(v))
    counters = {}
    for name in sorted(counter_vals):
        vals = counter_vals[name]
        counters[name] = {"count": len(vals),
                          "mean": round(sum(vals) / len(vals), 6),
                          "max": round(max(vals), 6),
                          "last": round(vals[-1], 6)}
    # the AOT warm-start ledger, promoted out of the counter soup: when
    # the `aot` track is present its LAST samples are the process totals
    # (utils/aot._bump emits cumulative counts), so "did this run compile
    # anything?" is a first-class report section, not a Perfetto hunt
    aot = {series[len("aot."):]: int(st["last"])
           for series, st in counters.items() if series.startswith("aot.")}
    # the serving autoscaler's track, promoted the same way: its LAST
    # replicas sample is the pool's final size and the serve.autoscale
    # instant count is how many scale decisions fired — "did the pool
    # actually track the load?" becomes a report line, not a Perfetto
    # hunt (serve/autoscale.py)
    autoscale = {series[len("serve.autoscale."):]: st["last"]
                 for series, st in counters.items()
                 if series.startswith("serve.autoscale.")}
    if autoscale:
        autoscale["decisions"] = instants.get("serve.autoscale", 0)
    # the continuous-deployment track, promoted the same way: the
    # trainer's publishes and the controller's deploy/promote/rollback
    # counts share the one `deploy` track, so a merged trainer+server
    # trace answers "did every good release reach traffic?" as a report
    # line (serve/continuous.py) — last values are cumulative totals
    deploy = {series[len("deploy."):]: st["last"]
              for series, st in counters.items()
              if series.startswith("deploy.")}
    if deploy:
        deploy["events"] = sum(v for k, v in instants.items()
                               if k.startswith("deploy."))
    # the elastic re-form track, promoted the same way: the `peers`
    # counter's `joined` series carries the joined-rank count after every
    # re-form (its LAST sample is the final world size) and the
    # elastic.* instants are the protocol milestones — "did the run
    # shrink and grow back?" becomes a report line (parallel/elastic)
    elastic = {k[len("elastic."):]: v for k, v in instants.items()
               if k.startswith("elastic.")}
    joined = counters.get("peers.joined")
    if joined is not None:
        elastic["joined"] = int(joined["last"])
    # the cross-process fleet track, promoted the same way: the
    # supervisor's `fleet` counter (live/restarts/degraded, last values
    # are the final state) plus the fleet.* instants (spawn/lost/
    # condemn/respawn/deploy milestones across supervisor, front tier,
    # and every worker process) — "did the fleet lose, replace, and
    # re-deploy members?" becomes a report line spanning every member's
    # trace (serve/fleet.py, serve/fleetfront.py)
    fleet = {series[len("fleet."):]: st["last"]
             for series, st in counters.items()
             if series.startswith("fleet.")}
    fleet_events = sum(v for k, v in instants.items()
                       if k.startswith("fleet."))
    if fleet or fleet_events:
        fleet["events"] = fleet_events
    # the continuous-batching decode engine's track, promoted the same
    # way: tokens/s, active-slot fill, prefill-vs-decode step fractions
    # and cache bytes/slot (serve/decode.py emits cumulative/derived
    # values per tick, so LAST is the steady-state answer) — "did the
    # decode loop stay full and cheap?" becomes a report line
    decode = {series[len("serve.decode."):]: st["last"]
              for series, st in counters.items()
              if series.startswith("serve.decode.")}
    return {"phases": phases, "ranks": ranks, "counters": counters,
            "aot": aot, "autoscale": autoscale, "deploy": deploy,
            "elastic": elastic, "fleet": fleet, "decode": decode,
            "data_wait_fraction": round(frac, 4),
            "diagnosis": ("input-bound (data_wait_fraction "
                          f"{frac:.2f} > 0.5: the host pipeline gates the "
                          "chip)" if frac > 0.5 else
                          f"compute-bound (data_wait_fraction {frac:.2f} "
                          "<= 0.5: the device step sets the pace)"),
            "straggler_ranks": stragglers,
            "instants": instants}


def format_report(breakdown: dict, merged: Optional[dict] = None) -> str:
    """Human-readable phase breakdown (the trace_report CLI's output)."""
    lines = []
    if merged is not None:
        meta = merged.get("otherData", {})
        lines.append(f"ranks: {meta.get('ranks', '?')}  events: "
                     f"{len(merged.get('traceEvents', []))}")
    lines.append(f"{'phase':<16}{'count':>8}{'total_s':>12}{'p50_ms':>10}"
                 f"{'p95_ms':>10}{'max_ms':>10}")
    for name, st in breakdown["phases"].items():
        lines.append(f"{name:<16}{st['count']:>8}{st['total_s']:>12.3f}"
                     f"{st['p50_ms']:>10.2f}{st['p95_ms']:>10.2f}"
                     f"{st['max_ms']:>10.2f}")
    lines.append(f"data_wait_fraction: {breakdown['data_wait_fraction']} "
                 f"— {breakdown['diagnosis']}")
    for rank, st in breakdown["ranks"].items():
        lines.append(f"  rank {rank}: wall {st['wall_s']:.3f}s, "
                     f"data_wait_fraction {st['data_wait_fraction']}, "
                     f"step mean "
                     f"{st['step_mean_s'] if st['step_mean_s'] is not None else '-'}")
    if breakdown["straggler_ranks"]:
        for s in breakdown["straggler_ranks"]:
            lines.append(f"STRAGGLER rank {s['rank']}: step mean "
                         f"{s['step_mean_s']}s = {s['x_median']}x the "
                         "median rank")
    else:
        lines.append("stragglers: none")
    if breakdown.get("counters"):
        lines.append(f"{'counter':<28}{'count':>8}{'mean':>14}{'max':>14}"
                     f"{'last':>14}")
        # sorted here too (not just at breakdown build): a breakdown that
        # round-tripped through JSON (trace_report --json | --diff) must
        # render the same row order
        for name in sorted(breakdown["counters"]):
            st = breakdown["counters"][name]
            lines.append(f"{name:<28}{st['count']:>8}{st['mean']:>14.6g}"
                         f"{st['max']:>14.6g}{st['last']:>14.6g}")
    if breakdown.get("aot"):
        lines.append("aot ledger: " + "  ".join(
            f"{k}={v}" for k, v in sorted(breakdown["aot"].items())))
    if breakdown.get("autoscale"):
        lines.append("autoscale: " + "  ".join(
            f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(breakdown["autoscale"].items())))
    if breakdown.get("deploy"):
        lines.append("deploy: " + "  ".join(
            f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(breakdown["deploy"].items())))
    if breakdown.get("elastic"):
        lines.append("elastic: " + "  ".join(
            f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(breakdown["elastic"].items())))
    if breakdown.get("fleet"):
        lines.append("fleet: " + "  ".join(
            f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(breakdown["fleet"].items())))
    if breakdown.get("decode"):
        lines.append("decode: " + "  ".join(
            f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(breakdown["decode"].items())))
    if breakdown["instants"]:
        lines.append("instant events: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(breakdown["instants"].items())))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# per-request critical paths (trace_report --requests)
# ---------------------------------------------------------------------------

#: hop name -> which latency segment the time ENTERING that hop belongs
#: to.  A segment is the gap between consecutive flow events of one
#: request; it is attributed by where the request ARRIVED (e.g. the gap
#: ending at ``queue.enqueue`` was spent in transport getting there).
_SEG_BY_DST = {
    "front.send": "dispatch",       # front admit -> picked a member
    "queue.enqueue": "transport",   # front send -> member admission
    "batch.assemble": "queue",      # enqueue -> pulled into a batch
    "decode.admit": "queue",        # enqueue -> admitted to a KV slot
    "decode.tick": "device",        # admit/tick -> next decode step
    "resolve": "device",            # batch assembly -> result resolved
    "front.done": "transport",      # member resolve -> front response
    "fleet.retry": "failover",      # send -> the attempt was abandoned
    "replica.lost": "failover",     # a replica died holding the request
    "decode.fault": "failover",     # a KV slot faulted mid-sequence
}
_SEGMENTS = ("dispatch", "queue", "device", "transport", "failover")


def request_breakdown(merged: dict, slowest: int = 5) -> dict:
    """Reconstruct per-request critical paths from a merged multi-process
    trace's flow events.

    Every flow phase ("s"/"t"/"f" with name=:data:`FLOW_NAME`) carries the
    request id in ``id`` and a ``hop`` arg naming the pipeline station it
    marks; consecutive hops of one id — across front, worker, and
    controller pids — partition the request's latency into segments
    (:data:`_SEGMENTS`).  Returns per-segment p50/p95/p99 over all
    requests, per-request totals, and the slowest-N hop timelines —
    "where did the p99 go" as data."""
    flows: Dict[str, List[dict]] = {}
    for e in merged.get("traceEvents", []):
        if e.get("ph") in ("s", "t", "f") and e.get("name") == FLOW_NAME:
            a = e.get("args") or {}
            flows.setdefault(str(e.get("id")), []).append(
                {"ts": float(e.get("ts", 0.0)), "rank": int(e.get("pid", 0)),
                 "hop": a.get("hop", "?"), "args": a})
    requests = {}
    seg_samples: Dict[str, List[float]] = {s: [] for s in _SEGMENTS}
    for rid, evs in flows.items():
        evs.sort(key=lambda e: e["ts"])
        segments = {s: 0.0 for s in _SEGMENTS}
        for prev, cur in zip(evs, evs[1:]):
            seg = _SEG_BY_DST.get(cur["hop"], "dispatch")
            segments[seg] += max(cur["ts"] - prev["ts"], 0.0)
        total_us = max(evs[-1]["ts"] - evs[0]["ts"], 0.0)
        members = sorted({e["args"]["member"] for e in evs
                          if "member" in e["args"]})
        status = next((e["args"]["status"] for e in reversed(evs)
                       if "status" in e["args"]), None)
        requests[rid] = {
            "total_ms": round(total_us / 1e3, 3),
            "hops": len(evs),
            "ranks": sorted({e["rank"] for e in evs}),
            "members": members,
            "status": status,
            "segments": {s: round(v / 1e3, 3)
                         for s, v in segments.items() if v > 0.0}}
        for s, v in segments.items():
            seg_samples[s].append(v / 1e3)
    seg_stats = {}
    for s in _SEGMENTS:
        vals = sorted(v for v in seg_samples[s] if v > 0.0)
        if not vals:
            continue
        seg_stats[s] = {"count": len(vals),
                        "total_ms": round(sum(vals), 3),
                        "p50_ms": round(_pct(vals, 0.50), 3),
                        "p95_ms": round(_pct(vals, 0.95), 3),
                        "p99_ms": round(_pct(vals, 0.99), 3)}
    slow = sorted(requests.items(), key=lambda kv: -kv[1]["total_ms"])
    slowest_list = []
    for rid, st in slow[:max(int(slowest), 0)]:
        evs = flows[rid]
        t0 = evs[0]["ts"]
        slowest_list.append({
            "id": rid, "total_ms": st["total_ms"], "status": st["status"],
            "timeline": [{"t_ms": round((e["ts"] - t0) / 1e3, 3),
                          "rank": e["rank"], "hop": e["hop"],
                          **({"member": e["args"]["member"]}
                             if "member" in e["args"] else {})}
                         for e in evs]})
    totals = sorted(st["total_ms"] for st in requests.values())
    return {"count": len(requests),
            "total_p50_ms": round(_pct(totals, 0.50), 3),
            "total_p95_ms": round(_pct(totals, 0.95), 3),
            "total_p99_ms": round(_pct(totals, 0.99), 3),
            "segments": seg_stats, "requests": requests,
            "slowest": slowest_list}


def format_requests(rb: dict) -> str:
    """Human-readable rendering of :func:`request_breakdown`."""
    if not rb.get("count"):
        return "requests: none (no flow events in this trace)"
    lines = [f"requests: {rb['count']}  total p50/p95/p99 ms: "
             f"{rb['total_p50_ms']}/{rb['total_p95_ms']}/"
             f"{rb['total_p99_ms']}",
             f"{'segment':<12}{'count':>8}{'total_ms':>12}{'p50_ms':>10}"
             f"{'p95_ms':>10}{'p99_ms':>10}"]
    for seg in _SEGMENTS:
        st = rb["segments"].get(seg)
        if st is None:
            continue
        lines.append(f"{seg:<12}{st['count']:>8}{st['total_ms']:>12.3f}"
                     f"{st['p50_ms']:>10.3f}{st['p95_ms']:>10.3f}"
                     f"{st['p99_ms']:>10.3f}")
    for s in rb["slowest"]:
        lines.append(f"slowest {s['id']}: {s['total_ms']}ms"
                     + (f" status={s['status']}" if s["status"] else ""))
        for h in s["timeline"]:
            member = f" member={h['member']}" if "member" in h else ""
            lines.append(f"  +{h['t_ms']:>10.3f}ms  rank {h['rank']:<3}"
                         f" {h['hop']}{member}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# run-to-run diff (trace_report --diff A B)
# ---------------------------------------------------------------------------

def diff_breakdowns(a: dict, b: dict) -> dict:
    """Structured diff of two phase breakdowns (A = baseline, B = new run).

    Per phase: count/total_s/p50 in both runs + the B/A total-time ratio;
    per counter series: last values in both runs + delta; the promoted
    ``fleet`` and ``decode`` sections (PRs 17–18) diff key-by-key the
    same way, so A/B runs compare tokens/s, fill, live members and
    restarts directly.  Phases or series present in only one run are
    flagged (``only``)."""
    phases = {}
    for name in sorted(set(a.get("phases", {})) | set(b.get("phases", {}))):
        pa, pb = a.get("phases", {}).get(name), \
            b.get("phases", {}).get(name)
        if pa is None or pb is None:
            phases[name] = {"only": "B" if pa is None else "A"}
            continue
        phases[name] = {
            "count": [pa["count"], pb["count"]],
            "total_s": [pa["total_s"], pb["total_s"]],
            "p50_ms": [pa["p50_ms"], pb["p50_ms"]],
            "total_ratio": round(pb["total_s"] / max(pa["total_s"], 1e-12),
                                 4)}
    counters = {}
    for name in sorted(set(a.get("counters", {})) |
                       set(b.get("counters", {}))):
        ca, cb = a.get("counters", {}).get(name), \
            b.get("counters", {}).get(name)
        if ca is None or cb is None:
            counters[name] = {"only": "B" if ca is None else "A"}
            continue
        counters[name] = {"last": [ca["last"], cb["last"]],
                          "delta": round(cb["last"] - ca["last"], 6)}
    sections = {}
    for sec in ("fleet", "decode"):
        sa, sb = a.get(sec) or {}, b.get(sec) or {}
        rows = {}
        for name in sorted(set(sa) | set(sb)):
            va, vb = sa.get(name), sb.get(name)
            if va is None or vb is None:
                rows[name] = {"only": "B" if va is None else "A"}
                continue
            rows[name] = {"last": [va, vb],
                          "delta": round(float(vb) - float(va), 6)}
        sections[sec] = rows
    return {"phases": phases, "counters": counters,
            "fleet": sections["fleet"], "decode": sections["decode"],
            "data_wait_fraction": [a.get("data_wait_fraction"),
                                   b.get("data_wait_fraction")]}


def format_diff(diff: dict) -> str:
    """Human-readable rendering of :func:`diff_breakdowns`."""
    lines = [f"{'phase':<16}{'count A/B':>14}{'total_s A':>12}"
             f"{'total_s B':>12}{'B/A':>8}"]
    for name, d in diff["phases"].items():
        if "only" in d:
            lines.append(f"{name:<16}  only in run {d['only']}")
            continue
        lines.append(f"{name:<16}{'%d/%d' % tuple(d['count']):>14}"
                     f"{d['total_s'][0]:>12.3f}{d['total_s'][1]:>12.3f}"
                     f"{d['total_ratio']:>8.2f}")
    if diff["counters"]:
        lines.append(f"{'counter':<28}{'last A':>14}{'last B':>14}"
                     f"{'delta':>12}")
        for name, d in diff["counters"].items():
            if "only" in d:
                lines.append(f"{name:<28}  only in run {d['only']}")
                continue
            lines.append(f"{name:<28}{d['last'][0]:>14.6g}"
                         f"{d['last'][1]:>14.6g}{d['delta']:>12.6g}")
    for sec in ("fleet", "decode"):
        rows = diff.get(sec) or {}
        if not rows:
            continue
        lines.append(f"{sec + ':':<28}{'A':>14}{'B':>14}{'delta':>12}")
        for name, d in rows.items():
            if "only" in d:
                lines.append(f"  {name:<26}  only in run {d['only']}")
                continue
            lines.append(f"  {name:<26}{d['last'][0]:>14.6g}"
                         f"{d['last'][1]:>14.6g}{d['delta']:>12.6g}")
    dw = diff["data_wait_fraction"]
    lines.append(f"data_wait_fraction: {dw[0]} -> {dw[1]}")
    return "\n".join(lines)
