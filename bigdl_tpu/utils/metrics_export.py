"""Live metrics plane: a process-wide registry + Prometheus text exposition.

The serving stack's only live surface used to be ad-hoc ``stats()`` JSON
polled over HTTP; BigDL 2.0's Cluster Serving pairs per-request tracing
with a scrapeable metrics endpoint, and this module is that second half.
One process-wide :class:`MetricsRegistry` collects

- **counters** (requests by status, sheds by cause, replica restarts),
- **gauges** (queue depth, batch fill, tokens/s — fed automatically from
  every existing ``telemetry.counter`` track via
  :meth:`MetricsRegistry.feed_counter`, so instrumented code needs no
  second call site),
- **histograms** (request latency, decode time-to-last-token), and
- a **rolling SLO-attainment gauge** (fraction of the last
  ``BIGDL_TPU_METRICS_WINDOW`` requests under
  ``BIGDL_TPU_METRICS_SLO_MS``),

and renders them as Prometheus text exposition (version 0.0.4) for
``GET /metrics`` on ``tools/serve_http.py`` / ``tools/serve_worker.py``.
The fleet front scrapes every live member's ``/metrics`` and re-exports
the union — each member sample labelled ``member="<idx>"`` plus a
fleet-wide sum per counter/histogram series — so one scrape of the front
sees the whole fleet (:func:`rollup`).

Disabled-mode contract (same as PR 4's tracer): until something calls
:func:`arm` — the HTTP servers do at startup unless
``BIGDL_TPU_METRICS=0`` — there is **no registry object, no events, no
allocation, and no thread** (the registry never has a thread; rendering
is pull-based at scrape time).  Instrumented code pays one module
attribute load + ``is None`` check per call when unarmed.

Knobs (utils/config tier):

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_METRICS`` | ``0`` keeps the HTTP servers from arming the registry | ``1`` |
| ``BIGDL_TPU_METRICS_SLO_MS`` | request-latency SLO for the rolling attainment gauge | ``100`` |
| ``BIGDL_TPU_METRICS_WINDOW`` | rolling window (requests) for SLO attainment | ``512`` |
"""

from __future__ import annotations

import math
import re
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import config

__all__ = ["MetricsRegistry", "arm", "disarm", "registry", "armed",
           "enabled", "render_rollup", "parse_exposition",
           "CONTENT_TYPE", "DEFAULT_BUCKETS"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: request-latency histogram bucket upper bounds, seconds (Prometheus
#: convention: cumulative, +Inf added by the renderer)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)

_NAME_OK = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(raw: str) -> str:
    """Sanitize a track/series name into a Prometheus metric name."""
    name = _NAME_OK.sub("_", raw.strip())
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{str(v)}"' for k, v in labels)
    return "{" + body + "}"


class MetricsRegistry:
    """Thread-safe counter/gauge/histogram store with text exposition.

    All mutators take ``**labels``; each distinct label set is one
    series.  There is deliberately no unregister and no background
    thread — the registry is a dict behind one lock, rendered on pull."""

    def __init__(self, *, slo_ms: Optional[float] = None,
                 window: Optional[int] = None):
        self._lock = threading.Lock()
        # name -> {labels_tuple: value}
        self._counters: Dict[str, Dict[tuple, float]] = {}
        self._gauges: Dict[str, Dict[tuple, float]] = {}
        # name -> {labels_tuple: [bucket_counts..., sum, count]}
        self._hists: Dict[str, Dict[tuple, list]] = {}
        self._hist_bounds: Dict[str, tuple] = {}
        self._help: Dict[str, str] = {}
        self.slo_s = (config.get_float("METRICS_SLO_MS", 100.0)
                      if slo_ms is None else float(slo_ms)) / 1e3
        n = (config.get_int("METRICS_WINDOW", 512)
             if window is None else int(window))
        self._slo_window: deque = deque(maxlen=max(n, 1))

    # -- mutators --------------------------------------------------------

    def counter_inc(self, name: str, value: float = 1.0,
                    help: Optional[str] = None, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + float(value)

    def gauge_set(self, name: str, value: float,
                  help: Optional[str] = None, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._gauges.setdefault(name, {})[key] = float(value)

    def observe(self, name: str, value: float,
                buckets: tuple = DEFAULT_BUCKETS,
                help: Optional[str] = None, **labels) -> None:
        key = tuple(sorted(labels.items()))
        v = float(value)
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            bounds = self._hist_bounds.setdefault(name, tuple(buckets))
            series = self._hists.setdefault(name, {})
            cell = series.get(key)
            if cell is None:
                cell = series[key] = [0] * len(bounds) + [0.0, 0]
            for i, b in enumerate(bounds):
                if v <= b:
                    cell[i] += 1
            cell[-2] += v
            cell[-1] += 1

    def observe_request(self, latency_s: float, status: str = "ok",
                        **labels) -> None:
        """The one call the serving resolve path makes: requests-total
        counter by status, latency histogram, and the rolling SLO window
        (a request attains the SLO when it resolved ok within
        ``slo_s``)."""
        self.counter_inc("bigdl_serve_requests_total", 1.0,
                         help="requests resolved, by final status",
                         status=status, **labels)
        self.observe("bigdl_serve_request_latency_seconds",
                     latency_s,
                     help="request latency (submit to resolve), seconds",
                     **labels)
        with self._lock:
            self._slo_window.append(
                1.0 if (status == "ok" and latency_s <= self.slo_s)
                else 0.0)

    def shed(self, cause: str, **labels) -> None:
        self.counter_inc("bigdl_serve_shed_total", 1.0,
                         help="requests shed at admission, by cause",
                         cause=cause, **labels)

    def feed_counter(self, track: str, values: Dict[str, float]) -> None:
        """telemetry.counter() mirror: every track.series sample becomes
        gauge ``bigdl_<track>_<series>`` — queue depth, batch fill,
        decode tokens/s, fleet live/restarts all arrive through here."""
        for k, v in values.items():
            try:
                f = float(v)
            except (TypeError, ValueError):
                continue
            self.gauge_set(f"bigdl_{_metric_name(track)}_{_metric_name(k)}",
                           f)

    # -- exposition ------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition of every series (plus the SLO
        gauge), sorted by metric name for a stable scrape diff."""
        with self._lock:
            counters = {n: dict(s) for n, s in self._counters.items()}
            gauges = {n: dict(s) for n, s in self._gauges.items()}
            hists = {n: {k: list(c) for k, c in s.items()}
                     for n, s in self._hists.items()}
            bounds = dict(self._hist_bounds)
            helps = dict(self._help)
            window = list(self._slo_window)
        if window:
            gauges["bigdl_serve_slo_attainment"] = {(): (
                sum(window) / len(window))}
            helps.setdefault(
                "bigdl_serve_slo_attainment",
                f"fraction of the last {len(window)} requests resolved ok "
                f"within {self.slo_s * 1e3:g}ms")
        lines: List[str] = []
        for name in sorted(set(counters) | set(gauges) | set(hists)):
            help_ = helps.get(name)
            if help_:
                lines.append(f"# HELP {name} {help_}")
            if name in counters:
                lines.append(f"# TYPE {name} counter")
                for key in sorted(counters[name]):
                    lines.append(f"{name}{_label_str(key)} "
                                 f"{_fmt(counters[name][key])}")
            elif name in gauges:
                lines.append(f"# TYPE {name} gauge")
                for key in sorted(gauges[name]):
                    lines.append(f"{name}{_label_str(key)} "
                                 f"{_fmt(gauges[name][key])}")
            else:
                lines.append(f"# TYPE {name} histogram")
                bnds = bounds[name]
                for key in sorted(hists[name]):
                    # observe() increments every bucket the value fits
                    # under, so cells are already cumulative (le= form)
                    cell = hists[name][key]
                    for i, b in enumerate(bnds):
                        lines.append(
                            f"{name}_bucket"
                            f"{_label_str(key + (('le', _fmt(b)),))} "
                            f"{cell[i]}")
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str(key + (('le', '+Inf'),))} "
                        f"{cell[-1]}")
                    lines.append(f"{name}_sum{_label_str(key)} "
                                 f"{_fmt(cell[-2])}")
                    lines.append(f"{name}_count{_label_str(key)} "
                                 f"{cell[-1]}")
        return "\n".join(lines) + "\n" if lines else ""


# ---------------------------------------------------------------------------
# process-wide slot (mirrors telemetry's _ACTIVE contract)
# ---------------------------------------------------------------------------

_REGISTRY: Optional[MetricsRegistry] = None


def enabled() -> bool:
    """The ``BIGDL_TPU_METRICS`` knob: may the HTTP servers arm the
    plane at startup?  (Library use never arms implicitly.)"""
    return config.get_str("METRICS", "1").strip() not in ("0", "false", "")


def armed() -> bool:
    return _REGISTRY is not None


def registry() -> Optional[MetricsRegistry]:
    """The armed registry or None — instrumented code's fast path."""
    return _REGISTRY


def arm() -> MetricsRegistry:
    """Create (idempotently) and return the process-wide registry."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = MetricsRegistry()
    return _REGISTRY


def disarm() -> None:
    """Drop the registry (tests; restores the zero-overhead mode)."""
    global _REGISTRY
    _REGISTRY = None


# ---------------------------------------------------------------------------
# fleet rollup: parse member expositions, re-export with member labels
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_exposition(text: str) -> Dict[str, dict]:
    """Parse Prometheus text exposition into
    ``{metric: {"type": str, "samples": [(sample_name, labels, value)]}}``
    — ``sample_name`` keeps the ``_bucket``/``_sum``/``_count`` suffix so
    a rollup can re-emit histograms faithfully."""
    metrics: Dict[str, dict] = {}
    current_type: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                current_type[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        sample_name, label_blob, raw = m.groups()
        base = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix) and \
                    sample_name[:-len(suffix)] in current_type:
                base = sample_name[:-len(suffix)]
                break
        labels = tuple(_LABEL_RE.findall(label_blob or ""))
        try:
            value = float(raw.replace("+Inf", "inf"))
        except ValueError:
            continue
        entry = metrics.setdefault(
            base, {"type": current_type.get(base, "untyped"),
                   "samples": []})
        entry["samples"].append((sample_name, labels, value))
    return metrics


def render_rollup(own_text: str,
                  member_texts: Dict[str, str]) -> str:
    """The fleet front's ``/metrics`` body: its own exposition followed by
    every member's samples re-labelled ``member="<idx>"`` under a
    ``fleet_`` prefix, plus a fleet-wide sum per counter/histogram
    series (gauges get per-member samples only — summing queue depths is
    meaningful, summing fill fractions is not, so the aggregate is left
    to the scraper)."""
    lines = [own_text.rstrip("\n")] if own_text.strip() else []
    merged: Dict[str, dict] = {}
    for idx in sorted(member_texts):
        for base, entry in parse_exposition(member_texts[idx]).items():
            slot = merged.setdefault(
                base, {"type": entry["type"], "per_member": [],
                       "sums": {}})
            for sample_name, labels, value in entry["samples"]:
                slot["per_member"].append(
                    (sample_name, labels + (("member", str(idx)),), value))
                if entry["type"] in ("counter", "histogram"):
                    key = (sample_name, labels)
                    slot["sums"][key] = slot["sums"].get(key, 0.0) + value
    for base in sorted(merged):
        slot = merged[base]
        lines.append(f"# TYPE fleet_{base} {slot['type']}")
        for key in sorted(slot["sums"]):
            sample_name, labels = key
            lines.append(f"fleet_{sample_name}{_label_str(labels)} "
                         f"{_fmt(slot['sums'][key])}")
        for sample_name, labels, value in slot["per_member"]:
            lines.append(f"fleet_{sample_name}{_label_str(labels)} "
                         f"{_fmt(value)}")
    return "\n".join(lines) + "\n" if lines else ""
