"""DirectedGraph / Node: the DAG backbone of the Graph container and model import.

Reference: BigDL `utils/DirectedGraph.scala:34,135` — `Node[T]` with edge ops
(`->`: :155), `topologySort` (:52), `DFS` (:85), `BFS` (:108).
"""

from __future__ import annotations

from collections import deque
from typing import Any, List

__all__ = ["Node", "DirectedGraph"]


class Node:
    """Graph node holding an `element` (DirectedGraph.scala:135)."""

    def __init__(self, element: Any):
        self.element = element
        self.prev_nodes: List["Node"] = []
        self.next_nodes: List["Node"] = []

    def point_to(self, other: "Node") -> "Node":
        """Add edge self -> other (reference's `->`, DirectedGraph.scala:155)."""
        self.next_nodes.append(other)
        other.prev_nodes.append(self)
        return other

    __gt__ = point_to  # a > b adds edge a->b

    def __repr__(self):
        return f"Node({self.element!r})"


class DirectedGraph:
    """DAG rooted at `source`; `reverse=True` walks prev edges
    (DirectedGraph.scala:34)."""

    def __init__(self, source: Node, reverse: bool = False):
        self.source = source
        self.reverse = reverse

    def _next(self, node: Node):
        return node.prev_nodes if self.reverse else node.next_nodes

    def _prev(self, node: Node):
        return node.next_nodes if self.reverse else node.prev_nodes

    def bfs(self):
        """Breadth-first traversal (DirectedGraph.scala:108)."""
        seen, order, q = {id(self.source)}, [], deque([self.source])
        while q:
            n = q.popleft()
            order.append(n)
            for m in self._next(n):
                if id(m) not in seen:
                    seen.add(id(m))
                    q.append(m)
        return order

    def dfs(self):
        """Depth-first traversal (DirectedGraph.scala:85)."""
        seen, order, stack = {id(self.source)}, [], [self.source]
        while stack:
            n = stack.pop()
            order.append(n)
            for m in self._next(n):
                if id(m) not in seen:
                    seen.add(id(m))
                    stack.append(m)
        return order

    def topology_sort(self):
        """Kahn topological sort of nodes reachable from source
        (DirectedGraph.scala:52); raises on cycles."""
        reachable = self.bfs()
        ids = {id(n) for n in reachable}
        indeg = {id(n): sum(1 for p in self._prev(n) if id(p) in ids)
                 for n in reachable}
        q = deque(n for n in reachable if indeg[id(n)] == 0)
        order = []
        while q:
            n = q.popleft()
            order.append(n)
            for m in self._next(n):
                if id(m) in ids:
                    indeg[id(m)] -= 1
                    if indeg[id(m)] == 0:
                        q.append(m)
        if len(order) != len(reachable):
            raise ValueError("graph contains a cycle")
        return order

    def size(self) -> int:
        return len(self.bfs())

    def edges(self) -> int:
        return sum(len(self._next(n)) for n in self.bfs())
