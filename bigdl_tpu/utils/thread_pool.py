"""ThreadPool: the host-side worker pool.

Reference: utils/ThreadPool.scala:32 — wraps an ExecutionContext with
`invoke` (async), `invokeAndWait` (:92), `invokeAndWait2` (java futures +
timeout, :106), `sync` (:176), and `setMKLThread` (:73).  BigDL used it as
`Engine.default` (framework tasks) and `Engine.model` (intra-layer work).

TPU re-design: intra-layer work belongs to XLA; the pool serves the HOST
side — data decoding, batch assembly (MTSampleToMiniBatch), checkpoint IO.
`set_native_threads` plays setMKLThread's role for the csrc/ kernels."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor, TimeoutError, wait
from typing import Callable, List, Optional, Sequence

__all__ = ["ThreadPool"]


class ThreadPool:
    def __init__(self, max_threads: int):
        self.max_threads = max_threads
        self._pool = ThreadPoolExecutor(max_workers=max_threads)

    def invoke(self, tasks: Sequence[Callable]) -> List:
        """Submit without waiting (ThreadPool.invoke :142) -> futures."""
        return [self._pool.submit(t) for t in tasks]

    def invoke_and_wait(self, tasks: Sequence[Callable],
                        timeout: Optional[float] = None) -> List:
        """Run all, return results in order (invokeAndWait :92 /
        invokeAndWait2 :106 with timeout)."""
        futures = self.invoke(tasks)
        done, not_done = wait(futures, timeout=timeout)
        if not_done:
            for f in not_done:
                f.cancel()
            raise TimeoutError(f"{len(not_done)} tasks timed out")
        return [f.result() for f in futures]

    def sync(self, futures) -> List:
        """Block on previously-invoked futures (ThreadPool.sync :176)."""
        return [f.result() for f in futures]

    def set_native_threads(self, n: int) -> "ThreadPool":
        """(reference: setMKLThread :73 — pins the native math threads)."""
        from . import native
        native.set_num_threads(n)
        return self

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)
