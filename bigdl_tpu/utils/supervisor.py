"""Training-run supervision: stall watchdog + multi-host liveness.

Reference gap this closes: BigDL inherited liveness from Spark — a dead
executor fails the synchronous job and the driver retries
(DistriOptimizer.scala:750-816) — but a compiled async backend has no
such umpire: a hung collective, a stalled tunneled RPC, or a dead peer
process hangs training *silently and forever*, the one failure mode the
checkpoint-lineage machinery (docs/robustness.md) cannot reach because
no exception is ever raised.  TF's supervisor/monitored-session design
(arxiv 1605.08695) shows the shape reproduced here: phase-tagged
heartbeats, per-phase deadlines, and a diagnostic dump on stall.

Core pieces
-----------
- :class:`Supervisor`: a daemon monitor thread watching phase-tagged
  heartbeats (``beat("data"|"step"|"checkpoint"|"validation")``) from the
  supervised loop.  Per-phase deadlines come from the constructor or the
  ``BIGDL_TPU_SUPERVISE_<PHASE>`` / ``_SUPERVISE_DEADLINE`` env knobs;
  the clock is injectable (like ``BIGDL_TPU_IO_*``'s timebase) so tests
  run wall-clock-free.
- On a missed deadline the supervisor writes a JSON **crash report**
  (all-thread stack dumps via ``sys._current_frames`` — plus a
  best-effort ``faulthandler`` dump for local dirs — the heartbeat
  timeline, ``chaos.counts()``, platform info, stale peers) next to the
  checkpoint dir via ``file_io`` (works on local, ``memory://``, any
  fsspec scheme), then acts per policy:

  * ``raise`` (default): async-raises a typed :class:`StallError` into
    the supervised thread (the most recent beater), which lands in the
    optimizer's existing retry machinery — recovery resumes from the
    checkpoint lineage.  The raise takes effect at the next Python
    bytecode; a backend wedged inside one C call never reaches one,
    which is what ``exit`` is for.
  * ``exit``: ``os._exit(86)`` after the report — for wedged backends
    where Python can't unwind (utils/timing.py documents exactly such a
    backend: ``block_until_ready`` returns while the RPC never does).
  * ``on_stall`` callback: the embedder owns the response (bench.py's
    emit-partial-results-and-exit watchdog is this supervisor with a
    callback — one liveness mechanism, not two).

- Auxiliary **channels** (:meth:`Supervisor.channel`): background workers
  of the supervised loop — the input-pipeline prefetch thread
  (dataset/prefetch.py) — heartbeat their own slot, watched against the
  same per-phase deadlines.  A stalled worker trips its phase deadline
  even while the main thread is busy inside a step (and a busy worker
  can never mask a stalled main loop); the StallError is async-raised
  into the WORKER, which forwards it to the consumer's ``next()``.

- Multi-host liveness: each process publishes a heartbeat file
  (``<peer_dir>/heartbeat.<rank>``, JSON with the last beat's wall time
  AND the monitor's publication wall time) through ``file_io``; every
  supervisor flags peers whose BEATS go stale
  (``BIGDL_TPU_SUPERVISE_PEER_STALE`` seconds), so an eternal allgather
  hang dies with "host 3 last seen 94s ago" in the crash report instead
  of hanging forever.  Publication happens from the MONITOR thread but
  stamps the supervised thread's last-beat time — a stalled rank goes
  stale on its peers even while its monitor lives.  Publication is
  best-effort and RETRIED: a transient store flake is counted
  (``heartbeat_errors``) and re-attempted on the next poll, never
  allowed to kill the monitor or silently stop beats.

- Elastic host-loss promotion (parallel/elastic): with
  ``BIGDL_TPU_ELASTIC_PEER_LOST`` armed, a peer whose *publication*
  (not just beats — a compiling or wedged rank still publishes) goes
  silent past that threshold is promoted to a typed ``PeerLostError``
  async-raised into the supervised thread, and an epoch-stamped
  ``elastic/recover.<rank>`` intent file is published so the other
  survivors converge on their next poll.  The optimizer's retry loop
  turns that into negotiate -> re-form -> resume (docs/robustness.md).

Knobs (utils/config tier):

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_SUPERVISE_DATA/_STEP/_CHECKPOINT/_VALIDATION`` | per-phase deadline seconds (0 = unwatched) | 0 |
| ``BIGDL_TPU_SUPERVISE_DEADLINE`` | default deadline for phases without their own | 0 |
| ``BIGDL_TPU_SUPERVISE_POLICY`` | ``raise`` or ``exit`` | raise |
| ``BIGDL_TPU_SUPERVISE_PEER_STALE`` | peer heartbeat (beat-age) staleness threshold, seconds | 60 |
| ``BIGDL_TPU_ELASTIC_PEER_LOST`` | publication-silence seconds promoting a peer to LOST (0 = off) | 0 |
| ``BIGDL_TPU_ELASTIC_REFORM_GRACE`` | post-reform seconds during which silence is NOT promoted to loss (members recompile their jitted step after every shrink/grow) | 2 |
"""

from __future__ import annotations

import collections
import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from . import chaos, config

logger = logging.getLogger("bigdl_tpu")

__all__ = ["StallError", "Supervisor", "PHASES", "notify", "set_active",
           "get_active", "env_deadlines"]

#: the optimizer loop's heartbeat phases.  "compile" tags the FIRST step
#: of each attempt (it holds the XLA compile — ~25s for LeNet on a TPU
#: backend — and must not false-trip a tight steady-state "step"
#: deadline); it is unwatched unless given its own deadline.  "serve" is
#: the online inference subsystem's replica-worker phase
#: (serve/server.py — each replica heartbeats its own channel).
PHASES = ("data", "step", "compile", "checkpoint", "validation", "serve")

# PyThreadState_SetAsyncExc raises the exception CLASS with no args in the
# target thread; the class pulls its message from here so the StallError
# the optimizer catches still names the phase/deadline/stale peers.
_LAST_STALL = {"message": None}


class StallError(RuntimeError):
    """A supervision deadline was missed: the run is hung, not crashed.

    Raised (asynchronously) into the supervised thread so the optimizer's
    retry loop treats the hang like any transient failure — recover from
    the checkpoint lineage and continue."""

    def __init__(self, *args):
        if not args and _LAST_STALL["message"]:
            args = (_LAST_STALL["message"],)
        super().__init__(*args or
                         ("training run stalled (supervision deadline "
                          "missed)",))


def _async_raise(thread_id: int, exc_class) -> bool:
    """Schedule `exc_class` to be raised in `thread_id` at its next
    bytecode boundary (CPython PyThreadState_SetAsyncExc)."""
    import ctypes
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(exc_class))
    if res > 1:  # delivered to >1 thread state: undo, report failure
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_id), None)
        return False
    return res == 1


def env_deadlines():
    """(per-phase deadlines dict, default deadline or None) from the
    ``BIGDL_TPU_SUPERVISE_*`` env knobs."""
    deadlines = {}
    for phase in PHASES:
        v = config.get_float("SUPERVISE_" + phase.upper(), 0.0)
        if v > 0:
            deadlines[phase] = v
    default = config.get_float("SUPERVISE_DEADLINE", 0.0)
    return deadlines, (default if default > 0 else None)


# process-default supervisor: low-level helpers (utils/timing's measure
# loops) refresh it via notify() without threading a handle through every
# call chain — tunneled-TPU benches get stall coverage for free
_ACTIVE: Optional["Supervisor"] = None


def set_active(sup: Optional["Supervisor"]) -> None:
    global _ACTIVE
    _ACTIVE = sup


def get_active() -> Optional["Supervisor"]:
    return _ACTIVE


def notify(phase: Optional[str] = None) -> None:
    """Heartbeat the process-default supervisor (no-op when none is
    active).  phase=None refreshes the current phase's timer without
    changing it — the generic progress-callback semantic."""
    sup = _ACTIVE
    if sup is not None:
        sup.beat(phase)


class _Channel:
    """Heartbeat handle for one auxiliary supervised thread (see
    Supervisor.channel).  beat(None) refreshes the timer without changing
    the phase; close() retires the slot (idempotent)."""

    __slots__ = ("_sup", "name")

    def __init__(self, sup: "Supervisor", name: str):
        self._sup = sup
        self.name = name

    def beat(self, phase: Optional[str] = None) -> None:
        self._sup._beat_channel(self.name, phase)

    def close(self) -> None:
        self._sup._close_channel(self.name)


def _platform_info() -> dict:
    """Best-effort environment snapshot for the crash report.  Must never
    touch the backend (jax.devices() can hang — it may be WHY we are
    here); only already-materialized facts."""
    import platform as _platform
    info = {"python": sys.version.split()[0],
            "platform": _platform.platform(),
            "pid": os.getpid(),
            "jax_platforms_env": os.environ.get("JAX_PLATFORMS")}
    jx = sys.modules.get("jax")
    if jx is not None:
        info["jax"] = getattr(jx, "__version__", "?")
    return info


class Supervisor:
    """Phase-tagged heartbeat watchdog with per-phase deadlines.

    Usage (the Optimizer wires this automatically when supervision is
    configured)::

        sup = Supervisor({"step": 120, "data": 60}, report_dir=ckpt_dir)
        sup.start()
        ...
        sup.beat("data"); batch = next(it)
        sup.beat("step"); loss = step(batch)
        ...
        sup.stop()

    Deadline lookup: exact phase name, else the prefix before ``:``
    (bench stages like ``compile:resnet50``), else `default_deadline`;
    None/0 means the phase is unwatched."""

    def __init__(self, deadlines: Optional[Dict[str, float]] = None,
                 default_deadline: Optional[float] = None, *,
                 report_dir: Optional[str] = None,
                 policy: Optional[str] = None,
                 on_stall: Optional[Callable[[dict], bool]] = None,
                 poll_interval: Optional[float] = None,
                 clock=None, sleep=None, wall_clock=None,
                 peer_dir: Optional[str] = None,
                 rank: int = 0, world: int = 1,
                 peer_stale: Optional[float] = None,
                 publish_interval: Optional[float] = None,
                 peer_lost: Optional[float] = None,
                 lineage_dir: Optional[str] = None,
                 on_peer_stale: Optional[Callable[[int, float],
                                                  None]] = None,
                 on_peer_returned: Optional[Callable[[int, int],
                                                     None]] = None,
                 generation: int = 0,
                 name: str = "bigdl-supervisor",
                 timeline_len: int = 64):
        self.deadlines = dict(deadlines or {})
        self.default_deadline = default_deadline
        self.report_dir = report_dir
        self.policy = policy or config.get_str("SUPERVISE_POLICY", "raise")
        if self.policy not in ("raise", "exit"):
            # a typo'd policy silently reverting to 'raise' would leave a
            # wedged backend hanging — exactly what 'exit' exists for
            raise ValueError(f"supervisor: unknown policy {self.policy!r} "
                             "(expected 'raise' or 'exit')")
        self.on_stall = on_stall
        self.clock = clock or time.monotonic
        self.wall_clock = wall_clock or time.time
        self.poll_interval = poll_interval
        self.peer_dir = peer_dir
        self.rank, self.world = int(rank), int(world)
        self.peer_stale = (peer_stale if peer_stale is not None
                           else config.get_float("SUPERVISE_PEER_STALE",
                                                 60.0))
        self.publish_interval = publish_interval
        # elastic host-loss promotion (parallel/elastic): peer_lost is the
        # PUBLICATION-silence threshold (0 = off); elastic_dir holds the
        # recover.<rank>/lineage.<rank> protocol files (usually
        # <ckpt>/elastic); on_peer_stale fires once per peer per stale
        # episode (programmatic access beside the log line)
        self.peer_lost = (peer_lost if peer_lost is not None
                          else config.get_float("ELASTIC_PEER_LOST", 0.0))
        # detection grace after every re-form: all members tear down and
        # recompile their jitted step right after a shrink/grow, and a
        # compile can starve the monitor thread past a tight peer_lost
        # threshold — silence inside this window is rebuild, not death
        self.reform_grace = config.get_float("ELASTIC_REFORM_GRACE", 2.0)
        self._promotion_grace_until = 0.0
        #: the CHECKPOINT/lineage dir whose `elastic/` subdir carries the
        #: recovery protocol files (parallel/elastic.elastic_dir)
        self.lineage_dir = lineage_dir
        self.on_peer_stale = on_peer_stale
        # on_peer_returned fires ONCE per returned-peer episode (mirror of
        # on_peer_stale): a rank recovered away from has published a
        # heartbeat with a HIGHER generation than the frozen one it left
        # behind — it wants back in (parallel/elastic grow).  `generation`
        # is stamped into this rank's own heartbeat blob; a joiner bumps
        # it past its previous life's so survivors can tell "came back"
        # from "stale file".
        self.on_peer_returned = on_peer_returned
        self.generation = int(generation)
        self.elastic_epoch = 0      # completed elastic recovery rounds
        self.heartbeat_errors = 0   # failed (retried) heartbeat publishes
        self._publish_suspended = False
        # ranks already recovered away from -> the heartbeat generation
        # last seen from them (membership test unchanged; the value is
        # what a RETURN must exceed)
        self._lost_peers: Dict[int, int] = {}
        self._returned_peers: Dict[int, int] = {}
        self._peer_gens: Dict[int, int] = {}
        self._peer_lost_pending = False
        self._lost_candidates: Dict[int, float] = {}
        self.name = name
        self._lock = threading.Lock()
        self._timeline = collections.deque(maxlen=timeline_len)
        self._count = 0
        self._last = ("init", self.clock())
        self._thread_id = threading.get_ident()
        # auxiliary supervised threads (e.g. the input-pipeline prefetch
        # worker): name -> [phase, last_beat, thread_id, beat_count].
        # Kept OUT of the main slot/timeline so a worker's liveness can
        # never mask a stalled main loop (and vice versa) — every channel
        # is checked against the deadlines independently.
        self._channels: Dict[str, list] = {}
        self._chan_seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_publish = None
        self._stale_peers: Dict[int, float] = {}
        self.reports = []   # crash-report paths written by this instance
        self.stalls = 0     # deadlines missed

    # -- heartbeats -----------------------------------------------------

    def beat(self, phase: Optional[str] = None) -> None:
        """Record liveness.  `phase` tags what the supervised thread is
        about to do; None keeps the current phase (pure refresh).  The
        most recent beater is the thread a ``raise``-policy stall
        targets."""
        now = self.clock()
        with self._lock:
            if phase is None:
                phase = self._last[0]
            self._last = (phase, now)
            self._count += 1
            self._timeline.append((phase, self._count, now,
                                   self.wall_clock()))
            self._thread_id = threading.get_ident()

    def channel(self, name: str, phase: str = "data") -> "_Channel":
        """Register an auxiliary supervised thread (e.g. the prefetch
        worker, utils/../dataset/prefetch.py) under its own heartbeat
        slot.  The channel's phase is watched against the same per-phase
        deadlines as the main slot, and a missed deadline async-raises
        the StallError into the CHANNEL's thread — which forwards it to
        the consumer (the prefetcher re-raises at ``next()``), landing in
        the retry loop exactly like a main-thread stall.  ``close()`` the
        returned handle when the worker retires, or its silence would
        read as a stall."""
        with self._lock:
            self._chan_seq += 1
            key = f"{name}#{self._chan_seq}"
            self._channels[key] = [phase, self.clock(), None, 0]
        return _Channel(self, key)

    def _beat_channel(self, key: str, phase: Optional[str]) -> None:
        now = self.clock()
        with self._lock:
            st = self._channels.get(key)
            if st is None:
                return
            st[0] = phase if phase is not None else st[0]
            st[1] = now
            st[2] = threading.get_ident()
            st[3] += 1

    def _close_channel(self, key: str) -> None:
        with self._lock:
            self._channels.pop(key, None)

    def deadline_for(self, phase: str) -> Optional[float]:
        if phase in self.deadlines:
            return self.deadlines[phase]
        root = phase.split(":", 1)[0]
        if root in self.deadlines:
            return self.deadlines[root]
        return self.default_deadline

    def set_deadlines(self, default: Optional[float] = None,
                      phases: Optional[Dict[str, float]] = None) -> None:
        """Reconfigure deadlines (bench installs its stage limits here)."""
        if default is not None:
            self.default_deadline = default
        if phases:
            self.deadlines.update(phases)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "Supervisor":
        if self._thread is not None:
            return self
        self._stop.clear()
        with self._lock:  # a stale pre-start beat must not fire instantly
            self._last = (self._last[0], self.clock())
        if self.poll_interval is None:
            cands = [d for d in (*self.deadlines.values(),
                                 self.default_deadline) if d]
            if self.peer_lost > 0 and self.peer_dir and self.world > 1:
                # elastic detection must poll fast enough to notice a
                # publication-silent peer well inside the threshold
                cands.append(self.peer_lost)
            self.poll_interval = (min(max(min(cands) / 4.0, 0.05), 10.0)
                                  if cands else 1.0)
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name=self.name)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._thread = None
        if get_active() is self:
            set_active(None)

    def __enter__(self) -> "Supervisor":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- the monitor ----------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_interval):
            # each sub-duty individually guarded: a broken peer listing or
            # report write must not skip the deadline checks (or vice
            # versa) — the watchdog outlives any single failure
            try:
                self._publish_heartbeat()
            except Exception:  # noqa: BLE001
                self.heartbeat_errors += 1
                logger.warning("supervisor: heartbeat publish errored "
                               "(non-fatal, will retry)", exc_info=True)
            stale: Dict[int, float] = {}
            try:
                stale = self._check_peers(log=True)
            except Exception:  # noqa: BLE001
                logger.exception("supervisor peer check error (non-fatal)")
            try:
                self._check_elastic(stale)
            except Exception:  # noqa: BLE001
                logger.exception("supervisor elastic check error "
                                 "(non-fatal)")
            try:
                now = self.clock()
                # auxiliary channels first: a stalled input-pipeline
                # worker is the CAUSE of the main thread's stale data
                # wait, so its raise (forwarded through the prefetcher's
                # queue) should own the recovery
                chan_fired_phase = None
                with self._lock:
                    chans = [(k, st[0], st[1], st[2])
                             for k, st in self._channels.items()]
                for key, phase, t, tid in chans:
                    deadline = self.deadline_for(phase)
                    if not deadline or now - t <= deadline:
                        continue
                    if self._handle_stall(phase, now - t, deadline,
                                          channel=key, channel_tid=tid):
                        return
                    chan_fired_phase = phase
                with self._lock:
                    phase, t = self._last
                    if chan_fired_phase is not None and \
                            phase.split(":", 1)[0] == chan_fired_phase:
                        # the main slot's wait is downstream of the
                        # channel stall just handled — give it a full
                        # deadline of grace instead of double-raising
                        self._last = (phase, self.clock())
                        continue
                deadline = self.deadline_for(phase)
                if not deadline:
                    continue
                idle = self.clock() - t
                if idle <= deadline:
                    continue
                if self._handle_stall(phase, idle, deadline):
                    return
            except Exception:  # noqa: BLE001 — the watchdog must outlive
                # any single broken report write / peer listing
                logger.exception("supervisor monitor error (non-fatal)")

    def _handle_stall(self, phase: str, idle: float, deadline: float,
                      channel: Optional[str] = None,
                      channel_tid: Optional[int] = None) -> bool:
        """Deadline missed: report, then act per callback/policy.
        Returns True when monitoring should stop."""
        self.stalls += 1
        stale = self._check_peers(log=False)
        where = f"phase {phase!r}" if channel is None else \
            f"phase {phase!r} (worker channel {channel!r})"
        msg = (f"supervisor[{self.name}]: {where} made no progress "
               f"for {idle:.1f}s (deadline {deadline:.1f}s)")
        if stale:
            msg += "; stale peers: " + ", ".join(
                f"host {r} last seen {age:.0f}s ago"
                for r, age in sorted(stale.items()))
        report_path = self._write_report(phase, idle, deadline, stale, msg)
        logger.error("%s%s", msg,
                     f" (crash report: {report_path})" if report_path
                     else "")
        if self.on_stall is not None:
            stall = {"phase": phase, "idle_seconds": round(idle, 1),
                     "deadline_seconds": deadline, "report": report_path,
                     "stale_peers": stale, "message": msg}
            if channel is not None:
                stall["channel"] = channel
            self._reset_timer(phase, channel)  # grace before any re-fire
            return bool(self.on_stall(stall))
        if self.policy == "exit":
            # the supervised thread is presumed wedged in C (Python can't
            # unwind) — flush what we can and leave; the NEXT incarnation
            # recovers via the checkpoint lineage
            logger.error("supervisor: policy=exit — hard-exiting the "
                         "wedged process (crash report: %s)", report_path)
            try:
                for h in logger.handlers:
                    h.flush()
                sys.stderr.flush()
            except Exception:  # noqa: BLE001
                pass
            os._exit(86)
        # reset the timer so recovery (which beats no phases until it
        # re-enters the loop) gets a full deadline of grace before the
        # supervisor can declare a second stall
        self._reset_timer(phase, channel)
        with self._lock:
            tid = (channel_tid if channel_tid is not None
                   else self._thread_id)
        _LAST_STALL["message"] = msg
        if not _async_raise(tid, StallError):
            logger.error("supervisor: could not deliver StallError to "
                         "thread %s (already exited?)", tid)
        return False

    def _reset_timer(self, phase: str, channel: Optional[str]) -> None:
        with self._lock:
            if channel is None:
                self._last = (phase, self.clock())
            elif channel in self._channels:
                self._channels[channel][1] = self.clock()

    # -- crash report ---------------------------------------------------

    def crash_report(self, phase: str, idle: float, deadline: float,
                     stale: Optional[Dict[int, float]] = None,
                     reason: Optional[str] = None) -> dict:
        """The diagnostic dump: every thread's stack, the heartbeat
        timeline, chaos counters, platform info, stale peers."""
        now = self.clock()
        names = {t.ident: t.name for t in threading.enumerate()}
        threads = {}
        for tid, frame in sys._current_frames().items():
            label = f"{names.get(tid, '?')} (tid {tid})"
            threads[label] = [l.rstrip("\n")
                              for l in traceback.format_stack(frame)]
        with self._lock:
            timeline = [{"phase": p, "count": c,
                         "age_seconds": round(now - t, 3), "time": w}
                        for p, c, t, w in self._timeline]
            channels = {k: {"phase": st[0],
                            "age_seconds": round(now - st[1], 3),
                            "beats": st[3]}
                        for k, st in self._channels.items()}
        report = {"reason": reason or f"phase {phase!r} stalled",
                  "phase": phase,
                  "idle_seconds": round(idle, 3),
                  "deadline_seconds": deadline,
                  "time": self.wall_clock(),
                  "rank": self.rank, "world": self.world,
                  "timeline": timeline,
                  "channels": channels,
                  "threads": threads,
                  "chaos_counts": chaos.counts(),
                  "stale_peers": {str(r): round(a, 1)
                                  for r, a in (stale or {}).items()},
                  "platform": _platform_info()}
        # run telemetry (utils/telemetry): the recent span/event tail shows
        # what the run was DOING in the seconds before the hang — embedded
        # here so the diagnosis survives even if the trace file is lost
        from . import telemetry
        tracer = telemetry.get_active()
        if tracer is not None:
            report["trace_tail"] = tracer.events_tail(64)
        return report

    def _write_report(self, phase, idle, deadline, stale, msg):
        # flush-on-crash: the trace file on storage must include the
        # events leading into the stall, not just the last periodic flush
        from . import telemetry
        tracer = telemetry.get_active()
        if tracer is not None:
            try:
                tracer.instant("stall", cat="supervisor", phase=phase,
                               idle_seconds=round(idle, 1))
                tracer.flush()
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                pass
        report = self.crash_report(phase, idle, deadline, stale, msg)
        data = json.dumps(report, indent=2, default=str).encode()
        if not self.report_dir:
            # no dir configured: the diagnostics still must not vanish
            logger.error("supervisor crash report (no report dir "
                         "configured):\n%s", data.decode(errors="replace"))
            return None
        from . import file_io
        base = file_io._strip_file_scheme(str(self.report_dir))
        path = file_io._join(
            base, f"crash_report-r{self.rank}-{self.stalls}.json")
        try:
            fs = file_io.get_filesystem(base)
            fs.makedirs(base)
            fs.write_bytes(path, data)
        except Exception as e:  # noqa: BLE001 — a broken report store must
            # not mask the stall itself
            logger.error("supervisor: crash report write to %s failed "
                         "(%s); dumping inline:\n%s", path, e,
                         data.decode(errors="replace"))
            return None
        # best-effort native-level dump beside the JSON (local dirs only:
        # faulthandler needs a real fd) — catches frames the pure-Python
        # walk cannot see
        try:
            import faulthandler
            if os.path.isdir(base):
                with open(path + ".stacks.txt", "w") as f:
                    faulthandler.dump_traceback(file=f, all_threads=True)
        except Exception:  # noqa: BLE001
            pass
        self.reports.append(path)
        return path

    # -- multi-host liveness --------------------------------------------

    def _heartbeat_path(self, rank: int) -> str:
        from . import file_io
        return file_io._join(file_io._strip_file_scheme(str(self.peer_dir)),
                             f"heartbeat.{rank}")

    def suspend_heartbeat(self) -> None:
        """Stop publishing liveness (the ``host.lost`` chaos drill:
        peers must see this rank go publication-silent)."""
        self._publish_suspended = True

    def resume_heartbeat(self) -> None:
        """Re-enable liveness publication — the JOINER path: a returning
        rank stays publication-silent until its announcement has cleaned
        the previous life's files and bumped the generation
        (parallel/elastic.announce_join), then resumes beating."""
        self._publish_suspended = False
        self._last_publish = None   # publish on the very next poll

    def hold_elastic(self) -> None:
        """Disable host-loss promotion until the next :meth:`reform` —
        the JOINER path: a rank gating on the cluster's checkpoint
        stream / awaiting admission is not yet a member and must not
        initiate a shrink of it (a transiently slow survivor heartbeat
        would otherwise read as a loss)."""
        self._peer_lost_pending = True

    def _publish_heartbeat(self) -> None:
        """Publish this process's last-beat wall time.  Runs on the
        MONITOR thread but stamps the SUPERVISED thread's last beat, so a
        stalled rank goes stale on its peers even while its monitor keeps
        publishing; the blob ALSO carries the monitor's own publication
        time (``published``) — the elastic host-LOST signal, which a
        merely-stalled or long-compiling rank keeps fresh.

        Best-effort with retry: a transient store failure is counted in
        ``heartbeat_errors`` and the publish re-attempted on the NEXT
        monitor poll (``_last_publish`` only advances on success) — one
        flake can delay a beat, never silently end liveness."""
        if not self.peer_dir or self.world <= 1 or self._publish_suspended:
            return
        now = self.clock()
        interval = self.publish_interval
        if interval is None:
            interval = max(self.peer_stale / 4.0, 0.5)
            if self.peer_lost > 0:
                # elastic-armed: publication age is the host-LOST signal,
                # so publishes must land well inside that threshold — the
                # 0.5s floor alone leaves no margin under a sub-second
                # peer_lost (a scheduling hiccup reads as a dead host)
                interval = min(interval, self.peer_lost / 4.0)
        if self._last_publish is not None and \
                now - self._last_publish < interval:
            return
        with self._lock:
            phase, _ = self._last
            count = self._count
            last_wall = (self._timeline[-1][3] if self._timeline
                         else self.wall_clock())
        blob = json.dumps({"rank": self.rank, "phase": phase,
                           "count": count, "time": last_wall,
                           "published": self.wall_clock(),
                           "generation": self.generation}).encode()
        path = self._heartbeat_path(self.rank)
        try:
            from . import file_io
            fs = file_io.get_filesystem(path)
            fs.makedirs(file_io._strip_file_scheme(str(self.peer_dir)))
            fs.write_bytes(path, blob)
        except Exception as e:  # noqa: BLE001 — liveness publication is
            # best-effort; a broken heartbeat store must not kill training
            self.heartbeat_errors += 1
            logger.warning("supervisor: heartbeat publish to %s failed "
                           "(%d so far; retrying next poll): %s",
                           path, self.heartbeat_errors, e)
            return
        self._last_publish = now

    def check_peers(self) -> Dict[int, float]:
        """rank -> seconds-since-last-beat for every peer whose heartbeat
        file is stale (public entry for tests/tools)."""
        return dict(self._check_peers(log=False))

    def stale_peers(self) -> Dict[int, float]:
        """The most recent peer-staleness observation (rank -> beat age,
        seconds) WITHOUT re-listing the store — the programmatic
        accessor beside the log line; refreshed every monitor poll."""
        with self._lock:
            return dict(self._stale_peers)

    def lost_peers(self) -> Dict[int, float]:
        """Peers whose heartbeat PUBLICATION is silent past the elastic
        ``peer_lost`` threshold (rank -> publication age, seconds) — the
        host-loss candidates, as of the last monitor poll."""
        with self._lock:
            return dict(self._lost_candidates)

    def _check_peers(self, log: bool) -> Dict[int, float]:
        # a world shrunk to 1 has no live peers to age-check, but lost
        # peers' frozen heartbeats must STAY watched: a returning rank
        # announces its next life there (parallel/elastic grow)
        if not self.peer_dir or (self.world <= 1 and not self._lost_peers):
            return {}
        from . import file_io
        base = file_io._strip_file_scheme(str(self.peer_dir))
        try:
            fs = file_io.get_filesystem(base)
            names = fs.listdir(base)
        except Exception:  # noqa: BLE001 — dir may not exist yet
            return {}
        now = self.wall_clock()
        stale = {}
        lost = {}
        for name in names:
            head, _, tail = name.rpartition(".")
            if head != "heartbeat" or not tail.isdigit():
                continue
            rank = int(tail)
            if rank == self.rank:
                continue
            if rank in self._lost_peers:
                # peers already recovered away from (elastic reform) keep
                # their final heartbeat file forever — not news, UNLESS a
                # HIGHER generation shows up: the rank's next life
                # announcing itself (parallel/elastic grow)
                if log:
                    self._check_returned(rank, fs)
                continue
            try:
                hb = json.loads(fs.read_bytes(self._heartbeat_path(rank)))
                age = now - float(hb["time"])
                # pre-elastic heartbeat blobs have no 'published' stamp:
                # fall back to the beat time (conservative — more lost)
                pub_age = now - float(hb.get("published", hb["time"]))
            except Exception:  # noqa: BLE001 — a torn heartbeat write is
                # transient; the next publish replaces it
                continue
            with self._lock:
                # remember each live peer's generation: on a loss it is
                # the baseline a RETURN must exceed
                self._peer_gens[rank] = int(hb.get("generation", 0))
            if self.peer_lost > 0 and pub_age > self.peer_lost:
                lost[rank] = pub_age
            if age > self.peer_stale:
                stale[rank] = age
                if log and rank not in self._stale_peers:
                    logger.warning(
                        "supervisor: peer host %d heartbeat is stale — "
                        "last seen %.0fs ago (phase %r); its collectives "
                        "will hang every rank", rank, age, hb.get("phase"))
                    if self.on_peer_stale is not None:
                        try:
                            self.on_peer_stale(rank, age)
                        except Exception:  # noqa: BLE001 — observer only
                            logger.exception("on_peer_stale callback "
                                             "failed (non-fatal)")
        if log and stale:
            # stragglers-about-to-die on the run timeline: one counter
            # sample per stale peer per poll (no-op when tracing is off)
            from . import telemetry
            telemetry.counter("peers", **{f"stale_age_r{r}": round(a, 3)
                                          for r, a in stale.items()})
        with self._lock:
            self._stale_peers = stale
            self._lost_candidates = lost
        return stale

    def _check_returned(self, rank: int, fs) -> None:
        """Detect a lost peer's RETURN: its heartbeat generation exceeds
        the one its previous life left behind.  Observation only (plus
        the once-per-episode ``on_peer_returned`` callback) — admission
        happens at the optimizer's next checkpoint boundary, never from
        the monitor thread."""
        try:
            hb = json.loads(fs.read_bytes(self._heartbeat_path(rank)))
            gen = int(hb.get("generation", 0))
        except Exception:  # noqa: BLE001 — torn write; next poll retries
            return
        with self._lock:
            if gen <= self._lost_peers.get(rank, 0) or \
                    rank in self._returned_peers:
                return
            self._returned_peers[rank] = gen
        logger.warning("supervisor: peer host %d RETURNED — heartbeat "
                       "generation %d supersedes its lost life; it can "
                       "be admitted at the next checkpoint boundary",
                       rank, gen)
        from . import telemetry
        telemetry.instant("elastic.peer_returned", cat="elastic",
                          rank=rank, generation=gen)
        if self.on_peer_returned is not None:
            try:
                self.on_peer_returned(rank, gen)
            except Exception:  # noqa: BLE001 — observer only
                logger.exception("on_peer_returned callback failed "
                                 "(non-fatal)")

    def returned_peers(self) -> Dict[int, int]:
        """Lost peers that have published a NEWER-generation heartbeat
        (rank -> generation) — returned hosts awaiting admission at the
        next checkpoint boundary; cleared by :meth:`reform`."""
        with self._lock:
            return dict(self._returned_peers)

    def peer_lost_pending(self) -> bool:
        """True between a host-loss promotion and the reform() that
        completes it — the window in which a join must be DEFERRED so
        shrink and grow re-forms never interleave."""
        return self._peer_lost_pending

    # -- elastic host-loss promotion (parallel/elastic) -----------------

    def _check_elastic(self, stale: Dict[int, float]) -> None:
        """Promote publication-silent peers into a typed PeerLostError
        (parallel/elastic step 1): stage the payload, publish the
        epoch-stamped ``elastic/recover.<rank>`` intent so slower ranks
        converge on their next poll, and async-raise into the supervised
        thread — the retry loop owns negotiate/re-form/resume."""
        if self.peer_lost <= 0 or self.world <= 1 or not self.peer_dir \
                or self._peer_lost_pending or not self.lineage_dir:
            return
        if self.clock() < self._promotion_grace_until:
            return  # post-reform rebuild window: observe, don't promote
        with self._lock:
            lost = {r: a for r, a in self._lost_candidates.items()
                    if r not in self._lost_peers}
        from ..parallel import elastic
        # fast convergence: another survivor already called this round
        intents = elastic.read_intents(
            self.lineage_dir, min_epoch=self.elastic_epoch + 1,
            exclude_rank=self.rank)
        for doc in intents.values():
            for r in doc.get("lost", []):
                if int(r) != self.rank and int(r) not in self._lost_peers:
                    lost.setdefault(int(r), 0.0)
        if not lost:
            return
        propose = max([self.elastic_epoch + 1] +
                      [int(d.get("epoch", 0)) for d in intents.values()])
        msg = (f"supervisor[{self.name}]: peer host(s) "
               f"{sorted(lost)} lost — heartbeat publication silent "
               f"{', '.join(f'{a:.0f}s (host {r})' for r, a in sorted(lost.items()))}"
               f"; starting elastic recovery round {propose}")
        try:
            elastic.publish_intent(self.lineage_dir, self.rank,
                                   propose, sorted(lost),
                                   self.wall_clock())
        except Exception:  # noqa: BLE001 — the local raise still recovers
            # this rank; peers fall back to their own thresholds
            logger.exception("supervisor: could not publish elastic "
                             "recovery intent (non-fatal)")
        from . import telemetry
        telemetry.instant("elastic.detect", cat="elastic",
                          lost=sorted(lost), epoch=propose)
        logger.error(msg)
        elastic.set_last_peer_lost(msg, sorted(lost), propose)
        self._peer_lost_pending = True
        with self._lock:
            tid = self._thread_id
        if not _async_raise(tid, elastic.PeerLostError):
            logger.error("supervisor: could not deliver PeerLostError to "
                         "thread %s (already exited?)", tid)

    def reform(self, rank: int, world: int, epoch: int,
               lost=(), returned=()) -> None:
        """Install the post-recovery topology (Optimizer._elastic_recover
        / _elastic_grow): the lost peers' frozen heartbeat files stop
        counting as news (each recorded with the generation its RETURN
        must exceed), `returned` ranks are re-admitted to the watch, the
        completed recovery round is recorded, and promotion re-arms for
        the NEXT loss."""
        with self._lock:
            self.rank, self.world = int(rank), int(world)
            for r in lost:
                self._lost_peers[int(r)] = self._peer_gens.get(int(r), 0)
            for r in returned:
                self._lost_peers.pop(int(r), None)
                self._returned_peers.pop(int(r), None)
            self._stale_peers = {r: a for r, a in self._stale_peers.items()
                                 if r not in self._lost_peers}
            self._lost_candidates = {
                r: a for r, a in self._lost_candidates.items()
                if r not in self._lost_peers}
        self.elastic_epoch = int(epoch)
        self._peer_lost_pending = False
        # every member recompiles against the new mesh now — hold the
        # next promotion until the rebuild window has passed
        self._promotion_grace_until = self.clock() + self.reform_grace
