"""Compile cards: every compiled executable self-describes its program.

The reference's ``getTimes()`` contract observes *runtime* (SURVEY §7.6 —
PR 4's tracer reproduced it); nothing observed the *compiled program*, yet
every perf claim since PR 6 is a structural property of the HLO: the
matmul conv route deletes every ``convolution`` from the train step, the
bucketed wire turns ~160 per-leaf casts/reduces into a handful of
bucket-sized ones, the fused update runs over a few dtype-homogeneous 1-D
buffers, and donation shows up as input/output aliases.  A **compile
card** pins those properties down at the moment an executable is born, so
a perf regression is a *diffable artifact*, not a hope — the MLPerf
TPU-pods work treats per-op compiled breakdowns as the primary
optimization instrument, and this is the always-on program-level
introspection TensorFlow ships for the same reason.

One card per (label, program), captured at the three compile choke points
(they all funnel through :func:`utils.aot.cached_compile` /
:func:`utils.aot.get_or_compile`):

- the Optimizer's pjit train step (``optim.optimizer._build_step``) —
  with ``card_extra`` carrying the step knobs, the wire-bucket count and
  the fused-buffer count, so structural claims about the step are in the
  card even before reading the HLO;
- Evaluator/Predictor/serve forward (``optim.optimizer._ShardedForward``)
  — the serve bucket ladder emits one card per bucket shape;
- ``bench.py``'s timed configs — each bench record embeds its card.

What a card holds (see :func:`compile_card`): the op histogram of the
**optimized HLO** text (``convolution`` / ``dot`` / ``convert`` /
all-reduce-family / ``custom-call`` counts), convert *direction* pairs
(the wire's per-bucket up-casts are distinguishable from its per-leaf
down-casts), ``cost_analysis()`` flops + bytes accessed when the backend
reports them, the ``input_output_alias`` (donation) count, the StableHLO
op histogram when the lowered computation is available, argument avals,
and the AOT cache fingerprint the executable is (or would be) stored
under.

Emission, when armed (:func:`enabled`):

- **process ledger**: :func:`cards` / :func:`stats` — the ``stats()``-
  style counter surface tests and ``InferenceServer.stats()`` read;
- **telemetry**: a ``compile.card`` instant + a ``compile`` counter track
  (convolutions / dots / converts / collectives / custom_calls /
  total_ops) on the active tracer, so ``tools/trace_report.py`` prints
  the compiled-program shape next to the runtime phases;
- **JSON artifact**: one ``card.<label>.<n>.json`` per card into the
  cards dir — ``BIGDL_TPU_COMPILE_CARDS=<dir>`` (any file_io scheme), or
  ``<trace-dir>/cards`` automatically when only tracing is armed.

Knobs:

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_COMPILE_CARDS`` | ``<dir>``: arm cards + write JSON artifacts there (any file_io scheme); ``1``: arm (ledger+telemetry only); ``0``: force off; empty: armed iff ``BIGDL_TPU_TRACE`` is set (artifacts land in ``<trace>/cards``) | "" |

Disabled (the default with tracing off) the whole module is inert: the
choke points pay one ``enabled()`` check — no HLO text is rendered, no
events, no files.  Card capture can never fail a compile: every error is
counted (``stats()["errors"]``) and logged, never raised.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("bigdl_tpu")

__all__ = ["enabled", "cards_dir", "op_histogram", "convert_pairs",
           "alias_count", "collective_count", "compile_card", "capture",
           "cards", "last_card", "stats", "reset", "write_card",
           "read_cards", "ledger"]

_FORMAT = "bigdl_tpu-compile-card-v1"

#: opcodes summed into the card's ``collectives`` count — the
#: all-reduce family GSPMD emits for gradient reduction, gathers, and
#: resharding moves
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "all-to-all", "collective-permute", "collective-broadcast")

# the in-process ledger is bounded: a long serve process warming many
# bucket ladders must not grow it without limit (oldest dropped)
_MAX_CARDS = 256

_lock = threading.Lock()
_cards: List[dict] = []
_seq = 0
_stats: Dict[str, int] = {"cards": 0, "writes": 0, "errors": 0, "dropped": 0}


# ----------------------------------------------------------------------
# arming
# ----------------------------------------------------------------------

def _knob() -> str:
    from . import config
    return config.get_str("COMPILE_CARDS", "").strip()


def enabled() -> bool:
    """True when compile cards are armed: ``BIGDL_TPU_COMPILE_CARDS`` set
    to anything but ``0``, or (with the knob empty) whenever run tracing
    (``BIGDL_TPU_TRACE``) is armed — a traced run always self-describes
    its executables."""
    k = _knob()
    if k == "0":
        return False
    if k:
        return True
    from . import telemetry
    return telemetry.enabled()


def cards_dir() -> Optional[str]:
    """Where card JSON artifacts go: the knob's dir, or ``<trace>/cards``
    beside an armed trace dir; None = no artifacts (ledger + telemetry
    only, e.g. ``BIGDL_TPU_COMPILE_CARDS=1``)."""
    k = _knob()
    if k == "0":
        return None
    if k and k != "1":
        return k
    from . import file_io, telemetry
    td = telemetry.trace_dir()
    if td:
        return file_io._join(file_io._strip_file_scheme(td), "cards")
    return None


# ----------------------------------------------------------------------
# HLO text analysis (pure functions; unit-testable without a backend)
# ----------------------------------------------------------------------

# optimized-HLO instruction: `%name = f32[8,8]{1,0} opcode(...)` — the
# result type may be a tuple `(f32[...], s32[...])`; opcodes are
# lowercase with dashes (all-reduce, custom-call)
_HLO_OP_RE = re.compile(
    r"=\s*(?:\([^)]*\)|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?)\s*"
    r"([a-z][a-z0-9\-]*)\(")
# StableHLO op: `%4 = stablehlo.convert %3 : ...`
_SHLO_OP_RE = re.compile(r"=\s*stablehlo\.([a-z_]+)")
# convert with visible operand type: `bf16[...] convert(f32[...] %x)`
_CONVERT_PAIR_RE = re.compile(
    r"=\s*([a-z0-9]+)\[[^\]]*\](?:\{[^}]*\})?\s*convert\(([a-z0-9]+)\[")
# StableHLO convert: `(tensor<8x8xf32>) -> tensor<8x8xbf16>` — the dtype
# is the trailing token after the dim prefix (`128xbf16` -> `bf16`)
_SHLO_CONVERT_RE = re.compile(
    r"stablehlo\.convert[^:]*:\s*\(tensor<(?:[0-9]+x)*([a-z][a-z0-9]*)>\)"
    r"\s*->\s*tensor<(?:[0-9]+x)*([a-z][a-z0-9]*)>")


def op_histogram(hlo_text: str) -> Dict[str, int]:
    """Opcode -> count over an HLO module text (optimized HLO or
    StableHLO, auto-detected).  Counts every instruction, including those
    inside fusion computations — a convert fused into a loop fusion is
    still a convert the backend executes."""
    hist: Dict[str, int] = {}
    matcher = (_SHLO_OP_RE if "stablehlo." in hlo_text else _HLO_OP_RE)
    for m in matcher.finditer(hlo_text):
        op = m.group(1)
        if op == "parameter":  # declarations, not work
            continue
        hist[op] = hist.get(op, 0) + 1
    return hist


def convert_pairs(hlo_text: str) -> Dict[str, int]:
    """``"<dst><-<src>" -> count`` for every convert in the text.  This is
    what separates the wire's **per-bucket up-casts** (``f32<-bf16``: one
    per bucket after concatenation) from its **per-leaf down-casts**
    (``bf16<-f32``: one per gradient leaf) — the wire-card test bounds the
    former by the bucket count, not the leaf count."""
    pairs: Dict[str, int] = {}
    if "stablehlo." in hlo_text:
        for m in _SHLO_CONVERT_RE.finditer(hlo_text):
            key = f"{m.group(2)}<-{m.group(1)}"
            pairs[key] = pairs.get(key, 0) + 1
    else:
        for m in _CONVERT_PAIR_RE.finditer(hlo_text):
            key = f"{m.group(1)}<-{m.group(2)}"
            pairs[key] = pairs.get(key, 0) + 1
    return pairs


def alias_count(hlo_text: str) -> int:
    """Number of input/output aliases in the module header — donation
    (``donate_argnums``) compiles into ``input_output_alias={ {0}: (0, {},
    may-alias), ... }``; 0 means no buffer is updated in place.  Counted
    on the header LINE (the alias spec nests braces, and `may-alias`
    tokens appear nowhere else in an HLO module)."""
    header = hlo_text.split("\n", 1)[0]
    if "input_output_alias" not in header:
        return 0
    return header.count("may-alias") + header.count("must-alias")


def collective_count(hist: Dict[str, int]) -> int:
    """Sum of the all-reduce-family opcodes in an op histogram (the ops
    ``-start``/``-done`` async pairs count once each)."""
    total = 0
    for op, n in hist.items():
        base = op[:-len("-start")] if op.endswith("-start") else op
        if base.endswith("-done"):
            continue  # the matching -start was already counted
        if base in COLLECTIVE_OPS:
            total += n
    return total


# ----------------------------------------------------------------------
# card construction + emission
# ----------------------------------------------------------------------

def compile_card(compiled=None, lowered=None, *, label: str,
                 key: Optional[str] = None, example_args=None,
                 extra: Optional[dict] = None,
                 source: str = "compile") -> dict:
    """Build a card dict for a compiled (and/or lowered) computation.

    ``compiled`` is a jax Compiled (``.as_text()`` = optimized HLO,
    ``.cost_analysis()`` when the backend supports it); ``lowered`` a jax
    Lowered (``.as_text()`` = StableHLO) — either may be None (an AOT
    cache hit through ``get_or_compile`` never lowered).  ``key`` is the
    AOT cache fingerprint the executable lives under (None when the cache
    is disabled).  ``extra`` is the caller's structural self-description
    (the train step passes its knobs + wire-bucket + fused-buffer
    counts)."""
    card: Dict[str, Any] = {"format": _FORMAT, "label": label,
                            "source": source, "aot_key": key,
                            "ts": round(time.time(), 3)}
    try:
        import jax
        card["backend"] = jax.default_backend()
        card["device_kind"] = getattr(jax.devices()[0], "device_kind", "?")
    except Exception:  # noqa: BLE001 — backend introspection is optional
        pass
    hist: Dict[str, int] = {}
    if compiled is not None:
        try:
            txt = compiled.as_text()
            hist = op_histogram(txt)
            card["ops"] = hist
            card["convert_pairs"] = convert_pairs(txt)
            aliases = alias_count(txt)
            card["input_output_aliases"] = aliases
            card["donation"] = aliases > 0
        except Exception as e:  # noqa: BLE001 — e.g. a deserialized
            # executable whose runtime refuses to re-render HLO text
            card["hlo_error"] = f"{type(e).__name__}: {e}"
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if ca:
                card["cost"] = {
                    "flops": float(ca.get("flops", 0.0)),
                    "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            pass
    if lowered is not None:
        try:
            shlo = lowered.as_text()
            card["stablehlo_ops"] = op_histogram(shlo)
            # as-WRITTEN convert directions: the optimizer may push the
            # wire's per-bucket up-cast through the split slices (per-leaf
            # again in the optimized text), so the bucket-bounded count
            # lives here, pre-optimization
            card["stablehlo_convert_pairs"] = convert_pairs(shlo)
        except Exception as e:  # noqa: BLE001
            card.setdefault("hlo_error", f"{type(e).__name__}: {e}")
    # the headline counts the perf gate diffs (derived from the optimized
    # histogram; 0s when only StableHLO was available)
    card["convolutions"] = hist.get("convolution", 0)
    card["dots"] = hist.get("dot", 0) + hist.get("dot_general", 0)
    card["converts"] = hist.get("convert", 0)
    card["collectives"] = collective_count(hist)
    card["custom_calls"] = hist.get("custom-call", 0)
    card["total_ops"] = sum(hist.values())
    if example_args is not None:
        try:
            from . import aot
            card["args"] = aot.aval_fingerprint(example_args)
        except Exception:  # noqa: BLE001
            pass
    if extra:
        card["extra"] = dict(extra)
    return card


def capture(compiled=None, lowered=None, *, label: str,
            key: Optional[str] = None, example_args=None,
            extra: Optional[dict] = None,
            source: str = "compile") -> Optional[dict]:
    """The choke-point hook: build + record a card when armed; a no-op
    returning None when disabled.  Never raises — a card must never take
    down the compile it describes."""
    if not enabled():
        return None
    try:
        card = compile_card(compiled, lowered, label=label, key=key,
                            example_args=example_args, extra=extra,
                            source=source)
    except Exception as e:  # noqa: BLE001
        logger.warning("hlostats: card capture for %s failed: %s: %s",
                       label, type(e).__name__, e)
        with _lock:
            _stats["errors"] += 1
        return None
    _record(card)
    return card


def _record(card: dict) -> None:
    global _seq
    from . import telemetry
    with _lock:
        _seq += 1
        seq = _seq
        _cards.append(card)
        if len(_cards) > _MAX_CARDS:
            del _cards[0]
            _stats["dropped"] += 1
        _stats["cards"] += 1
    # telemetry: one instant (the event: what compiled, when) + one
    # counter sample (the trend: op counts over the run's compiles)
    telemetry.instant("compile.card", cat="compile", label=card["label"],
                      source=card["source"],
                      convolutions=card["convolutions"],
                      converts=card["converts"],
                      total_ops=card["total_ops"])
    telemetry.counter("compile", convolutions=card["convolutions"],
                      dots=card["dots"], converts=card["converts"],
                      collectives=card["collectives"],
                      custom_calls=card["custom_calls"],
                      total_ops=card["total_ops"])
    d = cards_dir()
    if d is not None:
        try:
            write_card(card, d, seq=seq)
            with _lock:
                _stats["writes"] += 1
        except Exception as e:  # noqa: BLE001 — artifacts are best-effort
            logger.warning("hlostats: card write to %s failed: %s: %s",
                           d, type(e).__name__, e)
            with _lock:
                _stats["errors"] += 1


# ----------------------------------------------------------------------
# artifacts (plain JSON through file_io — local / memory:// / fsspec)
# ----------------------------------------------------------------------

def _safe_label(label: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", label)


def write_card(card: dict, dir_: str, *, seq: Optional[int] = None) -> str:
    """Write one card as ``card.<label>.<seq>.json`` under ``dir_`` (any
    file_io scheme).  Returns the path."""
    from . import file_io
    base = file_io._strip_file_scheme(str(dir_))
    fs = file_io.get_filesystem(base)
    fs.makedirs(base)
    if seq is None:
        global _seq
        with _lock:
            _seq += 1
            seq = _seq
    name = f"card.{_safe_label(card.get('label', 'unknown'))}.{seq}.json"
    path = file_io._join(base, name)
    fs.write_bytes(path, json.dumps(card, sort_keys=True).encode())
    return path


def read_cards(dir_: str) -> List[dict]:
    """Every ``card.*.json`` under ``dir_``, in emission (seq) order."""
    from . import file_io
    base = file_io._strip_file_scheme(str(dir_))
    fs = file_io.get_filesystem(base)
    out = []
    for name in fs.listdir(base):
        m = re.fullmatch(r"card\..*\.(\d+)\.json", name)
        if not m:
            continue
        out.append((int(m.group(1)), json.loads(
            fs.read_bytes(file_io._join(base, name)))))
    return [c for _, c in sorted(out, key=lambda t: t[0])]


# ----------------------------------------------------------------------
# the process ledger
# ----------------------------------------------------------------------

def cards(label: Optional[str] = None) -> List[dict]:
    """Cards captured by this process (newest last), optionally filtered
    by label."""
    with _lock:
        snap = [dict(c) for c in _cards]
    if label is not None:
        snap = [c for c in snap if c.get("label") == label]
    return snap


def last_card(label: Optional[str] = None) -> Optional[dict]:
    """The newest card (for ``label``, when given), or None."""
    got = cards(label)
    return got[-1] if got else None


def stats() -> Dict[str, int]:
    """Process-wide counters: cards captured, artifacts written, errors,
    ledger drops."""
    with _lock:
        return dict(_stats)


def ledger() -> Dict[str, int]:
    """Per-label card counts — the ``stats()``-style summary
    ``InferenceServer.stats()`` embeds (a warm serve ladder shows one
    card per bucket shape)."""
    with _lock:
        out: Dict[str, int] = {}
        for c in _cards:
            lb = c.get("label", "?")
            out[lb] = out.get(lb, 0) + 1
        return dict(sorted(out.items()))


def reset() -> None:
    """Zero the ledger and counters (tests)."""
    global _seq
    with _lock:
        _cards.clear()
        _seq = 0
        for k in _stats:
            _stats[k] = 0
