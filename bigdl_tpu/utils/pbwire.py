"""Generic protobuf wire-format codec (no protobuf runtime).

Reference: BigDL vendors ~157k LoC of protoc-generated Java
(caffe/Caffe.java, org/tensorflow/framework/*.java) solely to read/write
Caffe NetParameter and TF GraphDef/Event messages.  Rebuild: protobuf's wire
format is tiny — varint / fixed64 / length-delimited / fixed32 — so one
generic codec plus per-schema field tables (interop/caffe.py,
interop/tensorflow.py, visualization/proto.py) replaces all of it.

Decoding yields (field_number, wire_type, value) triples; schema knowledge
lives entirely in the callers.  `Fields` adds a dict-like view for the
common read patterns.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Sequence, Tuple, Union

__all__ = ["encode_varint", "decode_varint", "tag", "field_varint",
           "field_double", "field_float", "field_bytes", "field_string",
           "field_packed_doubles", "field_packed_floats",
           "field_packed_varints", "iter_fields", "Fields", "zigzag",
           "unzigzag"]


# ---------------------------------------------------------------- encoding

def encode_varint(value: int) -> bytes:
    out = bytearray()
    value &= (1 << 64) - 1  # two's-complement for negative int64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def tag(field: int, wire: int) -> bytes:
    return encode_varint((field << 3) | wire)


def field_varint(field: int, value: int) -> bytes:
    return tag(field, 0) + encode_varint(value)


def field_double(field: int, value: float) -> bytes:
    return tag(field, 1) + struct.pack("<d", value)


def field_float(field: int, value: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", value)


def field_bytes(field: int, value: bytes) -> bytes:
    return tag(field, 2) + encode_varint(len(value)) + value


def field_string(field: int, value: str) -> bytes:
    return field_bytes(field, value.encode())


def field_packed_doubles(field: int, values: Sequence[float]) -> bytes:
    return field_bytes(field, struct.pack(f"<{len(values)}d", *values))


def field_packed_floats(field: int, values) -> bytes:
    """Accepts a sequence of floats or a numpy array (fast path: no Python
    list materialization for large weight blobs)."""
    import numpy as np
    if isinstance(values, np.ndarray):
        return field_bytes(field,
                           np.ascontiguousarray(values, "<f4").tobytes())
    return field_bytes(field, struct.pack(f"<{len(values)}f", *values))


def field_packed_varints(field: int, values: Sequence[int]) -> bytes:
    return field_bytes(field, b"".join(encode_varint(v) for v in values))


# ---------------------------------------------------------------- decoding

def decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, raw_value).  wire 0 -> int,
    1 -> float (as double), 2 -> bytes, 5 -> float (as float32)."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = decode_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = decode_varint(buf, pos)
        elif wire == 1:
            val = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif wire == 2:
            ln, pos = decode_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


class Fields:
    """Dict-of-lists view over one message's fields, for schema-driven
    readers: `Fields(buf).int(1)`, `.str(2)`, `.sub(7)` etc."""

    def __init__(self, buf: bytes):
        self._f: Dict[int, List] = {}
        for field, wire, val in iter_fields(buf):
            self._f.setdefault(field, []).append((wire, val))

    def has(self, field: int) -> bool:
        return field in self._f

    def _all(self, field: int) -> List:
        return self._f.get(field, [])

    def int(self, field: int, default: int = 0) -> int:
        vals = self._all(field)
        return int(vals[-1][1]) if vals else default

    def ints(self, field: int) -> List[int]:
        """Repeated varints, handling both packed and unpacked encodings."""
        out: List[int] = []
        for wire, val in self._all(field):
            if wire == 2:  # packed
                pos = 0
                while pos < len(val):
                    v, pos = decode_varint(val, pos)
                    out.append(v)
            else:
                out.append(int(val))
        return out

    def float(self, field: int, default: float = 0.0) -> float:
        vals = self._all(field)
        return float(vals[-1][1]) if vals else default

    def floats(self, field: int) -> List[float]:
        """Repeated float32, packed or not."""
        out: List[float] = []
        for wire, val in self._all(field):
            if wire == 2:
                out.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                out.append(float(val))
        return out

    def doubles(self, field: int) -> List[float]:
        out: List[float] = []
        for wire, val in self._all(field):
            if wire == 2:
                out.extend(struct.unpack(f"<{len(val) // 8}d", val))
            else:
                out.append(float(val))
        return out

    def bytes(self, field: int, default: bytes = b"") -> bytes:
        vals = self._all(field)
        return bytes(vals[-1][1]) if vals else default

    def str(self, field: int, default: str = "") -> str:
        vals = self._all(field)
        return bytes(vals[-1][1]).decode() if vals else default

    def strs(self, field: int) -> List[str]:
        return [bytes(v).decode() for _w, v in self._all(field)]

    def sub(self, field: int) -> "Fields":
        return Fields(self.bytes(field))

    def subs(self, field: int) -> List["Fields"]:
        return [Fields(bytes(v)) for _w, v in self._all(field)]

    def raw(self, field: int) -> List[bytes]:
        return [bytes(v) for _w, v in self._all(field)]
