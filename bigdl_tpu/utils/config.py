"""Process-level configuration tiers.

Reference: BigDL's three config tiers (SURVEY.md §5.6) — JVM system
properties `bigdl.*` (utils/Engine.scala:113-152, DistriOptimizer.scala:751),
the bundled spark-bigdl.conf, and per-app CLIs.  TPU re-design: the system
properties become `BIGDL_TPU_*` environment variables (the process-level
knob JAX programs use); the spark conf tier has no equivalent (no Spark);
CLIs live in models/run.py and tools/.

| env var                   | reference property               | default |
|---------------------------|----------------------------------|---------|
| BIGDL_TPU_SEED            | (RandomGenerator default seed)   | 0       |
| BIGDL_TPU_RETRY_TIMES     | bigdl.failure.retryTimes         | 5       |
| BIGDL_TPU_RETRY_INTERVAL  | bigdl.failure.retryTimeInterval  | 120     |
| BIGDL_TPU_NUM_THREADS     | bigdl.coreNumber / MKL threads   | ncpu    |
| BIGDL_TPU_LOG_FILE        | bigdl.utils.LoggerFilter.logFile | bigdl_tpu.log |
| BIGDL_TPU_DISABLE_LOGGER_FILTER | bigdl.utils.LoggerFilter.disable | 0 |
| BIGDL_TPU_CHECK_SINGLETON | bigdl.check.singleton            | 0       |
| BIGDL_TPU_PREEMPTION_CHECKPOINT | (net-new: SIGTERM -> final snapshot) | 1 |
| BIGDL_TPU_DEVICE_TIMEOUT  | (net-new: Engine.init device-discovery watchdog, seconds) | 0 (off) |
| BIGDL_TPU_RNN_HOIST_MAX_ELEMENTS | (net-new: ConvLSTM hoist cap) | 2^28 |
| BIGDL_TPU_XLA_CACHE / _DIR | (net-new: persistent compile cache) | 1 / ~/.cache/bigdl_tpu/xla |
| BIGDL_TPU_CONV_PAD_MIN_CIN | (net-new: tiny-channel conv pad, nn/conv.py) | 8 |
| BIGDL_TPU_BN_IMPL / _FUSED_VJP / _STAT_ROWS | (net-new: BN variants, nn/normalization.py) | off |
| BIGDL_TPU_BN_BATCH | (net-new: bn_experiment batch) | 256 |
| BIGDL_TPU_BENCH_REMAT / _FLASH_SHAPE | (net-new: bench knobs) | off |
| BIGDL_TPU_BENCH_BN_AUTOTUNE | (net-new: resnet50_bf16 BN-variant race; 0=off, 1=force on CPU, default=TPU only) | tpu |
| BIGDL_TPU_ATTN_IMPL | (net-new: flash-attention dispatch, jnp/pallas; ops/attention.py) | auto |
| BIGDL_TPU_TEST_INSTALLED | (net-new: suite resolves installed wheel) | off |
| BIGDL_TPU_IO_RETRIES | (net-new: remote-IO retry attempts per op, utils/file_io.py) | 3 |
| BIGDL_TPU_IO_BACKOFF_BASE / _IO_BACKOFF_MAX | (net-new: remote-IO backoff seconds, exponential + deterministic jitter) | 0.05 / 2.0 |
| BIGDL_TPU_IO_DEADLINE | (net-new: total seconds a retried remote op may take) | 60 |
| BIGDL_TPU_CKPT_KEEP_LAST | (net-new: checkpoint retention keep-last-K; 0 = unlimited) | 0 |
| BIGDL_TPU_CKPT_KEEP_EVERY_EPOCHS | (net-new: mark a keeper snapshot every N epochs) | 0 |
| BIGDL_TPU_CHAOS | (net-new: fault-injection spec, utils/chaos.py; see docs/robustness.md) | off |
| BIGDL_TPU_SUPERVISE_DATA / _STEP / _COMPILE / _CHECKPOINT / _VALIDATION | (net-new: per-phase stall deadlines, seconds; utils/supervisor.py — COMPILE covers each attempt's first step, which holds the XLA compile) | 0 (off) |
| BIGDL_TPU_SUPERVISE_DEADLINE | (net-new: default stall deadline for unlisted phases) | 0 (off) |
| BIGDL_TPU_SUPERVISE_POLICY | (net-new: stall response — raise StallError or hard-exit) | raise |
| BIGDL_TPU_SUPERVISE_PEER_STALE | (net-new: multi-host heartbeat staleness threshold, seconds) | 60 |
| BIGDL_TPU_DATA_SKIP_BUDGET | (net-new: corrupt records quarantined per data pass; utils/recordio.py) | 0 (fail loud) |
| BIGDL_TPU_PREFETCH_DEPTH | (net-new: background input-pipeline depth in batches, dataset/prefetch.py; 0 = synchronous path) | 2 |
| BIGDL_TPU_PREFETCH_STAGE | (net-new: stage the next batch onto devices from the prefetch worker — host->device double-buffering) | 1 single-process, 0 multi-host |
| BIGDL_TPU_TRACE | (net-new: run-telemetry trace output dir, utils/telemetry.py; empty = tracing off) | off |
| BIGDL_TPU_TRACE_RING | (net-new: max buffered trace events; oldest dropped beyond this) | 65536 |
| BIGDL_TPU_TRACE_FLUSH_EVERY | (net-new: trace events between automatic file flushes) | 4096 |
| BIGDL_TPU_SERVE_MAX_BATCH | (net-new: online serving — max requests coalesced per device batch, serve/) | 8 |
| BIGDL_TPU_SERVE_MAX_WAIT_MS | (net-new: flush deadline — max ms the oldest queued request waits for batch fill) | 5 |
| BIGDL_TPU_SERVE_QUEUE_LIMIT | (net-new: bounded request queue; admission past it raises ServerOverloaded) | 64 |
| BIGDL_TPU_SERVE_REPLICAS | (net-new: replica worker threads draining the shared serve queue) | 1 |
| BIGDL_TPU_SERVE_DEADLINE_MS | (net-new: default per-request deadline; expired queued requests shed with RequestTimeout; 0 = none) | 0 |
| BIGDL_TPU_SERVE_STALL_SECONDS | (net-new: per-replica supervision deadline — a wedged replica trips a stall + crash report; 0 = unwatched) | 0 |
| BIGDL_TPU_SERVE_REPLICA_LOST | (net-new: serving control plane, serve/control.py — seconds of replica heartbeat silence before the monitor condemns + restarts it; 0 = monitor off) | 0 (off) |
| BIGDL_TPU_SERVE_RESTART_BUDGET | (net-new: replica restarts allowed per replica slot before the server flips unhealthy on /healthz) | 3 |
| BIGDL_TPU_SERVE_RESTART_BACKOFF | (net-new: base seconds between replica restarts, doubling per consecutive restart) | 0.1 |
| BIGDL_TPU_SERVE_CANARY_MIN_BATCHES | (net-new: clean canary batches — and matching incumbent window — required before auto-promotion) | 8 |
| BIGDL_TPU_SERVE_CANARY_WINDOW | (net-new: rolling per-arm latency window, batches, for the canary p99 comparator) | 64 |
| BIGDL_TPU_SERVE_CANARY_LATENCY_RATIO | (net-new: auto-rollback when canary p99 latency exceeds ratio x the incumbent's) | 2.0 |
| BIGDL_TPU_SERVE_CANARY_ERROR_MARGIN | (net-new: auto-rollback when canary batch error rate exceeds the incumbent's + margin) | 0.05 |
| BIGDL_TPU_SERVE_TENANT_QPS | (net-new: per-tenant token-bucket admission quota, requests/s; over-quota -> typed QuotaExceeded with retry_after_s; 0 = quotas off) | 0 (off) |
| BIGDL_TPU_SERVE_TENANT_BURST | (net-new: per-tenant token-bucket depth; 0 = 2x qps, min 1) | 0 (auto) |
| BIGDL_TPU_AOT_CACHE | (net-new: AOT executable-cache dir, utils/aot.py — serialized compiled executables; warm start = cache read, zero XLA compiles; empty/0 = off) | off |
| BIGDL_TPU_AOT_CACHE_TAG | (net-new: free-form AOT fingerprint salt; bump to invalidate every entry at once) | "" |
| BIGDL_TPU_PEAK_FLOPS | (net-new: per-device MFU denominator override, FLOP/s — utils/flops.device_peak_flops; default TPU table / 1e12 CPU-nominal) | 0 (auto) |
| BIGDL_TPU_FUSED_UPDATE | (net-new: multi-tensor fused optimizer update, optim/fused.py — flatten grad/param/slot trees into dtype-homogeneous 1-D buffers; bit-identical to the per-leaf path) | 0 (off) |
| BIGDL_TPU_WIRE_BUCKET_MB | (net-new: max wire-dtype MB per gradient bucket, parallel/wire.py; 0 = per-leaf wire cast) | 0 (per-leaf) |
| BIGDL_TPU_OVERLAP_FLAGS | (net-new: latency-hiding-scheduler / async-collective LIBTPU flags, utils/platform.enable_overlap_flags; 0 disables) | 1 |
| BIGDL_TPU_CONV_ROUTE | (net-new: tiny-C_in conv lowering — pad (zero-pad), matmul (im2col reshaped-matmul, ops/convmm.py), lax (untouched); nn/conv._conv_route) | pad |
| BIGDL_TPU_ELASTIC_PEER_LOST | (net-new: elastic host-loss threshold, seconds of heartbeat-PUBLICATION silence promoting a peer to PeerLostError; parallel/elastic — 0 disarms elasticity) | 0 (off) |
| BIGDL_TPU_ELASTIC_WORLD / _ELASTIC_RANK | (net-new: simulated-multi-host logical topology for the elastic drill harness; utils/engine.Engine.world/rank) | off |
| BIGDL_TPU_ELASTIC_NEGOTIATE_TIMEOUT / _ELASTIC_NEGOTIATE_POLL | (net-new: seconds to wait for every survivor's lineage view / poll cadence during elastic negotiation) | 60 / 0.25 |
| BIGDL_TPU_DEPLOY_CANARY_FRACTION | (net-new: continuous deployment, serve/continuous.py — canary batch fraction the DeployController routes to each new release; 0 = plain full swaps) | 0.25 |
| BIGDL_TPU_DEPLOY_ROLLBACK_BUDGET | (net-new: consecutive canary rollbacks before the deploy controller freezes unhealthy instead of flapping) | 2 |
| BIGDL_TPU_DEPLOY_POLL_S | (net-new: release-lineage poll cadence, seconds; the watch itself backs off on the IO knobs when polled without one) | 0.25 |
| BIGDL_TPU_DEPLOY_DECISION_TIMEOUT | (net-new: seconds to wait a canary verdict out before freezing; 0 = wait forever) | 0 (off) |
| BIGDL_TPU_DEPLOY_MAX_UNAVAILABLE | (net-new: fleet mode — members concurrently in-swap during a rolling release fan-out; serve/fleetfront.py) | 1 |
| BIGDL_TPU_FLEET_MEMBER_LOST | (net-new: cross-process fleet, serve/fleet.py — seconds of member heartbeat-publication silence before the supervisor condemns + respawns it) | 5.0 |
| BIGDL_TPU_FLEET_RESTART_BUDGET | (net-new: respawns allowed per fleet member slot before it degrades to the survivors) | 3 |
| BIGDL_TPU_FLEET_RESTART_BACKOFF | (net-new: first member respawn delay, seconds, doubling per consecutive restart) | 0.5 |
| BIGDL_TPU_FLEET_POLL | (net-new: fleet supervisor monitor poll cadence, seconds) | 0.5 |
| BIGDL_TPU_FLEET_SPAWN_GRACE | (net-new: seconds a fresh worker spawn may take to publish its first heartbeat before silence counts) | 30.0 |
| BIGDL_TPU_FLEET_HEARTBEAT | (net-new: fleet worker beat interval, seconds; tools/serve_worker.py) | 0.5 |
| BIGDL_TPU_FLEET_KEEP_GENERATIONS | (net-new: member-record generations kept per index by the writer-side retention sweep) | 4 |
| BIGDL_TPU_FLEET_TIMEOUT_S | (net-new: fleet front tier, serve/fleetfront.py — per-member HTTP request timeout, seconds) | 60 |
| BIGDL_TPU_FLEET_RETRIES | (net-new: retry-on-next-member attempts after the first, idempotent predicts only) | 2 |
| BIGDL_TPU_FLEET_REFRESH_S | (net-new: fleet registry cache refresh interval, seconds) | 0.25 |
| BIGDL_TPU_FLEET_MAX_UNAVAILABLE | (net-new: front-tier default for members concurrently in-swap during a rolling deploy) | 1 |
| BIGDL_TPU_PROTOCOL_KEEP | (net-new: numbered protocol files — elastic grow offers — kept by the writer-side retention sweep, file_io.sweep_numbered) | 8 |
"""

from __future__ import annotations

import os

__all__ = ["get_int", "get_float", "get_bool", "get_str",
           "retry_times", "retry_time_interval", "num_threads", "seed"]


def get_str(name: str, default: str) -> str:
    return os.environ.get(f"BIGDL_TPU_{name}", default)


def get_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(f"BIGDL_TPU_{name}", default))
    except ValueError:
        return default


def get_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(f"BIGDL_TPU_{name}", default))
    except ValueError:
        return default


def get_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(f"BIGDL_TPU_{name}")
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def retry_times() -> int:
    """(reference: bigdl.failure.retryTimes, DistriOptimizer.scala:751)."""
    return get_int("RETRY_TIMES", 5)


def retry_time_interval() -> float:
    """Sliding window (seconds) that resets the retry counter
    (reference: bigdl.failure.retryTimeInterval, DistriOptimizer.scala:752)."""
    return get_float("RETRY_INTERVAL", 120.0)


def num_threads() -> int:
    return get_int("NUM_THREADS", os.cpu_count() or 1)


def seed() -> int:
    return get_int("SEED", 0)
