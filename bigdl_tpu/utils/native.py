"""Loader for the native C++ runtime library (csrc/).

Reference: BigDL's native layer is the BigDL-core JNI wrapper shipping
`libjmkl.so` inside per-OS jars, loaded lazily on first use
(tensor/Tensor.scala:688 comment; MKL.isMKLLoaded, MKL.setNumThreads).  Here
the device math lives in XLA; the native library instead accelerates the
host-side runtime: CRC32C (hardware SSE4.2 when available), BDRecord file IO,
bf16 wire conversion, and batch-assembly kernels.

Pure-Python fallbacks exist for every entry point — the framework works
without the compiled library, just slower on the host paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

__all__ = ["lib", "crc32c", "crc32c_extend", "is_native_loaded", "build",
           "set_num_threads",
           "get_num_threads", "f32_to_bf16", "bf16_to_f32",
           "NativeRecordWriter", "NativeRecordReader",
           "NativePrefetchReader", "has_prefetch"]

_pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_csrc_dir = os.path.join(os.path.dirname(_pkg_dir), "csrc")
_candidates = [
    os.path.join(_pkg_dir, "lib", "libbigdl_tpu_native.so"),
    os.path.join(_csrc_dir, "build", "libbigdl_tpu_native.so"),
]

lib: Optional[ctypes.CDLL] = None
crc32c = None
crc32c_extend = None


def _bind(cdll: ctypes.CDLL) -> None:
    global crc32c, crc32c_extend
    cdll.bigdl_crc32c.restype = ctypes.c_uint32
    cdll.bigdl_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    cdll.bigdl_masked_crc32c.restype = ctypes.c_uint32
    cdll.bigdl_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    if hasattr(cdll, "bigdl_crc32c_extend"):
        # optional (newer than the first shipped .so): the streaming
        # continuation used by the checkpoint framer; older binaries fall
        # back to the pure-Python loop in utils/recordio.py
        cdll.bigdl_crc32c_extend.restype = ctypes.c_uint32
        cdll.bigdl_crc32c_extend.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t]

        def crc32c_extend(crc: int, data: bytes) -> int:  # noqa: F811
            return cdll.bigdl_crc32c_extend(crc, data, len(data))
    cdll.bigdl_record_writer_open.restype = ctypes.c_void_p
    cdll.bigdl_record_writer_open.argtypes = [ctypes.c_char_p]
    cdll.bigdl_record_writer_write.restype = ctypes.c_int
    cdll.bigdl_record_writer_write.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    cdll.bigdl_record_writer_close.restype = ctypes.c_int
    cdll.bigdl_record_writer_close.argtypes = [ctypes.c_void_p]
    cdll.bigdl_record_reader_open.restype = ctypes.c_void_p
    cdll.bigdl_record_reader_open.argtypes = [ctypes.c_char_p]
    cdll.bigdl_record_reader_next.restype = ctypes.c_int64
    cdll.bigdl_record_reader_next.argtypes = [ctypes.c_void_p]
    cdll.bigdl_record_reader_data.restype = ctypes.c_void_p
    cdll.bigdl_record_reader_data.argtypes = [ctypes.c_void_p]
    cdll.bigdl_record_reader_close.restype = None
    cdll.bigdl_record_reader_close.argtypes = [ctypes.c_void_p]
    if hasattr(cdll, "bigdl_prefetch_open"):
        # optional (newer than the first shipped .so): an older binary
        # without these symbols must still provide crc32c/record IO/hostops
        cdll.bigdl_prefetch_open.restype = ctypes.c_void_p
        cdll.bigdl_prefetch_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64]
        cdll.bigdl_prefetch_next.restype = ctypes.c_int64
        cdll.bigdl_prefetch_next.argtypes = [ctypes.c_void_p]
        cdll.bigdl_prefetch_data.restype = ctypes.c_void_p
        cdll.bigdl_prefetch_data.argtypes = [ctypes.c_void_p]
        cdll.bigdl_prefetch_close.restype = None
        cdll.bigdl_prefetch_close.argtypes = [ctypes.c_void_p]
    cdll.bigdl_set_num_threads.restype = None
    cdll.bigdl_set_num_threads.argtypes = [ctypes.c_int]
    cdll.bigdl_get_num_threads.restype = ctypes.c_int
    cdll.bigdl_f32_to_bf16.restype = None
    cdll.bigdl_f32_to_bf16.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    cdll.bigdl_bf16_to_f32.restype = None
    cdll.bigdl_bf16_to_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    cdll.bigdl_gather_rows.restype = None
    cdll.bigdl_gather_rows.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p), ctypes.c_size_t,
        ctypes.c_size_t]
    cdll.bigdl_reduce_sum_f32.restype = None
    cdll.bigdl_reduce_sum_f32.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ctypes.c_size_t]

    def crc32c(data: bytes) -> int:  # noqa: F811
        return cdll.bigdl_crc32c(data, len(data))


def _try_load() -> None:
    global lib
    for _p in _candidates:
        if os.path.exists(_p):
            try:
                cdll = ctypes.CDLL(_p)
                _bind(cdll)
                lib = cdll
                return
            except (OSError, AttributeError):
                lib = None


_try_load()


def build(quiet: bool = True) -> bool:
    """Compile csrc/ with make and load the result.  Returns True if the
    native library is loaded afterwards (reference analog: BigDL-core's
    Maven native build producing libjmkl.so)."""
    if lib is not None:
        return True
    if not os.path.isdir(_csrc_dir):
        return False
    try:
        subprocess.run(
            ["make", "-C", _csrc_dir, "-j"],
            check=True,
            stdout=subprocess.DEVNULL if quiet else None,
            stderr=subprocess.DEVNULL if quiet else None)
    except (OSError, subprocess.CalledProcessError):
        return False
    _try_load()
    return lib is not None


def is_native_loaded() -> bool:
    """(reference: MKL.isMKLLoaded)."""
    return lib is not None


def has_prefetch() -> bool:
    """True when the loaded .so exports the bigdl_prefetch_* symbols
    (optional: older binaries predate csrc/prefetch.cc)."""
    return lib is not None and hasattr(lib, "bigdl_prefetch_open")


def set_num_threads(n: int) -> None:
    """(reference: MKL.setNumThreads via Engine/ThreadPool.setMKLThread)."""
    if lib is not None:
        lib.bigdl_set_num_threads(n)


def get_num_threads() -> int:
    """(reference: MKL.getNumThreads)."""
    return lib.bigdl_get_num_threads() if lib is not None else 1


def f32_to_bf16(arr: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even float32 -> bf16 (as uint16 payload).  Host-side
    wire/checkpoint compression (reference: FP16CompressedTensor truncation,
    parameters/FP16CompressedTensor.scala:271-279 — truncate-only; we round
    like the TPU hardware does)."""
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    if lib is not None and arr.size:
        out = np.empty(arr.shape, dtype=np.uint16)
        lib.bigdl_f32_to_bf16(arr.ctypes.data, out.ctypes.data, arr.size)
        return out
    import ml_dtypes  # hard transitive dep of jax
    return arr.astype(ml_dtypes.bfloat16).view(np.uint16)


def bf16_to_f32(arr: np.ndarray) -> np.ndarray:
    arr = np.ascontiguousarray(arr, dtype=np.uint16)
    if lib is not None and arr.size:
        out = np.empty(arr.shape, dtype=np.float32)
        lib.bigdl_bf16_to_f32(arr.ctypes.data, out.ctypes.data, arr.size)
        return out
    import ml_dtypes
    return arr.view(ml_dtypes.bfloat16).astype(np.float32)


def gather_rows(rows) -> np.ndarray:
    """Stack equal-shape contiguous arrays into one batch array using the
    parallel native memcpy kernel (the batching half of
    MTLabeledBGRImgToBatch); np.stack fallback."""
    rows = [np.ascontiguousarray(r) for r in rows]
    if lib is None or not rows:
        return np.stack(rows) if rows else np.empty((0,))
    if any(r.shape != rows[0].shape or r.dtype != rows[0].dtype
           for r in rows[1:]):
        # heterogeneous rows: the native memcpy would read out of bounds;
        # np.stack keeps behavior identical with and without the library
        # (promoting dtypes, raising on shape mismatch)
        return np.stack(rows)
    out = np.empty((len(rows),) + rows[0].shape, dtype=rows[0].dtype)
    ptrs = (ctypes.c_void_p * len(rows))(
        *[r.ctypes.data for r in rows])
    lib.bigdl_gather_rows(out.ctypes.data, ptrs, rows[0].nbytes, len(rows))
    return out


def reduce_sum_f32(bufs) -> np.ndarray:
    """Elementwise sum of equal-shape float32 arrays via the parallel native
    kernel (host-side analog of the reference's gradient-sum loop,
    DistriOptimizer.scala:226-250); np.sum fallback."""
    bufs = [np.ascontiguousarray(b, dtype=np.float32) for b in bufs]
    if lib is None or not bufs:
        return np.sum(bufs, axis=0, dtype=np.float32)
    if any(b.shape != bufs[0].shape for b in bufs[1:]):
        raise ValueError("reduce_sum_f32 requires equal shapes")
    out = np.empty_like(bufs[0])
    ptrs = (ctypes.c_void_p * len(bufs))(*[b.ctypes.data for b in bufs])
    lib.bigdl_reduce_sum_f32(out.ctypes.data, ptrs, len(bufs), out.size)
    return out


class NativeRecordWriter:
    """Streaming BDRecord writer over the native handle."""

    def __init__(self, path: str):
        if lib is None:
            raise RuntimeError("native library not loaded")
        self._h = lib.bigdl_record_writer_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path!r} for writing")

    def write(self, payload: bytes) -> None:
        if lib.bigdl_record_writer_write(self._h, payload, len(payload)) != 0:
            raise IOError("record write failed")

    def close(self) -> None:
        if self._h:
            rc = lib.bigdl_record_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("record writer close failed (flush error)")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeRecordReader:
    """Streaming BDRecord reader; iterate to get payload bytes."""

    def __init__(self, path: str):
        if lib is None:
            raise RuntimeError("native library not loaded")
        self._path = path
        self._h = lib.bigdl_record_reader_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path!r}")

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if not self._h:  # use-after-close would hand C a NULL handle
            raise StopIteration
        n = lib.bigdl_record_reader_next(self._h)
        if n == -1:
            raise StopIteration
        if n < 0:
            # typed like the Python reader so callers match on ONE error;
            # non-resumable — the C reader's stream state is undefined
            # after a frame error (skip-budget reads use the Python path)
            from .recordio import CorruptRecord
            raise CorruptRecord(
                f"corrupt record (crc mismatch) in {self._path!r}",
                path=self._path, resumable=False)
        return ctypes.string_at(lib.bigdl_record_reader_data(self._h), n)

    def close(self) -> None:
        if self._h:
            lib.bigdl_record_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativePrefetchReader:
    """Multithreaded shard prefetcher (csrc/prefetch.cc): N C++ reader
    threads stream BDRecord shards into a bounded ring buffer; iterating
    yields payload bytes.  Record order interleaves across shards (the
    Spark-partition semantics of the reference's SeqFileFolder datasets);
    single consumer only."""

    def __init__(self, paths, num_threads: int = 4, capacity: int = 256):
        if not has_prefetch():
            raise RuntimeError("native library not loaded or too old "
                               "(no bigdl_prefetch_* symbols)")
        paths = [str(p) for p in paths]
        if not paths:
            raise ValueError("no shard paths")
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._h = lib.bigdl_prefetch_open(arr, len(paths), num_threads,
                                          capacity)
        if not self._h:
            raise IOError(f"cannot open prefetcher over {len(paths)} shards")

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if not self._h:  # use-after-close would hand C a NULL handle
            raise StopIteration
        n = lib.bigdl_prefetch_next(self._h)
        if n == -1:
            raise StopIteration
        if n < 0:
            raise IOError("prefetch: IO error or corrupt record")
        return ctypes.string_at(lib.bigdl_prefetch_data(self._h), n)

    def close(self) -> None:
        if self._h:
            lib.bigdl_prefetch_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
