"""Loader for the native C++ runtime library (csrc/).

Reference: BigDL's native layer is the BigDL-core JNI wrapper shipping
`libjmkl.so` inside per-OS jars, loaded lazily on first use
(tensor/Tensor.scala:688 comment; MKL.isMKLLoaded).  Here the math lives in
XLA; the native library instead accelerates the host-side runtime: CRC32C
(hardware SSE4.2 when available), record-file IO, and the prefetch pipeline.

Pure-Python fallbacks exist for every entry point — the framework works
without the compiled library, just slower on the host paths.
"""

from __future__ import annotations

import ctypes
import os

__all__ = ["lib", "crc32c", "is_native_loaded"]

_here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_candidates = [
    os.path.join(_here, "lib", "libbigdl_tpu_native.so"),
    os.path.join(os.path.dirname(_here), "csrc", "build",
                 "libbigdl_tpu_native.so"),
]

lib = None
for _p in _candidates:
    if os.path.exists(_p):
        try:
            lib = ctypes.CDLL(_p)
            break
        except OSError:
            lib = None

crc32c = None
if lib is not None:
    try:
        lib.bigdl_crc32c.restype = ctypes.c_uint32
        lib.bigdl_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]

        def crc32c(data: bytes) -> int:  # noqa: F811
            return lib.bigdl_crc32c(data, len(data))
    except AttributeError:
        crc32c = None


def is_native_loaded() -> bool:
    """(reference: MKL.isMKLLoaded)."""
    return lib is not None
