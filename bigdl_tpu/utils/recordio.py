"""BDRecord: the sharded record-file format replacing Hadoop SequenceFiles.

Reference: BigDL reads training corpora from Spark-cached Hadoop SequenceFiles
(`DataSet.SeqFileFolder`, dataset/DataSet.scala:319; ETL in
models/utils/ImageNetSeqFileGenerator.scala).  On TPU hosts there is no HDFS;
the equivalent is a dumb, seekable, shardable local record format.

Format (little-endian), per record:
    u64  length
    u32  masked crc32c of the 8-byte length field
    <length bytes>
    u32  masked crc32c of the payload
i.e. exactly the TFRecord framing (also used by the TensorBoard event writer,
visualization/tensorboard), with the same CRC mask.  CRC32C is computed by the
native C++ library (csrc/) when built, with a pure-Python fallback.

Payloads are pickled objects (typically `Sample`s) via `write_records`, or raw
bytes via the *_bytes variants.
"""

from __future__ import annotations

import glob
import os
import pickle
import struct
from typing import Any, Iterable, Iterator, List

__all__ = ["write_records", "read_records", "count_records",
           "write_record_bytes",
           "read_record_bytes", "masked_crc32c", "crc32c_update"]


def _table():
    global _TABLE
    if _TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _TABLE = table
    return _TABLE


def crc32c_update(crc: int, data: bytes) -> int:
    """Continue a finalized CRC32C over more bytes (seed 0 for the first
    chunk): crc32c_update(crc32c_update(0, a), b) == crc32c(a + b).  The
    checkpoint framer (utils/file_io) streams pickles through this; native
    `bigdl_crc32c_extend` when the compiled library exports it, pure-Python
    table loop otherwise."""
    from .native import crc32c_extend as native_extend
    if native_extend is not None:
        return native_extend(crc, data)
    tb = _table()
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = tb[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _crc32c_py(data: bytes) -> int:
    """Pure-Python CRC32C (Castagnoli) — fallback when the native lib is
    absent (reference vendors the same algorithm as netty/Crc32c.java)."""
    tb = _table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tb[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_TABLE = None


def _crc32c(data: bytes) -> int:
    from .native import crc32c as native_crc32c
    if native_crc32c is not None:
        return native_crc32c(data)
    return _crc32c_py(data)


def masked_crc32c(data: bytes) -> int:
    """TFRecord CRC mask (reference: RecordWriter.scala:44-57 /
    netty/Crc32c.java)."""
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def write_record_bytes(f, payload: bytes) -> None:
    header = struct.pack("<Q", len(payload))
    f.write(header)
    f.write(struct.pack("<I", masked_crc32c(header)))
    f.write(payload)
    f.write(struct.pack("<I", masked_crc32c(payload)))


def read_record_bytes(f) -> bytes:
    header = f.read(8)
    if len(header) < 8:
        raise EOFError
    (length,) = struct.unpack("<Q", header)
    (hcrc,) = struct.unpack("<I", f.read(4))
    if hcrc != masked_crc32c(header):
        raise IOError("corrupt record header (crc mismatch)")
    payload = f.read(length)
    (pcrc,) = struct.unpack("<I", f.read(4))
    if pcrc != masked_crc32c(payload):
        raise IOError("corrupt record payload (crc mismatch)")
    return payload


class _PyRecordWriter:
    """Same write/close interface as native.NativeRecordWriter."""

    def __init__(self, path: str):
        self._f = open(path, "wb")

    def write(self, payload: bytes) -> None:
        write_record_bytes(self._f, payload)

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _PyRecordReader:
    """Same iterator interface as native.NativeRecordReader."""

    def __init__(self, path: str):
        self._f = open(path, "rb")

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        try:
            return read_record_bytes(self._f)
        except EOFError:
            raise StopIteration

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records: Iterable[Any],
                  shards: int = 1) -> List[str]:
    """Write records round-robin over `shards` files: path-00000-of-00008 style
    (the sharded layout Spark partitions played in the reference).  Uses the
    native C++ writer (csrc/recordio.cc) when built."""
    from . import native

    if shards == 1:
        paths = [path]
    else:
        paths = [f"{path}-{i:05d}-of-{shards:05d}" for i in range(shards)]
    if native.is_native_loaded():
        files = [native.NativeRecordWriter(p + ".tmp") for p in paths]
    else:
        files = [_PyRecordWriter(p + ".tmp") for p in paths]
    try:
        for i, rec in enumerate(records):
            files[i % shards].write(pickle.dumps(rec, pickle.HIGHEST_PROTOCOL))
    finally:
        for fh in files:
            fh.close()
    for p in paths:
        os.replace(p + ".tmp", p)
    return paths


def read_records(path: str) -> Iterator[Any]:
    """Read one shard file, a glob pattern, or a `base` written with shards>1.
    Uses the native C++ reader (csrc/recordio.cc) when built."""
    from . import native

    paths = sorted(glob.glob(path)) or sorted(glob.glob(path + "-*-of-*"))
    if not paths and os.path.exists(path):
        paths = [path]
    if not paths:
        raise FileNotFoundError(path)
    opener = (native.NativeRecordReader if native.is_native_loaded()
              else _PyRecordReader)
    for p in paths:
        with opener(p) as reader:
            for payload in reader:
                yield pickle.loads(payload)


def count_records(path: str) -> int:
    """Count records in one shard by walking the frame headers (length +
    seek past payload) — no CRC check, no unpickling; used by streaming
    datasets to size/balance a corpus without decoding it."""
    import struct

    n = 0
    with open(path, "rb") as f:
        while True:
            hdr = f.read(8)
            if not hdr:
                return n
            if len(hdr) < 8:
                raise IOError(f"truncated record header in {path!r}")
            (length,) = struct.unpack("<Q", hdr)
            f.seek(4 + length + 4, 1)
            n += 1
