"""BDRecord: the sharded record-file format replacing Hadoop SequenceFiles.

Reference: BigDL reads training corpora from Spark-cached Hadoop SequenceFiles
(`DataSet.SeqFileFolder`, dataset/DataSet.scala:319; ETL in
models/utils/ImageNetSeqFileGenerator.scala).  On TPU hosts there is no HDFS;
the equivalent is a dumb, seekable, shardable local record format.

Format (little-endian), per record:
    u64  length
    u32  masked crc32c of the 8-byte length field
    <length bytes>
    u32  masked crc32c of the payload
i.e. exactly the TFRecord framing (also used by the TensorBoard event writer,
visualization/tensorboard), with the same CRC mask.  CRC32C is computed by the
native C++ library (csrc/) when built, with a pure-Python fallback.

Payloads are pickled objects (typically `Sample`s) via `write_records`, or raw
bytes via the *_bytes variants.

Corruption handling: CRC/framing failures raise the typed
:class:`CorruptRecord` (sibling of file_io.CorruptCheckpoint; subclasses
both IOError and ValueError so legacy handlers keep catching) carrying
the shard path and byte offset.  Readers are fail-loud by default; an
opt-in :class:`SkipBudget` (``BIGDL_TPU_DATA_SKIP_BUDGET``) lets the data
path quarantine up to N corrupt records per pass — offset + reason
logged, counted — instead of killing a multi-day run on one rotten byte.
The ``data.record`` chaos point (utils/chaos) mutates payload bytes
BEFORE the CRC check, so injected corruption exercises exactly the real
detection path.
"""

from __future__ import annotations

import glob
import logging
import os
import pickle
import struct
from typing import Any, Iterable, Iterator, List, Optional

from . import chaos

logger = logging.getLogger("bigdl_tpu")

__all__ = ["write_records", "read_records", "count_records",
           "write_record_bytes", "read_record_bytes", "masked_crc32c",
           "crc32c_update", "CorruptRecord", "SkipBudget",
           "quarantine_stats", "reset_quarantine_stats"]


class CorruptRecord(IOError, ValueError):
    """A data record whose CRC/framing/payload failed verification.

    Carries ``path`` and ``offset`` (byte offset of the record start, or
    None when unknowable).  ``resumable`` says whether the stream is
    positioned after the bad record so a skip-budget reader can continue
    (False for e.g. a corrupt length header — the length itself is
    untrusted, resync is impossible, the error stays fatal regardless of
    budget).  Subclasses both IOError (sibling of CorruptCheckpoint) and
    ValueError (what the seqfile reader historically raised)."""

    def __init__(self, message: str, path: Optional[str] = None,
                 offset: Optional[int] = None, resumable: bool = True):
        super().__init__(message)
        self.path = path
        self.offset = offset
        self.resumable = resumable


# process-wide quarantine counters (diagnostics / test assertions — the
# chaos.counts() analog for the corrupt-record path)
_QUARANTINE_STATS = {"records": 0}


def quarantine_stats() -> dict:
    return dict(_QUARANTINE_STATS)


def reset_quarantine_stats() -> None:
    _QUARANTINE_STATS["records"] = 0


class SkipBudget:
    """Bounded corrupt-record quarantine for one data pass.

    budget=None reads ``BIGDL_TPU_DATA_SKIP_BUDGET`` (default 0 = today's
    fail-loud).  ``quarantine(exc)`` returns True when the record was
    absorbed (logged + counted); False means the budget is exhausted (or
    the error is non-resumable) and the caller must re-raise."""

    def __init__(self, budget: Optional[int] = None):
        if budget is None:
            from . import config
            budget = config.get_int("DATA_SKIP_BUDGET", 0)
        self.budget = int(budget)
        self.quarantined: List[tuple] = []  # (path, offset, reason)

    @property
    def count(self) -> int:
        return len(self.quarantined)

    def quarantine(self, exc: CorruptRecord) -> bool:
        if not getattr(exc, "resumable", False):
            return False
        if self.count >= self.budget:
            return False
        self.quarantined.append((exc.path, exc.offset, str(exc)))
        _QUARANTINE_STATS["records"] += 1
        logger.warning(
            "data: quarantined corrupt record %d/%d in %s at offset %s: %s",
            self.count, self.budget, exc.path, exc.offset, exc)
        return True


def _table():
    global _TABLE
    if _TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _TABLE = table
    return _TABLE


def crc32c_update(crc: int, data: bytes) -> int:
    """Continue a finalized CRC32C over more bytes (seed 0 for the first
    chunk): crc32c_update(crc32c_update(0, a), b) == crc32c(a + b).  The
    checkpoint framer (utils/file_io) streams pickles through this; native
    `bigdl_crc32c_extend` when the compiled library exports it, pure-Python
    table loop otherwise."""
    from .native import crc32c_extend as native_extend
    if native_extend is not None:
        return native_extend(crc, data)
    tb = _table()
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = tb[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _crc32c_py(data: bytes) -> int:
    """Pure-Python CRC32C (Castagnoli) — fallback when the native lib is
    absent (reference vendors the same algorithm as netty/Crc32c.java)."""
    tb = _table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tb[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_TABLE = None


def _crc32c(data: bytes) -> int:
    from .native import crc32c as native_crc32c
    if native_crc32c is not None:
        return native_crc32c(data)
    return _crc32c_py(data)


def masked_crc32c(data: bytes) -> int:
    """TFRecord CRC mask (reference: RecordWriter.scala:44-57 /
    netty/Crc32c.java)."""
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def write_record_bytes(f, payload: bytes) -> None:
    header = struct.pack("<Q", len(payload))
    f.write(header)
    f.write(struct.pack("<I", masked_crc32c(header)))
    f.write(payload)
    f.write(struct.pack("<I", masked_crc32c(payload)))


def read_record_bytes(f, path: Optional[str] = None) -> bytes:
    """One framed record; raises the typed :class:`CorruptRecord`
    (path + byte offset) on any CRC/truncation failure.  A header-CRC
    failure is non-resumable (the length field itself is untrusted, the
    stream cannot resync); payload failures leave the stream positioned
    at the next record, so skip-budget readers can continue."""
    offset = None
    try:
        offset = f.tell()
    except (OSError, AttributeError):
        pass
    header = f.read(8)
    if not header:
        raise EOFError
    if len(header) < 8:
        raise CorruptRecord(f"truncated record header in {path!r}",
                            path=path, offset=offset)
    (length,) = struct.unpack("<Q", header)
    hcrc_raw = f.read(4)
    if len(hcrc_raw) < 4:
        raise CorruptRecord(f"truncated record header crc in {path!r}",
                            path=path, offset=offset)
    (hcrc,) = struct.unpack("<I", hcrc_raw)
    if hcrc != masked_crc32c(header):
        raise CorruptRecord(
            f"corrupt record header (crc mismatch) in {path!r} at offset "
            f"{offset}", path=path, offset=offset, resumable=False)
    payload = f.read(length)
    if len(payload) < length:
        raise CorruptRecord(
            f"truncated record payload in {path!r} at offset {offset} "
            f"(frame declares {length} bytes, file holds {len(payload)})",
            path=path, offset=offset)
    pcrc_raw = f.read(4)
    if len(pcrc_raw) < 4:
        raise CorruptRecord(f"truncated record payload crc in {path!r} at "
                            f"offset {offset}", path=path, offset=offset)
    (pcrc,) = struct.unpack("<I", pcrc_raw)
    # chaos mutates the payload BEFORE the CRC check: injected corruption
    # (flip/truncate) trips exactly the verification real bit-rot would
    payload = chaos.transform("data.record", payload)
    if pcrc != masked_crc32c(payload):
        raise CorruptRecord(
            f"corrupt record payload (crc mismatch) in {path!r} at offset "
            f"{offset}", path=path, offset=offset)
    return payload


class _PyRecordWriter:
    """Same write/close interface as native.NativeRecordWriter."""

    def __init__(self, path: str):
        self._f = open(path, "wb")

    def write(self, payload: bytes) -> None:
        write_record_bytes(self._f, payload)

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _PyRecordReader:
    """Same iterator interface as native.NativeRecordReader."""

    def __init__(self, path: str):
        self._path = path
        self._f = open(path, "rb")

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        try:
            return read_record_bytes(self._f, path=self._path)
        except EOFError:
            raise StopIteration

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records: Iterable[Any],
                  shards: int = 1) -> List[str]:
    """Write records round-robin over `shards` files: path-00000-of-00008 style
    (the sharded layout Spark partitions played in the reference).  Uses the
    native C++ writer (csrc/recordio.cc) when built."""
    from . import native

    if shards == 1:
        paths = [path]
    else:
        paths = [f"{path}-{i:05d}-of-{shards:05d}" for i in range(shards)]
    if native.is_native_loaded():
        files = [native.NativeRecordWriter(p + ".tmp") for p in paths]
    else:
        files = [_PyRecordWriter(p + ".tmp") for p in paths]
    try:
        for i, rec in enumerate(records):
            files[i % shards].write(pickle.dumps(rec, pickle.HIGHEST_PROTOCOL))
    finally:
        for fh in files:
            fh.close()
    for p in paths:
        os.replace(p + ".tmp", p)
    return paths


def read_records(path: str, skip: Optional[SkipBudget] = None
                 ) -> Iterator[Any]:
    """Read one shard file, a glob pattern, or a `base` written with shards>1.
    Uses the native C++ reader (csrc/recordio.cc) when built.

    `skip` (a :class:`SkipBudget`) opts into bounded corrupt-record
    quarantine: resumable :class:`CorruptRecord` failures (payload CRC,
    truncation, unpicklable payload) are logged + counted and the read
    continues, until the budget is exhausted.  Skipping (and the
    ``data.record`` chaos point) forces the pure-Python reader — the
    native reader can neither resync nor inject."""
    from . import native

    paths = sorted(glob.glob(path)) or sorted(glob.glob(path + "-*-of-*"))
    if not paths and os.path.exists(path):
        paths = [path]
    if not paths:
        raise FileNotFoundError(path)
    use_native = (native.is_native_loaded()
                  and (skip is None or skip.budget <= 0)
                  and not chaos.armed("data.record"))
    opener = native.NativeRecordReader if use_native else _PyRecordReader
    for p in paths:
        with opener(p) as reader:
            it = iter(reader)
            while True:
                try:
                    payload = next(it)
                except StopIteration:
                    break
                except CorruptRecord as e:
                    if skip is not None and skip.quarantine(e):
                        continue
                    raise
                try:
                    rec = pickle.loads(payload)
                except Exception as e:  # noqa: BLE001 — any unpickle
                    # failure on a CRC-clean payload is still a corrupt
                    # record (e.g. a writer torn mid-object)
                    ce = CorruptRecord(
                        f"unreadable record payload in {p!r} "
                        f"({type(e).__name__}: {e})", path=p)
                    if skip is not None and skip.quarantine(ce):
                        continue
                    raise ce from e
                yield rec


def count_records(path: str) -> int:
    """Count records in one shard by walking the frame headers (length +
    seek past payload) — no CRC check, no unpickling; used by streaming
    datasets to size/balance a corpus without decoding it."""
    import struct

    n = 0
    with open(path, "rb") as f:
        while True:
            hdr = f.read(8)
            if not hdr:
                return n
            if len(hdr) < 8:
                raise IOError(f"truncated record header in {path!r}")
            (length,) = struct.unpack("<Q", hdr)
            f.seek(4 + length + 4, 1)
            n += 1
