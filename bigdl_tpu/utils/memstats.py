"""Device-memory accounting for the bench/smoke trajectory.

FSDP's whole value proposition is a MEMORY number — per-device
parameter+slot bytes dropping to ~1/N — and donation's is a PEAK number
(no second params+slots copy alive during the update).  Neither shows
up in images/sec, so bench.py records them explicitly in every
per-config record (satellite of ISSUE 9):

- :func:`device_memory_stats` — the accelerator runtime's own ledger
  (``device.memory_stats()``: ``bytes_in_use`` / ``peak_bytes_in_use``
  on TPU/GPU plugins).  Returns None where the backend has no ledger
  (CPU), in which case callers fall back to
- :func:`live_device_bytes` — the live-buffer sum: every
  ``jax.live_arrays()`` leaf's addressable shards on one device.  No
  peak semantics, but deltas across a step still show donation working
  (a donated step leaves no second copy alive).
- :func:`tree_device_bytes` — one pytree's bytes on one device: the
  per-device parameter (or slot) footprint, == total/N under an FSDP=N
  layout and == total when replicated.
"""

from __future__ import annotations

from typing import Optional

import jax

__all__ = ["device_memory_stats", "live_device_bytes", "tree_device_bytes",
           "tree_total_bytes", "memory_record", "pipeline_stage_bytes",
           "embedding_table_bytes", "compiled_memory_analysis"]


def device_memory_stats(device=None) -> Optional[dict]:
    """``device.memory_stats()`` where the backend implements it, else
    None (CPU devices raise/return nothing useful)."""
    dev = device or jax.devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 — unimplemented on this backend
        return None
    if not stats or "bytes_in_use" not in stats:
        return None
    return dict(stats)


def _shard_bytes_on(leaf, device) -> int:
    """Bytes leaf `leaf` occupies on `device` (0 when absent there)."""
    if not hasattr(leaf, "addressable_shards"):
        return 0
    total = 0
    for s in leaf.addressable_shards:
        if s.device == device:
            total += int(s.data.nbytes)
    return total


def live_device_bytes(device=None) -> int:
    """Sum of all live jax.Array bytes resident on one device — the
    CPU-measurable stand-in for ``bytes_in_use``.  Deleted (donated)
    buffers are not live, so a donated train step shows here as NOT
    doubling params+slots."""
    dev = device or jax.devices()[0]
    total = 0
    for arr in jax.live_arrays():
        try:
            total += _shard_bytes_on(arr, dev)
        except Exception:  # noqa: BLE001 — a concurrently deleted array
            continue
    return total


def tree_device_bytes(tree, device=None) -> int:
    """One pytree's bytes on one device (per-device param/slot
    footprint: total/N under FSDP=N, total when replicated)."""
    dev = device or jax.devices()[0]
    return sum(_shard_bytes_on(leaf, dev) for leaf in jax.tree.leaves(tree)
               if hasattr(leaf, "addressable_shards"))


def tree_total_bytes(tree) -> int:
    """The tree's LOGICAL size (global bytes, sharding-independent)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None and hasattr(leaf, "size"):
            nbytes = int(leaf.size) * leaf.dtype.itemsize
        total += int(nbytes or 0)
    return total


def pipeline_stage_bytes(model, params, device=None):
    """Per-stage parameter accounting for every GPipeSequential in the
    model (parallel/pipeline): the stacked stage params' logical bytes,
    bytes per stage, and the bytes actually resident on one device —
    1/n_stages of the stack under a pipe=n layout, the whole stack when
    replicated.  Walks the module tree parallel to the params pytree
    (the Container/Graph list-alignment, like layout.role_tree).
    Returns a list of one dict per pipeline, or None when the model has
    no pipelined region."""
    from ..parallel.pipeline import GPipeSequential
    dev = device or jax.devices()[0]
    out = []

    def walk(mod, p):
        if isinstance(mod, GPipeSequential):
            total = tree_total_bytes(p)
            n = len(mod.stages)
            out.append({"stages": n,
                        "stage_param_bytes": total // max(n, 1),
                        "stacked_param_bytes": total,
                        "param_bytes_per_device": tree_device_bytes(p, dev)})
            return
        kids = getattr(mod, "modules", None)
        if kids is not None and isinstance(p, list) and len(kids) == len(p):
            for m, cp in zip(kids, p):
                walk(m, cp)

    walk(model, params)
    return out or None


def embedding_table_bytes(model, params, device=None):
    """Per-table accounting for every module whose param_roles() place a
    parameter under ``embedding_row`` (LookupTable and friends): logical
    table bytes, bytes resident on one device, and the resident fraction
    — exactly 1/N under an fsdp×tp=N row-sharded layout, 1.0 when
    replicated.  Embedding tables dominate recommender memory (the
    wide-and-deep workload's whole FSDP story), so bench.py reports this
    block per config.  Walks the module tree parallel to the params
    pytree (the Container/Graph list-alignment, like
    pipeline_stage_bytes).  Returns a list of one dict per table, or
    None when the model has no embedding-role parameters."""
    dev = device or jax.devices()[0]
    out = []

    def walk(mod, p):
        kids = getattr(mod, "modules", None)
        if kids is not None and isinstance(p, list) and len(kids) == len(p):
            for m, cp in zip(kids, p):
                walk(m, cp)
            return
        roles = mod.param_roles() if hasattr(mod, "param_roles") else None
        if not roles or not isinstance(p, dict):
            return
        for name, leaf in p.items():
            role = roles.get(name, roles.get("*"))
            if role != "embedding_row":
                continue
            total = tree_total_bytes(leaf)
            per_dev = tree_device_bytes(leaf, dev)
            out.append({"module": type(mod).__name__, "param": name,
                        "rows": int(leaf.shape[0]) if leaf.ndim else 0,
                        "table_bytes": total,
                        "table_bytes_per_device": per_dev,
                        "device_fraction": round(per_dev / total, 6)
                        if total else 0.0})

    walk(model, params)
    return out or None


def compiled_memory_analysis(compiled) -> Optional[dict]:
    """XLA's own memory budget for one compiled executable
    (``Compiled.memory_analysis()``) as a plain dict, or None where the
    backend doesn't expose it.

    ``temp_bytes`` is the compiler's peak scratch estimate — every
    intermediate the program keeps alive at once, which for a train step
    is dominated by saved-for-backward activations.  This is the
    CPU-measurable proxy for the pipeline-schedule memory claim
    (ISSUE 13): a 1F1B step's bounded in-flight stash must budget no
    more temp than the GPipe step's keep-every-microbatch backward
    (``tools/pipeline_smoke.py`` + tests assert the ≤)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — unimplemented on this backend
        return None
    if ma is None:
        return None
    out = {}
    for name, key in (("temp_size_in_bytes", "temp_bytes"),
                      ("argument_size_in_bytes", "argument_bytes"),
                      ("output_size_in_bytes", "output_bytes"),
                      ("alias_size_in_bytes", "alias_bytes"),
                      ("generated_code_size_in_bytes", "code_bytes")):
        val = getattr(ma, name, None)
        if val is not None:
            out[key] = int(val)
    return out or None


def memory_record(params=None, opt_state=None, device=None) -> dict:
    """The bench-record memory block: runtime ledger when available
    (``source: memory_stats``), live-buffer sum fallback
    (``source: live_buffer_sum``), plus per-device and total bytes for
    the given params/opt_state trees."""
    dev = device or jax.devices()[0]
    rec: dict = {}
    stats = device_memory_stats(dev)
    if stats is not None:
        rec["source"] = "memory_stats"
        rec["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
        if "peak_bytes_in_use" in stats:
            rec["peak_bytes_in_use"] = int(stats["peak_bytes_in_use"])
    else:
        rec["source"] = "live_buffer_sum"
        rec["bytes_in_use"] = live_device_bytes(dev)
    if params is not None:
        rec["param_bytes_per_device"] = tree_device_bytes(params, dev)
        rec["param_bytes_total"] = tree_total_bytes(params)
    if opt_state is not None:
        rec["slot_bytes_per_device"] = tree_device_bytes(opt_state, dev)
        rec["slot_bytes_total"] = tree_total_bytes(opt_state)
    return rec
