"""Backend-platform selection helpers.

This image's sitecustomize imports jax at interpreter startup (axon TPU
plugin), so JAX_PLATFORMS env vars set after startup are too late; only
`jax.config.update` works, and only before the backend is first used.  This
helper is the single home for that idiom (previously duplicated across
tests/conftest.py, __graft_entry__.py, tools/scaling.py, bench.py).
"""

from __future__ import annotations

from typing import Optional

__all__ = ["force_cpu", "enable_compilation_cache", "enable_overlap_flags"]


#: latency-hiding-scheduler / async-collective flags for the TPU compiler.
#: The bucketed gradient wire (parallel/wire.py) gives XLA a handful of
#: bucket-sized bf16 all-reduces; these flags let it ISSUE them while the
#: backward tail is still computing instead of serializing them after it —
#: the MLPerf TPU-pods overlap move (PAPERS.md).  Flag-by-flag: the
#: latency-hiding scheduler reorders ops to hide collective latency behind
#: compute; async-collective fusion converts blocking collectives to
#: start/done pairs (multiple_steps lets one fusion span several of them);
#: overlap_compute_collective_tc runs collectives on the transfer core
#: concurrently with TensorCore compute.
_OVERLAP_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)


def enable_overlap_flags() -> Optional[str]:
    """Arm the XLA collective-overlap flags via LIBTPU_INIT_ARGS.

    Must run BEFORE the TPU backend initializes (libtpu reads the env at
    load); call it next to `force_cpu`/`enable_compilation_cache` at
    process start (bench.py does).  Flags go into LIBTPU_INIT_ARGS — read
    only by libtpu, so the call is inert on CPU/GPU processes — and any
    flag the operator already set there wins (only missing keys are
    appended).  ``BIGDL_TPU_OVERLAP_FLAGS=0`` disables.  Returns the
    LIBTPU_INIT_ARGS value in effect, or None when disabled.
    """
    import os

    from . import config as _config

    if not _config.get_bool("OVERLAP_FLAGS", True):
        return None
    cur = os.environ.get("LIBTPU_INIT_ARGS", "")
    add = [f for f in _OVERLAP_FLAGS if f.split("=", 1)[0] not in cur]
    if add:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join(
            ([cur] if cur else []) + add)
    return os.environ.get("LIBTPU_INIT_ARGS")


def enable_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax at a persistent XLA compilation cache (verified working on
    the tunneled axon backend: cross-process warm compiles).

    Why it matters here: XLA compiles of some small models are pathologically
    slow on this backend (LeNet's train step: 809s in one measured run,
    >905s in another, vs 27s for ResNet-50 — see docs/benchmarking.md), so a
    warm on-disk cache is the difference between a bench config fitting the
    harness budget or stalling out.

    `path` defaults to $BIGDL_TPU_XLA_CACHE_DIR or ~/.cache/bigdl_tpu/xla;
    set BIGDL_TPU_XLA_CACHE=0 to disable.  Returns the cache dir in use, or
    None when disabled/unavailable (backend already initialized with a
    different cache config is fine — jax applies this lazily per compile).

    Layering note: this warms the XLA *compiler* per jit function; the AOT
    executable cache (utils/aot.py, BIGDL_TPU_AOT_CACHE) sits one level
    above and skips compilation entirely for whole cached executables.
    They compose — an AOT miss still compiles through this cache — and
    either can be disabled independently.
    """
    import os

    from . import config as _config

    if not _config.get_bool("XLA_CACHE", True):
        return None
    path = path or _config.get_str(
        "XLA_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "bigdl_tpu", "xla"))
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return None
    import jax

    # Feature-detect every knob instead of assuming this jax version has
    # it (the config-option set drifts release to release: the threshold
    # knobs appeared mid-0.4.x, `jax_enable_compilation_cache` later) —
    # an older/newer jax missing one knob should not forfeit the cache,
    # it just keeps that knob's own default.
    def _maybe(knob, val):
        if not _has_config_option(jax, knob):
            return False
        try:
            jax.config.update(knob, val)
            return True
        except Exception:  # noqa: BLE001 — present but rejects the value
            return False

    # cache everything: even sub-second entries save tunnel round-trips,
    # and the pathological compiles are exactly the ones worth keeping
    _maybe("jax_enable_compilation_cache", True)
    _maybe("jax_persistent_cache_min_compile_time_secs", 0.0)
    _maybe("jax_persistent_cache_min_entry_size_bytes", 0)
    if not _maybe("jax_compilation_cache_dir", path):
        # the dir knob is the one that actually arms the cache — without
        # it there is no persistent cache on this jax
        return None
    # jax latches its cache object the first time any compile consults it
    # (compilation_cache._cache_initialized): a process that already
    # compiled something with NO dir configured would silently ignore this
    # call forever.  Feature-detect the reset hook and get back to a
    # pristine state so the new dir takes effect mid-process too.
    try:
        from jax._src import compilation_cache as _cc
        if getattr(_cc, "_cache_initialized", False) and \
                hasattr(_cc, "reset_cache"):
            current = getattr(getattr(_cc, "_cache", None), "_path", None)
            if str(current) != path:
                _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private surface; absence is fine
        pass
    return path


def _has_config_option(jax_mod, knob: str) -> bool:
    """True when this jax build knows `knob` (checked against the config
    registry when available, falling back to attribute presence)."""
    values = getattr(jax_mod.config, "_value_holders", None)
    if values is None:
        values = getattr(jax_mod.config, "values", None)
    if isinstance(values, dict):
        return knob in values
    return hasattr(jax_mod.config, knob)


def force_cpu(n_devices: Optional[int] = None) -> bool:
    """Point jax at the CPU backend with `n_devices` virtual devices.

    Returns True when the config took effect, False when the backend was
    already initialized (caller should then check jax.devices() itself).
    """
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        if n_devices is not None:
            try:
                jax.config.update("jax_num_cpu_devices", int(n_devices))
            except AttributeError:
                # older jax has no jax_num_cpu_devices config option; the
                # XLA flag is read lazily at CPU-client creation, so the
                # env var still works even after `import jax` as long as
                # no backend is initialized yet
                import os
                flag = ("--xla_force_host_platform_device_count="
                        f"{int(n_devices)}")
                cur = os.environ.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in cur:
                    os.environ["XLA_FLAGS"] = (cur + " " + flag).strip()
        return True
    except RuntimeError:
        return False  # backend already initialized — use as-is


def backend_kind() -> str:
    """The active backend, with TPU plugin names resolved: 'tpu', 'cpu',
    or the raw platform name for anything else.

    The tunneled plugin on this image registers as 'tpu', but other
    builds expose the plugin name (e.g. 'axon') while device_kind stays
    'TPU ...' — gate TPU-only code paths (Pallas kernels) on this, never
    on `jax.default_backend() == "tpu"` alone (see timing.is_tpu_like).
    """
    import jax

    from .timing import is_tpu_like

    b = jax.default_backend()
    if b == "cpu":
        return "cpu"
    if b == "tpu" or any(is_tpu_like(d) for d in jax.local_devices()):
        return "tpu"
    return b
