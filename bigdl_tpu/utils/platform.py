"""Backend-platform selection helpers.

This image's sitecustomize imports jax at interpreter startup (axon TPU
plugin), so JAX_PLATFORMS env vars set after startup are too late; only
`jax.config.update` works, and only before the backend is first used.  This
helper is the single home for that idiom (previously duplicated across
tests/conftest.py, __graft_entry__.py, tools/scaling.py, bench.py).
"""

from __future__ import annotations

from typing import Optional

__all__ = ["force_cpu"]


def force_cpu(n_devices: Optional[int] = None) -> bool:
    """Point jax at the CPU backend with `n_devices` virtual devices.

    Returns True when the config took effect, False when the backend was
    already initialized (caller should then check jax.devices() itself).
    """
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        if n_devices is not None:
            jax.config.update("jax_num_cpu_devices", int(n_devices))
        return True
    except RuntimeError:
        return False  # backend already initialized — use as-is
