"""ML-pipeline estimators: fit/transform adapters over the Optimizer.

Reference: org/apache/spark/ml/DLEstimator.scala:53 and DLClassifier.scala —
Spark ML `Estimator`s that train a BigDL module from a DataFrame
(feature/label columns -> MiniBatch RDD -> optimizer fit) and return a
`DLModel` transformer whose `transform` appends a prediction column.

TPU re-design: there is no Spark; the host data structures are numpy
arrays / pandas DataFrames, and the API follows the scikit-learn
fit/predict protocol (the ecosystem's pipeline convention, as Spark ML was
the reference's).  `DLEstimator.fit(X, y)` -> `DLModel` with
`.transform(X)` / `.predict(X)`; `DLClassifier` adds argmax + accuracy
`score`."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from .dataset import DataSet, Sample, SampleToMiniBatch
from .nn.criterion import Criterion
from .nn.module import Module
from .optim.method import OptimMethod
from .optim.optimizer import Optimizer
from .optim.trigger import Trigger

__all__ = ["DLEstimator", "DLModel", "DLClassifier", "DLClassifierModel"]


def _as_2d(X) -> np.ndarray:
    X = np.asarray(X, dtype=np.float32)
    return X


class DLEstimator:
    """(reference: DLEstimator.scala:53).  Configure like the Optimizer
    facade, then `fit(X, y) -> DLModel`."""

    def __init__(self, model: Module, criterion: Criterion,
                 feature_size: Optional[Sequence[int]] = None,
                 label_size: Optional[Sequence[int]] = None,
                 batch_size: int = 32, max_epoch: int = 10,
                 optim_method: Optional[OptimMethod] = None):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size) if feature_size else None
        self.label_size = tuple(label_size) if label_size else None
        self.batch_size = batch_size
        self.max_epoch = max_epoch
        self.optim_method = optim_method

    def fit(self, X, y) -> "DLModel":
        X = _as_2d(X)
        y = np.asarray(y, dtype=np.float32)
        samples = []
        for i in range(len(X)):
            f = X[i].reshape(self.feature_size) if self.feature_size else X[i]
            lbl = (y[i].reshape(self.label_size) if self.label_size
                   else y[i])
            samples.append(Sample(f, lbl))
        # pad_last keeps the trailing partial batch at the compiled step's
        # static shape (drop_last=False would retrace / break mesh-divisible
        # sharding; see Optimizer's own batch path)
        ds = DataSet.array(samples).transform(
            SampleToMiniBatch(self.batch_size, pad_last=True))
        opt = Optimizer(self.model, ds, self.criterion) \
            .set_end_when(Trigger.max_epoch(self.max_epoch))
        if self.optim_method is not None:
            opt.set_optim_method(self.optim_method)
        trained = opt.optimize()
        return self._make_model(trained)

    def _make_model(self, trained: Module) -> "DLModel":
        return DLModel(trained, self.feature_size,
                       batch_size=self.batch_size)


class DLModel:
    """Fitted transformer (reference: DLModel/DLTransformerBase)."""

    def __init__(self, model: Module, feature_size=None, batch_size=128):
        self.model = model
        self.feature_size = tuple(feature_size) if feature_size else None
        self.batch_size = batch_size
        self._fwd = None

    def _forward_batch(self, xb: np.ndarray) -> np.ndarray:
        if self._fwd is None:
            m = self.model

            @jax.jit
            def fwd(params, state, x):
                out, _ = m.apply(params, state, x, training=False)
                return out

            self._fwd = fwd
        return np.asarray(self._fwd(self.model.params, self.model.state,
                                    np.asarray(xb, np.float32)))

    def transform(self, X) -> np.ndarray:
        """Returns the raw model outputs row-aligned with X (the reference
        appends a prediction column to the DataFrame)."""
        X = _as_2d(X)
        outs = []
        for i in range(0, len(X), self.batch_size):
            xb = X[i:i + self.batch_size]
            if self.feature_size:
                xb = xb.reshape((-1,) + self.feature_size)
            outs.append(self._forward_batch(xb))
        return np.concatenate(outs, axis=0)

    predict = transform


class DLClassifier(DLEstimator):
    """(reference: DLClassifier.scala — argmax transform)."""

    def _make_model(self, trained: Module) -> "DLClassifierModel":
        return DLClassifierModel(trained, self.feature_size,
                                 batch_size=self.batch_size)


class DLClassifierModel(DLModel):
    def predict(self, X) -> np.ndarray:
        """Class indices (0-based; the reference emitted 1-based ml labels)."""
        return np.argmax(self.transform(X), axis=-1)

    def score(self, X, y) -> float:
        return float(np.mean(self.predict(X) == np.asarray(y)))
