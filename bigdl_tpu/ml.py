"""ML-pipeline estimators: fit/transform adapters over the Optimizer.

Reference: org/apache/spark/ml/DLEstimator.scala:53 and DLClassifier.scala —
Spark ML `Estimator`s that train a BigDL module from a DataFrame
(feature/label columns -> MiniBatch RDD -> optimizer fit) and return a
`DLModel` transformer whose `transform` appends a prediction column.

TPU re-design: there is no Spark; the host data structures are numpy
arrays / pandas DataFrames, and the API follows the scikit-learn
fit/predict protocol (the ecosystem's pipeline convention, as Spark ML was
the reference's).  `DLEstimator.fit(X, y)` -> `DLModel` with
`.transform(X)` / `.predict(X)`; `DLClassifier` adds argmax + accuracy
`score`."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from .dataset import DataSet, Sample, SampleToMiniBatch
from .nn.criterion import Criterion
from .nn.module import Module
from .optim.method import OptimMethod
from .optim.optimizer import Optimizer
from .optim.trigger import Trigger

__all__ = ["DLEstimator", "DLModel", "DLClassifier", "DLClassifierModel"]


def _as_2d(X) -> np.ndarray:
    X = np.asarray(X, dtype=np.float32)
    return X


def _extract_columns(data, y, features_col, label_col):
    """Resolve (X, y) from either arrays or a DataFrame-like with named
    columns — the reference's featuresCol/labelCol contract
    (DLEstimator.scala:53-109: DataFrame rows -> feature/label tensors)."""
    if hasattr(data, "columns"):  # pandas DataFrame (or anything alike)
        if features_col is None:
            cols = [c for c in data.columns if c != label_col]
        elif isinstance(features_col, str):
            cols = [features_col]
        else:
            cols = list(features_col)
        X = np.stack([np.stack(np.asarray(data[c], dtype=object)
                               ).astype(np.float32)
                      if data[c].dtype == object
                      else np.asarray(data[c], np.float32) for c in cols],
                     axis=-1)
        if X.shape[-1] == 1 and X.ndim > 2:
            X = X[..., 0]
        if y is None and label_col is not None and label_col in data.columns:
            y = np.asarray(data[label_col], np.float32)
        return X, y
    return _as_2d(data), y


class DLEstimator:
    """(reference: DLEstimator.scala:53).  Configure like the Optimizer
    facade, then `fit(X, y)` / `fit(df)` -> DLModel.

    DataFrame column semantics mirror the reference: `features_col` (one
    column of array cells or a list of scalar columns) and `label_col`
    select the training data; the fitted model's `transform(df)` returns a
    copy with `prediction_col` appended.  Validation data + an early-
    stopping patience play the role the reference delegates to
    setValidation/Plateau (optim/Optimizer.scala:98, SGD.scala:534)."""

    def __init__(self, model: Module, criterion: Criterion,
                 feature_size: Optional[Sequence[int]] = None,
                 label_size: Optional[Sequence[int]] = None,
                 batch_size: int = 32, max_epoch: int = 10,
                 optim_method: Optional[OptimMethod] = None,
                 features_col=None, label_col: str = "label",
                 prediction_col: str = "prediction",
                 validation_data=None, early_stopping_patience: int = 0):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size) if feature_size else None
        self.label_size = tuple(label_size) if label_size else None
        self.batch_size = batch_size
        self.max_epoch = max_epoch
        self.optim_method = optim_method
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.validation_data = validation_data  # (X_val, y_val) or None
        self.early_stopping_patience = early_stopping_patience

    def set_validation(self, X_val, y_val,
                       early_stopping_patience: int = 0) -> "DLEstimator":
        self.validation_data = (X_val, y_val)
        if early_stopping_patience:
            self.early_stopping_patience = early_stopping_patience
        return self

    def _samples(self, X, y):
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float32)
        samples = []
        for i in range(len(X)):
            f = X[i].reshape(self.feature_size) if self.feature_size else X[i]
            lbl = (y[i].reshape(self.label_size) if self.label_size
                   else y[i])
            samples.append(Sample(f, lbl))
        return samples

    def fit(self, X, y=None) -> "DLModel":
        from .optim.validation import Loss
        X, y = _extract_columns(X, y, self.features_col, self.label_col)
        if y is None:
            raise ValueError(
                f"no labels: pass y or a DataFrame with a "
                f"'{self.label_col}' column")
        # pad_last keeps the trailing partial batch at the compiled step's
        # static shape (drop_last=False would retrace / break mesh-divisible
        # sharding; see Optimizer's own batch path)
        ds = DataSet.array(self._samples(X, y)).transform(
            SampleToMiniBatch(self.batch_size, pad_last=True))
        end = Trigger.max_epoch(self.max_epoch)
        opt = Optimizer(self.model, ds, self.criterion)
        if self.validation_data is not None:
            Xv, yv = self.validation_data
            Xv, yv = _extract_columns(Xv, yv, self.features_col,
                                      self.label_col)
            vds = DataSet.array(self._samples(Xv, yv)).transform(
                SampleToMiniBatch(self.batch_size, pad_last=True))
            opt.set_validation(Trigger.every_epoch(), vds,
                               [Loss(self.criterion)])
            if self.early_stopping_patience:
                end = Trigger.or_(end, Trigger.plateau(
                    "val_loss", patience=self.early_stopping_patience))
        opt.set_end_when(end)
        if self.optim_method is not None:
            opt.set_optim_method(self.optim_method)
        trained = opt.optimize()
        self.optimizer_ = opt  # post-fit introspection (epochs run, state)
        return self._make_model(trained)

    def _make_model(self, trained: Module) -> "DLModel":
        return DLModel(trained, self.feature_size,
                       batch_size=self.batch_size,
                       features_col=self.features_col,
                       label_col=self.label_col,
                       prediction_col=self.prediction_col)


class DLModel:
    """Fitted transformer (reference: DLModel/DLTransformerBase)."""

    def __init__(self, model: Module, feature_size=None, batch_size=128,
                 features_col=None, label_col: str = "label",
                 prediction_col: str = "prediction"):
        self.model = model
        self.feature_size = tuple(feature_size) if feature_size else None
        self.batch_size = batch_size
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self._fwd = None

    def _forward_batch(self, xb: np.ndarray) -> np.ndarray:
        if self._fwd is None:
            # mesh-sharded SPMD inference, the same engine Evaluator and
            # Predictor use — a bare jax.jit would run on ONE device while
            # training used the whole mesh (the round-2 Evaluator gap)
            from .optim.optimizer import _ShardedForward
            self._fwd = _ShardedForward(self.model)
        from .optim.optimizer import _trim
        out, n = self._fwd(np.asarray(xb, np.float32))
        return _trim(out, n)  # n = pre-pad row count; handles table outputs

    def _raw_outputs(self, X) -> np.ndarray:
        X = np.asarray(X, np.float32)
        outs = []
        for i in range(0, len(X), self.batch_size):
            xb = X[i:i + self.batch_size]
            if self.feature_size:
                xb = xb.reshape((-1,) + self.feature_size)
            outs.append(self._forward_batch(xb))
        return np.concatenate(outs, axis=0)

    def transform(self, X):
        """Array in -> raw outputs row-aligned with X.  DataFrame in -> a
        COPY with `prediction_col` appended (the reference's
        DLModel.transform contract, DLEstimator.scala)."""
        if hasattr(X, "columns"):
            feats, _ = _extract_columns(X, None, self.features_col,
                                        self.label_col)
            out = self._raw_outputs(feats)
            df = X.copy()
            df[self.prediction_col] = (list(out) if out.ndim > 1
                                       else out)
            return df
        return self._raw_outputs(X)

    def predict(self, X) -> np.ndarray:
        if hasattr(X, "columns"):
            X, _ = _extract_columns(X, None, self.features_col,
                                    self.label_col)
        return self._raw_outputs(X)


class DLClassifier(DLEstimator):
    """(reference: DLClassifier.scala — argmax transform)."""

    def _make_model(self, trained: Module) -> "DLClassifierModel":
        return DLClassifierModel(trained, self.feature_size,
                                 batch_size=self.batch_size,
                                 features_col=self.features_col,
                                 label_col=self.label_col,
                                 prediction_col=self.prediction_col)


class DLClassifierModel(DLModel):
    def predict(self, X) -> np.ndarray:
        """Class indices (0-based; the reference emitted 1-based ml labels)."""
        if hasattr(X, "columns"):
            X, _ = _extract_columns(X, None, self.features_col,
                                    self.label_col)
        return np.argmax(self._raw_outputs(X), axis=-1)

    def transform(self, X):
        """DataFrame in -> copy with argmax class in `prediction_col`;
        array in -> raw outputs (DLModel behavior)."""
        if hasattr(X, "columns"):
            df = X.copy()
            df[self.prediction_col] = self.predict(X)
            return df
        return self._raw_outputs(X)

    def score(self, X, y=None) -> float:
        if hasattr(X, "columns"):
            X, y = _extract_columns(X, y, self.features_col, self.label_col)
        if y is None:
            raise ValueError(
                f"no labels: pass y or a DataFrame with a "
                f"'{self.label_col}' column")
        return float(np.mean(self.predict(X) == np.asarray(y)))
