"""BigDL native-format support for the sequence/embedding zoo.

Round-4 verdict item 4: the reference serializes *every* module
automatically (JVM object serialization needs no per-class code,
nn/Module.scala:41-43), so its RNN and text-classification models —
`Recurrent(RnnCell|LSTM|GRU)`, `TimeDistributed`, `LookupTable`,
`TemporalConvolution`, and `Graph` DAGs — roundtrip out of the box.  This
module closes that gap for `interop/bigdl.py`'s name-based mapper.

The interesting part is weight RE-HOMING.  The reference builds its cells
out of sub-modules (nn/RNN.scala:46-80, nn/LSTM.scala:74-184,
nn/GRU.scala:79-180): the input half of every gate projection lives in a
`preTopology = TimeDistributed(Linear(in, G*hidden))` hoisted out of the
recurrence, and the hidden half in `Linear` layers buried inside the
cell's Sequential graph.  This framework fuses both halves into single
scan-friendly kernels (nn/recurrent.py), so load/save must split/merge:

  RnnCell   ref i2h Linear(H,I) + h2h Linear(H,H)  <->  w_ih/w_hh/bias
            (bias = i2h.b + h2h.b — identical forward, one fused add)
  LSTM p=0  ref gate order [i, g, f, o] (LSTM.scala:124-133 comment
            "input, hidden, forget, output")  <->  ours [i, f, g, o] in
            one (I+H, 4H) kernel — chunks permuted on the way through
  GRU p=0   ref h' = (1-z)*cand + z*h (GRU.scala:155-172); ours
            h' = (1-u)*h + u*cand, i.e. u = 1-z — so the update-gate
            weights are NEGATED (sigmoid(-x) = 1-sigmoid(x)): exact, not
            approximate
  Temporal  ref weight (out, kw*in), window flattened frame-major
            (TemporalConvolution.scala:160-166)  <->  ours (kw, in, out)
  Graph     utils/DirectedGraph.scala Node objects (element/nexts/prevs,
            a CYCLIC object graph — handle sharing in javaser covers it)

The `p != 0` cell variants restructure the reference graph entirely
(per-gate Dropout+Linear stacks, no preTopology) and fail loudly.

Saving rebuilds the reference's *actual* internal cell topology (the
Sequential/ParallelTable/SelectTable machine from buildLSTM/buildGRU), so
a JVM deserializing the stream gets a structurally faithful, runnable
module graph, with real @SerialVersionUIDs where the reference declares
them (classes without the annotation get the JVM's computed default,
which cannot be derived without a JVM — see _SUID in bigdl.py).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from .javaser import JavaArray, JavaObject

_PKG = "com.intel.analytics.bigdl.nn."
_NODE = "com.intel.analytics.bigdl.utils.Node"
_T = "Lcom/intel/analytics/bigdl/tensor/Tensor;"
_MODULE_SIG = "Lcom/intel/analytics/bigdl/nn/abstractnn/AbstractModule;"
_BUF_SIG = "Lscala/collection/mutable/ArrayBuffer;"


def _short(classname: str) -> str:
    return classname[len(_PKG):] if classname.startswith(_PKG) else classname


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

def _walk(obj, seen=None):
    """DFS over a JavaObject graph in field order (cycle-safe)."""
    if seen is None:
        seen = set()
    if not isinstance(obj, (JavaObject, JavaArray)) or id(obj) in seen:
        return
    seen.add(id(obj))
    yield obj
    if isinstance(obj, JavaArray):
        vals = list(obj.values) if obj.values is not None else []
        for v in vals:
            yield from _walk(v, seen)
        return
    for v in obj.fields.values():
        yield from _walk(v, seen)
    for anns in obj.annotations.values():
        for a in anns:
            yield from _walk(a, seen)


def _find_linears(obj) -> List[JavaObject]:
    return [o for o in _walk(obj)
            if isinstance(o, JavaObject) and o.classname == _PKG + "Linear"]


def _seq_items(v) -> list:
    """Items of a serialized scala sequence (ArrayBuffer / plain array /
    WrappedArray).  None and plain (possibly empty) Python sequences mean
    "no elements" — callers pass `fields.get("nexts", [])`, and a Node
    with a null/absent successor buffer must read as a leaf, not as an
    'unsupported scala sequence encoding' error."""
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [x for x in v if x is not None]
    if isinstance(v, JavaArray):
        return [x for x in v.values if x is not None]
    if isinstance(v, JavaObject):
        f = v.fields
        if "array" in f:  # ArrayBuffer / WrappedArray$ofRef
            arr = f["array"]
            n = int(f.get("size0", len(arr.values)))
            return [x for x in list(arr.values)[:n] if x is not None]
    raise ValueError(
        f"bigdl format: unsupported scala sequence encoding {v!r:.80}")


_ACT_BY_NAME: dict = {}
_NAME_BY_ACT: dict = {}


def _init_act_maps():
    if _ACT_BY_NAME:
        return
    import jax
    import jax.numpy as jnp
    _ACT_BY_NAME.update({"Tanh": jnp.tanh, "Sigmoid": jax.nn.sigmoid,
                         "ReLU": jax.nn.relu})
    _NAME_BY_ACT.update({id(v): k for k, v in _ACT_BY_NAME.items()})


def build_seq(short: str, obj: JavaObject, build: Callable):
    """Reader dispatch for the sequence zoo; None = class not handled here.
    `build` is interop.bigdl._build (recursion into generic layers)."""
    from .. import nn
    from .bigdl import _children, _to_numpy

    f = obj.fields
    if short == "TimeDistributed":
        m, p, s = build(f["layer"])
        return nn.TimeDistributed(m), [p], [s]

    if short == "LookupTable":
        max_norm = f.get("maxNorm")
        max_norm = (None if max_norm is None
                    or max_norm >= np.finfo(np.float64).max else
                    float(max_norm))
        pad = float(f.get("paddingValue", 0.0))
        m = nn.LookupTable(int(f["nIndex"]), int(f["nOutput"]),
                           padding_value=pad if pad > 0 else None,
                           max_norm=max_norm,
                           norm_type=float(f.get("normType", 2.0)),
                           one_based=True)  # reference indices are 1-based
        return m, {"weight": _to_numpy(f["weight"])}, {}

    if short == "TemporalConvolution":
        m = nn.TemporalConvolution(int(f["inputFrameSize"]),
                                   int(f["outputFrameSize"]),
                                   int(f["kernelW"]),
                                   int(f.get("strideW", 1)))
        w = _to_numpy(f["weight"])  # (out, kw*in), window frame-major
        kw, cin = m.kernel_w, m.input_frame_size
        w = w.reshape(w.shape[0], kw, cin).transpose(1, 2, 0)  # (kw, in, out)
        return m, {"weight": w, "bias": _to_numpy(f["bias"])}, {}

    if short == "Recurrent":
        return _build_recurrent(obj, build)

    if short == "BiRecurrent":
        return _build_birecurrent(obj, build)

    if short == "Graph":
        return _build_graph(obj, build)

    if short == "BinaryTreeLSTM":
        return _build_treelstm(obj, build)

    return None


def _ref_linear_wb(lin: JavaObject):
    from .bigdl import _to_numpy

    w = _to_numpy(lin.fields["weight"])  # (out, in)
    b = (_to_numpy(lin.fields["bias"])
         if lin.fields.get("bias") is not None else None)
    return w, b


def _build_recurrent(obj: JavaObject, build):
    from .. import nn
    from .bigdl import _children, _to_numpy

    _init_act_maps()
    kids = _children(obj)
    if len(kids) != 2:
        raise ValueError(
            "bigdl format: Recurrent without a hoisted preTopology "
            f"({len(kids)} children) — the p!=0 dropout cell variants "
            "restructure the reference graph and are not mapped")
    pre, topo = kids
    if _short(pre.classname) == "Sequential":
        # LSTMPeephole wraps its preTopology as Sequential(Dropout, TD)
        # (LSTMPeephole.scala:71-75).  Only inference-identity Dropout
        # siblings may be discarded — any other module would change the
        # forward, so unwrapping it silently would mis-load the stream.
        kids_pre = _children(pre)
        tds = [c for c in kids_pre
               if _short(c.classname) == "TimeDistributed"]
        others = [c for c in kids_pre
                  if _short(c.classname) not in ("TimeDistributed",
                                                 "Dropout")]
        if len(tds) == 1 and not others:
            pre = tds[0]
    if _short(pre.classname) != "TimeDistributed":
        raise ValueError(f"bigdl format: Recurrent preTopology "
                         f"{pre.classname} not supported")
    wi, bi = _ref_linear_wb(pre.fields["layer"])
    tshort = _short(topo.classname)
    tf = topo.fields

    if tshort == "RnnCell":
        wh, bh = _ref_linear_wb(tf["h2h"])
        hidden = wh.shape[0]
        cell_modules = _children(tf["cell"])
        act_name = _short(cell_modules[2].classname)
        if act_name not in _ACT_BY_NAME:
            raise ValueError(f"bigdl format: RnnCell activation {act_name} "
                             "not mapped")
        cell = nn.RnnCell(wi.shape[1], hidden, _ACT_BY_NAME[act_name])
        bias = (bi if bi is not None else 0.0) + \
               (bh if bh is not None else 0.0)
        p = {"w_ih": wi.T.copy(), "w_hh": wh.T.copy(),
             "bias": np.asarray(bias, np.float32)}
    elif tshort == "LSTM":
        if float(tf.get("p", 0.0)) != 0.0:
            raise ValueError("bigdl format: LSTM with p!=0 uses the "
                             "per-gate dropout graph — not mapped")
        hidden = int(tf["hiddenSize"])
        insize = int(tf["inputSize"])
        [h2g] = _find_linears(tf["cell"])
        wh, _ = _ref_linear_wb(h2g)          # (4H, H), no bias
        # ref chunk rows [i, g, f, o] -> ours columns [i, f, g, o]
        perm = _gate_perm_ref_to_ours(hidden)
        cell = nn.LSTM(insize, hidden)
        kernel = np.concatenate([wi[perm].T, wh[perm].T], axis=0)
        p = {"kernel": kernel.copy(),
             "bias": np.asarray(bi[perm], np.float32)}
    elif tshort == "GRU":
        if float(tf.get("p", 0.0)) != 0.0:
            raise ValueError("bigdl format: GRU with p!=0 uses the "
                             "per-gate dropout graph — not mapped")
        out = int(tf["outputSize"])
        insize = int(tf["inputSize"])
        linears = _find_linears(tf["cell"])
        h2g = next(l for l in linears
                   if int(l.fields["outputSize"]) == 2 * out)
        hhat = next(l for l in linears
                    if int(l.fields["outputSize"]) == out)
        wh2g, _ = _ref_linear_wb(h2g)        # (2O, O) rows [r, z]
        whh, _ = _ref_linear_wb(hhat)        # (O, O)
        cell = nn.GRU(insize, out)
        # u = 1 - z  =>  negate the z rows (sigmoid(-x) = 1 - sigmoid(x))
        gate_i = np.concatenate([wi[:out], -wi[out:2 * out]], axis=0)
        gate_h = np.concatenate([wh2g[:out], -wh2g[out:]], axis=0)
        p = {"gate_kernel": np.concatenate([gate_i.T, gate_h.T], axis=0),
             "gate_bias": np.concatenate([bi[:out], -bi[out:2 * out]]),
             "cand_kernel": np.concatenate([wi[2 * out:].T, whh.T], axis=0),
             "cand_bias": np.asarray(bi[2 * out:], np.float32)}
    elif tshort == "LSTMPeephole":
        if float(tf.get("p", 0.0)) != 0.0:
            raise ValueError("bigdl format: LSTMPeephole with p!=0 is not "
                             "mapped")
        hidden = int(tf["hiddenSize"])
        insize = int(tf["inputSize"])
        # gate identity comes from each gate ParallelTable's Narrow offset
        # (buildGate/buildHidden, LSTMPeephole.scala:77-130): offset 1=i,
        # 1+H=f, 1+2H=g (hidden, no peephole), 1+3H=o — wire chunk order
        # [i, f, g, o], the SAME as this framework's kernel, no permute
        wh = {}
        peep = {}
        for pt in (o for o in _walk(tf["cell"])
                   if isinstance(o, JavaObject)
                   and o.classname == _PKG + "ParallelTable"):
            members = _children(pt)
            narrows = [c for c in members
                       if _short(c.classname) == "Narrow"]
            if len(narrows) != 1:
                continue
            chunk = (int(narrows[0].fields["offset"]) - 1) // hidden
            [lin] = _find_linears(pt)
            wh[chunk], _ = _ref_linear_wb(lin)
            cmuls = [c for c in _walk(pt)
                     if isinstance(c, JavaObject)
                     and c.classname == _PKG + "CMul"]
            if cmuls:
                peep[chunk] = _to_numpy(
                    cmuls[0].fields["weight"]).reshape(-1)
        if sorted(wh) != [0, 1, 2, 3] or sorted(peep) != [0, 1, 3]:
            raise ValueError(
                f"bigdl format: LSTMPeephole cell structure not recognized "
                f"(gates {sorted(wh)}, peepholes {sorted(peep)})")
        cell = nn.LSTMPeephole(insize, hidden)
        kernel = np.concatenate(
            [wi.T] + [np.concatenate([wh[c].T for c in range(4)], axis=1)],
            axis=0)
        p = {"kernel": kernel, "bias": np.asarray(bi, np.float32),
             "peep_i": peep[0], "peep_f": peep[1], "peep_o": peep[3]}
    else:
        raise ValueError(f"bigdl format: Recurrent cell {tshort} not "
                         "mapped (RnnCell/LSTM/GRU/LSTMPeephole only)")
    # the cell object is built here, not via _build dispatch, so its
    # AbstractModule grad scales are re-applied here too
    for attr, key in (("scale_w", "scaleW"), ("scale_b", "scaleB")):
        v = tf.get(key)
        if v is not None and float(v) != 1.0:
            setattr(cell, attr, float(v))
    return nn.Recurrent(cell), [p], [{}]


def _build_birecurrent(obj: JavaObject, build):
    """BiRecurrent.scala:33 — `layer`/`revLayer` Recurrents (revLayer holds
    a CLONED cell with independent weights) merged by the last module of
    the internal `birnn` Sequential (CAddTable default, JoinTable for
    concat)."""
    from .. import nn
    from .bigdl import _children

    fwd_m, fwd_p, fwd_s = build(obj.fields["layer"])
    rev_m, rev_p, rev_s = build(obj.fields["revLayer"])
    merge_obj = _children(obj.fields["birnn"])[-1]
    mshort = _short(merge_obj.classname)
    if mshort == "CAddTable":
        merge = "sum"
    elif mshort == "JoinTable":
        dim = int(merge_obj.fields.get("dimension", 3))
        if dim != 3:  # (batch, time, feature) 1-based: features only
            raise ValueError(
                f"bigdl format: BiRecurrent JoinTable merge over dim {dim} "
                "has no mapping here (feature concat, dim=3, only)")
        merge = "concat"
    else:
        raise ValueError(f"bigdl format: BiRecurrent merge {mshort} not "
                         "mapped (CAddTable/JoinTable only)")
    bi = nn.BiRecurrent(fwd_m.modules[0], merge)
    bi.modules[0] = fwd_m   # keep the two loaded Recurrents verbatim
    bi.modules[1] = rev_m   # (revLayer's weights are independent)
    return bi, [fwd_p, rev_p], [fwd_s, rev_s]


def _gate_perm_ref_to_ours(h: int) -> np.ndarray:
    """Row permutation taking the reference's [i, g, f, o] gate chunks to
    this framework's [i, f, g, o] (involution — also ours -> ref)."""
    idx = np.arange(4 * h)
    return np.concatenate([idx[0:h], idx[2 * h:3 * h],
                           idx[h:2 * h], idx[3 * h:4 * h]])


def _build_graph(obj: JavaObject, build):
    from .. import nn

    inputs = _seq_items(obj.fields["inputs"])
    outputs = _seq_items(obj.fields["outputs"])
    built: dict = {}   # id(java Node) -> (ModuleNode, params, state)

    def get_node(jn: JavaObject):
        if id(jn) in built:
            return built[id(jn)]
        if jn.classname != _NODE:
            raise ValueError(f"bigdl format: Graph expected Node, got "
                             f"{jn.classname}")
        elem = jn.fields["element"]
        if _short(elem.classname) == "Input":
            mn = nn.Input()
            entry = (mn, {}, {})
        else:
            m, p, s = build(elem)
            mn = nn.ModuleNode(m)
            entry = (mn, p, s)
        built[id(jn)] = entry
        for nxt in _seq_items(jn.fields.get("nexts", [])):
            mn.point_to(get_node(nxt)[0])
        return entry

    for jn in list(inputs) + list(outputs):
        get_node(jn)
    g = nn.Graph([built[id(j)][0] for j in inputs],
                 [built[id(j)][0] for j in outputs])
    by_mod = {id(mn.element): (p, s) for (mn, p, s) in built.values()}
    params = [by_mod[id(m)][0] for m in g.modules]
    states = [by_mod[id(m)][1] for m in g.modules]
    return g, params, states


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

def _obj(dc, short, prims, objs) -> JavaObject:
    """Same shape helper as bigdl._w_module's local obj()."""
    name = short if "." in short else _PKG + short
    fields = ([(t, n, None) for t, n, _v in prims] +
              [("L" if not s.startswith("[") else "[", n, s)
               for n, s, _v in objs])
    cd = dc.get(name, fields)
    vals = {n: v for _t, n, v in prims}
    vals.update({n: v for n, _s, v in objs})
    return JavaObject(cd, vals)


def _buffer(dc, items) -> JavaObject:
    from .bigdl import _w_buffer
    return _w_buffer(dc, items)


_OBJ_SIG = "Ljava/lang/Object;"


def _boxed_float(dc, v: float) -> JavaObject:
    """A java.lang.Float box — the erased value of a Scala `val x: T`
    field under TensorNumeric[Float].  Real JDK SUIDs (spec constants), so
    an actual ObjectInputStream resolves the boxes."""
    num_cd = dc.get("java.lang.Number", [])
    cd = dc.get("java.lang.Float", [("F", "value", None)],
                super_desc=num_cd)
    return JavaObject(cd, {"value": float(v)})


def _dropout(dc, init_p: float) -> JavaObject:
    """Dropout with the DERIVED runtime field the JVM's updateOutput reads
    (`private var p = initP`) — a stream carrying only initP deserializes
    with p = 0.0 (JOS missing-field default) and drops nothing/everything
    wrongly on a real BigDL."""
    return _obj(dc, "Dropout",
                [("D", "initP", float(init_p)), ("D", "p", float(init_p)),
                 ("Z", "inplace", False), ("Z", "scale", True)], [])


def _mul_constant(dc, v: float) -> JavaObject:
    # `scalar` is a derived non-transient val (ev.fromType(constant)) the
    # reference's updateOutput multiplies by — omit it and a JVM load
    # computes with scalar = null (NPE) despite a well-formed stream
    return _obj(dc, "MulConstant",
                [("D", "constant", float(v)), ("Z", "inplace", False)],
                [("scalar", _OBJ_SIG, _boxed_float(dc, v))])


def _add_constant(dc, v: float) -> JavaObject:
    return _obj(dc, "AddConstant",
                [("D", "constant_scalar", float(v)),
                 ("Z", "inplace", False)],
                [("scalar", _OBJ_SIG, _boxed_float(dc, v))])


def _container(dc, short, children, extra_prims=(), extra_objs=()) \
        -> JavaObject:
    # `modules` is declared on the Container SUPER desc (attached by
    # _DescCache automatically) — only class-own fields go on this desc;
    # the value is written under Container's classdata
    o = _obj(dc, short, list(extra_prims), list(extra_objs))
    o.fields["modules"] = _buffer(dc, children)
    return o


def _seq(dc, *children) -> JavaObject:
    return _container(dc, "Sequential", list(children))


def _concat_table(dc, *children) -> JavaObject:
    return _container(dc, "ConcatTable", list(children))


def _parallel_table(dc, *children) -> JavaObject:
    return _container(dc, "ParallelTable", list(children))


def _simple(dc, short) -> JavaObject:
    return _obj(dc, short, [], [])


def _select(dc, i) -> JavaObject:
    return _obj(dc, "SelectTable", [("I", "index", i)], [])


def _narrow_table(dc, offset, length) -> JavaObject:
    return _obj(dc, "NarrowTable",
                [("I", "offset", offset), ("I", "length", length),
                 ("I", "len", length)], [])


def _cadd(dc, inplace) -> JavaObject:
    return _obj(dc, "CAddTable", [("Z", "inplace", inplace)], [])


def _reshape(dc, sizes) -> JavaObject:
    return _obj(dc, "Reshape", [],
                [("size", "[I", JavaArray(dc.array("[I"),
                                          np.asarray(sizes, np.int32)))])


def _split_table(dc, dim, n_input_dims) -> JavaObject:
    return _obj(dc, "SplitTable",
                [("I", "dimension", dim), ("I", "nInputDims", n_input_dims)],
                [])


def _linear(dc, w_out_in, bias) -> JavaObject:
    from .bigdl import _w_tensor

    out_size, in_size = w_out_in.shape
    return _obj(dc, "Linear",
                [("I", "inputSize", in_size), ("I", "outputSize", out_size),
                 ("Z", "withBias", bias is not None)],
                [("weight", _T, _w_tensor(dc, w_out_in)),
                 ("bias", _T, _w_tensor(dc, bias)
                  if bias is not None else None)])


def _time_distributed(dc, inner) -> JavaObject:
    return _obj(dc, "TimeDistributed", [], [("layer", _MODULE_SIG, inner)])


def _hiddens_shape(dc, sizes) -> JavaArray:
    return JavaArray(dc.array("[I"), np.asarray(sizes, np.int32))


def write_seq(dc, m, params, state, w_module):
    """Writer dispatch for the sequence zoo; None = class not handled here.
    `w_module` is interop.bigdl._w_module (recursion)."""
    from .. import nn
    from ..nn.graph import _InputModule

    _init_act_maps()
    from .bigdl import _scales

    def stamped(o):
        o.fields.update(_scales(m))  # layer-wise grad scale survives
        return o

    if isinstance(m, nn.TimeDistributed):
        return stamped(_time_distributed(
            dc, w_module(dc, m.modules[0], params[0], state[0])))

    if isinstance(m, nn.LookupTable):
        if not m.one_based:
            raise ValueError(
                "bigdl format save: LookupTable(one_based=False) has no "
                "reference equivalent (reference indices are 1-based)")
        from .bigdl import _w_tensor
        big = np.finfo(np.float64).max
        return stamped(_obj(dc, "LookupTable",
                    [("I", "nIndex", m.n_index), ("I", "nOutput", m.n_output),
                     ("D", "paddingValue", float(m.padding_value or 0.0)),
                     ("D", "maxNorm", float(m.max_norm)
                      if m.max_norm is not None else big),
                     ("D", "normType", float(m.norm_type))],
                    [("weight", _T, _w_tensor(dc, params["weight"]))]))

    if isinstance(m, nn.TemporalConvolution):
        from .bigdl import _w_tensor
        w = np.asarray(params["weight"])           # (kw, in, out)
        w2 = w.transpose(2, 0, 1).reshape(m.output_frame_size, -1)
        return stamped(_obj(dc, "TemporalConvolution",
                    [("I", "inputFrameSize", m.input_frame_size),
                     ("I", "outputFrameSize", m.output_frame_size),
                     ("I", "kernelW", m.kernel_w),
                     ("I", "strideW", m.stride_w),
                     ("Z", "propagateBack", True)],
                    [("weight", _T, _w_tensor(dc, w2)),
                     ("bias", _T, _w_tensor(dc, params["bias"]))]))

    if isinstance(m, nn.BiRecurrent):
        layer = _write_recurrent(dc, m.modules[0], params[0], state[0])
        rev = _write_recurrent(dc, m.modules[1], params[1], state[1])
        if m.merge == "concat":
            # (batch, time, feature) 1-based: feature dim 3
            merge_obj = _obj(dc, "JoinTable",
                             [("I", "dimension", 3),
                              ("I", "nInputDims", 0)], [])
        else:
            merge_obj = _cadd(dc, True)
        rev_wrap = _seq(dc, _obj(dc, "Reverse", [("I", "dimension", 2)], []),
                        rev,
                        _obj(dc, "Reverse", [("I", "dimension", 2)], []))
        birnn = _seq(
            dc,
            _concat_table(dc, _simple(dc, "Identity"),
                          _simple(dc, "Identity")),
            _parallel_table(dc, layer, rev_wrap),
            merge_obj)
        # the reference's own modules buffer stays EMPTY (its add()
        # delegates to layer/revLayer; BiRecurrent.scala:52-57)
        return stamped(_container(dc, "BiRecurrent", [], (
            ("I", "timeDim", 2),),
            [("layer", _MODULE_SIG, layer),
             ("revLayer", _MODULE_SIG, rev),
             ("birnn", _MODULE_SIG, birnn)]))

    if isinstance(m, nn.Recurrent):
        return stamped(_write_recurrent(dc, m, params, state))

    if isinstance(m, nn.Graph):
        return stamped(_write_graph(dc, m, params, state, w_module))

    if isinstance(m, nn.BinaryTreeLSTM):
        return stamped(_write_treelstm(dc, m, params, w_module))

    if isinstance(m, _InputModule):
        return _simple(dc, "Input")

    return None


def _write_recurrent(dc, m, params, state) -> JavaObject:
    from .. import nn

    cell = m.modules[0]
    cp = params[0]
    if isinstance(cell, nn.RnnCell):
        act_name = _NAME_BY_ACT.get(id(cell.activation))
        if act_name is None:
            raise ValueError("bigdl format save: RnnCell activation "
                             f"{cell.activation} has no reference class")
        H = cell.hidden_size
        # the fused bias goes to i2h; h2h gets zeros (forward-identical)
        pre = _time_distributed(dc, _linear(
            dc, np.asarray(cp["w_ih"]).T, np.asarray(cp["bias"])))
        h2h = _linear(dc, np.asarray(cp["w_hh"]).T, np.zeros(H, np.float32))
        i2h = _simple(dc, "Identity")
        pt = _parallel_table(dc, i2h, h2h)
        cadd = _cadd(dc, False)
        act = _simple(dc, act_name)
        inner = _seq(dc, pt, cadd, act,
                     _concat_table(dc, _simple(dc, "Identity"),
                                   _simple(dc, "Identity")))
        topo = _obj(dc, "RnnCell", [],
                    [("parallelTable", _MODULE_SIG, pt),
                     ("i2h", _MODULE_SIG, i2h),
                     ("h2h", _MODULE_SIG, h2h),
                     ("cAddTable", _MODULE_SIG, cadd),
                     ("cell", _MODULE_SIG, inner)])
        topo.fields["hiddensShape"] = _hiddens_shape(dc, [H])  # Cell desc
    elif isinstance(cell, nn.LSTM):
        I, H = cell.input_size, cell.hidden_size
        perm = _gate_perm_ref_to_ours(H)     # involution: ours -> ref too
        kernel = np.asarray(cp["kernel"])
        wi = kernel[:I].T[perm]              # (4H, I) rows [i, g, f, o]
        wh = kernel[I:].T[perm]              # (4H, H)
        bi = np.asarray(cp["bias"])[perm]
        pre = _time_distributed(dc, _linear(dc, wi, bi))
        h2g = _linear(dc, wh, None)
        gates = _seq(
            dc, _narrow_table(dc, 1, 2),
            _parallel_table(dc, _simple(dc, "Identity"), h2g),
            _cadd(dc, False), _reshape(dc, [4, H]), _split_table(dc, 1, 2),
            _parallel_table(dc, _simple(dc, "Sigmoid"), _simple(dc, "Tanh"),
                            _simple(dc, "Sigmoid"), _simple(dc, "Sigmoid")))
        cell_layer = _seq(
            dc,
            _concat_table(
                dc,
                _seq(dc, _narrow_table(dc, 1, 2), _simple(dc, "CMulTable")),
                _seq(dc, _concat_table(dc, _select(dc, 3), _select(dc, 5)),
                     _simple(dc, "CMulTable"))),
            _cadd(dc, True))
        lstm = _seq(
            dc, _simple(dc, "FlattenTable"),
            _concat_table(dc, gates, _select(dc, 3)),
            _simple(dc, "FlattenTable"),
            _concat_table(dc, cell_layer, _select(dc, 4)),
            _simple(dc, "FlattenTable"),
            _concat_table(
                dc,
                _seq(dc,
                     _concat_table(dc,
                                   _seq(dc, _select(dc, 1),
                                        _simple(dc, "Tanh")),
                                   _select(dc, 2)),
                     _simple(dc, "CMulTable")),
                _select(dc, 1)),
            _concat_table(dc, _select(dc, 1), _simple(dc, "Identity")))
        topo = _obj(dc, "LSTM",
                    [("I", "inputSize", I), ("I", "hiddenSize", H),
                     ("D", "p", 0.0)],
                    [("gates", _MODULE_SIG, gates),
                     ("cellLayer", _MODULE_SIG, None),
                     ("cell", _MODULE_SIG, lstm)])
        topo.fields["hiddensShape"] = _hiddens_shape(dc, [H, H])  # Cell desc
    elif isinstance(cell, nn.LSTMPeephole):
        I, H = cell.input_size, cell.hidden_size
        kernel = np.asarray(cp["kernel"])
        wi = kernel[:I].T                      # (4H, I), chunks [i,f,g,o]
        bi = np.asarray(cp["bias"])
        pre = _seq(dc, _dropout(dc, 0.0),
                   _time_distributed(dc, _linear(dc, wi, bi)))

        def h2h_seq(chunk):
            w = kernel[I:, chunk * H:(chunk + 1) * H].T    # (H, H)
            return _seq(dc, _dropout(dc, 0.0),
                        _linear(dc, w, None))

        def cmul(weight):
            from .bigdl import _w_tensor
            return _obj(dc, "CMul", [],
                        [("size", "[I", _hiddens_shape(dc, [H])),
                         ("weight", _T, _w_tensor(
                             dc, np.asarray(weight).reshape(H)))])

        def gate(chunk, peep):                 # buildGate, :77-93
            return _seq(
                dc,
                _parallel_table(
                    dc,
                    _obj(dc, "Narrow",
                         [("I", "dimension", 2),
                          ("I", "offset", 1 + chunk * H),
                          ("I", "length", H)], []),
                    h2h_seq(chunk), cmul(peep)),
                _cadd(dc, False), _simple(dc, "Sigmoid"))

        input_gate = gate(0, cp["peep_i"])
        forget_gate = gate(1, cp["peep_f"])
        output_gate = gate(3, cp["peep_o"])
        hidden_layer = _seq(                   # buildHidden, :110-130
            dc, _narrow_table(dc, 1, 2),
            _parallel_table(
                dc,
                _obj(dc, "Narrow",
                     [("I", "dimension", 2), ("I", "offset", 1 + 2 * H),
                      ("I", "length", H)], []),
                h2h_seq(2)),
            _cadd(dc, False), _simple(dc, "Tanh"))
        forget_layer = _seq(
            dc, _concat_table(dc, forget_gate, _select(dc, 3)),
            _simple(dc, "CMulTable"))
        input_layer = _seq(
            dc, _concat_table(dc, input_gate, hidden_layer),
            _simple(dc, "CMulTable"))
        cell_layer = _seq(                     # buildCell, :133-156
            dc, _concat_table(dc, forget_layer, input_layer),
            _cadd(dc, False))
        lstm = _seq(                           # buildLSTM, :159-184
            dc, _simple(dc, "FlattenTable"),
            _concat_table(dc, _narrow_table(dc, 1, 2), cell_layer),
            _simple(dc, "FlattenTable"),
            _concat_table(
                dc,
                _seq(dc,
                     _concat_table(dc, output_gate,
                                   _seq(dc, _select(dc, 3),
                                        _simple(dc, "Tanh"))),
                     _simple(dc, "CMulTable")),
                _select(dc, 3)),
            _concat_table(dc, _select(dc, 1), _simple(dc, "Identity")))
        topo = _obj(dc, "LSTMPeephole",
                    [("I", "inputSize", I), ("I", "hiddenSize", H),
                     ("D", "p", 0.0), ("I", "featDim", 2)],
                    [("inputGate", _MODULE_SIG, input_gate),
                     ("forgetGate", _MODULE_SIG, forget_gate),
                     ("outputGate", _MODULE_SIG, output_gate),
                     ("hiddenLayer", _MODULE_SIG, hidden_layer),
                     ("cellLayer", _MODULE_SIG, cell_layer),
                     ("cell", _MODULE_SIG, lstm)])
        topo.fields["hiddensShape"] = _hiddens_shape(dc, [H, H])
    elif isinstance(cell, nn.GRU):
        I, O = cell.input_size, cell.hidden_size
        gk = np.asarray(cp["gate_kernel"])
        gb = np.asarray(cp["gate_bias"])
        ck = np.asarray(cp["cand_kernel"])
        cb = np.asarray(cp["cand_bias"])
        # ours u = 1 - ref z: negate the u chunk back into z
        wi = np.concatenate([gk[:I, :O].T, -gk[:I, O:].T, ck[:I].T], axis=0)
        bi = np.concatenate([gb[:O], -gb[O:], cb])
        wh2g = np.concatenate([gk[I:, :O].T, -gk[I:, O:].T], axis=0)
        whh = ck[I:].T
        pre = _time_distributed(dc, _linear(dc, wi, bi))
        i2g = _obj(dc, "Narrow",
                   [("I", "dimension", 2), ("I", "offset", 1),
                    ("I", "length", 2 * O)], [])
        h2g = _linear(dc, wh2g, None)
        gates = _seq(
            dc, _parallel_table(dc, i2g, h2g), _cadd(dc, True),
            _reshape(dc, [2, O]), _split_table(dc, 1, 2),
            _parallel_table(dc, _simple(dc, "Sigmoid"),
                            _simple(dc, "Sigmoid")))
        f2g = _obj(dc, "Narrow",
                   [("I", "dimension", 2), ("I", "offset", 1 + 2 * O),
                    ("I", "length", O)], [])
        h_hat = _seq(
            dc,
            _concat_table(dc, _seq(dc, _select(dc, 1), f2g),
                          _seq(dc, _narrow_table(dc, 2, 2),
                               _simple(dc, "CMulTable"))),
            _parallel_table(
                dc, _simple(dc, "Identity"),
                _seq(dc, _dropout(dc, 0.0),
                     _linear(dc, whh, None))),
            _cadd(dc, True), _simple(dc, "Tanh"))
        gru = _seq(
            dc, _concat_table(dc, _simple(dc, "Identity"), gates),
            _simple(dc, "FlattenTable"),
            _concat_table(
                dc,
                _seq(dc,
                     _concat_table(
                         dc, h_hat,
                         _seq(dc,
                              _select(dc, 4),
                              _mul_constant(dc, -1.0),
                              _add_constant(dc, 1.0))),
                     _simple(dc, "CMulTable")),
                _seq(dc, _concat_table(dc, _select(dc, 2), _select(dc, 4)),
                     _simple(dc, "CMulTable"))),
            _cadd(dc, False),
            _concat_table(dc, _simple(dc, "Identity"),
                          _simple(dc, "Identity")))
        topo = _obj(dc, "GRU",
                    [("I", "inputSize", I), ("I", "outputSize", O),
                     ("D", "p", 0.0), ("I", "featDim", 2)],
                    [("i2g", _MODULE_SIG, i2g),
                     ("h2g", _MODULE_SIG, h2g),
                     ("gates", _MODULE_SIG, gates),
                     ("cell", _MODULE_SIG, gru)])
        topo.fields["hiddensShape"] = _hiddens_shape(dc, [O])  # Cell desc
    else:
        raise ValueError(f"bigdl format save: Recurrent cell "
                         f"{type(cell).__name__} not mapped")
    from .bigdl import _scales
    topo.fields.update(_scales(cell))  # the cell module's own grad scale
    rec = _container(dc, "Recurrent", [pre, topo], (),
                     [("topology", _MODULE_SIG, topo),
                      ("preTopology", _MODULE_SIG, pre)])
    rec.fields.update(_scales(m))
    return rec


def _write_graph(dc, m, params, state, w_module) -> JavaObject:
    node_cd = dc.get(_NODE, [("L", "element", "Ljava/lang/Object;"),
                             ("L", "nexts", _BUF_SIG),
                             ("L", "prevs", _BUF_SIG)])
    elems = {}   # id(our Node) -> element JavaObject
    jnodes = {}  # id(our Node) -> Node JavaObject
    for node, p, s in zip(m.exec_order, params, state):
        elems[id(node)] = w_module(dc, node.element, p, s)
        jnodes[id(node)] = JavaObject(node_cd, {})
    known = set(jnodes)
    for node in m.exec_order:
        jn = jnodes[id(node)]
        jn.fields["element"] = elems[id(node)]
        jn.fields["nexts"] = _buffer(
            dc, [jnodes[id(n)] for n in node.next_nodes if id(n) in known])
        jn.fields["prevs"] = _buffer(
            dc, [jnodes[id(n)] for n in node.prev_nodes if id(n) in known])
    return _container(
        dc, "Graph", [elems[id(n)] for n in m.exec_order], (),
        [("inputs", _BUF_SIG,
          _buffer(dc, [jnodes[id(n)] for n in m.input_nodes])),
         ("outputs", _BUF_SIG,
          _buffer(dc, [jnodes[id(n)] for n in m.output_nodes]))])


# ---------------------------------------------------------------------------
# BinaryTreeLSTM (treeLSTMSentiment zoo family)
# ---------------------------------------------------------------------------
# The reference builds its leaf/composer as Graph modules
# (BinaryTreeLSTM.scala:59-111, withGraph=true default): leaf
# c = Linear(I,H)(x), h = Sigmoid(Linear(I,H)(x)) * Tanh(c); composer
# gates i/lf/rf/update/o each = CAddTable(Linear(H,H)(lh), Linear(H,H)(rh))
# -> Sigmoid (Tanh for update), c = i*update + lf*lc + rf*rc,
# h = Sigmoid(o) * Tanh(c).  This framework fuses the ten gate Linears
# into one (2H, 5H) kernel (nn/tree.py, column order [i, f_l, f_r, o, g]),
# so load/save re-homes by identifying each gate's ROLE from the node
# graph: side (lh/rh) from which Input feeds its Linear, role from the
# activation type and what consumes it (update=Tanh; lf/rf multiply the
# lc/rc Inputs; i multiplies the update; o is the h gate).

def _jnodes(graph_obj):
    """All Node objects of a serialized Graph, reachable from inputs."""
    inputs = _seq_items(graph_obj.fields["inputs"])
    seen, out, stack = set(), [], list(inputs)
    while stack:
        jn = stack.pop()
        if id(jn) in seen:
            continue
        seen.add(id(jn))
        out.append(jn)
        stack.extend(_seq_items(jn.fields.get("nexts", [])))
    return inputs, _seq_items(graph_obj.fields["outputs"]), out


def _elem_short(jn):
    e = jn.fields.get("element")
    return _short(e.classname) if isinstance(e, JavaObject) else None


def _build_treelstm(obj: JavaObject, build):
    from .. import nn

    f = obj.fields
    I, H = int(f["inputSize"]), int(f["hiddenSize"])
    gate_output = bool(f.get("gateOutput", True))
    if not gate_output:
        raise ValueError("bigdl format: BinaryTreeLSTM(gateOutput=false) "
                         "not mapped")
    if not bool(f.get("withGraph", True)):
        # withGraph=false builds Sequential/ConcatTable cell trees
        # (createLeafModuleWithSequential, BinaryTreeLSTM.scala:112-139)
        raise ValueError("bigdl format: BinaryTreeLSTM(withGraph=false) "
                         "not mapped (Graph-built cells only)")

    # leaf: Linear feeding a Sigmoid is the o gate; the other is c
    lin_c = lin_o = None
    _, _, nodes = _jnodes(f["leafModule"])
    for jn in nodes:
        if _elem_short(jn) != "Linear":
            continue
        nxts = [_elem_short(n) for n in _seq_items(jn.fields["nexts"])]
        if "Sigmoid" in nxts:
            lin_o = jn.fields["element"]
        else:
            lin_c = jn.fields["element"]
    if lin_c is None or lin_o is None:
        raise ValueError("bigdl format: BinaryTreeLSTM leaf graph not "
                         "recognized")
    wc, bc = _ref_linear_wb(lin_c)
    wo, bo = _ref_linear_wb(lin_o)

    # composer: role-identify the five CAddTable gates
    inputs, _, nodes = _jnodes(f["composer"])
    if len(inputs) != 4:
        raise ValueError("bigdl format: BinaryTreeLSTM composer graph "
                         f"has {len(inputs)} inputs, expected 4 "
                         "(lc, lh, rc, rh)")
    lc_n, lh_n, rc_n, rh_n = inputs
    gates = {}
    update_act = None
    cadds = [jn for jn in nodes
             if _elem_short(jn) == "CAddTable"
             and len([p for p in _seq_items(jn.fields["prevs"])
                      if _elem_short(p) == "Linear"]) == 2]
    for jn in cadds:
        w_side = {}
        for p in _seq_items(jn.fields["prevs"]):
            if _elem_short(p) != "Linear":
                continue
            feeder = _seq_items(p.fields["prevs"])[0]
            if feeder is lh_n:
                w_side["l"] = p.fields["element"]
            elif feeder is rh_n:
                w_side["r"] = p.fields["element"]
        acts = [n for n in _seq_items(jn.fields["nexts"])
                if _elem_short(n) in ("Sigmoid", "Tanh")]
        if len(w_side) != 2 or len(acts) != 1:
            raise ValueError("bigdl format: BinaryTreeLSTM composer gate "
                             "not recognized")
        act = acts[0]
        if _elem_short(act) == "Tanh":
            role = "g"
            update_act = act
        else:
            role = None
            for consumer in _seq_items(act.fields["nexts"]):
                if _elem_short(consumer) != "CMulTable":
                    continue
                partners = [p for p in _seq_items(consumer.fields["prevs"])
                            if p is not act]
                for partner in partners:
                    if partner is lc_n:
                        role = "f_l"
                    elif partner is rc_n:
                        role = "f_r"
            if role is None:
                role = "_sigmoid_pending"
        gates[id(jn)] = (role, w_side, act)

    # second pass: i multiplies the update Tanh; o is the remaining one
    roles = {}
    for role, w_side, act in gates.values():
        if role == "_sigmoid_pending":
            is_i = any(
                update_act is not None and partner is update_act
                for consumer in _seq_items(act.fields["nexts"])
                if _elem_short(consumer) == "CMulTable"
                for partner in _seq_items(consumer.fields["prevs"])
                if partner is not act)
            role = "i" if is_i else "o"
        roles[role] = w_side
    if sorted(roles) != ["f_l", "f_r", "g", "i", "o"]:
        raise ValueError(f"bigdl format: BinaryTreeLSTM composer roles "
                         f"{sorted(roles)} incomplete")

    cols = {"i": 0, "f_l": 1, "f_r": 2, "o": 3, "g": 4}
    comp_w = np.zeros((2 * H, 5 * H), np.float32)
    comp_b = np.zeros((5 * H,), np.float32)
    for role, w_side in roles.items():
        c0 = cols[role] * H
        wl, bl = _ref_linear_wb(w_side["l"])
        wr, br = _ref_linear_wb(w_side["r"])
        comp_w[:H, c0:c0 + H] = wl.T
        comp_w[H:, c0:c0 + H] = wr.T
        comp_b[c0:c0 + H] = ((bl if bl is not None else 0.0)
                             + (br if br is not None else 0.0))

    m = nn.BinaryTreeLSTM(I, H, gate_output)
    p = {"leaf_c": wc.T.copy(), "leaf_cb": np.asarray(bc, np.float32),
         "leaf_o": wo.T.copy(), "leaf_ob": np.asarray(bo, np.float32),
         "comp_w": comp_w, "comp_b": comp_b}
    return m, p, {}


def _write_treelstm(dc, m, params, w_module):
    """Emit the reference-shaped leaf/composer Graphs with re-homed
    weights, then the BinaryTreeLSTM object around them."""
    from .. import nn
    from .bigdl import _w_buffer, _w_tensor

    if not m.gate_output:
        # the load path refuses gateOutput=false streams; emitting one
        # here would silently write o-gated graphs a real JVM computes
        # differently with
        raise ValueError("bigdl format save: "
                         "BinaryTreeLSTM(gate_output=False) not mapped")
    I, H = m.input_size, m.hidden_size
    cols = {"i": 0, "f_l": 1, "f_r": 2, "o": 3, "g": 4}
    comp_w = np.asarray(params["comp_w"])
    comp_b = np.asarray(params["comp_b"])

    lin_params = {}

    def linear(w_out_in, b):
        lin = nn.Linear(w_out_in.shape[1], w_out_in.shape[0])
        lin_params[id(lin)] = {"weight": np.asarray(w_out_in, np.float32),
                               "bias": np.asarray(b, np.float32)}
        return lin

    # leaf graph (BinaryTreeLSTM.scala:59-76)
    inp = nn.Input()
    c = linear(np.asarray(params["leaf_c"]).T, params["leaf_cb"])(inp)
    o = nn.Sigmoid()(
        linear(np.asarray(params["leaf_o"]).T, params["leaf_ob"])(inp))
    h = nn.CMulTable()([o, nn.Tanh()(c)])
    leaf_graph = nn.Graph(inp, [c, h])

    # composer graph (:78-111)
    lc, lh, rc, rh = (nn.Input() for _ in range(4))

    def gate(role):
        c0 = cols[role] * H
        wl = comp_w[:H, c0:c0 + H].T      # (H, H) out,in
        wr = comp_w[H:, c0:c0 + H].T
        # the fused bias goes to the lh-side Linear; rh-side gets zeros
        add = nn.CAddTable()([linear(wl, comp_b[c0:c0 + H])(lh),
                              linear(wr, np.zeros(H, np.float32))(rh)])
        act = nn.Tanh() if role == "g" else nn.Sigmoid()
        return act(add)

    gi, gfl, gfr, gu = gate("i"), gate("f_l"), gate("f_r"), gate("g")
    go = gate("o")
    c2 = nn.CAddTable()([nn.CMulTable()([gi, gu]),
                         nn.CMulTable()([gfl, lc]),
                         nn.CMulTable()([gfr, rc])])
    h2 = nn.CMulTable()([go, nn.Tanh()(c2)])
    comp_graph = nn.Graph([lc, lh, rc, rh], [c2, h2])

    def graph_obj(g):
        ps = [lin_params.get(id(mod), {}) for mod in g.modules]
        ss = [{} for _ in g.modules]
        return _write_graph(dc, g, ps, ss, w_module)

    leaf_obj = graph_obj(leaf_graph)
    comp_obj = graph_obj(comp_graph)
    tree = _obj(dc, "BinaryTreeLSTM",
                [("Z", "gateOutput", True), ("Z", "withGraph", True)],
                [("composer", _MODULE_SIG, comp_obj),
                 ("leafModule", _MODULE_SIG, leaf_obj),
                 ("composers", _BUF_SIG, _w_buffer(dc, [comp_obj])),
                 ("leafModules", _BUF_SIG, _w_buffer(dc, [leaf_obj])),
                 ("cells", _BUF_SIG, _w_buffer(dc, []))])
    # TreeLSTM super-desc fields (inputSize/hiddenSize/memZero)
    tree.fields["inputSize"] = I
    tree.fields["hiddenSize"] = H
    tree.fields["memZero"] = _w_tensor(dc, np.zeros(H, np.float32))
    return tree
