"""Java Object Serialization Stream codec (reader + writer), pure Python.

Why: the reference's native model format IS Java serialization —
`Module.save` → `File.save` → `ObjectOutputStream.writeObject(module)`
(`nn/Module.scala:41-43`, `utils/File.scala:25`), so loading a model file
written by actual BigDL means parsing the JDK's object-stream protocol
(JavaTM Object Serialization Specification, §6 "Object Serialization Stream
Protocol").  The stream is fully self-describing — every object carries its
class descriptor (name, serialVersionUID, typed field list, super chain) —
so a generic parser needs no a-priori knowledge of BigDL's classes; the
mapping layer (`interop/bigdl.py`) then picks the fields it understands.

Implemented protocol subset: objects (incl. class hierarchies and
writeObject custom data), primitive + object arrays, strings (short/long),
enums, class literals, block data, back-references, TC_NULL.  Not
implemented (raise): proxies, TC_RESET, TC_EXCEPTION — none of which the
reference's writers emit.

The writer emits the same protocol (used by `interop/bigdl.save` and the
checked-in fixtures); without a JVM in this image the fixtures are
hand-built to the specification rather than written by BigDL itself —
`tests/test_bigdl_format.py` pins the frozen bytes.
"""

from __future__ import annotations

import io
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["JavaObject", "JavaClassDesc", "JavaArray", "JavaEnum",
           "load_stream", "loads", "JavaWriter"]

_MAGIC = 0xACED
_VERSION = 5

TC_NULL = 0x70
TC_REFERENCE = 0x71
TC_CLASSDESC = 0x72
TC_OBJECT = 0x73
TC_STRING = 0x74
TC_ARRAY = 0x75
TC_CLASS = 0x76
TC_BLOCKDATA = 0x77
TC_ENDBLOCKDATA = 0x78
TC_RESET = 0x79
TC_BLOCKDATALONG = 0x7A
TC_EXCEPTION = 0x7B
TC_LONGSTRING = 0x7C
TC_PROXYCLASSDESC = 0x7D
TC_ENUM = 0x7E
_BASE_HANDLE = 0x7E0000

SC_WRITE_METHOD = 0x01
SC_SERIALIZABLE = 0x02
SC_EXTERNALIZABLE = 0x04
SC_BLOCK_DATA = 0x08

# primitive field/array typecodes -> (struct format, numpy dtype)
_PRIM = {
    "B": (">b", np.int8), "C": (">H", np.uint16), "D": (">d", np.float64),
    "F": (">f", np.float32), "I": (">i", np.int32), "J": (">q", np.int64),
    "S": (">h", np.int16), "Z": (">?", np.bool_),
}


@dataclass
class JavaClassDesc:
    name: str
    suid: int
    flags: int
    fields: List[Tuple[str, str, Optional[str]]]  # (typecode, name, signature)
    super_desc: Optional["JavaClassDesc"]
    annotations: List[Any] = field(default_factory=list)

    def hierarchy(self):
        """Super-first chain, the order classdata appears in the stream."""
        chain = []
        c = self
        while c is not None:
            chain.append(c)
            c = c.super_desc
        return list(reversed(chain))


@dataclass
class JavaObject:
    classdesc: JavaClassDesc
    fields: Dict[str, Any] = field(default_factory=dict)  # flattened
    class_fields: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    annotations: Dict[str, List[Any]] = field(default_factory=dict)

    @property
    def classname(self) -> str:
        return self.classdesc.name

    def __repr__(self):
        return f"JavaObject({self.classname}, {list(self.fields)})"


@dataclass
class JavaArray:
    classdesc: JavaClassDesc
    values: Any  # numpy array for primitives, list for object arrays

    @property
    def classname(self) -> str:
        return self.classdesc.name


@dataclass
class JavaEnum:
    classdesc: JavaClassDesc
    constant: str


class _Reader:
    def __init__(self, f):
        self.f = f
        self.handles: List[Any] = []

    # -- primitives ----------------------------------------------------
    def _read(self, n):
        b = self.f.read(n)
        if len(b) != n:
            raise EOFError(f"truncated stream: wanted {n} bytes, got {len(b)}")
        return b

    def u1(self):
        return self._read(1)[0]

    def u2(self):
        return struct.unpack(">H", self._read(2))[0]

    def i4(self):
        return struct.unpack(">i", self._read(4))[0]

    def i8(self):
        return struct.unpack(">q", self._read(8))[0]

    def utf(self):
        return self._read(self.u2()).decode("utf-8", errors="replace")

    def long_utf(self):
        n = struct.unpack(">Q", self._read(8))[0]
        return self._read(n).decode("utf-8", errors="replace")

    def _new_handle(self, obj):
        self.handles.append(obj)
        return obj

    # -- grammar -------------------------------------------------------
    def stream(self):
        if self.u2() != _MAGIC or self.u2() != _VERSION:
            raise ValueError("not a Java object serialization stream")
        out = []
        while True:
            b = self.f.read(1)
            if not b:
                return out
            out.append(self.content(b[0]))

    def content(self, tc=None):
        if tc is None:
            tc = self.u1()
        if tc == TC_OBJECT:
            return self.object_()
        if tc == TC_CLASSDESC:
            return self.new_classdesc()
        if tc == TC_REFERENCE:
            h = self.i4() - _BASE_HANDLE
            return self.handles[h]
        if tc == TC_STRING:
            return self._new_handle(self.utf())
        if tc == TC_LONGSTRING:
            return self._new_handle(self.long_utf())
        if tc == TC_ARRAY:
            return self.array_()
        if tc == TC_NULL:
            return None
        if tc == TC_CLASS:
            cd = self.classdesc()
            self._new_handle(cd)
            return cd
        if tc == TC_BLOCKDATA:
            return self._read(self.u1())
        if tc == TC_BLOCKDATALONG:
            return self._read(self.i4())
        if tc == TC_ENUM:
            cd = self.classdesc()
            e = JavaEnum(cd, "")
            self._new_handle(e)
            e.constant = self.content()
            return e
        raise ValueError(f"unsupported stream element 0x{tc:02x}")

    def classdesc(self) -> Optional[JavaClassDesc]:
        tc = self.u1()
        if tc == TC_CLASSDESC:
            return self.new_classdesc()
        if tc == TC_NULL:
            return None
        if tc == TC_REFERENCE:
            h = self.i4() - _BASE_HANDLE
            cd = self.handles[h]
            if not isinstance(cd, JavaClassDesc):
                raise ValueError("classdesc reference to a non-classdesc")
            return cd
        if tc == TC_PROXYCLASSDESC:
            raise ValueError("dynamic proxy class descriptors not supported")
        raise ValueError(f"bad classDesc tag 0x{tc:02x}")

    def new_classdesc(self) -> JavaClassDesc:
        name = self.utf()
        suid = self.i8()
        cd = JavaClassDesc(name, suid, 0, [], None)
        self._new_handle(cd)
        cd.flags = self.u1()
        nfields = self.u2()
        for _ in range(nfields):
            t = chr(self.u1())
            fname = self.utf()
            sig = self.content() if t in "[L" else None  # String (or ref)
            cd.fields.append((t, fname, sig))
        # classAnnotation: contents until TC_ENDBLOCKDATA
        while True:
            tc = self.u1()
            if tc == TC_ENDBLOCKDATA:
                break
            cd.annotations.append(self.content(tc))
        cd.super_desc = self.classdesc()
        return cd

    def object_(self) -> JavaObject:
        cd = self.classdesc()
        obj = JavaObject(cd)
        self._new_handle(obj)
        for cls in cd.hierarchy():
            if not cls.flags & (SC_SERIALIZABLE | SC_EXTERNALIZABLE):
                continue
            vals: Dict[str, Any] = {}
            if cls.flags & SC_SERIALIZABLE:
                for t, fname, _sig in cls.fields:
                    if t in _PRIM:
                        fmt, _ = _PRIM[t]
                        v = struct.unpack(fmt,
                                          self._read(struct.calcsize(fmt)))[0]
                    else:
                        v = self.content()
                    vals[fname] = v
                obj.class_fields[cls.name] = vals
                obj.fields.update(vals)
                if cls.flags & SC_WRITE_METHOD:
                    obj.annotations[cls.name] = self._annotation()
            else:  # externalizable
                if not cls.flags & SC_BLOCK_DATA:
                    raise ValueError(
                        f"{cls.name}: pre-JDK1.2 external format unsupported")
                obj.annotations[cls.name] = self._annotation()
        return obj

    def _annotation(self):
        items = []
        while True:
            tc = self.u1()
            if tc == TC_ENDBLOCKDATA:
                return items
            items.append(self.content(tc))

    def array_(self) -> JavaArray:
        cd = self.classdesc()
        arr = JavaArray(cd, None)
        self._new_handle(arr)
        n = self.i4()
        comp = cd.name[1] if cd.name.startswith("[") else "L"
        if comp in _PRIM:
            fmt, dt = _PRIM[comp]
            raw = self._read(n * struct.calcsize(fmt))
            arr.values = np.frombuffer(raw, dtype=np.dtype(dt).newbyteorder(">"),
                                       count=n).astype(dt)
        else:
            arr.values = [self.content() for _ in range(n)]
        return arr


def load_stream(f) -> List[Any]:
    """Parse a whole stream; returns the list of top-level contents."""
    return _Reader(f).stream()


def loads(data: bytes) -> List[Any]:
    return load_stream(io.BytesIO(data))


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

class JavaWriter:
    """Protocol-faithful writer for the subset the reader understands.

    Descriptors and values are JavaClassDesc / JavaObject / JavaArray /
    str / None — the same object model `load_stream` returns, so
    read(write(x)) is an exact roundtrip.  Handle assignment mirrors the
    spec (descs, objects, arrays and strings each get the next handle);
    repeated descriptors and strings are emitted as TC_REFERENCE."""

    def __init__(self):
        self.buf = io.BytesIO()
        self.handles: Dict[int, int] = {}   # id(obj) -> handle index
        self.string_handles: Dict[str, int] = {}
        self.next_handle = 0
        self.buf.write(struct.pack(">HH", _MAGIC, _VERSION))

    def getvalue(self) -> bytes:
        return self.buf.getvalue()

    # -- low-level -----------------------------------------------------
    def _u1(self, v):
        self.buf.write(bytes([v]))

    def _utf(self, s):
        b = s.encode("utf-8")
        self.buf.write(struct.pack(">H", len(b)))
        self.buf.write(b)

    def _assign(self, obj) -> int:
        h = self.next_handle
        self.next_handle += 1
        if isinstance(obj, str):
            self.string_handles[obj] = h
        else:
            self.handles[id(obj)] = h
        return h

    def _ref(self, h):
        self._u1(TC_REFERENCE)
        self.buf.write(struct.pack(">i", _BASE_HANDLE + h))

    # -- grammar -------------------------------------------------------
    def write_content(self, v):
        if v is None:
            self._u1(TC_NULL)
        elif isinstance(v, str):
            self.write_string(v)
        elif isinstance(v, JavaObject):
            self.write_object(v)
        elif isinstance(v, JavaArray):
            self.write_array(v)
        elif isinstance(v, (bytes, bytearray)):
            # blockdata: short frame when it fits, TC_BLOCKDATALONG above
            # 255 bytes (ObjectOutputStream's own split; the reader accepts
            # both).  Previously >255 crashed in bytes([len]).
            v = bytes(v)
            if len(v) <= 0xFF:
                self._u1(TC_BLOCKDATA)
                self._u1(len(v))
                self.buf.write(v)
            else:
                self._u1(TC_BLOCKDATALONG)
                self.buf.write(struct.pack(">i", len(v)))
                self.buf.write(v)
        else:
            raise TypeError(f"cannot serialize {type(v).__name__}")

    def write_string(self, s: str):
        if s in self.string_handles:
            self._ref(self.string_handles[s])
            return
        self._u1(TC_STRING)
        self._assign(s)
        self._utf(s)

    def write_classdesc(self, cd: Optional[JavaClassDesc]):
        if cd is None:
            self._u1(TC_NULL)
            return
        if id(cd) in self.handles:
            self._ref(self.handles[id(cd)])
            return
        self._u1(TC_CLASSDESC)
        self._utf(cd.name)
        self.buf.write(struct.pack(">q", cd.suid))
        self._assign(cd)
        self._u1(cd.flags)
        self.buf.write(struct.pack(">H", len(cd.fields)))
        for t, fname, sig in cd.fields:
            self._u1(ord(t))
            self._utf(fname)
            if t in "[L":
                self.write_string(sig)
        for a in cd.annotations:
            self.write_content(a)
        self._u1(TC_ENDBLOCKDATA)
        self.write_classdesc(cd.super_desc)

    def write_object(self, obj: JavaObject):
        if id(obj) in self.handles:
            self._ref(self.handles[id(obj)])
            return
        self._u1(TC_OBJECT)
        self.write_classdesc(obj.classdesc)
        self._assign(obj)
        for cls in obj.classdesc.hierarchy():
            if not cls.flags & SC_SERIALIZABLE:
                continue
            vals = obj.class_fields.get(cls.name, obj.fields)
            for t, fname, _sig in cls.fields:
                v = vals[fname]
                if t in _PRIM:
                    fmt, _ = _PRIM[t]
                    self.buf.write(struct.pack(fmt, v))
                else:
                    self.write_content(v)
            if cls.flags & SC_WRITE_METHOD:
                for a in obj.annotations.get(cls.name, []):
                    self.write_content(a)
                self._u1(TC_ENDBLOCKDATA)

    def write_array(self, arr: JavaArray):
        if id(arr) in self.handles:
            self._ref(self.handles[id(arr)])
            return
        self._u1(TC_ARRAY)
        self.write_classdesc(arr.classdesc)
        self._assign(arr)
        comp = arr.classdesc.name[1]
        if comp in _PRIM:
            vals = np.asarray(arr.values)
            self.buf.write(struct.pack(">i", vals.size))
            fmt, dt = _PRIM[comp]
            self.buf.write(
                vals.astype(np.dtype(dt).newbyteorder(">")).tobytes())
        else:
            self.buf.write(struct.pack(">i", len(arr.values)))
            for v in arr.values:
                self.write_content(v)
