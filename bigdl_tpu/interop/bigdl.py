"""Load/save models in the reference's native format (Java serialization).

Reference: `Module.save`/`Module.load` serialize the module object graph with
`ObjectOutputStream` (`nn/Module.scala:41-43`, `utils/File.scala:25`); the
reference's own `example/loadmodel/ModelValidator.scala` treats "bigdl" as a
first-class format alongside caffe/torch.  This module closes that interop
axis: `load` parses any object stream via `interop/javaser.py` (the stream is
self-describing), walks the module tree by class NAME, and rebuilds the
equivalent `bigdl_tpu` modules with layout-converted weights; `save` emits the
same wire format for the supported layer subset (and generates the checked-in
fixtures — no JVM exists in this image to run actual BigDL).

Layouts (same conversions as the Caffe/Torch importers):
  Linear weight   (out, in)                        -> (in, out)
  SpatialConvolution weight (g, out/g, in/g, kh, kw) -> HWIO (kh, kw, in/g, out)
  BatchNormalization runningMean/runningVar          -> state pytree

Unknown layer classes fail loudly with the class name (fail-loud default,
like interop/tensorflow.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .javaser import (SC_SERIALIZABLE, SC_WRITE_METHOD, JavaArray,
                      JavaClassDesc, JavaObject, JavaWriter, load_stream)

__all__ = ["load", "save"]

_PKG = "com.intel.analytics.bigdl.nn."
_TENSOR = "com.intel.analytics.bigdl.tensor.DenseTensor"
_STORAGE = "com.intel.analytics.bigdl.tensor.ArrayStorage"
# SerialVersionUIDs from the reference source (@SerialVersionUID
# annotations) — a JVM ObjectInputStream validates these on read, so every
# class the writer emits carries its real value
_SUID = {
    _TENSOR: 5876322619614900645,
    _PKG + "Sequential": 5375403296928513267,
    _PKG + "Linear": 359656776803598943,
    _PKG + "ReLU": 1208478077576570643,
    _PKG + "SpatialConvolution": -8446523046224797382,
    _PKG + "SpatialShareConvolution": 4479683852714800631,
    _PKG + "SpatialMaxPooling": 2277597677473874749,
    _PKG + "SpatialAveragePooling": 4533142511857387857,
    _PKG + "BatchNormalization": -3181824540272906068,
    _PKG + "SpatialBatchNormalization": -9106336963903528047,
    _PKG + "Reshape": -830146931795053244,
    _PKG + "View": 1238814703013238333,
    _PKG + "Dropout": -4636332259181125718,
    _PKG + "Identity": -8429221694319933625,
    _PKG + "Tanh": 9062199894710333035,
    _PKG + "Sigmoid": 6855417348268610044,
    _PKG + "LogSoftMax": -2954501946670913825,
    _PKG + "Concat": -5218461876031660707,
    _PKG + "ConcatTable": -704681653938468956,
    _PKG + "JoinTable": -8435694717504118735,
    _PKG + "CAddTable": 7959261460060075605,
    _PKG + "SpatialZeroPadding": -5144173515559923276,
    _PKG + "SpatialCrossMapLRN": 3641570491004969703,
    _PKG + "Threshold": 3953292249027271493,
    _PKG + "Power": -6637789603381436472,
    # sequence/embedding zoo (round-4 verdict #4)
    _PKG + "Graph": -2896121321564992779,
    _PKG + "Input": -8525406230282608924,
    "com.intel.analytics.bigdl.utils.Node": -6021651923538325999,
    _PKG + "LookupTable": -4832171200145114633,
    _PKG + "LSTM": -8176191554025511686,
    _PKG + "GRU": 6717988395573528459,
    _PKG + "ParallelTable": -1197848941394786045,
    _PKG + "NarrowTable": 8046335768231475724,
    _PKG + "SelectTable": 8787233248773612598,
    _PKG + "FlattenTable": 7620301574431959449,
    _PKG + "SplitTable": -4318640284973082779,
    _PKG + "CMulTable": 8888147326550637025,
    _PKG + "Narrow": 988790441682879293,
    _PKG + "MulConstant": -8747642888169310696,
    _PKG + "AddConstant": -1572711921601326233,
    _PKG + "Container": -2120105647780417237,
    _PKG + "LSTMPeephole": -7566757838561436619,
    _PKG + "MapTable": 4403280698280280268,
    _PKG + "Squeeze": 7998127436291978408,
    _PKG + "CMul": 8888147326550637025,  # same literal as CMulTable in src
    # JDK box classes (MulConstant/AddConstant's derived `scalar: T` field
    # erases to a boxed java.lang.Float) — SUIDs are JDK spec constants
    "java.lang.Number": -8742448824652078965,
    "java.lang.Float": -2671257302660747028,
    "java.lang.Double": -9172774392245257468,
    # Recurrent / RnnCell / TimeDistributed / TemporalConvolution /
    # AbstractModule / Cell / BiRecurrent / Reverse carry no
    # @SerialVersionUID annotation in the reference source; the JVM
    # computes a structural default (a SHA-1 over the compiled class's
    # members) that cannot be derived without a JVM — they fall back to
    # _DescCache's default of 1.
}


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

def _to_numpy(t: Optional[JavaObject]) -> Optional[np.ndarray]:
    """DenseTensor -> numpy via (_storage, _storageOffset, _size, _stride)."""
    if t is None:
        return None
    if t.classname != _TENSOR:
        raise ValueError(f"expected DenseTensor, got {t.classname}")
    storage = t.fields["_storage"]
    values = np.asarray(storage.fields["values"].values
                        if isinstance(storage.fields["values"], JavaArray)
                        else storage.fields["values"])
    ndim = int(t.fields["nDimension"])
    if ndim == 0:
        return np.zeros((0,), values.dtype)
    size = np.asarray(t.fields["_size"].values)[:ndim]
    stride = np.asarray(t.fields["_stride"].values)[:ndim]
    off = int(t.fields["_storageOffset"])
    out = np.lib.stride_tricks.as_strided(
        values[off:], shape=tuple(int(s) for s in size),
        strides=tuple(int(st) * values.itemsize for st in stride))
    return np.array(out)  # copy: detach from the storage buffer


def _children(obj: JavaObject) -> List[JavaObject]:
    """Container.modules: scala ArrayBuffer (fields `array` + `size0`)."""
    buf = obj.fields.get("modules")
    if buf is None:
        return []
    arr = buf.fields.get("array")
    n = int(buf.fields.get("size0", 0))
    items = arr.values[:n] if isinstance(arr, JavaArray) else []
    return [m for m in items if m is not None]


def _build(obj: JavaObject):
    """Map one reference module object -> (bigdl_tpu module, params, state);
    re-applies the stream's AbstractModule scaleW/scaleB so layer-wise
    scales survive migration."""
    m, p, s = _build_raw(obj)
    f = obj.fields
    for attr, key in (("scale_w", "scaleW"), ("scale_b", "scaleB")):
        v = f.get(key)
        if v is not None and float(v) != 1.0:
            setattr(m, attr, float(v))  # property setter bumps scale epoch
    return m, p, s


def _build_raw(obj: JavaObject):
    from .. import nn

    cls = obj.classname
    short = cls[len(_PKG):] if cls.startswith(_PKG) else cls
    f = obj.fields
    if short in ("Sequential", "Concat", "ConcatTable", "ParallelTable",
                 "MapTable"):
        if short == "Sequential":
            container = nn.Sequential()
        elif short == "ParallelTable":
            container = nn.ParallelTable()
        elif short == "MapTable":
            # one SHARED child; the reference also stores per-application
            # clones in `modules` — only the master (field `module`) maps
            container = nn.MapTable()
            m, p, s = _build(f["module"])
            container.modules = [m]
            return container, [p], [s]
        elif short == "Concat":
            # reference dimension is 1-based over NCHW: 2 = channels, which
            # is the LAST axis in this framework's NHWC layout (the only
            # concat axis the zoo models use — fail loud otherwise)
            dim = int(f.get("dimension", 2))
            if dim != 2:
                raise ValueError(
                    f"bigdl format: Concat over NCHW dim {dim} has no "
                    "NHWC mapping here (only channel concat, dim=2)")
            container = nn.Concat(-1)
        else:
            container = nn.ConcatTable()
        params, states = [], []
        for child in _children(obj):
            m, p, s = _build(child)
            container.add(m)
            params.append(p)
            states.append(s)
        return container, params, states
    if short == "Linear":
        m = nn.Linear(int(f["inputSize"]), int(f["outputSize"]),
                      with_bias=f.get("withBias", True))
        # both sides store (out, in) — nn.Linear keeps the reference layout
        p = {"weight": _to_numpy(f["weight"])}
        if f.get("withBias", True) and f.get("bias") is not None:
            p["bias"] = _to_numpy(f["bias"])
        return m, p, {}
    if short in ("SpatialConvolution", "SpatialShareConvolution"):
        g = int(f.get("nGroup", 1))
        ctor = (nn.SpatialShareConvolution
                if short == "SpatialShareConvolution"
                else nn.SpatialConvolution)
        m = ctor(
            int(f["nInputPlane"]), int(f["nOutputPlane"]),
            int(f["kernelW"]), int(f["kernelH"]),
            int(f.get("strideW", 1)), int(f.get("strideH", 1)),
            int(f.get("padW", 0)), int(f.get("padH", 0)), g,
            with_bias=bool(f.get("withBias", True))
            and f.get("bias") is not None)
        w = _to_numpy(f["weight"])  # (g, out/g, in/g, kh, kw)
        # -> HWIO (kh, kw, in/g, out):  merge the group dim into out
        w = w.transpose(3, 4, 2, 0, 1).reshape(
            w.shape[3], w.shape[4], w.shape[2], -1)
        p = {"weight": w}
        if f.get("bias") is not None:
            p["bias"] = _to_numpy(f["bias"])
        return m, p, {}
    if short in ("SpatialBatchNormalization", "BatchNormalization"):
        ctor = (nn.SpatialBatchNormalization
                if short == "SpatialBatchNormalization"
                else nn.BatchNormalization)
        m = ctor(int(f["nOutput"]), eps=float(f.get("eps", 1e-5)),
                 momentum=float(f.get("momentum", 0.1)),
                 affine=bool(f.get("affine", True)))
        p = {}
        if f.get("weight") is not None:
            p = {"weight": _to_numpy(f["weight"]),
                 "bias": _to_numpy(f["bias"])}
        s = {"running_mean": _to_numpy(f["runningMean"]),
             "running_var": _to_numpy(f["runningVar"])}
        return m, p, s
    if short == "SpatialMaxPooling":
        return nn.SpatialMaxPooling(int(f["kW"]), int(f["kH"]),
                                    int(f["dW"]), int(f["dH"]),
                                    int(f.get("padW", 0)),
                                    int(f.get("padH", 0))), {}, {}
    if short == "SpatialAveragePooling":
        return nn.SpatialAveragePooling(int(f["kW"]), int(f["kH"]),
                                        int(f.get("dW", 1)),
                                        int(f.get("dH", 1)),
                                        int(f.get("padW", 0)),
                                        int(f.get("padH", 0))), {}, {}
    if short == "Reshape":
        size = [int(x) for x in np.asarray(f["size"].values)]
        return nn.Reshape(size), {}, {}
    if short == "View":
        sizes = [int(x) for x in np.asarray(f["sizes"].values)]
        return nn.View(*sizes), {}, {}
    if short == "CAddTable":
        return nn.CAddTable(bool(f.get("inplace", False))), {}, {}
    if short == "CMulTable":
        return nn.CMulTable(), {}, {}
    if short == "FlattenTable":
        return nn.FlattenTable(), {}, {}
    if short == "JoinTable":
        dim = int(f.get("dimension", 2))
        if dim != 2:
            raise ValueError(f"bigdl format: JoinTable over NCHW dim {dim} "
                             "has no NHWC mapping here (channel only)")
        return nn.JoinTable(-1,
                            int(f.get("nInputDims", 0))), {}, {}
    if short == "SpatialZeroPadding":
        return nn.SpatialZeroPadding(int(f["padLeft"]), int(f["padRight"]),
                                     int(f["padTop"]),
                                     int(f["padBottom"])), {}, {}
    if short == "SpatialCrossMapLRN":
        return nn.SpatialCrossMapLRN(int(f.get("size", 5)),
                                     float(f.get("alpha", 1.0)),
                                     float(f.get("beta", 0.75)),
                                     float(f.get("k", 1.0))), {}, {}
    if short == "Threshold":
        return nn.Threshold(float(f.get("threshold", 1e-6)),
                            float(f.get("value", 0.0)),
                            bool(f.get("inPlace", False))), {}, {}
    if short == "Power":
        return nn.Power(float(f["power"]), float(f.get("scale", 1.0)),
                        float(f.get("shift", 0.0))), {}, {}
    if short == "Squeeze":
        dims = f.get("dims")
        if bool(f.get("batchMode", False)) and dims is None:
            # squeeze-all + batch-mode re-adds the batch singleton
            # (Squeeze.scala:58-60) — unrepresentable here, fail loud
            raise ValueError("bigdl format: Squeeze(batchMode=true, "
                             "dims=null) has no mapping here")
        if dims is not None:
            d = [int(v) for v in np.asarray(dims.values)]
            if len(d) != 1:
                raise ValueError(f"bigdl format: Squeeze over dims {d} has "
                                 "no single-axis mapping here")
            # reference dims are 1-based including batch
            return nn.Squeeze(d[0] - 1), {}, {}
        return nn.Squeeze(), {}, {}
    if short == "ReLU":
        return nn.ReLU(), {}, {}
    if short == "Tanh":
        return nn.Tanh(), {}, {}
    if short == "Sigmoid":
        return nn.Sigmoid(), {}, {}
    if short == "LogSoftMax":
        return nn.LogSoftMax(), {}, {}
    if short == "Dropout":
        return nn.Dropout(float(f.get("initP", 0.5))), {}, {}
    if short == "Identity":
        return nn.Identity(), {}, {}
    from . import bigdl_seq
    built = bigdl_seq.build_seq(short, obj, _build)
    if built is not None:
        return built
    raise ValueError(
        f"bigdl format: unsupported layer class {cls} — extend "
        "interop/bigdl._build (fail-loud, like the TensorFlow importer)")


def load(path: str):
    """Load a reference-format model file -> built bigdl_tpu Module
    (params/state attached, ready for forward/predict)."""
    with open(path, "rb") as fh:
        return load_bytes(fh.read())


def load_bytes(data: bytes):
    """As `load`, from in-memory bytes (remote-path callers read via
    file_io/fsspec and hand the payload here)."""
    import io

    import jax.numpy as jnp

    contents = load_stream(io.BytesIO(data))
    roots = [c for c in contents if isinstance(c, JavaObject)]
    if not roots:
        raise ValueError("bigdl stream: no serialized object found")
    module, params, state = _build(roots[0])

    def to_jax(tree):
        if isinstance(tree, dict):
            return {k: to_jax(v) for k, v in tree.items()}
        if isinstance(tree, list):
            return [to_jax(v) for v in tree]
        return jnp.asarray(tree)

    module.attach(to_jax(params), to_jax(state))
    return module


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

# JVM-grade classdesc machinery.  A real ObjectInputStream matches the
# stream's classdesc hierarchy against the local classes, so the writer
# must emit (a) the actual superclass chain (Linear -> TensorModule ->
# AbstractModule, ReLU -> Threshold -> ..., containers -> Container),
# (b) AbstractModule's own non-transient base fields, and (c) fields in
# the JOS canonical order (primitives before objects, each sorted by
# name — java.io.ObjectStreamField.compareTo).  The name-based reader is
# order-agnostic, so old flat streams (the frozen fixture) still load.
_ABSTRACTNN = "com.intel.analytics.bigdl.nn.abstractnn."
_AM = _ABSTRACTNN + "AbstractModule"
_TM = _ABSTRACTNN + "TensorModule"
_CONTAINER = _PKG + "Container"
_CELL = _PKG + "Cell"
_ACTIVITY_SIG = "Lcom/intel/analytics/bigdl/nn/abstractnn/Activity;"
_STRING_SIG = "Ljava/lang/String;"
_BUF_SIG = "Lscala/collection/mutable/ArrayBuffer;"
# AbstractModule.scala:58-341 non-transient members
_AM_FIELDS = [
    ("D", "scaleW", None), ("D", "scaleB", None),
    ("J", "forwardTime", None), ("J", "backwardTime", None),
    ("L", "output", _ACTIVITY_SIG), ("L", "gradInput", _ACTIVITY_SIG),
    ("Z", "train", None),
    ("L", "name", _STRING_SIG), ("L", "namePostfix", _STRING_SIG),
    ("L", "line", _STRING_SIG),
    ("L", "engineType", "Lcom/intel/analytics/bigdl/utils/EngineType;"),
]
# shared field lists for classes that appear both as a concrete class and
# as someone's superclass (ReLU extends Threshold; SpatialBatchNormalization
# extends BatchNormalization) — one definition so the descs cannot diverge
_TENSOR_SIG = "Lcom/intel/analytics/bigdl/tensor/Tensor;"
_THRESHOLD_FIELDS = [("D", "threshold", None), ("D", "value", None),
                     ("Z", "inPlace", None)]
_SCONV_FIELDS = [("I", "nInputPlane", None), ("I", "nOutputPlane", None),
                 ("I", "kernelW", None), ("I", "kernelH", None),
                 ("I", "strideW", None), ("I", "strideH", None),
                 ("I", "padW", None), ("I", "padH", None),
                 ("I", "nGroup", None),
                 ("L", "weight", _TENSOR_SIG), ("L", "bias", _TENSOR_SIG)]
_BN_FIELDS = [("I", "nOutput", None), ("D", "eps", None),
              ("D", "momentum", None), ("Z", "affine", None),
              ("L", "weight", _TENSOR_SIG), ("L", "bias", _TENSOR_SIG),
              ("L", "runningMean", _TENSOR_SIG),
              ("L", "runningVar", _TENSOR_SIG)]
# default values for inherited/base fields the module builders don't set
# explicitly; save() fills them in one walk over the finished object graph
_FILL_DEFAULTS = {
    "scaleW": 1.0, "scaleB": 1.0, "forwardTime": 0, "backwardTime": 0,
    "train": True, "output": None, "gradInput": None, "name": None,
    "namePostfix": "0", "line": "\n", "engineType": None,
    "regularizers": None,
    # ReLU is Threshold(0, 0, ip) in the reference (ReLU.scala)
    "threshold": 0.0, "value": 0.0, "inPlace": False,
}
_PARENT_CONTAINER = {"Sequential", "Concat", "ConcatTable", "ParallelTable",
                     "MapTable", "Recurrent", "BiRecurrent", "Graph"}
_PARENT_CELL = {"RnnCell", "LSTM", "GRU", "LSTMPeephole"}
_PARENT_AM_DIRECT = {"CAddTable", "CMulTable", "JoinTable", "SplitTable",
                     "NarrowTable", "SelectTable", "FlattenTable",
                     "Identity"}


def _canonical(fields):
    """JOS field order: primitives first, each group sorted by name."""
    return sorted(fields, key=lambda f: (0 if f[0] in "BCDFIJSZ" else 1,
                                         f[1]))


class _DescCache:
    """One JavaClassDesc per class per stream (so repeats become refs).
    nn-module classes get their real superclass chain attached
    automatically; fields are stored in JOS canonical order."""

    def __init__(self):
        self.cache: Dict[str, JavaClassDesc] = {}

    def get(self, name: str, fields, super_desc=None) -> JavaClassDesc:
        if name not in self.cache:
            if super_desc is None:
                super_desc = self._auto_super(name)
            self.cache[name] = JavaClassDesc(
                name, _SUID.get(name, 1), SC_SERIALIZABLE,
                _canonical(fields), super_desc)
        return self.cache[name]

    def _auto_super(self, name: str):
        if name == _AM:
            return None
        if name == _TM or name in (_CONTAINER, _CELL):
            # Container.scala:40 / Cell.scala:44 / TensorModule all extend
            # AbstractModule directly
            return self.get(_AM, list(_AM_FIELDS))
        if not name.startswith(_PKG) or name.startswith(_ABSTRACTNN):
            return None
        short = name[len(_PKG):]
        if "." in short:  # nested package (not an nn module class)
            return None
        if short == "ReLU":  # ReLU.scala: extends Threshold
            return self.get(_PKG + "Threshold", list(_THRESHOLD_FIELDS))
        if short == "SpatialBatchNormalization":  # extends BatchNormalization
            return self.get(_PKG + "BatchNormalization", list(_BN_FIELDS))
        if short == "SpatialShareConvolution":  # extends SpatialConvolution
            return self.get(_PKG + "SpatialConvolution",
                            list(_SCONV_FIELDS))
        if short in _PARENT_CONTAINER:
            return self.get(_CONTAINER, [("L", "modules", _BUF_SIG)])
        if short == "BinaryTreeLSTM":  # extends TreeLSTM (TreeLSTM.scala:25)
            return self.get(
                _PKG + "TreeLSTM",
                [("I", "inputSize", None), ("I", "hiddenSize", None),
                 ("L", "memZero", _TENSOR_SIG)])
        if short == "TreeLSTM":
            return self.get(_AM, list(_AM_FIELDS))
        if short in _PARENT_CELL:
            return self.get(_CELL, [
                ("[", "hiddensShape", "[I"),
                ("L", "regularizers",
                 "[Lcom/intel/analytics/bigdl/optim/Regularizer;")])
        if short in _PARENT_AM_DIRECT:
            return self.get(_AM, list(_AM_FIELDS))
        return self.get(_TM, [])  # TensorModule: no fields of its own

    def array(self, signature: str) -> JavaClassDesc:
        return self.get(signature, [])


def _fill_base_fields(root: JavaObject) -> None:
    """Fill inherited-field defaults for every module object in the graph
    (one walk, cycle-safe); unknown missing fields fail loud."""
    seen = set()

    def walk(o):
        if id(o) in seen:
            return
        seen.add(id(o))
        if isinstance(o, JavaArray):
            if o.values is not None and getattr(o.values, "dtype",
                                                None) is None:
                for v in o.values:
                    walk(v)
            return
        if not isinstance(o, JavaObject):
            return
        for cls in o.classdesc.hierarchy():
            for _t, fname, _sig in cls.fields:
                if fname not in o.fields:
                    if fname not in _FILL_DEFAULTS:
                        raise ValueError(
                            f"bigdl format save: {cls.name}.{fname} has no "
                            "value and no known default")
                    o.fields[fname] = _FILL_DEFAULTS[fname]
        for v in list(o.fields.values()):
            walk(v)

    walk(root)


def _w_tensor(dc: _DescCache, a: np.ndarray) -> JavaObject:
    a = np.ascontiguousarray(np.asarray(a, np.float32))
    storage_cd = dc.get(_STORAGE, [("[", "values", "[F")])
    storage = JavaObject(storage_cd, {
        "values": JavaArray(dc.array("[F"), a.reshape(-1))})
    stride = np.cumprod((1,) + a.shape[::-1][:-1])[::-1].astype(np.int32)
    cd = dc.get(_TENSOR, [
        ("I", "_storageOffset", None), ("I", "nDimension", None),
        ("L", "_storage", "Lcom/intel/analytics/bigdl/tensor/Storage;"),
        ("[", "_size", "[I"), ("[", "_stride", "[I")])
    return JavaObject(cd, {
        "_storageOffset": 0, "nDimension": a.ndim, "_storage": storage,
        "_size": JavaArray(dc.array("[I"), np.asarray(a.shape, np.int32)),
        "_stride": JavaArray(dc.array("[I"), stride)})


def _w_buffer(dc: "_DescCache", items) -> JavaObject:
    """scala.collection.mutable.ArrayBuffer wire shape (one definition —
    MapTable, the container branch, and bigdl_seq all share it)."""
    cd = dc.get("scala.collection.mutable.ArrayBuffer",
                [("I", "initialSize", None), ("I", "size0", None),
                 ("[", "array", "[Ljava/lang/Object;")])
    return JavaObject(cd, {
        "initialSize": 16, "size0": len(items),
        "array": JavaArray(dc.array("[Ljava.lang.Object;"), list(items))})


def _scales(m) -> dict:
    """The module's real scale_w/scale_b (AbstractModule.scala:73-74
    scaleW/scaleB) so the layer-wise gradient scale survives migration."""
    return {"scaleW": float(getattr(m, "scale_w", 1.0)),
            "scaleB": float(getattr(m, "scale_b", 1.0))}


def _w_module(dc: _DescCache, m, params, state) -> JavaObject:
    from .. import nn

    def obj(short, prim_fields, obj_fields):
        fields = ([(t, n, None) for t, n, _v in prim_fields] +
                  [("L" if not s.startswith("[") else "[", n, s)
                   for n, s, _v in obj_fields])
        cd = dc.get(_PKG + short, fields)
        vals = {n: v for _t, n, v in prim_fields}
        vals.update({n: v for n, _s, v in obj_fields})
        vals.update(_scales(m))
        return JavaObject(cd, vals)

    t = "Lcom/intel/analytics/bigdl/tensor/Tensor;"
    if isinstance(m, nn.MapTable):
        inner = _w_module(dc, m.modules[0], params[0], state[0])
        cd = dc.get(_PKG + "MapTable",
                    [("L", "module",
                      "Lcom/intel/analytics/bigdl/nn/abstractnn/"
                      "AbstractModule;")])
        return JavaObject(cd, {
            "module": inner, "modules": _w_buffer(dc, [inner]),
            **_scales(m)})
    if isinstance(m, nn.Squeeze):
        if m.dim is not None and m.dim < 0:
            # the reference's squeeze is strictly 1-based positive
            # (DenseTensor.scala:60) — a negative axis cannot be resolved
            # without the input rank, so refuse instead of emitting a
            # stream the JVM rejects at forward time
            raise ValueError(f"bigdl format save: Squeeze(dim={m.dim}) "
                             "needs a non-negative axis")
        return obj("Squeeze",
                   [("Z", "batchMode", False)],
                   [("dims", "[I",
                     JavaArray(dc.array("[I"),
                               np.asarray([m.dim + 1], np.int32))
                     if m.dim is not None else None)])
    if isinstance(m, nn.ConvBNAddReLU):
        # de-fuse to the reference residual-block shape: the tail fusion
        # is a TPU-local rewrite (nn/fused.py), not a reference class —
        # the wire carries ConcatTable(branch, shortcut) -> CAddTable ->
        # ReLU with the params re-nested to match
        head, conv, bn, shortcut = m.modules
        branch = nn.Sequential(*head.modules).add(conv).add(bn)
        seq = (nn.Sequential()
               .add(nn.ConcatTable().add(branch).add(shortcut))
               .add(nn.CAddTable())
               .add(nn.ReLU()))
        hp, cp, bp, sp = params
        hs, cs, bs, ss = state
        return _w_module(dc, seq,
                         [[list(hp) + [cp, bp], sp], {}, {}],
                         [[list(hs) + [cs, bs], ss], {}, {}])
    if isinstance(m, (nn.Sequential, nn.Concat, nn.ConcatTable,
                      nn.ParallelTable)):
        kids = [_w_module(dc, c, p, s)
                for c, p, s in zip(m.modules, params, state)]
        buf = _w_buffer(dc, kids)
        # `modules` lives on the Container superclass desc (attached by
        # _DescCache automatically); only class-own fields are declared here
        if isinstance(m, nn.Concat):
            if m.dimension not in (-1, 3):
                raise ValueError("bigdl format save: only channel Concat "
                                 "maps to the reference's NCHW dim 2")
            cd = dc.get(_PKG + "Concat", [("I", "dimension", None)])
            return JavaObject(cd, {"dimension": 2, "modules": buf,
                                   **_scales(m)})
        # fused subclasses (nn.ConvBN) are a TPU-local optimization, not a
        # reference class: serialize as the plain Sequential they subclass
        short = ("Sequential" if isinstance(m, nn.Sequential)
                 else type(m).__name__)
        cd = dc.get(_PKG + short, [])
        return JavaObject(cd, {"modules": buf, **_scales(m)})
    if isinstance(m, nn.CAddTable):
        return obj("CAddTable", [("Z", "inplace", bool(m.inplace))], [])
    if isinstance(m, nn.View):
        return obj("View", [],
                   [("sizes", "[I", JavaArray(
                       dc.array("[I"), np.asarray(m.sizes, np.int32)))])
    if isinstance(m, nn.JoinTable):
        if m.dimension not in (-1, 3):
            raise ValueError("bigdl format save: only channel JoinTable "
                             "maps to the reference's NCHW dim 2")
        return obj("JoinTable",
                   [("I", "dimension", 2),
                    ("I", "nInputDims", int(getattr(m, "n_input_dims", 0)))],
                   [])
    if isinstance(m, nn.SpatialZeroPadding):
        return obj("SpatialZeroPadding",
                   [("I", "padLeft", m.l), ("I", "padRight", m.r),
                    ("I", "padTop", m.t), ("I", "padBottom", m.b)], [])
    if isinstance(m, nn.Linear):
        return obj("Linear",
                   [("I", "inputSize", m.input_size),
                    ("I", "outputSize", m.output_size),
                    ("Z", "withBias", m.with_bias)],
                   [("weight", t, _w_tensor(dc, params["weight"])),
                    ("bias", t, _w_tensor(dc, params["bias"])
                     if m.with_bias else None)])
    if isinstance(m, nn.SpatialConvolution):
        kh, kw = m.kernel
        sh, sw = m.stride
        ph, pw = m.pad
        w = np.asarray(params["weight"])  # HWIO
        g = m.n_group
        w5 = w.reshape(kh, kw, w.shape[2], g, -1).transpose(3, 4, 2, 0, 1)
        sconv_cd = dc.get(_PKG + "SpatialConvolution", list(_SCONV_FIELDS))
        cd = (dc.get(_PKG + "SpatialShareConvolution", [],
                     super_desc=sconv_cd)
              if isinstance(m, nn.SpatialShareConvolution) else sconv_cd)
        return JavaObject(cd, {
            "nInputPlane": m.n_input_plane,
            "nOutputPlane": m.n_output_plane,
            "kernelW": kw, "kernelH": kh, "strideW": sw, "strideH": sh,
            "padW": pw, "padH": ph, "nGroup": g,
            "weight": _w_tensor(dc, w5),
            "bias": (_w_tensor(dc, params["bias"])
                     if m.with_bias else None),
            **_scales(m)})
    if isinstance(m, (nn.SpatialBatchNormalization, nn.BatchNormalization)):
        # SpatialBatchNormalization extends BatchNormalization (which holds
        # every field) — the subclass desc is empty with the BN super desc
        bn_cd = dc.get(_PKG + "BatchNormalization", list(_BN_FIELDS))
        cd = (dc.get(_PKG + "SpatialBatchNormalization", [],
                     super_desc=bn_cd)
              if isinstance(m, nn.SpatialBatchNormalization) else bn_cd)
        return JavaObject(cd, {
            "nOutput": m.n_output, "eps": m.eps, "momentum": m.momentum,
            "affine": m.affine,
            "weight": _w_tensor(dc, params["weight"]) if m.affine else None,
            "bias": _w_tensor(dc, params["bias"]) if m.affine else None,
            "runningMean": _w_tensor(dc, state["running_mean"]),
            "runningVar": _w_tensor(dc, state["running_var"]),
            **_scales(m)})
    if isinstance(m, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
        kh, kw = m.kernel
        sh, sw = m.stride
        ph, pw = m.pad
        short = ("SpatialMaxPooling" if isinstance(m, nn.SpatialMaxPooling)
                 else "SpatialAveragePooling")
        return obj(short,
                   [("I", "kW", kw), ("I", "kH", kh), ("I", "dW", sw),
                    ("I", "dH", sh), ("I", "padW", pw), ("I", "padH", ph)],
                   [])
    if isinstance(m, nn.Dropout):
        # initP (ctor) plus the DERIVED runtime fields updateOutput reads:
        # `private var p = initP`, inplace, scale — a stream without them
        # deserializes with JOS zero-defaults (p=0.0: dropout silently off)
        return obj("Dropout",
                   [("D", "initP", float(m.p)), ("D", "p", float(m.p)),
                    ("Z", "inplace", False), ("Z", "scale", True)], [])
    if isinstance(m, nn.SpatialCrossMapLRN):
        return obj("SpatialCrossMapLRN",
                   [("I", "size", m.size), ("D", "alpha", float(m.alpha)),
                    ("D", "beta", float(m.beta)), ("D", "k", float(m.k))],
                   [])
    if isinstance(m, nn.Threshold):
        return obj("Threshold",
                   [("D", "threshold", float(m.th)),
                    ("D", "value", float(m.v)),
                    ("Z", "inPlace", m.ip)], [])
    if isinstance(m, nn.Power):
        return obj("Power",
                   [("D", "power", float(m.power)),
                    ("D", "scale", float(m.scale)),
                    ("D", "shift", float(m.shift))], [])
    if isinstance(m, nn.Reshape):
        return obj("Reshape", [],
                   [("size", "[I", JavaArray(
                       dc.array("[I"), np.asarray(m.size, np.int32)))])
    simple = {nn.ReLU: "ReLU", nn.Tanh: "Tanh", nn.Sigmoid: "Sigmoid",
              nn.LogSoftMax: "LogSoftMax", nn.Identity: "Identity",
              nn.CMulTable: "CMulTable", nn.FlattenTable: "FlattenTable"}
    for pycls, short in simple.items():
        if isinstance(m, pycls):
            return obj(short, [], [])
    from . import bigdl_seq
    written = bigdl_seq.write_seq(dc, m, params, state, _w_module)
    if written is not None:
        return written
    raise ValueError(f"bigdl format save: unsupported layer "
                     f"{type(m).__name__}")


def save(model, path: str):
    """Write `model` (built, params attached) in the reference wire format."""
    if model.params is None:
        raise ValueError("model has no parameters attached — call build() "
                         "or load weights first")

    def host(tree):
        if isinstance(tree, dict):
            return {k: host(v) for k, v in tree.items()}
        if isinstance(tree, list):
            return [host(v) for v in tree]
        return np.asarray(tree)

    dc = _DescCache()
    root = _w_module(dc, model, host(model.params), host(model.state))
    _fill_base_fields(root)  # inherited AbstractModule/field defaults
    w = JavaWriter()
    w.write_object(root)
    with open(path, "wb") as fh:
        fh.write(w.getvalue())
