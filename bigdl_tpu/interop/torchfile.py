"""Torch7 binary serialization (.t7) reader/writer.

Reference: utils/TorchFile.scala:67 (load :79, save :95; type tags
`TorchObject:42`) — BigDL reads/writes Torch7 objects so models round-trip
with Lua Torch.  The .t7 wire format (public, from torch7/File.lua):

    every value is [i32 type-tag][payload]:
      0 TYPE_NIL
      1 TYPE_NUMBER   f64
      2 TYPE_STRING   i32 len + bytes
      3 TYPE_TABLE    i32 index, then i32 count + count*(key, value)
      4 TYPE_TORCH    i32 index, then version string ("V <n>"), class name
                      string, then class-specific payload
      5 TYPE_BOOLEAN  i32 (0/1)
      6/7/8 FUNCTION variants (unsupported here)

    indices implement reference sharing: the second occurrence of a
    table/object writes only its index.

    torch.XTensor payload: i32 ndim, i64[ndim] size, i64[ndim] stride,
      i64 storageOffset (1-based), then the Storage object (or nil).
    torch.XStorage payload: i64 size, size * element bytes.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, Optional

import numpy as np

__all__ = ["load_t7", "save_t7", "T7Writer", "T7Reader"]

TYPE_NIL, TYPE_NUMBER, TYPE_STRING, TYPE_TABLE, TYPE_TORCH, TYPE_BOOLEAN = \
    0, 1, 2, 3, 4, 5

_TENSOR_CLASSES = {
    "torch.FloatTensor": ("torch.FloatStorage", np.float32),
    "torch.DoubleTensor": ("torch.DoubleStorage", np.float64),
    "torch.IntTensor": ("torch.IntStorage", np.int32),
    "torch.LongTensor": ("torch.LongStorage", np.int64),
    "torch.ByteTensor": ("torch.ByteStorage", np.uint8),
}
_STORAGE_DTYPES = {storage: dtype
                   for storage, dtype in _TENSOR_CLASSES.values()}


class T7Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def _i32(self) -> int:
        return struct.unpack("<i", self.f.read(4))[0]

    def _i64(self) -> int:
        return struct.unpack("<q", self.f.read(8))[0]

    def _f64(self) -> float:
        return struct.unpack("<d", self.f.read(8))[0]

    def _string(self) -> str:
        n = self._i32()
        return self.f.read(n).decode("latin-1")

    def read(self) -> Any:
        tag = self._i32()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            v = self._f64()
            return int(v) if v == int(v) else v
        if tag == TYPE_STRING:
            return self._string()
        if tag == TYPE_BOOLEAN:
            return bool(self._i32())
        if tag == TYPE_TABLE:
            idx = self._i32()
            if idx in self.memo:
                return self.memo[idx]
            out: Dict[Any, Any] = {}
            self.memo[idx] = out
            count = self._i32()
            for _ in range(count):
                k = self.read()
                v = self.read()
                out[k] = v
            # Lua arrays: 1..n integer keys -> python list
            n = len(out)
            if n and all(isinstance(k, int) for k in out) and \
                    set(out) == set(range(1, n + 1)):
                lst = [out[i] for i in range(1, n + 1)]
                self.memo[idx] = lst
                return lst
            return out
        if tag == TYPE_TORCH:
            idx = self._i32()
            if idx in self.memo:
                return self.memo[idx]
            version = self._string()
            if version.startswith("V "):
                cls = self._string()
            else:  # legacy: no version record
                cls = version
            obj = self._read_torch(cls, idx)
            return obj
        raise ValueError(f"unsupported t7 type tag {tag}")

    def _read_torch(self, cls: str, idx: int) -> Any:
        if cls in _TENSOR_CLASSES:
            ndim = self._i32()
            size = [self._i64() for _ in range(ndim)]
            stride = [self._i64() for _ in range(ndim)]
            offset = self._i64() - 1
            storage = self.read()
            if storage is None:
                arr = np.zeros(size, dtype=_TENSOR_CLASSES[cls][1])
            else:
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:],
                    shape=size,
                    strides=[s * storage.itemsize for s in stride]).copy()
            self.memo[idx] = arr
            return arr
        if cls in _STORAGE_DTYPES:
            dtype = _STORAGE_DTYPES[cls]
            n = self._i64()
            arr = np.frombuffer(
                self.f.read(n * np.dtype(dtype).itemsize), dtype=dtype)
            self.memo[idx] = arr
            return arr
        # unknown torch class: its payload is a table of fields
        payload = self.read()
        obj = {"__torch_class__": cls, **(payload or {})} \
            if isinstance(payload, dict) else \
            {"__torch_class__": cls, "value": payload}
        self.memo[idx] = obj
        return obj


class T7Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self._next_index = 1
        self._seen: Dict[int, int] = {}
        # keep written objects alive: _seen is keyed by id(), which CPython
        # reuses once an object is collected — a dangling id would alias two
        # distinct tables into one shared reference record
        self._keepalive: list = []

    def _i32(self, v: int):
        self.f.write(struct.pack("<i", v))

    def _i64(self, v: int):
        self.f.write(struct.pack("<q", v))

    def _f64(self, v: float):
        self.f.write(struct.pack("<d", v))

    def _string(self, s: str):
        b = s.encode("latin-1")
        self._i32(len(b))
        self.f.write(b)

    def write(self, obj: Any):
        if obj is None:
            self._i32(TYPE_NIL)
        elif isinstance(obj, bool):
            self._i32(TYPE_BOOLEAN)
            self._i32(int(obj))
        elif isinstance(obj, (int, float)):
            self._i32(TYPE_NUMBER)
            self._f64(float(obj))
        elif isinstance(obj, str):
            self._i32(TYPE_STRING)
            self._string(obj)
        elif isinstance(obj, np.ndarray):
            self._write_tensor(obj)
        elif isinstance(obj, (list, tuple)):
            self.write({i + 1: v for i, v in enumerate(obj)})
        elif isinstance(obj, dict) and "__torch_class__" in obj:
            key = id(obj)
            self._i32(TYPE_TORCH)
            if key in self._seen:
                self._i32(self._seen[key])
                return
            idx = self._next_index
            self._next_index += 1
            self._seen[key] = idx
            self._keepalive.append(obj)
            self._i32(idx)
            self._string("V 1")
            self._string(obj["__torch_class__"])
            self.write({k: v for k, v in obj.items()
                        if k != "__torch_class__"})
        elif isinstance(obj, dict):
            self._i32(TYPE_TABLE)
            key = id(obj)
            if key in self._seen:
                self._i32(self._seen[key])
                return
            idx = self._next_index
            self._next_index += 1
            self._seen[key] = idx
            self._keepalive.append(obj)
            self._i32(idx)
            self._i32(len(obj))
            for k, v in obj.items():
                self.write(k)
                self.write(v)
        else:
            raise TypeError(f"cannot serialize {type(obj).__name__} to t7")

    def _write_tensor(self, arr: np.ndarray):
        cls = {np.dtype(np.float32): "torch.FloatTensor",
               np.dtype(np.float64): "torch.DoubleTensor",
               np.dtype(np.int32): "torch.IntTensor",
               np.dtype(np.int64): "torch.LongTensor",
               np.dtype(np.uint8): "torch.ByteTensor"}.get(arr.dtype)
        if cls is None:
            arr = arr.astype(np.float32)
            cls = "torch.FloatTensor"
        storage_cls = _TENSOR_CLASSES[cls][0]
        arr = np.ascontiguousarray(arr)
        self._i32(TYPE_TORCH)
        idx = self._next_index
        self._next_index += 1
        self._i32(idx)
        self._string("V 1")
        self._string(cls)
        self._i32(arr.ndim)
        for s in arr.shape:
            self._i64(s)
        itemsize = arr.itemsize
        for s in arr.strides:
            self._i64(s // itemsize)
        self._i64(1)  # storageOffset, 1-based
        # storage object
        self._i32(TYPE_TORCH)
        sidx = self._next_index
        self._next_index += 1
        self._i32(sidx)
        self._string("V 1")
        self._string(storage_cls)
        self._i64(arr.size)
        self.f.write(arr.tobytes())


def load_t7(path: str) -> Any:
    """(reference: TorchFile.load, utils/TorchFile.scala:79)."""
    with open(path, "rb") as f:
        return T7Reader(f).read()


def load_torch_module(path: str):
    """Map a serialized Lua-Torch nn model to a bigdl_tpu module with weights
    (reference: Module.loadTorch, nn/Module.scala:45 + the per-class readers
    in TorchFile.scala).  Covers the common feed-forward classes; returns
    (module, params_list) like the caffe/tf loaders."""
    obj = load_t7(path)
    from .. import nn as N
    from .caffe import _fc_cols_chw_to_hwc

    # Torch activations are NCHW; ours are NHWC.  Track channels so FC
    # weights crossing a conv->flatten boundary get their columns permuted
    # from (C,H,W) to (H,W,C) order, and 3-D reshapes get transposed
    # (round-1 advisor finding — mirrors the CaffeLoader handling).
    ctx = {"ch": None, "spatial": False, "flat_ch": None}

    def convert(o):
        cls = o.get("__torch_class__", "") if isinstance(o, dict) else ""
        if cls == "nn.Sequential":
            seq = N.Sequential()
            mods, ps = [], []
            for child in o.get("modules", []):
                m, p = convert(child)
                if m is not None:
                    seq.add(m)
                    ps.append(p)
            return seq, ps
        if cls == "nn.Linear":
            w = np.asarray(o["weight"], np.float32)
            b = o.get("bias")
            c = ctx["flat_ch"]
            if c and w.shape[1] % c == 0:
                w = _fc_cols_chw_to_hwc(w, c)
            m = N.Linear(w.shape[1], w.shape[0], with_bias=b is not None)
            p = {"weight": w}
            if b is not None:
                p["bias"] = np.asarray(b, np.float32).reshape(-1)
            ctx.update(ch=w.shape[0], spatial=False, flat_ch=None)
            return m, p
        if cls in ("nn.SpatialConvolution", "nn.SpatialConvolutionMM"):
            n_out = int(o["nOutputPlane"])
            n_in = int(o["nInputPlane"])
            kw, kh = int(o["kW"]), int(o["kH"])
            dw, dh = int(o.get("dW", 1)), int(o.get("dH", 1))
            pw, ph = int(o.get("padW", 0)), int(o.get("padH", 0))
            w = np.asarray(o["weight"], np.float32).reshape(
                n_out, n_in, kh, kw)
            b = o.get("bias")
            m = N.SpatialConvolution(n_in, n_out, kw, kh, dw, dh, pw, ph,
                                     with_bias=b is not None)
            p = {"weight": np.transpose(w, (2, 3, 1, 0))}
            if b is not None:
                p["bias"] = np.asarray(b, np.float32).reshape(-1)
            ctx.update(ch=n_out, spatial=True)
            return m, p
        if cls == "nn.SpatialMaxPooling":
            m = N.SpatialMaxPooling(int(o["kW"]), int(o["kH"]),
                                    int(o.get("dW", o["kW"])),
                                    int(o.get("dH", o["kH"])),
                                    int(o.get("padW", 0)),
                                    int(o.get("padH", 0)))
            if o.get("ceil_mode"):
                m.ceil()
            return m, {}
        if cls == "nn.SpatialAveragePooling":
            return N.SpatialAveragePooling(
                int(o["kW"]), int(o["kH"]),
                int(o.get("dW", o["kW"])), int(o.get("dH", o["kH"])),
                int(o.get("padW", 0)), int(o.get("padH", 0))), {}
        simple = {"nn.ReLU": N.ReLU, "nn.Tanh": N.Tanh,
                  "nn.Sigmoid": N.Sigmoid, "nn.SoftMax": N.SoftMax,
                  "nn.LogSoftMax": N.LogSoftMax, "nn.Identity": N.Identity}
        if cls in simple:
            return simple[cls](), {}
        if cls == "nn.Dropout":
            return N.Dropout(float(o.get("p", 0.5))), {}
        if cls in ("nn.Reshape", "nn.View"):
            size = o.get("size")
            dims = [int(s) for s in np.asarray(size).ravel()] \
                if size is not None else [-1]
            if len(dims) == 3:  # torch (C,H,W) -> our NHWC (H,W,C)
                c, h, w_ = dims
                ctx.update(ch=c, spatial=True)
                return N.Reshape((h, w_, c)), {}
            if ctx["spatial"]:
                ctx["flat_ch"] = ctx["ch"]
            ctx["spatial"] = False
            return N.Reshape(tuple(dims)), {}
        raise ValueError(f"load_torch_module: unsupported class {cls!r}")

    module, params = convert(obj)
    import jax
    _, state = module.init(jax.random.key(0))
    module.attach(params, state)
    return module, params


def save_t7(obj: Any, path: str) -> None:
    """(reference: TorchFile.save, utils/TorchFile.scala:95)."""
    with open(path, "wb") as f:
        T7Writer(f).write(obj)


def save_torch_module(module, params, path: str) -> None:
    """Serialize a bigdl_tpu module as a Lua-Torch nn object tree
    (reference: Module.saveTorch via TorchFile.save)."""
    from .. import nn as N
    from .caffe import _fc_cols_hwc_to_chw

    ctx = {"ch": None, "spatial": False, "flat_ch": None}

    def convert(mod, p):
        cls = type(mod).__name__
        if isinstance(mod, N.Sequential):
            return {"__torch_class__": "nn.Sequential",
                    "modules": [convert(m, pp)
                                for m, pp in zip(mod.modules, p)]}
        if isinstance(mod, N.Linear):
            w = np.asarray(p["weight"], np.float32)
            c = ctx["flat_ch"]
            if c and w.shape[1] % c == 0:
                # our columns are NHWC-flat (H,W,C); torch wants (C,H,W)
                w = _fc_cols_hwc_to_chw(w, c)
            o = {"__torch_class__": "nn.Linear", "weight": w}
            if "bias" in p:
                o["bias"] = np.asarray(p["bias"], np.float32)
            ctx.update(ch=mod.output_size, spatial=False, flat_ch=None)
            return o
        if isinstance(mod, N.SpatialConvolution):
            kh, kw = mod.kernel
            sh, sw = mod.stride
            ph, pw = mod.pad
            if ph == -1 or pw == -1:  # SAME sentinel (see CaffePersister)
                if (sh, sw) == (1, 1) and kh % 2 == 1 and kw % 2 == 1:
                    ph, pw = kh // 2, kw // 2
                else:
                    raise ValueError(
                        "save_torch_module: SAME padding (pad=-1) with "
                        f"stride {mod.stride} has no Torch equivalent")
            w = np.transpose(np.asarray(p["weight"], np.float32),
                             (3, 2, 0, 1))  # HWIO -> OIHW
            o = {"__torch_class__": "nn.SpatialConvolution",
                 "nInputPlane": mod.n_input_plane,
                 "nOutputPlane": mod.n_output_plane,
                 "kW": kw, "kH": kh, "dW": sw, "dH": sh,
                 "padW": pw, "padH": ph, "weight": w}
            if "bias" in p:
                o["bias"] = np.asarray(p["bias"], np.float32)
            ctx.update(ch=mod.n_output_plane, spatial=True)
            return o
        if isinstance(mod, N.SpatialMaxPooling):
            kh, kw = mod.kernel
            sh, sw = mod.stride
            ph, pw = mod.pad
            return {"__torch_class__": "nn.SpatialMaxPooling",
                    "kW": kw, "kH": kh, "dW": sw, "dH": sh,
                    "padW": pw, "padH": ph,
                    "ceil_mode": bool(mod.ceil_mode)}
        simple = {"ReLU": "nn.ReLU", "Tanh": "nn.Tanh",
                  "Sigmoid": "nn.Sigmoid", "SoftMax": "nn.SoftMax",
                  "LogSoftMax": "nn.LogSoftMax", "Identity": "nn.Identity"}
        if cls in simple:
            return {"__torch_class__": simple[cls]}
        if isinstance(mod, N.Dropout):
            return {"__torch_class__": "nn.Dropout", "p": mod.p}
        if isinstance(mod, (N.Reshape, N.View)):
            size = tuple(getattr(mod, "size", None)
                         or getattr(mod, "sizes", ()))
            if len(size) == 3:  # our NHWC (H,W,C) -> torch (C,H,W)
                h, w_, c = size
                ctx.update(ch=c, spatial=True)
                return {"__torch_class__": "nn.Reshape",
                        "size": np.asarray((c, h, w_), np.int64)}
            if ctx["spatial"]:
                ctx["flat_ch"] = ctx["ch"]
            ctx["spatial"] = False
            return {"__torch_class__": "nn.Reshape",
                    "size": np.asarray(size, np.int64)}
        raise ValueError(f"save_torch_module: unsupported {cls}")

    save_t7(convert(module, params), path)
