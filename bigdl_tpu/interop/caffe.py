"""Caffe model interop: load .caffemodel files into bigdl_tpu modules and
persist modules back out.

Reference: utils/caffe/CaffeLoader.scala:56 (loadBinary :93, copyParameters
:239, layer mapping in Converter/LayerConverter/V1LayerConverter.scala) and
utils/caffe/CaffePersister.scala, all driven by the protoc-generated
caffe/Caffe.java.  Rebuild: the generic wire codec (utils/pbwire.py) plus
the public caffe.proto field numbers below; layers map to TPU-native nn
modules and weights are transposed into our NHWC/HWIO layouts.

caffe.proto field numbers used (public schema):
    NetParameter: name=1, input=3, layers(V1)=2, layer(V2)=100
    LayerParameter: name=1, type=2 (string), bottom=3, top=4, blobs=7,
        pooling_param=103, convolution_param=106, dropout_param=108,
        inner_product_param=117, lrn_param=118
    V1LayerParameter: bottom=2, top=3, name=4, type=5 (enum), blobs=6,
        pooling_param=19, convolution_param=12, dropout_param=23? (unused),
        inner_product_param=17, lrn_param=18
    BlobProto: shape=7 (BlobShape.dim=1), data=5 (packed float),
        num=1 channels=2 height=3 width=4 (legacy 4-D)
    ConvolutionParameter: num_output=1 bias_term=2 pad=3 kernel_size=4
        group=5 stride=6 pad_h=9 pad_w=10 kernel_h=11 kernel_w=12
        stride_h=13 stride_w=14 dilation=18
    PoolingParameter: pool=1 (0 MAX, 1 AVE) kernel_size=2 stride=3 pad=4
        kernel_h=5 kernel_w=6 stride_h=7 stride_w=8 pad_h=9 pad_w=10
        global_pooling=12
    InnerProductParameter: num_output=1 bias_term=2
    LRNParameter: local_size=1 alpha=2 beta=3 norm_region=4 k=5
    DropoutParameter: dropout_ratio=1
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import pbwire
from ..utils.pbwire import Fields

logger = logging.getLogger(__name__)

__all__ = ["CaffeLoader", "CaffePersister", "load_caffe", "save_caffe"]

# V1LayerParameter.LayerType enum -> V2 string type (public caffe.proto)
_V1_TYPES = {
    3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout",
    14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU",
    20: "Softmax", 21: "SoftmaxWithLoss", 22: "Split", 23: "TanH",
    19: "Sigmoid", 8: "Flatten", 33: "Slice", 25: "Eltwise",
}


class _Layer:
    """Parsed layer description, schema-neutral between V1 and V2."""

    def __init__(self, name: str, type_: str, bottoms: List[str],
                 tops: List[str], blobs: List[np.ndarray],
                 blob_shapes: List[Tuple[int, ...]], params: Dict[int, Fields]):
        self.name = name
        self.type = type_
        self.bottoms = bottoms
        self.tops = tops
        self.blobs = blobs
        self.blob_shapes = blob_shapes
        self.params = params


def _parse_blob(f: Fields) -> Tuple[np.ndarray, Tuple[int, ...]]:
    data = np.array(f.floats(5), dtype=np.float32)
    if f.has(7):
        shape = tuple(f.sub(7).ints(1))
    else:  # legacy num/channels/height/width
        shape = tuple(f.int(i, 1) for i in (1, 2, 3, 4))
        while len(shape) > 1 and shape[0] == 1:
            shape = shape[1:]
    if data.size and int(np.prod(shape)) == data.size:
        data = data.reshape(shape)
    return data, shape


def _parse_layers(buf: bytes) -> Tuple[str, List[_Layer]]:
    net = Fields(buf)
    layers: List[_Layer] = []
    for lf in net.subs(100):  # V2
        blobs = [_parse_blob(b) for b in lf.subs(7)]
        layers.append(_Layer(
            lf.str(1), lf.str(2), lf.strs(3), lf.strs(4),
            [b for b, _ in blobs], [s for _, s in blobs],
            {n: lf.sub(n) for n in (103, 106, 108, 117, 118) if lf.has(n)}))
    for lf in net.subs(2):  # V1
        blobs = [_parse_blob(b) for b in lf.subs(6)]
        layers.append(_Layer(
            lf.str(4), _V1_TYPES.get(lf.int(5), f"V1_{lf.int(5)}"),
            lf.strs(2), lf.strs(3),
            [b for b, _ in blobs], [s for _, s in blobs],
            {103: lf.sub(19), 106: lf.sub(12), 117: lf.sub(17),
             118: lf.sub(18)}))
    return net.str(1), layers


def _conv_args(p: Fields):
    kh = p.int(11) or (p.ints(4)[0] if p.ints(4) else 1)
    kw = p.int(12) or (p.ints(4)[-1] if p.ints(4) else 1)
    sh = p.int(13) or (p.ints(6)[0] if p.ints(6) else 1)
    sw = p.int(14) or (p.ints(6)[-1] if p.ints(6) else 1)
    ph = p.int(9) or (p.ints(3)[0] if p.ints(3) else 0)
    pw = p.int(10) or (p.ints(3)[-1] if p.ints(3) else 0)
    return kh, kw, sh, sw, ph, pw, p.int(1), p.int(5, 1), p.int(2, 1)


class CaffeLoader:
    """Build a bigdl_tpu Graph from a binary .caffemodel
    (reference: CaffeLoader.loadBinary + Converter.toBigDL)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            self.net_name, self.layers = _parse_layers(f.read())

    def build(self):
        """Returns (module, params_tree): a Graph wired by bottom/top names
        with weights copied in (conv blobs OIHW -> HWIO, NCHW -> NHWC)."""
        from .. import nn
        from ..nn.graph import Graph, Input

        tensors: Dict[str, object] = {}
        inputs = []
        params: Dict[str, Dict] = {}
        modules: Dict[str, object] = {}
        ordered: List[str] = []

        def get_bottom(name):
            if name not in tensors:
                node = Input()
                tensors[name] = node
                inputs.append(node)
            return tensors[name]

        for ly in self.layers:
            t = ly.type
            mod = None
            p: Optional[Dict] = None
            if t in ("Data", "Input", "Split"):
                continue
            elif t == "Convolution":
                kh, kw, sh, sw, ph, pw, n_out, group, bias = _conv_args(
                    ly.params.get(106, Fields(b"")))
                w = ly.blobs[0]  # (out, in/g, kh, kw)
                n_in = w.shape[1] * group
                mod = nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh,
                                            pw, ph, group,
                                            with_bias=bool(bias))
                p = {"weight": np.transpose(w, (2, 3, 1, 0))}
                if bias and len(ly.blobs) > 1:
                    p["bias"] = ly.blobs[1].reshape(-1)
            elif t == "InnerProduct":
                ip = ly.params.get(117, Fields(b""))
                w = ly.blobs[0]
                w = w.reshape(ip.int(1), -1)
                mod = nn.Linear(w.shape[1], w.shape[0],
                                with_bias=bool(ip.int(2, 1)))
                p = {"weight": w}
                if ip.int(2, 1) and len(ly.blobs) > 1:
                    p["bias"] = ly.blobs[1].reshape(-1)
            elif t == "Pooling":
                pp = ly.params.get(103, Fields(b""))
                kh = pp.int(5) or pp.int(2, 1)
                kw = pp.int(6) or pp.int(2, 1)
                sh = pp.int(7) or pp.int(3, 1)
                sw = pp.int(8) or pp.int(3, 1)
                ph = pp.int(9) or pp.int(4, 0)
                pw = pp.int(10) or pp.int(4, 0)
                if pp.int(1, 0) == 0:
                    mod = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph).ceil()
                else:
                    mod = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph,
                                                   ceil_mode=True)
            elif t == "ReLU":
                mod = nn.ReLU()
            elif t == "TanH":
                mod = nn.Tanh()
            elif t == "Sigmoid":
                mod = nn.Sigmoid()
            elif t in ("Softmax", "SoftmaxWithLoss"):
                mod = nn.SoftMax()
            elif t == "Dropout":
                ratio = ly.params.get(108, Fields(b"")).float(1, 0.5)
                mod = nn.Dropout(ratio)
            elif t == "LRN":
                lp = ly.params.get(118, Fields(b""))
                mod = nn.SpatialCrossMapLRN(lp.int(1, 5), lp.float(2, 1.0),
                                            lp.float(3, 0.75),
                                            lp.float(5, 1.0))
            elif t == "Flatten":
                mod = nn.InferReshape((0, -1))
            elif t == "Concat":
                mod = nn.JoinTable(-1)
            elif t == "Eltwise":
                mod = nn.CAddTable()
            else:
                logger.warning("caffe layer type %s (%s) unsupported; "
                               "treating as identity", t, ly.name)
                mod = nn.Identity()

            bottoms = [get_bottom(b) for b in ly.bottoms]
            if len(bottoms) == 1:
                node = mod(bottoms[0])
            else:
                node = mod(bottoms)
            for top in ly.tops:
                tensors[top] = node
            modules[ly.name] = mod
            ordered.append(ly.name)
            if p is not None:
                params[ly.name] = p

        # output = top of the last layer
        last_top = tensors[self.layers[-1].tops[0]] if self.layers else None
        graph = Graph(inputs if len(inputs) > 1 else inputs[0], last_top)
        import jax
        init_params, state = graph.init(jax.random.key(0))
        # graph params are keyed positionally; map by module identity
        init_params = self._copy_params(graph, init_params, modules, params)
        graph.attach(init_params, state)
        return graph, init_params

    @staticmethod
    def _copy_params(graph, init_params, modules, params):
        """Overwrite initialized leaves with loaded blobs
        (reference: CaffeLoader.copyParameters — match by name, fail loud
        unless the user opts out)."""
        name_by_module = {id(m): n for n, m in modules.items()}
        for i, m in enumerate(graph.modules):
            lname = name_by_module.get(id(m))
            if lname and lname in params:
                loaded = params[lname]
                tgt = init_params[i]
                for k, v in loaded.items():
                    want = np.asarray(tgt[k]).shape
                    if v.shape != want:
                        raise ValueError(
                            f"caffe layer {lname} param {k}: shape "
                            f"{v.shape} vs model {want}")
                    tgt[k] = v.astype(np.asarray(tgt[k]).dtype)
        return init_params


def load_caffe(path: str):
    """(reference: Module.loadCaffe, nn/Module.scala:50)."""
    return CaffeLoader(path).build()


class CaffePersister:
    """Write a Sequential/Graph of supported layers back to a binary
    NetParameter (reference: utils/caffe/CaffePersister.scala)."""

    @staticmethod
    def _blob(arr: np.ndarray) -> bytes:
        shape_msg = b"".join(pbwire.field_varint(1, int(d))
                             for d in arr.shape)
        return (pbwire.field_bytes(7, shape_msg) +
                pbwire.field_packed_floats(5, arr.ravel()))

    @classmethod
    def save(cls, model, params, path: str, net_name: str = "bigdl_tpu"):
        from .. import nn

        chunks = [pbwire.field_string(1, net_name)]
        flat = cls._flatten(model, params)
        prev_top = "data"
        for i, (mod, p) in enumerate(flat):
            name = f"{type(mod).__name__.lower()}_{i}"
            body = pbwire.field_string(1, name)
            bottoms = [prev_top]
            top = name
            blobs = []
            if isinstance(mod, nn.SpatialConvolution):
                type_s = "Convolution"
                w = np.transpose(np.asarray(p["weight"], np.float32),
                                 (3, 2, 0, 1))
                blobs.append(w)
                if "bias" in p:
                    blobs.append(np.asarray(p["bias"], np.float32))
                kh, kw = mod.kernel
                sh, sw = mod.stride
                ph, pw = mod.pad
                if ph == -1 or pw == -1:
                    # SAME sentinel: caffe has only explicit pads; exact
                    # only for stride-1 odd kernels
                    if (sh, sw) == (1, 1) and kh % 2 == 1 and kw % 2 == 1:
                        ph, pw = kh // 2, kw // 2
                    else:
                        raise ValueError(
                            "CaffePersister: SAME padding (pad=-1) with "
                            f"stride {mod.stride} kernel {mod.kernel} has "
                            "no exact caffe equivalent")
                conv = (pbwire.field_varint(1, mod.n_output_plane) +
                        pbwire.field_varint(2, int("bias" in p)) +
                        pbwire.field_varint(5, mod.n_group) +
                        pbwire.field_varint(9, ph) +
                        pbwire.field_varint(10, pw) +
                        pbwire.field_varint(11, kh) +
                        pbwire.field_varint(12, kw) +
                        pbwire.field_varint(13, sh) +
                        pbwire.field_varint(14, sw))
                body += pbwire.field_bytes(106, conv)
            elif isinstance(mod, nn.Linear):
                type_s = "InnerProduct"
                blobs.append(np.asarray(p["weight"], np.float32))
                if "bias" in p:
                    blobs.append(np.asarray(p["bias"], np.float32))
                body += pbwire.field_bytes(
                    117, pbwire.field_varint(1, mod.output_size) +
                    pbwire.field_varint(2, int("bias" in p)))
            elif isinstance(mod, nn.SpatialMaxPooling) or \
                    isinstance(mod, nn.SpatialAveragePooling):
                type_s = "Pooling"
                is_max = isinstance(mod, nn.SpatialMaxPooling)
                kh, kw = mod.kernel
                sh, sw = mod.stride
                ph, pw = mod.pad
                pool = (pbwire.field_varint(1, 0 if is_max else 1) +
                        pbwire.field_varint(5, kh) +
                        pbwire.field_varint(6, kw) +
                        pbwire.field_varint(7, sh) +
                        pbwire.field_varint(8, sw) +
                        pbwire.field_varint(9, ph) +
                        pbwire.field_varint(10, pw))
                body += pbwire.field_bytes(103, pool)
            elif isinstance(mod, nn.ReLU):
                type_s = "ReLU"
            elif isinstance(mod, nn.Tanh):
                type_s = "TanH"
            elif isinstance(mod, nn.Sigmoid):
                type_s = "Sigmoid"
            elif isinstance(mod, (nn.SoftMax, nn.LogSoftMax)):
                type_s = "Softmax"
            elif isinstance(mod, nn.Dropout):
                type_s = "Dropout"
                body += pbwire.field_bytes(
                    108, pbwire.field_float(1, mod.p))
            elif isinstance(mod, nn.SpatialCrossMapLRN):
                type_s = "LRN"
                lrn = (pbwire.field_varint(1, mod.size) +
                       pbwire.field_float(2, mod.alpha) +
                       pbwire.field_float(3, mod.beta) +
                       pbwire.field_float(5, mod.k))
                body += pbwire.field_bytes(118, lrn)
            elif isinstance(mod, (nn.Reshape, nn.InferReshape, nn.View)):
                type_s = "Flatten"
            else:
                raise ValueError(
                    f"CaffePersister: unsupported layer {type(mod).__name__}"
                    " (reference also persisted a fixed layer set)")
            body += pbwire.field_string(2, type_s)
            for b in bottoms:
                body += pbwire.field_string(3, b)
            body += pbwire.field_string(4, top)
            for b in blobs:
                body += pbwire.field_bytes(7, cls._blob(b))
            chunks.append(pbwire.field_bytes(100, body))
            prev_top = top
        with open(path, "wb") as f:
            f.write(b"".join(chunks))
        return path

    @staticmethod
    def _flatten(model, params):
        from ..nn.containers import Sequential
        from ..nn.graph import Graph

        if isinstance(model, (Sequential, Graph)):
            mods = model.modules
            from ..nn.graph import _InputModule
            return [(m, params[i]) for i, m in enumerate(mods)
                    if not isinstance(m, _InputModule)]
        return [(model, params)]


def save_caffe(model, params, path: str):
    """(reference: Module.saveCaffe via CaffePersister)."""
    return CaffePersister.save(model, params, path)
