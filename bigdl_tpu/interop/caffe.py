"""Caffe model interop: load .caffemodel files into bigdl_tpu modules and
persist modules back out.

Reference: utils/caffe/CaffeLoader.scala:56 (loadBinary :93, copyParameters
:239, layer mapping in Converter/LayerConverter/V1LayerConverter.scala) and
utils/caffe/CaffePersister.scala, all driven by the protoc-generated
caffe/Caffe.java.  Rebuild: the generic wire codec (utils/pbwire.py) plus
the public caffe.proto field numbers below; layers map to TPU-native nn
modules and weights are transposed into our NHWC/HWIO layouts.

Layout notes (the cross-framework traps):
  * conv blobs are OIHW -> our HWIO; activations NCHW -> our NHWC.
  * InnerProduct weights flatten the preceding conv feature map in
    (C, H, W) order; our flatten is NHWC, i.e. (H, W, C) order — the
    loader permutes FC weight columns at any spatial->InnerProduct
    boundary (and the persister permutes back), so genuine pretrained
    caffemodels predict correctly (reference: LayerConverter's fcbackend
    handling; round-1 advisor finding).
  * BatchNorm stores (mean, var, scale_factor) with a separate Scale
    layer for gamma/beta — the loader folds an adjacent Scale into one
    affine SpatialBatchNormalization, like LayerConverter.scala's
    BatchNorm+Scale fusion.

caffe.proto field numbers used (public schema):
    NetParameter: name=1, input=3, layers(V1)=2, layer(V2)=100
    LayerParameter: name=1, type=2 (string), bottom=3, top=4, blobs=7,
        concat_param=104, pooling_param=103, convolution_param=106,
        dropout_param=108, eltwise_param=110, inner_product_param=117,
        lrn_param=118, power_param=122, reshape_param=133,
        batch_norm_param=139, scale_param=142
    V1LayerParameter: bottom=2, top=3, name=4, type=5 (enum), blobs=6,
        concat_param=9, convolution_param=10, dropout_param=12,
        inner_product_param=17, lrn_param=18, pooling_param=19,
        power_param=21, eltwise_param=24
    BlobProto: shape=7 (BlobShape.dim=1), data=5 (packed float),
        num=1 channels=2 height=3 width=4 (legacy 4-D)
    ConvolutionParameter: num_output=1 bias_term=2 pad=3 kernel_size=4
        group=5 stride=6 pad_h=9 pad_w=10 kernel_h=11 kernel_w=12
        stride_h=13 stride_w=14 dilation=18
    PoolingParameter: pool=1 (0 MAX, 1 AVE) kernel_size=2 stride=3 pad=4
        kernel_h=5 kernel_w=6 stride_h=7 stride_w=8 pad_h=9 pad_w=10
        global_pooling=12 round_mode=13 (0 CEIL, 1 FLOOR)
    InnerProductParameter: num_output=1 bias_term=2
    LRNParameter: local_size=1 alpha=2 beta=3 norm_region=4 k=5
    DropoutParameter: dropout_ratio=1
    ConcatParameter: concat_dim=1 (legacy) axis=2
    EltwiseParameter: operation=1 (0 PROD, 1 SUM, 2 MAX) coeff=2
    PowerParameter: power=1 scale=2 shift=3
    ReshapeParameter: shape=1 (BlobShape)
    BatchNormParameter: use_global_stats=1 moving_average_fraction=2 eps=3
    ScaleParameter: axis=1 num_axes=2 bias_term=4
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import pbwire
from ..utils.pbwire import Fields

logger = logging.getLogger(__name__)

__all__ = ["CaffeLoader", "CaffePersister", "load_caffe", "save_caffe"]

# V1LayerParameter.LayerType enum -> V2 string type (public caffe.proto)
_V1_TYPES = {
    2: "BNLL", 3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout",
    8: "Flatten", 14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU",
    19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss", 22: "Split",
    23: "TanH", 25: "Eltwise", 26: "Power", 33: "Slice", 35: "AbsVal",
    36: "Silence", 38: "Exp", 39: "Deconvolution",
}

# caffe NCHW axis -> our NHWC axis
_NCHW_TO_NHWC = {0: 0, 1: -1, 2: 1, 3: 2}
_NHWC_TO_NCHW = {0: 0, -1: 1, 3: 1, 1: 2, 2: 3}


class _Layer:
    """Parsed layer description, schema-neutral between V1 and V2."""

    def __init__(self, name: str, type_: str, bottoms: List[str],
                 tops: List[str], blobs: List[np.ndarray],
                 blob_shapes: List[Tuple[int, ...]], params: Dict[int, Fields]):
        self.name = name
        self.type = type_
        self.bottoms = bottoms
        self.tops = tops
        self.blobs = blobs
        self.blob_shapes = blob_shapes
        self.params = params


def _parse_blob(f: Fields) -> Tuple[np.ndarray, Tuple[int, ...]]:
    data = np.array(f.floats(5), dtype=np.float32)
    if f.has(7):
        shape = tuple(f.sub(7).ints(1))
    else:  # legacy num/channels/height/width
        shape = tuple(f.int(i, 1) for i in (1, 2, 3, 4))
        while len(shape) > 1 and shape[0] == 1:
            shape = shape[1:]
    if data.size and int(np.prod(shape)) == data.size:
        data = data.reshape(shape)
    return data, shape


# LayerParameter param-message fields the loader reads, keyed by the V2
# field number (V1 layers are remapped onto the same keys).
_V2_PARAM_FIELDS = (103, 104, 106, 108, 110, 117, 118, 122, 133, 139, 142)
_V1_PARAM_MAP = {103: 19, 104: 9, 106: 10, 108: 12, 110: 24, 117: 17,
                 118: 18, 122: 21}


def _parse_layers(buf: bytes) -> Tuple[str, List[_Layer]]:
    net = Fields(buf)
    layers: List[_Layer] = []
    for lf in net.subs(100):  # V2
        blobs = [_parse_blob(b) for b in lf.subs(7)]
        layers.append(_Layer(
            lf.str(1), lf.str(2), lf.strs(3), lf.strs(4),
            [b for b, _ in blobs], [s for _, s in blobs],
            {n: lf.sub(n) for n in _V2_PARAM_FIELDS if lf.has(n)}))
    for lf in net.subs(2):  # V1
        blobs = [_parse_blob(b) for b in lf.subs(6)]
        layers.append(_Layer(
            lf.str(4), _V1_TYPES.get(lf.int(5), f"V1_{lf.int(5)}"),
            lf.strs(2), lf.strs(3),
            [b for b, _ in blobs], [s for _, s in blobs],
            {v2: lf.sub(v1) for v2, v1 in _V1_PARAM_MAP.items()}))
    return net.str(1), layers


def _conv_args(p: Fields):
    kh = p.int(11) or (p.ints(4)[0] if p.ints(4) else 1)
    kw = p.int(12) or (p.ints(4)[-1] if p.ints(4) else 1)
    sh = p.int(13) or (p.ints(6)[0] if p.ints(6) else 1)
    sw = p.int(14) or (p.ints(6)[-1] if p.ints(6) else 1)
    ph = p.int(9) or (p.ints(3)[0] if p.ints(3) else 0)
    pw = p.int(10) or (p.ints(3)[-1] if p.ints(3) else 0)
    return kh, kw, sh, sw, ph, pw, p.int(1), p.int(5, 1), p.int(2, 1)


def _fc_cols_chw_to_hwc(w: np.ndarray, channels: int) -> np.ndarray:
    """Permute FC weight columns from caffe's (C,H,W) flatten order to our
    NHWC (H,W,C) order.  Only C and H*W matter: column c*HW + hw moves to
    hw*C + c."""
    out, n_in = w.shape
    hw = n_in // channels
    return (w.reshape(out, channels, hw).transpose(0, 2, 1)
            .reshape(out, n_in))


def _fc_cols_hwc_to_chw(w: np.ndarray, channels: int) -> np.ndarray:
    out, n_in = w.shape
    hw = n_in // channels
    return (w.reshape(out, hw, channels).transpose(0, 2, 1)
            .reshape(out, n_in))


class CaffeLoader:
    """Build a bigdl_tpu Graph from a binary .caffemodel
    (reference: CaffeLoader.loadBinary + Converter.toBigDL).

    Unsupported layer types raise by default (round-1 advisor: silent
    Identity mapping makes imports "succeed" and predict garbage); pass
    ``permissive=True`` to map them to Identity with a warning."""

    def __init__(self, path: str, permissive: bool = False):
        with open(path, "rb") as f:
            self.net_name, self.layers = _parse_layers(f.read())
        self.permissive = permissive

    def build(self):
        """Returns (module, params_tree): a Graph wired by bottom/top names
        with weights copied in (conv blobs OIHW -> HWIO, NCHW -> NHWC)."""
        from .. import nn
        from ..nn.graph import Graph, Input

        tensors: Dict[str, object] = {}
        inputs = []
        params: Dict[str, Dict] = {}
        modules: Dict[str, object] = {}
        channels: Dict[str, Optional[int]] = {}  # tensor -> NHWC channels
        spatial: Dict[str, bool] = {}            # tensor -> is 4-D NHWC
        flat_ch: Dict[str, Optional[int]] = {}   # flattened-from channels
        consumed = set()  # layer indices folded into a predecessor

        def get_bottom(name):
            if name not in tensors:
                node = Input()
                tensors[name] = node
                inputs.append(node)
            return tensors[name]

        for i, ly in enumerate(self.layers):
            if i in consumed:
                continue
            t = ly.type
            mod = None
            p: Optional[Dict] = None
            bottom0 = ly.bottoms[0] if ly.bottoms else None
            in_ch = channels.get(bottom0)
            out_ch = in_ch
            out_spatial = spatial.get(bottom0, False)
            if t in ("Data", "Input", "Split", "Silence"):
                # data layers introduce tensors; assume image data is spatial
                for top in ly.tops:
                    spatial[top] = True
                continue
            elif t == "Convolution":
                kh, kw, sh, sw, ph, pw, n_out, group, bias = _conv_args(
                    ly.params.get(106, Fields(b"")))
                w = ly.blobs[0]  # (out, in/g, kh, kw)
                n_in = w.shape[1] * group
                mod = nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh,
                                            pw, ph, group,
                                            with_bias=bool(bias))
                p = {"weight": np.transpose(w, (2, 3, 1, 0))}
                if bias and len(ly.blobs) > 1:
                    p["bias"] = ly.blobs[1].reshape(-1)
                out_ch, out_spatial = n_out, True
            elif t == "Deconvolution":
                kh, kw, sh, sw, ph, pw, n_out, group, bias = _conv_args(
                    ly.params.get(106, Fields(b"")))
                if group != 1:
                    raise ValueError("caffe Deconvolution with group > 1 "
                                     "is not supported")
                w = ly.blobs[0]  # (in, out, kh, kw)
                mod = nn.SpatialFullConvolution(
                    w.shape[0], n_out, kw, kh, sw, sh, pw, ph,
                    no_bias=not bias)
                p = {"weight": np.transpose(w, (2, 3, 0, 1))}
                if bias and len(ly.blobs) > 1:
                    p["bias"] = ly.blobs[1].reshape(-1)
                out_ch, out_spatial = n_out, True
            elif t == "BatchNorm":
                bp = ly.params.get(139, Fields(b""))
                eps = bp.float(3, 1e-5)
                n_c = int(ly.blob_shapes[0][0])
                sf = float(ly.blobs[2].reshape(-1)[0]) if len(ly.blobs) > 2 \
                    else 1.0
                sf = sf if sf != 0 else 1.0
                mean = ly.blobs[0].reshape(-1) / sf
                var = ly.blobs[1].reshape(-1) / sf
                # fold an adjacent Scale (gamma/beta) into affine BN, like
                # LayerConverter.scala's BatchNorm+Scale pairing
                nxt = (self.layers[i + 1]
                       if i + 1 < len(self.layers) else None)
                fold = (nxt is not None and nxt.type == "Scale"
                        and nxt.bottoms and nxt.bottoms[0] == ly.tops[0])
                mod = nn.SpatialBatchNormalization(n_c, eps=eps,
                                                   affine=fold)
                p = {"__state__": {"running_mean": mean,
                                   "running_var": var}}
                if fold:
                    p["weight"] = nxt.blobs[0].reshape(-1)
                    p["bias"] = (nxt.blobs[1].reshape(-1)
                                 if len(nxt.blobs) > 1
                                 else np.zeros(n_c, np.float32))
                    consumed.add(i + 1)
                    ly = _Layer(ly.name, ly.type, ly.bottoms, nxt.tops,
                                ly.blobs, ly.blob_shapes, ly.params)
                out_ch = n_c
            elif t == "Scale":
                sp = ly.params.get(142, Fields(b""))
                w = ly.blobs[0].reshape(-1)
                mod = nn.Scale((w.shape[0],))
                bias = (ly.blobs[1].reshape(-1)
                        if sp.int(4, 0) and len(ly.blobs) > 1
                        else np.zeros_like(w))
                p = {"weight": w, "bias": bias}
                out_ch = w.shape[0]
            elif t == "InnerProduct":
                ip = ly.params.get(117, Fields(b""))
                w = ly.blobs[0]
                w = w.reshape(ip.int(1), -1)
                c = in_ch if out_spatial else flat_ch.get(bottom0)
                if c and w.shape[1] % c == 0:
                    w = _fc_cols_chw_to_hwc(w, c)
                linear = nn.Linear(w.shape[1], w.shape[0],
                                   with_bias=bool(ip.int(2, 1)))
                if out_spatial:
                    # caffe InnerProduct flattens its 4-D bottom implicitly
                    mod = (nn.Sequential()
                           .add(nn.InferReshape((0, -1))).add(linear))
                    p = {"__child__": 1, "weight": w}
                else:
                    mod = linear
                    p = {"weight": w}
                if ip.int(2, 1) and len(ly.blobs) > 1:
                    p["bias"] = ly.blobs[1].reshape(-1)
                out_ch, out_spatial = w.shape[0], False
            elif t == "Pooling":
                pp = ly.params.get(103, Fields(b""))
                kh = pp.int(5) or pp.int(2, 1)
                kw = pp.int(6) or pp.int(2, 1)
                sh = pp.int(7) or pp.int(3, 1)
                sw = pp.int(8) or pp.int(3, 1)
                ph = pp.int(9) or pp.int(4, 0)
                pw = pp.int(10) or pp.int(4, 0)
                ceil = pp.int(13, 0) == 0  # round_mode: 0 CEIL (default)
                is_max = pp.int(1, 0) == 0
                if pp.int(12, 0):  # global_pooling
                    if is_max:
                        raise ValueError("global MAX pooling unsupported")
                    mod = nn.SpatialAveragePooling(1, 1,
                                                   global_pooling=True)
                elif is_max:
                    mod = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph)
                    if ceil:
                        mod.ceil()
                else:
                    mod = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph,
                                                   ceil_mode=ceil)
            elif t == "ReLU":
                mod = nn.ReLU()
            elif t == "TanH":
                mod = nn.Tanh()
            elif t == "Sigmoid":
                mod = nn.Sigmoid()
            elif t == "AbsVal":
                mod = nn.Abs()
            elif t == "BNLL":
                mod = nn.SoftPlus()
            elif t == "Exp":
                mod = nn.Exp()
            elif t == "Log":
                mod = nn.Log()
            elif t == "Power":
                pw_ = ly.params.get(122, Fields(b""))
                mod = nn.Power(pw_.float(1, 1.0), pw_.float(2, 1.0),
                               pw_.float(3, 0.0))
            elif t in ("Softmax", "SoftmaxWithLoss"):
                mod = nn.SoftMax()
            elif t == "Dropout":
                ratio = ly.params.get(108, Fields(b"")).float(1, 0.5)
                mod = nn.Dropout(ratio)
            elif t == "LRN":
                lp = ly.params.get(118, Fields(b""))
                mod = nn.SpatialCrossMapLRN(lp.int(1, 5), lp.float(2, 1.0),
                                            lp.float(3, 0.75),
                                            lp.float(5, 1.0))
            elif t == "Flatten":
                mod = nn.InferReshape((0, -1))
                for top in ly.tops:
                    flat_ch[top] = in_ch
                out_spatial = False
            elif t == "Reshape":
                dims = tuple(ly.params.get(133, Fields(b""))
                             .sub(1).ints(1)) or (0, -1)
                if len(dims) == 4:  # caffe (0,C,H,W) -> our NHWC
                    dims = (dims[0], dims[2], dims[3], dims[1])
                    out_spatial = True
                    out_ch = dims[3]
                else:
                    if out_spatial:
                        for top in ly.tops:
                            flat_ch[top] = in_ch
                    out_spatial = False
                mod = nn.InferReshape(dims)
            elif t == "Concat":
                cp = ly.params.get(104, Fields(b""))
                axis = cp.int(2, 1) if cp.has(2) else cp.int(1, 1)
                if axis < 0:  # caffe negative axes count from rank (NCHW 4-D)
                    axis += 4
                if axis not in _NCHW_TO_NHWC:
                    raise ValueError(f"Concat axis {axis} unsupported")
                mod = nn.JoinTable(_NCHW_TO_NHWC[axis])
                if axis == 1:
                    chs = [channels.get(b) for b in ly.bottoms]
                    out_ch = (sum(chs) if all(c is not None for c in chs)
                              else None)
            elif t == "Eltwise":
                ep = ly.params.get(110, Fields(b""))
                coeffs = ep.floats(2)
                if coeffs and any(c != 1.0 for c in coeffs):
                    raise ValueError("Eltwise with non-unit coefficients "
                                     "is not supported")
                op = ep.int(1, 1)
                mod = {0: nn.CMulTable, 1: nn.CAddTable,
                       2: nn.CMaxTable}[op]()
            else:
                if not self.permissive:
                    raise ValueError(
                        f"caffe layer type {t!r} ({ly.name}) unsupported; "
                        "pass permissive=True to map it to Identity")
                logger.warning("caffe layer type %s (%s) unsupported; "
                               "treating as identity", t, ly.name)
                mod = nn.Identity()

            bottoms = [get_bottom(b) for b in ly.bottoms]
            if len(bottoms) == 1:
                node = mod(bottoms[0])
            else:
                node = mod(bottoms)
            for top in ly.tops:
                tensors[top] = node
                channels[top] = out_ch
                spatial[top] = out_spatial
            modules[ly.name] = mod
            if p is not None:
                params[ly.name] = p

        last = next(ly for ly in reversed(self.layers)
                    if ly.tops and ly.tops[0] in tensors)
        graph = Graph(inputs if len(inputs) > 1 else inputs[0],
                      tensors[last.tops[0]])
        import jax
        init_params, init_state = graph.init(jax.random.key(0))
        self._copy_params(graph, init_params, init_state, modules, params)
        graph.attach(init_params, init_state)
        return graph, init_params

    @staticmethod
    def _copy_params(graph, init_params, init_state, modules, params):
        """Overwrite initialized leaves with loaded blobs
        (reference: CaffeLoader.copyParameters — match by name, fail loud
        on shape mismatch).  "__state__" entries target the module's state
        (BN running stats); "__child__" redirects into a child of a
        wrapper Sequential."""
        name_by_module = {id(m): n for n, m in modules.items()}
        for i, m in enumerate(graph.modules):
            lname = name_by_module.get(id(m))
            if not lname or lname not in params:
                continue
            loaded = dict(params[lname])
            st = loaded.pop("__state__", None)
            child = loaded.pop("__child__", None)
            tgt = init_params[i] if child is None else init_params[i][child]
            for k, v in loaded.items():
                want = np.asarray(tgt[k]).shape
                if v.shape != want:
                    raise ValueError(
                        f"caffe layer {lname} param {k}: shape "
                        f"{v.shape} vs model {want}")
                tgt[k] = v.astype(np.asarray(tgt[k]).dtype)
            if st:
                stgt = init_state[i] if child is None else init_state[i][child]
                for k, v in st.items():
                    want = np.asarray(stgt[k]).shape
                    if v.shape != want:
                        raise ValueError(
                            f"caffe layer {lname} state {k}: shape "
                            f"{v.shape} vs model {want}")
                    stgt[k] = v.astype(np.asarray(stgt[k]).dtype)
        return init_params


def load_caffe(path: str, permissive: bool = False):
    """(reference: Module.loadCaffe, nn/Module.scala:50)."""
    return CaffeLoader(path, permissive=permissive).build()


class _EmitCtx:
    """Accumulates NetParameter layer messages + per-tensor layout facts."""

    def __init__(self):
        self.chunks: List[bytes] = []
        self.n = 0
        self.ch: Optional[int] = None      # channels of the current tensor
        self.spatial = True                # current tensor is 4-D NHWC
        self.flat_ch: Optional[int] = None  # channels before the flatten
        self.topology: List[tuple] = []    # (name, type, bottoms, top)

    def layer(self, type_s: str, bottoms, blobs=(), extra: bytes = b"",
              top: str = None) -> str:
        name = f"{type_s.lower()}_{self.n}"
        self.n += 1
        top = top or name
        self.topology.append((name, type_s, list(bottoms), top))
        body = (pbwire.field_string(1, name) +
                pbwire.field_string(2, type_s))
        for b in bottoms:
            body += pbwire.field_string(3, b)
        body += pbwire.field_string(4, top)
        body += extra
        for b in blobs:
            body += pbwire.field_bytes(7, CaffePersister._blob(b))
        self.chunks.append(pbwire.field_bytes(100, body))
        return top


class CaffePersister:
    """Write a model (Sequential / Graph-free composite of supported layers,
    including ConcatTable+Eltwise residual branches and Concat towers) back
    to a binary NetParameter (reference: utils/caffe/CaffePersister.scala)."""

    @staticmethod
    def _blob(arr: np.ndarray) -> bytes:
        shape_msg = b"".join(pbwire.field_varint(1, int(d))
                             for d in arr.shape)
        return (pbwire.field_bytes(7, shape_msg) +
                pbwire.field_packed_floats(5, arr.ravel()))

    @classmethod
    def save(cls, model, params, path: str, net_name: str = "bigdl_tpu",
             state=None, prototxt_path: str = None):
        """Binary NetParameter to `path`; with `prototxt_path`, also a text
        net definition (layer name/type/bottom/top topology, weight-free) —
        the two-file contract of the reference's
        CaffePersister.saveToCaffe(prototxtPath, modelPath)."""
        if state is None:
            state = getattr(model, "state", None)
        ctx = _EmitCtx()
        cls._emit(model, params, state, "data", ctx)
        with open(path, "wb") as f:
            f.write(b"".join([pbwire.field_string(1, net_name)] + ctx.chunks))
        if prototxt_path is not None:
            lines = [f'name: "{net_name}"']
            for name, type_s, bottoms, top in ctx.topology:
                lines.append("layer {")
                lines.append(f'  name: "{name}"')
                lines.append(f'  type: "{type_s}"')
                for b in bottoms:
                    lines.append(f'  bottom: "{b}"')
                lines.append(f'  top: "{top}"')
                lines.append("}")
            with open(prototxt_path, "w") as f:
                f.write("\n".join(lines) + "\n")
        return path

    @staticmethod
    def _resolve_same_pad(mod, kind: str):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        if ph == -1 or pw == -1:
            # SAME sentinel: caffe has only explicit pads; exact only for
            # stride-1 odd kernels (conv and pooling alike)
            if (sh, sw) == (1, 1) and kh % 2 == 1 and kw % 2 == 1:
                ph, pw = kh // 2, kw // 2
            else:
                raise ValueError(
                    f"CaffePersister: SAME padding (pad=-1) on {kind} with "
                    f"stride {mod.stride} kernel {mod.kernel} has no exact "
                    "caffe equivalent")
        return kh, kw, sh, sw, ph, pw

    @classmethod
    def _emit(cls, mod, p, s, bottom, ctx: _EmitCtx):
        """Emit `mod` taking tensor `bottom` (a top name, or a list of top
        names after a ConcatTable); returns the new top."""
        from .. import nn
        from ..nn.containers import (ConcatTable, Concat as ConcatC,
                                     Identity, Sequential)
        from ..nn.graph import Graph, _InputModule

        def sub_s(i):
            return s[i] if s is not None else None

        if isinstance(mod, Graph):
            # walk exec_order, naming tensors per node so load->save
            # round-trips work (the loader returns a Graph)
            if len(mod.input_nodes) != 1:
                raise ValueError("CaffePersister: multi-input Graph "
                                 "persistence is unsupported")
            names = {id(mod.input_nodes[0]): bottom}
            layouts = {id(mod.input_nodes[0]):
                       (ctx.ch, ctx.spatial, ctx.flat_ch)}
            for i, n in enumerate(mod.exec_order):
                if id(n) in names:
                    continue
                preds = n.prev_nodes
                bots = [names[id(pn)] for pn in preds]
                ctx.ch, ctx.spatial, ctx.flat_ch = layouts[id(preds[0])]
                top = cls._emit(n.element, p[i], sub_s(i),
                                bots[0] if len(bots) == 1 else bots, ctx)
                names[id(n)] = top
                layouts[id(n)] = (ctx.ch, ctx.spatial, ctx.flat_ch)
            return names[id(mod.output_nodes[0])]
        if isinstance(mod, _InputModule):
            return bottom
        if isinstance(mod, Sequential):
            top = bottom
            for i, m in enumerate(mod.modules):
                top = cls._emit(m, p[i], sub_s(i), top, ctx)
            return top
        if isinstance(mod, ConcatTable):
            tops, states = [], []
            ch0, sp0, fc0 = ctx.ch, ctx.spatial, ctx.flat_ch
            for i, m in enumerate(mod.modules):
                ctx.ch, ctx.spatial, ctx.flat_ch = ch0, sp0, fc0
                tops.append(cls._emit(m, p[i], sub_s(i), bottom, ctx))
                states.append((ctx.ch, ctx.spatial, ctx.flat_ch))
            ctx.ch, ctx.spatial, ctx.flat_ch = states[0]
            return tops
        if isinstance(mod, ConcatC):
            tops = []
            chs = []
            ch0, sp0, fc0 = ctx.ch, ctx.spatial, ctx.flat_ch
            for i, m in enumerate(mod.modules):
                ctx.ch, ctx.spatial, ctx.flat_ch = ch0, sp0, fc0
                tops.append(cls._emit(m, p[i], sub_s(i), bottom, ctx))
                chs.append(ctx.ch)
            axis = _NHWC_TO_NCHW.get(mod.dimension)
            if axis is None:
                raise ValueError(f"Concat along axis {mod.dimension} has no "
                                 "caffe NCHW equivalent")
            ctx.ch = (sum(chs) if axis == 1 and
                      all(c is not None for c in chs) else None)
            ctx.spatial = sp0
            extra = pbwire.field_bytes(104, pbwire.field_varint(2, axis))
            return ctx.layer("Concat", tops, extra=extra)
        if isinstance(mod, Identity):
            return bottom
        if isinstance(mod, (nn.CAddTable, nn.CMulTable, nn.CMaxTable)):
            if not isinstance(bottom, list):
                raise ValueError("Eltwise layer needs a list input "
                                 "(ConcatTable upstream)")
            op = {nn.CMulTable: 0, nn.CAddTable: 1, nn.CMaxTable: 2}[
                type(mod)]
            extra = pbwire.field_bytes(110, pbwire.field_varint(1, op))
            return ctx.layer("Eltwise", bottom, extra=extra)
        if isinstance(mod, nn.JoinTable):
            if not isinstance(bottom, list):
                raise ValueError("JoinTable needs a list input")
            axis = _NHWC_TO_NCHW.get(mod.dimension)
            if axis is None:
                raise ValueError(f"JoinTable axis {mod.dimension} has no "
                                 "caffe NCHW equivalent")
            extra = pbwire.field_bytes(104, pbwire.field_varint(2, axis))
            return ctx.layer("Concat", bottom, extra=extra)

        if isinstance(bottom, list):
            raise ValueError(
                f"CaffePersister: {type(mod).__name__} cannot take the "
                "multi-tensor output of a ConcatTable")

        if isinstance(mod, nn.SpatialConvolution):
            w = np.transpose(np.asarray(p["weight"], np.float32),
                             (3, 2, 0, 1))
            blobs = [w]
            if "bias" in p:
                blobs.append(np.asarray(p["bias"], np.float32))
            kh, kw, sh, sw, ph, pw = cls._resolve_same_pad(mod, "conv")
            conv = (pbwire.field_varint(1, mod.n_output_plane) +
                    pbwire.field_varint(2, int("bias" in p)) +
                    pbwire.field_varint(5, mod.n_group) +
                    pbwire.field_varint(9, ph) +
                    pbwire.field_varint(10, pw) +
                    pbwire.field_varint(11, kh) +
                    pbwire.field_varint(12, kw) +
                    pbwire.field_varint(13, sh) +
                    pbwire.field_varint(14, sw))
            ctx.ch, ctx.spatial = mod.n_output_plane, True
            return ctx.layer("Convolution", [bottom], blobs,
                             pbwire.field_bytes(106, conv))
        if isinstance(mod, nn.SpatialFullConvolution):
            if mod.n_group != 1:
                raise ValueError("Deconvolution with group > 1 unsupported")
            # ours (kh, kw, in, out) -> caffe (in, out, kh, kw)
            w = np.transpose(np.asarray(p["weight"], np.float32),
                             (2, 3, 0, 1))
            blobs = [w]
            if "bias" in p:
                blobs.append(np.asarray(p["bias"], np.float32))
            kh, kw, sh, sw, ph, pw = cls._resolve_same_pad(mod, "deconv")
            conv = (pbwire.field_varint(1, mod.n_output_plane) +
                    pbwire.field_varint(2, int("bias" in p)) +
                    pbwire.field_varint(9, ph) +
                    pbwire.field_varint(10, pw) +
                    pbwire.field_varint(11, kh) +
                    pbwire.field_varint(12, kw) +
                    pbwire.field_varint(13, sh) +
                    pbwire.field_varint(14, sw))
            ctx.ch, ctx.spatial = mod.n_output_plane, True
            return ctx.layer("Deconvolution", [bottom], blobs,
                             pbwire.field_bytes(106, conv))
        if isinstance(mod, (nn.BatchNormalization,)):
            if s is None:
                raise ValueError(
                    "CaffePersister: BatchNormalization needs running stats"
                    " — pass state= (or save a built model with .state)")
            mean = np.asarray(s["running_mean"], np.float32)
            var = np.asarray(s["running_var"], np.float32)
            bn_extra = pbwire.field_bytes(
                139, pbwire.field_float(3, mod.eps))
            top = ctx.layer("BatchNorm", [bottom],
                            [mean, var, np.ones(1, np.float32)], bn_extra)
            ctx.ch = mod.n_output
            if mod.affine:
                sc_extra = pbwire.field_bytes(
                    142, pbwire.field_varint(4, 1))
                top = ctx.layer("Scale", [top],
                                [np.asarray(p["weight"], np.float32),
                                 np.asarray(p["bias"], np.float32)],
                                sc_extra)
            return top
        if isinstance(mod, nn.Scale):
            if len(mod.size) != 1:
                raise ValueError("caffe Scale persists 1-D (per-channel) "
                                 "sizes only")
            sc_extra = pbwire.field_bytes(142, pbwire.field_varint(4, 1))
            return ctx.layer("Scale", [bottom],
                             [np.asarray(p["weight"], np.float32),
                              np.asarray(p["bias"], np.float32)], sc_extra)
        if isinstance(mod, nn.Linear):
            w = np.asarray(p["weight"], np.float32)
            c = ctx.flat_ch
            if c and w.shape[1] % c == 0:
                # our columns are NHWC-flat (H,W,C); caffe wants (C,H,W)
                w = _fc_cols_hwc_to_chw(w, c)
            blobs = [w]
            if "bias" in p:
                blobs.append(np.asarray(p["bias"], np.float32))
            extra = pbwire.field_bytes(
                117, pbwire.field_varint(1, mod.output_size) +
                pbwire.field_varint(2, int("bias" in p)))
            ctx.ch, ctx.spatial, ctx.flat_ch = mod.output_size, False, None
            return ctx.layer("InnerProduct", [bottom], blobs, extra)
        if isinstance(mod, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            is_max = isinstance(mod, nn.SpatialMaxPooling)
            if getattr(mod, "global_pooling", False):
                pool = (pbwire.field_varint(1, 0 if is_max else 1) +
                        pbwire.field_varint(12, 1))
                return ctx.layer("Pooling", [bottom],
                                 extra=pbwire.field_bytes(103, pool))
            kh, kw, sh, sw, ph, pw = cls._resolve_same_pad(mod, "pooling")
            ceil = getattr(mod, "ceil_mode", False)
            pool = (pbwire.field_varint(1, 0 if is_max else 1) +
                    pbwire.field_varint(5, kh) +
                    pbwire.field_varint(6, kw) +
                    pbwire.field_varint(7, sh) +
                    pbwire.field_varint(8, sw) +
                    pbwire.field_varint(9, ph) +
                    pbwire.field_varint(10, pw) +
                    pbwire.field_varint(13, 0 if ceil else 1))
            return ctx.layer("Pooling", [bottom],
                             extra=pbwire.field_bytes(103, pool))
        if isinstance(mod, nn.ReLU):
            return ctx.layer("ReLU", [bottom])
        if isinstance(mod, nn.Tanh):
            return ctx.layer("TanH", [bottom])
        if isinstance(mod, nn.Sigmoid):
            return ctx.layer("Sigmoid", [bottom])
        if isinstance(mod, nn.Abs):
            return ctx.layer("AbsVal", [bottom])
        if isinstance(mod, nn.SoftPlus):
            return ctx.layer("BNLL", [bottom])
        if isinstance(mod, nn.Exp):
            return ctx.layer("Exp", [bottom])
        if isinstance(mod, nn.Log):
            return ctx.layer("Log", [bottom])
        if isinstance(mod, nn.LogSoftMax):
            # caffe has no LogSoftmax: Softmax followed by a Log layer
            top = ctx.layer("Softmax", [bottom])
            return ctx.layer("Log", [top])
        if isinstance(mod, nn.SoftMax):
            return ctx.layer("Softmax", [bottom])
        if isinstance(mod, nn.Power):
            extra = pbwire.field_bytes(
                122, pbwire.field_float(1, mod.power) +
                pbwire.field_float(2, mod.scale) +
                pbwire.field_float(3, mod.shift))
            return ctx.layer("Power", [bottom], extra=extra)
        if isinstance(mod, nn.MulConstant):
            extra = pbwire.field_bytes(
                122, pbwire.field_float(1, 1.0) +
                pbwire.field_float(2, float(mod.constant)) +
                pbwire.field_float(3, 0.0))
            return ctx.layer("Power", [bottom], extra=extra)
        if isinstance(mod, nn.Dropout):
            extra = pbwire.field_bytes(108, pbwire.field_float(1, mod.p))
            return ctx.layer("Dropout", [bottom], extra=extra)
        if isinstance(mod, nn.SpatialCrossMapLRN):
            lrn = (pbwire.field_varint(1, mod.size) +
                   pbwire.field_float(2, mod.alpha) +
                   pbwire.field_float(3, mod.beta) +
                   pbwire.field_float(5, mod.k))
            return ctx.layer("LRN", [bottom],
                             extra=pbwire.field_bytes(118, lrn))
        if isinstance(mod, (nn.Reshape, nn.InferReshape, nn.View)):
            size = (getattr(mod, "size", None)
                    or getattr(mod, "sizes", None) or ())
            if len(size) == 4 and size[0] == 0:  # (0,H,W,C) batch-preserving
                size = size[1:]
            if len(size) == 3:  # reshape to NHWC spatial -> caffe (0,C,H,W)
                h, w, c = size
                dims = b"".join(pbwire.field_varint(1, int(d))
                                for d in (0, c, h, w))
                extra = pbwire.field_bytes(133, pbwire.field_bytes(1, dims))
                ctx.ch, ctx.spatial = c, True
                return ctx.layer("Reshape", [bottom], extra=extra)
            if ctx.spatial:
                ctx.flat_ch = ctx.ch
            ctx.spatial = False
            return ctx.layer("Flatten", [bottom])
        raise ValueError(
            f"CaffePersister: unsupported layer {type(mod).__name__}"
            " (reference also persisted a fixed layer set)")


def save_caffe(model, params, path: str, state=None,
               prototxt_path: str = None):
    """(reference: Module.saveCaffe via CaffePersister)."""
    return CaffePersister.save(model, params, path, state=state,
                               prototxt_path=prototxt_path)
