"""Model interop: Caffe, TensorFlow, Torch7, and the native format.

Reference: BigDL's `Module.load/loadTorch/loadCaffe/loadTF` entry points
(nn/Module.scala:41-73) over utils/caffe/, utils/tf/, utils/TorchFile.scala.
The native format here is the pickle-based save/load in utils/file_io.py
(the reference's was JVM serialization, utils/File.scala)."""

from .caffe import CaffeLoader, CaffePersister, load_caffe, save_caffe
from .tensorflow import TensorflowLoader, TensorflowSaver, load_tf, save_tf
from .torchfile import (load_t7, save_t7, T7Reader, T7Writer,
                        load_torch_module, save_torch_module)

__all__ = ["CaffeLoader", "CaffePersister", "load_caffe", "save_caffe",
           "TensorflowLoader", "TensorflowSaver", "load_tf", "save_tf",
           "load_t7", "save_t7", "T7Reader", "T7Writer",
           "load_torch_module", "save_torch_module"]
