"""Model interop: Caffe, TensorFlow, Torch7, and both native formats.

Reference: BigDL's `Module.load/loadTorch/loadCaffe/loadTF` entry points
(nn/Module.scala:41-73) over utils/caffe/, utils/tf/, utils/TorchFile.scala.
Native formats: this framework's pickle save/load (utils/file_io.py) AND
the reference's own JVM object-stream format (interop/bigdl.py over the
generic Java-serialization codec interop/javaser.py) — files written by
actual BigDL load here, and vice versa for the supported layer set."""

from .bigdl import load as load_bigdl, save as save_bigdl
from .caffe import CaffeLoader, CaffePersister, load_caffe, save_caffe
from .tensorflow import TensorflowLoader, TensorflowSaver, load_tf, save_tf
from .torchfile import (load_t7, save_t7, T7Reader, T7Writer,
                        load_torch_module, save_torch_module)

__all__ = ["CaffeLoader", "CaffePersister", "load_caffe", "save_caffe",
           "TensorflowLoader", "TensorflowSaver", "load_tf", "save_tf",
           "load_t7", "save_t7", "T7Reader", "T7Writer",
           "load_torch_module", "save_torch_module",
           "load_bigdl", "save_bigdl"]
