"""TensorFlow GraphDef interop: load frozen graphs into bigdl_tpu modules
and save modules out as GraphDefs.

Reference: utils/tf/TensorflowLoader.scala:50 (parse :68, buildTFGraph :85,
buildBigDLModel :126) with the 1,216-LoC pattern-fusion table
TensorflowToBigDL.scala, and savers utils/tf/{TensorflowSaver,
BigDLToTensorflow}.scala — all over protoc-generated GraphDef protos.
Rebuild: generic wire codec + the public field numbers below; the same
core op set is covered (Const/Identity/Placeholder, MatMul+BiasAdd,
Conv2D+BiasAdd, Relu/Tanh/Sigmoid/Softmax, MaxPool/AvgPool, Reshape),
fused pairwise instead of via subgraph isomorphism.

Field numbers (public tensorflow/core/framework/*.proto):
    GraphDef: node=1
    NodeDef: name=1, op=2, input=3 (repeated), device=4, attr=5 (map)
    map entry: key=1, value=2
    AttrValue: s=2 b=3? — actual: list=1, s=2, i=3, f=4, b=5, type=6,
        shape=7, tensor=8
    TensorProto: dtype=1, tensor_shape=2, tensor_content=4,
        float_val=5, int_val=6
    TensorShapeProto: dim=2 (TensorShapeProto.Dim: size=1, name=2)
    AttrValue.ListValue: s=2, i=3, f=4, b=5, type=6, shape=7
    DataType: DT_FLOAT=1, DT_INT32=3
"""

from __future__ import annotations

import logging
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import pbwire
from ..utils.pbwire import Fields

logger = logging.getLogger(__name__)

__all__ = ["TensorflowLoader", "TensorflowSaver", "load_tf", "save_tf"]

DT_FLOAT, DT_INT32 = 1, 3


class TFNode:
    def __init__(self, f: Fields):
        self.name = f.str(1)
        self.op = f.str(2)
        self.inputs = [i.split(":")[0].lstrip("^") for i in f.strs(3)]
        self.attrs: Dict[str, Fields] = {}
        for entry in f.subs(5):
            self.attrs[entry.str(1)] = entry.sub(2)

    def attr_tensor(self) -> Optional[np.ndarray]:
        if "value" not in self.attrs:
            return None
        t = self.attrs["value"].sub(8)
        dtype = t.int(1)
        shape = tuple(d.int(1) for d in t.sub(2).subs(2))
        content = t.bytes(4)
        if content:
            np_dt = np.float32 if dtype == DT_FLOAT else np.int32
            arr = np.frombuffer(content, dtype=np_dt)
        elif dtype == DT_FLOAT:
            arr = np.array(t.floats(5), np.float32)
        else:
            arr = np.array(t.ints(6), np.int32)
        if shape and arr.size == int(np.prod(shape)):
            arr = arr.reshape(shape)
        elif shape and arr.size == 1:  # splat
            arr = np.full(shape, arr.ravel()[0])
        return arr

    def attr_ints(self, key: str) -> List[int]:
        if key not in self.attrs:
            return []
        return self.attrs[key].sub(1).ints(3)

    def attr_s(self, key: str) -> str:
        return self.attrs[key].bytes(2).decode() if key in self.attrs else ""

    def attr_b(self, key: str) -> bool:
        return bool(self.attrs[key].int(5)) if key in self.attrs else False


class TensorflowLoader:
    """Build a bigdl_tpu Graph from a frozen GraphDef binary
    (reference: TensorflowLoader.load -> buildBigDLModel)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            buf = f.read()
        self.nodes = [TFNode(nf) for nf in Fields(buf).subs(1)]
        self.by_name = {n.name: n for n in self.nodes}

    def build(self, input_names: Optional[List[str]] = None,
              output_name: Optional[str] = None):
        from .. import nn
        from ..nn.graph import Graph, Input

        consts: Dict[str, np.ndarray] = {}
        for n in self.nodes:
            if n.op == "Const":
                consts[n.name] = n.attr_tensor()

        def resolve(name):
            """Follow Identity chains to a const (frozen-graph reads)."""
            seen = 0
            while name in self.by_name and seen < 10:
                node = self.by_name[name]
                if node.op == "Const":
                    return consts[name]
                if node.op == "Identity" and node.inputs:
                    name = node.inputs[0]
                    seen += 1
                    continue
                break
            return None

        tensors: Dict[str, object] = {}
        inputs: List = []
        params: List = []
        modules: List = []
        consumed: set = set()

        # mark BiasAdd fusions: conv/matmul -> biasadd
        bias_of: Dict[str, str] = {}
        for n in self.nodes:
            if n.op == "BiasAdd":
                prod = self.by_name.get(n.inputs[0])
                if prod and prod.op in ("Conv2D", "MatMul"):
                    bias_of[prod.name] = n.name
                    consumed.add(n.name)

        def node_out(name):
            if name in tensors:
                return tensors[name]
            node = self.by_name.get(name)
            if node is None:
                raise KeyError(f"unknown tf node {name}")
            out = emit(node)
            tensors[name] = out
            return out

        def add_module(mod, p, bottoms):
            modules.append(mod)
            params.append(p)
            if len(bottoms) == 1:
                return mod(bottoms[0])
            return mod(bottoms)

        def emit(node):
            op = node.op
            if op in ("Placeholder", "PlaceholderV2"):
                inp = Input()
                inputs.append(inp)
                return inp
            if op in ("Identity", "StopGradient", "CheckNumerics", "NoOp"):
                return node_out(node.inputs[0])
            if op == "BiasAdd" and node.name in consumed:
                # fused into its Conv2D/MatMul producer
                return node_out(node.inputs[0])
            if op == "MatMul":
                w = resolve(node.inputs[1])
                if w is None:
                    raise ValueError(
                        f"MatMul {node.name}: weight input "
                        f"{node.inputs[1]!r} is not a constant — only "
                        "frozen graphs are supported (reference: "
                        "TensorflowLoader reads frozen GraphDefs)")
                if node.attr_b("transpose_a"):
                    raise ValueError(f"MatMul {node.name}: transpose_a "
                                     "unsupported")
                if node.attr_b("transpose_b"):
                    w = np.ascontiguousarray(w.T)
                bias = None
                if node.name in bias_of:
                    bias = resolve(self.by_name[bias_of[node.name]].inputs[1])
                mod = nn.Linear(w.shape[0], w.shape[1],
                                with_bias=bias is not None)
                p = {"weight": np.ascontiguousarray(w.T)}
                if bias is not None:
                    p["bias"] = bias.reshape(-1)
                return add_module(mod, p, [node_out(node.inputs[0])])
            if op == "Conv2D":
                w = resolve(node.inputs[1])  # HWIO already (TF layout)
                if w is None:
                    raise ValueError(
                        f"Conv2D {node.name}: filter input "
                        f"{node.inputs[1]!r} is not a constant — only "
                        "frozen graphs are supported")
                bias = None
                if node.name in bias_of:
                    bias = resolve(self.by_name[bias_of[node.name]].inputs[1])
                strides = node.attr_ints("strides") or [1, 1, 1, 1]
                kh, kw, cin, cout = w.shape
                same = node.attr_s("padding") == "SAME"
                mod = nn.SpatialConvolution(
                    cin, cout, kw, kh, strides[2], strides[1],
                    -1 if same else 0, -1 if same else 0,
                    with_bias=bias is not None)
                p = {"weight": w}
                if bias is not None:
                    p["bias"] = bias.reshape(-1)
                return add_module(mod, p, [node_out(node.inputs[0])])
            if op in ("MaxPool", "AvgPool"):
                k = node.attr_ints("ksize") or [1, 1, 1, 1]
                s = node.attr_ints("strides") or [1, 1, 1, 1]
                # SAME maps to our pad=-1 convention (TF divisor semantics
                # for AvgPool exclude padding -> count_include_pad=False)
                pad = -1 if node.attr_s("padding") == "SAME" else 0
                if op == "MaxPool":
                    mod = nn.SpatialMaxPooling(k[2], k[1], s[2], s[1],
                                               pad, pad)
                else:
                    mod = nn.SpatialAveragePooling(
                        k[2], k[1], s[2], s[1], pad, pad,
                        count_include_pad=False)
                return add_module(mod, {}, [node_out(node.inputs[0])])
            if op == "Relu":
                return add_module(nn.ReLU(), {},
                                  [node_out(node.inputs[0])])
            if op == "Tanh":
                return add_module(nn.Tanh(), {},
                                  [node_out(node.inputs[0])])
            if op == "Sigmoid":
                return add_module(nn.Sigmoid(), {},
                                  [node_out(node.inputs[0])])
            if op == "Softmax":
                return add_module(nn.SoftMax(), {},
                                  [node_out(node.inputs[0])])
            if op == "Reshape":
                shape = resolve(node.inputs[1])
                size = tuple(int(v) for v in np.asarray(shape).ravel())
                size = tuple(0 if v == -1 and i == 0 else v
                             for i, v in enumerate(size))
                mod = nn.InferReshape(tuple(
                    v if v != 0 else 0 for v in size))
                return add_module(mod, {}, [node_out(node.inputs[0])])
            if op in ("Add", "AddV2"):
                return add_module(nn.CAddTable(), {},
                                  [node_out(i) for i in node.inputs])
            if op == "ConcatV2":
                return add_module(nn.JoinTable(-1), {},
                                  [node_out(i) for i in node.inputs[:-1]])
            logger.warning("tf op %s (%s) unsupported; identity",
                           op, node.name)
            return add_module(nn.Identity(), {},
                              [node_out(node.inputs[0])])

        # choose the output: explicit, else last non-consumed non-const node
        if output_name is None:
            cands = [n for n in self.nodes
                     if n.op not in ("Const", "Identity", "NoOp")
                     and n.name not in consumed]
            output_name = cands[-1].name
        out_node = self.by_name[output_name]
        if out_node.op == "BiasAdd":  # fused into its producer
            output_name = out_node.inputs[0]
        out = node_out(output_name)

        graph = Graph(inputs if len(inputs) > 1 else inputs[0], out)
        import jax
        init_params, state = graph.init(jax.random.key(0))
        by_id = {id(m): p for m, p in zip(modules, params)}
        for i, m in enumerate(graph.modules):
            loaded = by_id.get(id(m))
            if loaded:
                for k, v in loaded.items():
                    want = np.asarray(init_params[i][k]).shape
                    if v.shape != want:
                        raise ValueError(
                            f"tf node param {k}: {v.shape} vs {want}")
                    init_params[i][k] = v.astype(
                        np.asarray(init_params[i][k]).dtype)
        graph.attach(init_params, state)
        return graph, init_params


# ------------------------------------------------------------------ saving

def _tensor_proto(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = DT_FLOAT if arr.dtype.kind == "f" else DT_INT32
    arr = arr.astype(np.float32 if dt == DT_FLOAT else np.int32)
    shape = b"".join(
        pbwire.field_bytes(2, pbwire.field_varint(1, int(d)))
        for d in arr.shape)
    return (pbwire.field_varint(1, dt) +
            pbwire.field_bytes(2, shape) +
            pbwire.field_bytes(4, arr.tobytes()))


def _attr(key: str, value: bytes) -> bytes:
    return pbwire.field_bytes(
        5, pbwire.field_string(1, key) + pbwire.field_bytes(2, value))


def _node_def(name: str, op: str, inputs: List[str],
              attrs: Dict[str, bytes] = None) -> bytes:
    body = pbwire.field_string(1, name) + pbwire.field_string(2, op)
    for i in inputs:
        body += pbwire.field_string(3, i)
    for k, v in (attrs or {}).items():
        body += _attr(k, v)
    return pbwire.field_bytes(1, body)


class TensorflowSaver:
    """Emit a frozen GraphDef for a Sequential of supported layers
    (reference: TensorflowSaver/BigDLToTensorflow.scala)."""

    @classmethod
    def save(cls, model, params, path: str):
        from .. import nn

        out = bytearray()
        out += _node_def("input", "Placeholder", [],
                         {"dtype": pbwire.field_varint(6, DT_FLOAT)})
        prev = "input"
        flat = _flatten_seq(model, params)
        for i, (mod, p) in enumerate(flat):
            name = f"{type(mod).__name__.lower()}_{i}"
            if isinstance(mod, nn.Linear):
                wname, bname = name + "/weight", name + "/bias"
                out += _node_def(wname, "Const", [], {
                    "dtype": pbwire.field_varint(6, DT_FLOAT),
                    "value": pbwire.field_bytes(8, _tensor_proto(
                        np.asarray(p["weight"], np.float32).T))})
                out += _node_def(name, "MatMul", [prev, wname])
                prev = name
                if "bias" in p:
                    out += _node_def(bname, "Const", [], {
                        "dtype": pbwire.field_varint(6, DT_FLOAT),
                        "value": pbwire.field_bytes(8, _tensor_proto(
                            np.asarray(p["bias"], np.float32)))})
                    out += _node_def(name + "/badd", "BiasAdd",
                                     [name, bname])
                    prev = name + "/badd"
            elif isinstance(mod, nn.SpatialConvolution):
                wname = name + "/weight"
                out += _node_def(wname, "Const", [], {
                    "dtype": pbwire.field_varint(6, DT_FLOAT),
                    "value": pbwire.field_bytes(8, _tensor_proto(
                        np.asarray(p["weight"], np.float32)))})
                sh, sw = mod.stride
                strides = pbwire.field_bytes(
                    1, pbwire.field_packed_varints(3, [1, sh, sw, 1]))
                # TF only has SAME/VALID; explicit symmetric half-kernel
                # padding at stride 1 is exactly SAME
                kh, kw = mod.kernel
                ph, pw = mod.pad
                if ph == -1 or pw == -1 or (
                        (sh, sw) == (1, 1) and (ph, pw) == (kh // 2, kw // 2)
                        and kh % 2 == 1 and kw % 2 == 1):
                    pad = b"SAME"
                elif (ph, pw) == (0, 0):
                    pad = b"VALID"
                else:
                    raise ValueError(
                        f"TensorflowSaver: conv padding {mod.pad} with "
                        f"stride {mod.stride} has no SAME/VALID equivalent")
                out += _node_def(name, "Conv2D", [prev, wname], {
                    "strides": strides,
                    "padding": pbwire.field_bytes(2, pad)})
                prev = name
                if "bias" in p:
                    bname = name + "/bias"
                    out += _node_def(bname, "Const", [], {
                        "dtype": pbwire.field_varint(6, DT_FLOAT),
                        "value": pbwire.field_bytes(8, _tensor_proto(
                            np.asarray(p["bias"], np.float32)))})
                    out += _node_def(name + "/badd", "BiasAdd",
                                     [name, bname])
                    prev = name + "/badd"
            elif isinstance(mod, nn.ReLU):
                out += _node_def(name, "Relu", [prev])
                prev = name
            elif isinstance(mod, nn.Tanh):
                out += _node_def(name, "Tanh", [prev])
                prev = name
            elif isinstance(mod, nn.Sigmoid):
                out += _node_def(name, "Sigmoid", [prev])
                prev = name
            elif isinstance(mod, (nn.SoftMax,)):
                out += _node_def(name, "Softmax", [prev])
                prev = name
            elif isinstance(mod, (nn.SpatialMaxPooling,
                                  nn.SpatialAveragePooling)):
                kh, kw = mod.kernel
                sh, sw = mod.stride
                pad = b"SAME" if -1 in mod.pad else b"VALID"
                op_name = ("MaxPool" if isinstance(mod, nn.SpatialMaxPooling)
                           else "AvgPool")
                out += _node_def(name, op_name, [prev], {
                    "ksize": pbwire.field_bytes(
                        1, pbwire.field_packed_varints(3, [1, kh, kw, 1])),
                    "strides": pbwire.field_bytes(
                        1, pbwire.field_packed_varints(3, [1, sh, sw, 1])),
                    "padding": pbwire.field_bytes(2, pad)})
                prev = name
            elif isinstance(mod, (nn.Reshape, nn.InferReshape, nn.View)):
                # our Reshape sizes are per-sample; TF shapes carry the
                # batch dim, so prepend -1 (loader maps it back to a
                # copy-batch-dim 0)
                shp = getattr(mod, "size", (-1,))
                sname = name + "/shape"
                out += _node_def(sname, "Const", [], {
                    "dtype": pbwire.field_varint(6, DT_INT32),
                    "value": pbwire.field_bytes(8, _tensor_proto(np.array(
                        [-1] + [int(s) for s in shp], np.int32)))})
                out += _node_def(name, "Reshape", [prev, sname])
                prev = name
            else:
                raise ValueError(
                    f"TensorflowSaver: unsupported {type(mod).__name__}")
        with open(path, "wb") as f:
            f.write(out)
        return path


def _flatten_seq(model, params):
    from ..nn.containers import Sequential
    from ..nn.graph import Graph, _InputModule
    if isinstance(model, (Sequential, Graph)):
        return [(m, params[i]) for i, m in enumerate(model.modules)
                if not isinstance(m, _InputModule)]
    return [(model, params)]


def load_tf(path: str, inputs=None, outputs=None):
    """(reference: Module.loadTF, nn/Module.scala:63)."""
    return TensorflowLoader(path).build(inputs, outputs)


def save_tf(model, params, path: str):
    """(reference: Module.saveTF)."""
    return TensorflowSaver.save(model, params, path)
