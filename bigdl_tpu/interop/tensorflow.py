"""TensorFlow GraphDef interop: load frozen graphs into bigdl_tpu modules
and save modules out as GraphDefs.

Reference: utils/tf/TensorflowLoader.scala:50 (parse :68, buildTFGraph :85,
buildBigDLModel :126) with the 1,216-LoC pattern-fusion table
TensorflowToBigDL.scala and the nn/tf helper ops (Const/Fill/Shape/
SplitAndSelect/StrideSlice, nn/tf/Const.scala:32), plus savers
utils/tf/{TensorflowSaver,BigDLToTensorflow}.scala — all over
protoc-generated GraphDef protos.

TPU-native re-design: instead of subgraph isomorphism against a fixed
pattern table, the loader (a) CONST-FOLDS every subgraph that depends only
on constants with numpy at load time — this subsumes the reference's
BatchNorm-folding patterns, whose rsqrt(var+eps)*gamma arithmetic is
entirely constant in a frozen graph — and (b) covers the remaining runtime
ops generically (elementwise ops with tensor or folded-constant operands,
Split with output slots, FusedBatchNorm, StridedSlice, Pad, Mean...), so
an unrolled LSTM/GRU cell imports as its raw op graph and computes
correctly without a cell-level pattern.  Unsupported ops FAIL LOUD by
default (round-1 advisor: silent Identity mapping produced wrong models);
pass permissive=True for the old behavior.

Field numbers (public tensorflow/core/framework/*.proto):
    GraphDef: node=1
    NodeDef: name=1, op=2, input=3 (repeated), device=4, attr=5 (map)
    map entry: key=1, value=2
    AttrValue: list=1, s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8
    TensorProto: dtype=1, tensor_shape=2, tensor_content=4,
        float_val=5, int_val=6
    TensorShapeProto: dim=2 (TensorShapeProto.Dim: size=1, name=2)
    AttrValue.ListValue: s=2, i=3, f=4, b=5, type=6, shape=7
    DataType: DT_FLOAT=1, DT_INT32=3
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from ..utils import pbwire
from ..utils.pbwire import Fields
from ..nn.module import Module

logger = logging.getLogger(__name__)

__all__ = ["TensorflowLoader", "TensorflowSaver", "load_tf", "save_tf"]

DT_FLOAT, DT_INT32 = 1, 3


def _base(ref: str) -> str:
    return ref.split(":")[0]


def _slot(ref: str) -> int:
    parts = ref.split(":")
    return int(parts[1]) if len(parts) > 1 else 0


class TFNode:
    def __init__(self, f: Fields):
        self.name = f.str(1)
        self.op = f.str(2)
        # keep output-slot suffixes ("node:1"); drop control deps ("^node")
        self.inputs = [i for i in f.strs(3) if not i.startswith("^")]
        self.attrs: Dict[str, Fields] = {}
        for entry in f.subs(5):
            self.attrs[entry.str(1)] = entry.sub(2)

    def attr_tensor(self) -> Optional[np.ndarray]:
        if "value" not in self.attrs:
            return None
        t = self.attrs["value"].sub(8)
        dtype = t.int(1)
        shape = tuple(d.int(1) for d in t.sub(2).subs(2))
        content = t.bytes(4)
        if content:
            np_dt = np.float32 if dtype == DT_FLOAT else np.int32
            arr = np.frombuffer(content, dtype=np_dt)
        elif dtype == DT_FLOAT:
            arr = np.array(t.floats(5), np.float32)
        else:
            arr = np.array(t.ints(6), np.int32)
        if shape and arr.size == int(np.prod(shape)):
            arr = arr.reshape(shape)
        elif shape and arr.size == 1:  # splat
            arr = np.full(shape, arr.ravel()[0])
        return arr

    def attr_ints(self, key: str) -> List[int]:
        if key not in self.attrs:
            return []
        return self.attrs[key].sub(1).ints(3)

    def attr_i(self, key: str, default: int = 0) -> int:
        return self.attrs[key].int(3, default) if key in self.attrs \
            else default

    def attr_f(self, key: str, default: float = 0.0) -> float:
        return self.attrs[key].float(4, default) if key in self.attrs \
            else default

    def attr_s(self, key: str) -> str:
        return self.attrs[key].bytes(2).decode() if key in self.attrs else ""

    def attr_b(self, key: str) -> bool:
        return bool(self.attrs[key].int(5)) if key in self.attrs else False


# --------------------------------------------------- runtime helper modules

import jax.numpy as jnp  # noqa: E402 (after numpy/pbwire for import cost)

_BINOPS = {"Add": jnp.add, "AddV2": jnp.add, "Sub": jnp.subtract,
           "Mul": jnp.multiply, "RealDiv": jnp.divide,
           "Maximum": jnp.maximum, "Minimum": jnp.minimum}


class _ConstBinary(Module):
    """x (op) folded-constant — plays nn/tf/Const.scala's role: the constant
    side of the op was folded from the frozen graph at load time."""

    def __init__(self, op_name: str, const, const_first: bool = False):
        super().__init__()
        self.op_name = op_name
        self._const = np.asarray(const)
        self.const_first = const_first

    def _init(self, rng):
        return {"const": jnp.asarray(self._const)}

    def _apply(self, params, x):
        c = params["const"]
        a, b = (c, x) if self.const_first else (x, c)
        return _BINOPS[self.op_name](a, b)


class _TFSplit(Module):
    """tf.split into `num` equal chunks along `axis` (the reference's
    SplitAndSelect helper); output is a table, consumers pick slots via
    SelectTable."""

    def __init__(self, axis: int, num: int):
        super().__init__()
        self.axis, self.num = axis, num

    def _apply(self, params, x):
        return list(jnp.split(x, self.num, axis=self.axis))


class _TFMean(Module):
    def __init__(self, axes, keepdims: bool):
        super().__init__()
        self.axes, self.keepdims = tuple(axes), keepdims

    def _apply(self, params, x):
        return jnp.mean(x, axis=self.axes, keepdims=self.keepdims)


class _TFPad(Module):
    def __init__(self, paddings):
        super().__init__()
        self.paddings = tuple(tuple(int(v) for v in row) for row in paddings)

    def _apply(self, params, x):
        return jnp.pad(x, self.paddings)


class _TFStridedSlice(Module):
    """StridedSlice with constant begin/end/strides (the reference's
    StrideSlice helper, nn/tf/StrideSlice.scala)."""

    def __init__(self, begin, end, strides, begin_mask=0, end_mask=0,
                 shrink_axis_mask=0):
        super().__init__()
        self.begin = [int(v) for v in begin]
        self.end = [int(v) for v in end]
        self.strides = [int(v) for v in strides]
        self.begin_mask = begin_mask
        self.end_mask = end_mask
        self.shrink = shrink_axis_mask

    def _apply(self, params, x):
        sl, shrink_axes = [], []
        for i in range(len(self.begin)):
            if self.shrink >> i & 1:
                sl.append(slice(self.begin[i], self.begin[i] + 1))
                shrink_axes.append(i)
                continue
            b = None if self.begin_mask >> i & 1 else self.begin[i]
            e = None if self.end_mask >> i & 1 else self.end[i]
            sl.append(slice(b, e, self.strides[i]))
        y = x[tuple(sl) + (slice(None),) * (x.ndim - len(sl))]
        for ax in reversed(shrink_axes):
            y = jnp.squeeze(y, axis=ax)
        return y


# numpy evaluators for load-time constant folding
_FOLD_UNARY = {"Rsqrt": lambda a: 1.0 / np.sqrt(a), "Sqrt": np.sqrt,
               "Square": np.square, "Neg": np.negative, "Exp": np.exp,
               "Log": np.log, "Abs": np.abs}


class TensorflowLoader:
    """Build a bigdl_tpu Graph from a frozen GraphDef binary
    (reference: TensorflowLoader.load -> buildBigDLModel)."""

    def __init__(self, path: str, permissive: bool = False):
        with open(path, "rb") as f:
            buf = f.read()
        self.nodes = [TFNode(nf) for nf in Fields(buf).subs(1)]
        self.by_name = {n.name: n for n in self.nodes}
        self.permissive = permissive
        self._fold_memo: Dict[str, Optional[np.ndarray]] = {}

    # ------------------------------------------------ constant folding
    def resolve(self, ref: str) -> Optional[np.ndarray]:
        """Evaluate `ref` with numpy if it depends only on constants.
        Subsumes the reference's BatchNorm-folding patterns: the
        rsqrt(var+eps)*gamma chains of a frozen decomposed BN are pure
        constant arithmetic."""
        name = _base(ref)
        if name in self._fold_memo:
            return self._fold_memo[name]
        self._fold_memo[name] = None  # cycle guard
        node = self.by_name.get(name)
        val = None
        if node is not None:
            op = node.op
            ins = node.inputs
            if op == "Const":
                val = node.attr_tensor()
            elif op in ("Identity", "StopGradient", "CheckNumerics") and ins:
                val = self.resolve(ins[0])
            elif op in _FOLD_UNARY and ins:
                a = self.resolve(ins[0])
                val = _FOLD_UNARY[op](a) if a is not None else None
            elif op in _BINOPS and len(ins) == 2:
                a, b = self.resolve(ins[0]), self.resolve(ins[1])
                if a is not None and b is not None:
                    val = {"Add": np.add, "AddV2": np.add,
                           "Sub": np.subtract, "Mul": np.multiply,
                           "RealDiv": np.divide, "Maximum": np.maximum,
                           "Minimum": np.minimum}[op](a, b)
            elif op == "Reshape" and len(ins) == 2:
                a, shp = self.resolve(ins[0]), self.resolve(ins[1])
                if a is not None and shp is not None:
                    val = a.reshape([int(v) for v in np.ravel(shp)])
            elif op == "ExpandDims" and len(ins) == 2:
                a, ax = self.resolve(ins[0]), self.resolve(ins[1])
                if a is not None and ax is not None:
                    val = np.expand_dims(a, int(np.ravel(ax)[0]))
            elif op == "Squeeze" and ins:
                a = self.resolve(ins[0])
                if a is not None:
                    dims = node.attr_ints("squeeze_dims")
                    val = np.squeeze(a, tuple(dims) if dims else None)
            elif op == "Cast" and ins:
                a = self.resolve(ins[0])
                if a is not None:
                    dt = node.attr_i("DstT", DT_FLOAT)
                    val = a.astype(np.float32 if dt == DT_FLOAT
                                   else np.int32)
            elif op == "Fill" and len(ins) == 2:
                dims, v = self.resolve(ins[0]), self.resolve(ins[1])
                if dims is not None and v is not None:
                    val = np.full([int(d) for d in np.ravel(dims)],
                                  np.ravel(v)[0])
            elif op == "Pack" and ins:
                vals = [self.resolve(i) for i in ins]
                if all(v is not None for v in vals):
                    val = np.stack(vals, axis=node.attr_i("axis", 0))
            elif op == "ConcatV2" and len(ins) >= 2:
                vals = [self.resolve(i) for i in ins[:-1]]
                ax = self.resolve(ins[-1])
                if ax is not None and all(v is not None for v in vals):
                    val = np.concatenate(vals, int(np.ravel(ax)[0]))
        self._fold_memo[name] = val
        return val

    # ------------------------------------------------------- graph build
    def build(self, input_names: Optional[List[str]] = None,
              output_name: Optional[str] = None):
        from .. import nn
        from ..nn.graph import Graph, Input

        tensors: Dict[tuple, object] = {}
        inputs: List = []
        params: List = []
        state_overrides: List = []
        modules: List = []
        consumed: set = set()
        multi_out = {}  # node name -> its table-producing graph node

        # mark BiasAdd fusions: conv/matmul -> biasadd
        bias_of: Dict[str, str] = {}
        for n in self.nodes:
            if n.op == "BiasAdd":
                prod = self.by_name.get(_base(n.inputs[0]))
                if prod and prod.op in ("Conv2D", "MatMul"):
                    bias_of[prod.name] = n.name
                    consumed.add(n.name)

        def node_out(ref):
            name, slot = _base(ref), _slot(ref)
            if (name, slot) in tensors:
                return tensors[(name, slot)]
            node = self.by_name.get(name)
            if node is None:
                raise KeyError(f"unknown tf node {name}")
            base = emit(node)
            if name in multi_out:
                sel = add_module(nn.SelectTable(slot), {},
                                 [multi_out[name]])
                tensors[(name, slot)] = sel
                return sel
            if slot != 0:
                raise ValueError(f"tf node {name} ({node.op}): output slot "
                                 f"{slot} unsupported")
            tensors[(name, 0)] = base
            return base

        def add_module(mod, p, bottoms, st=None):
            modules.append(mod)
            params.append(p)
            state_overrides.append(st)
            if len(bottoms) == 1:
                return mod(bottoms[0])
            return mod(bottoms)

        def binary(node):
            """Elementwise binary op with tensor or folded-const operands."""
            a_ref, b_ref = node.inputs[:2]
            ca, cb = self.resolve(a_ref), self.resolve(b_ref)
            if ca is not None and cb is None:
                return add_module(
                    _ConstBinary(node.op, ca, const_first=True), {},
                    [node_out(b_ref)])
            if cb is not None and ca is None:
                return add_module(_ConstBinary(node.op, cb), {},
                                  [node_out(a_ref)])
            table = {"Add": nn.CAddTable, "AddV2": nn.CAddTable,
                     "Sub": nn.CSubTable, "Mul": nn.CMulTable,
                     "RealDiv": nn.CDivTable, "Maximum": nn.CMaxTable,
                     "Minimum": nn.CMinTable}[node.op]
            return add_module(table(), {},
                              [node_out(a_ref), node_out(b_ref)])

        def emit(node):
            op = node.op
            if op in ("Placeholder", "PlaceholderV2"):
                inp = Input()
                inputs.append(inp)
                return inp
            if op in ("Identity", "StopGradient", "CheckNumerics", "NoOp"):
                return node_out(node.inputs[0])
            if op == "BiasAdd" and node.name in consumed:
                # fused into its Conv2D/MatMul producer
                return node_out(node.inputs[0])
            if op == "MatMul":
                w = self.resolve(node.inputs[1])
                if w is None:
                    raise ValueError(
                        f"MatMul {node.name}: weight input "
                        f"{node.inputs[1]!r} is not a constant — only "
                        "frozen graphs are supported (reference: "
                        "TensorflowLoader reads frozen GraphDefs)")
                if node.attr_b("transpose_a"):
                    raise ValueError(f"MatMul {node.name}: transpose_a "
                                     "unsupported")
                if node.attr_b("transpose_b"):
                    w = np.ascontiguousarray(w.T)
                bias = None
                if node.name in bias_of:
                    bias = self.resolve(
                        self.by_name[bias_of[node.name]].inputs[1])
                mod = nn.Linear(w.shape[0], w.shape[1],
                                with_bias=bias is not None)
                p = {"weight": np.ascontiguousarray(w.T)}
                if bias is not None:
                    p["bias"] = bias.reshape(-1)
                return add_module(mod, p, [node_out(node.inputs[0])])
            if op == "Conv2D":
                w = self.resolve(node.inputs[1])  # HWIO already (TF layout)
                if w is None:
                    raise ValueError(
                        f"Conv2D {node.name}: filter input "
                        f"{node.inputs[1]!r} is not a constant — only "
                        "frozen graphs are supported")
                bias = None
                if node.name in bias_of:
                    bias = self.resolve(
                        self.by_name[bias_of[node.name]].inputs[1])
                strides = node.attr_ints("strides") or [1, 1, 1, 1]
                kh, kw, cin, cout = w.shape
                same = node.attr_s("padding") == "SAME"
                mod = nn.SpatialConvolution(
                    cin, cout, kw, kh, strides[2], strides[1],
                    -1 if same else 0, -1 if same else 0,
                    with_bias=bias is not None)
                p = {"weight": w}
                if bias is not None:
                    p["bias"] = bias.reshape(-1)
                return add_module(mod, p, [node_out(node.inputs[0])])
            if op in ("FusedBatchNorm", "FusedBatchNormV2",
                      "FusedBatchNormV3"):
                gamma = self.resolve(node.inputs[1])
                beta = self.resolve(node.inputs[2])
                mean = self.resolve(node.inputs[3])
                var = self.resolve(node.inputs[4])
                if any(v is None for v in (gamma, beta, mean, var)):
                    raise ValueError(f"{op} {node.name}: non-constant "
                                     "scale/offset/moments")
                mod = nn.SpatialBatchNormalization(
                    int(gamma.shape[0]), eps=node.attr_f("epsilon", 1e-3),
                    affine=True)
                p = {"weight": gamma.reshape(-1), "bias": beta.reshape(-1)}
                st = {"running_mean": mean.reshape(-1),
                      "running_var": var.reshape(-1)}
                return add_module(mod, p, [node_out(node.inputs[0])], st)
            if op in ("MaxPool", "AvgPool"):
                k = node.attr_ints("ksize") or [1, 1, 1, 1]
                s = node.attr_ints("strides") or [1, 1, 1, 1]
                # SAME maps to our pad=-1 convention (TF divisor semantics
                # for AvgPool exclude padding -> count_include_pad=False)
                pad = -1 if node.attr_s("padding") == "SAME" else 0
                if op == "MaxPool":
                    mod = nn.SpatialMaxPooling(k[2], k[1], s[2], s[1],
                                               pad, pad)
                else:
                    mod = nn.SpatialAveragePooling(
                        k[2], k[1], s[2], s[1], pad, pad,
                        count_include_pad=False)
                return add_module(mod, {}, [node_out(node.inputs[0])])
            simple = {"Relu": nn.ReLU, "Relu6": nn.ReLU6, "Tanh": nn.Tanh,
                      "Sigmoid": nn.Sigmoid, "Softmax": nn.SoftMax,
                      "LogSoftmax": nn.LogSoftMax, "Softplus": nn.SoftPlus,
                      "Elu": nn.ELU, "Sqrt": nn.Sqrt, "Square": nn.Square,
                      "Exp": nn.Exp, "Abs": nn.Abs}
            if op in simple:
                return add_module(simple[op](), {},
                                  [node_out(node.inputs[0])])
            if op == "LeakyRelu":
                return add_module(nn.LeakyReLU(node.attr_f("alpha", 0.2)),
                                  {}, [node_out(node.inputs[0])])
            if op == "Rsqrt":
                return add_module(nn.Power(-0.5), {},
                                  [node_out(node.inputs[0])])
            if op == "Neg":
                return add_module(nn.MulConstant(-1.0), {},
                                  [node_out(node.inputs[0])])
            if op == "Reshape":
                shape = self.resolve(node.inputs[1])
                if shape is None:
                    raise ValueError(f"Reshape {node.name}: non-constant "
                                     "shape")
                size = tuple(int(v) for v in np.asarray(shape).ravel())
                size = tuple(0 if v == -1 and i == 0 else v
                             for i, v in enumerate(size))
                mod = nn.InferReshape(size)
                return add_module(mod, {}, [node_out(node.inputs[0])])
            if op == "Squeeze":
                dims = node.attr_ints("squeeze_dims")
                mod = nn.Squeeze(dims[0] if len(dims) == 1 else None)
                return add_module(mod, {}, [node_out(node.inputs[0])])
            if op == "Pad":
                paddings = self.resolve(node.inputs[1])
                if paddings is None:
                    raise ValueError(f"Pad {node.name}: non-constant "
                                     "paddings")
                return add_module(_TFPad(paddings), {},
                                  [node_out(node.inputs[0])])
            if op == "Mean":
                axes = self.resolve(node.inputs[1])
                if axes is None:
                    raise ValueError(f"Mean {node.name}: non-constant axes")
                mod = _TFMean([int(a) for a in np.ravel(axes)],
                              node.attr_b("keep_dims"))
                return add_module(mod, {}, [node_out(node.inputs[0])])
            if op == "StridedSlice":
                begin = self.resolve(node.inputs[1])
                end = self.resolve(node.inputs[2])
                strides = self.resolve(node.inputs[3])
                if any(v is None for v in (begin, end, strides)):
                    raise ValueError(f"StridedSlice {node.name}: "
                                     "non-constant begin/end/strides")
                if node.attr_i("ellipsis_mask") or \
                        node.attr_i("new_axis_mask"):
                    raise ValueError(f"StridedSlice {node.name}: ellipsis/"
                                     "new-axis masks unsupported")
                mod = _TFStridedSlice(
                    np.ravel(begin), np.ravel(end), np.ravel(strides),
                    node.attr_i("begin_mask"), node.attr_i("end_mask"),
                    node.attr_i("shrink_axis_mask"))
                return add_module(mod, {}, [node_out(node.inputs[0])])
            if op in ("Split", "SplitV"):
                if op == "Split":  # inputs: axis, value
                    axis = self.resolve(node.inputs[0])
                    value_ref = node.inputs[1]
                    num = node.attr_i("num_split")
                else:  # SplitV inputs: value, size_splits, axis
                    sizes = self.resolve(node.inputs[1])
                    if sizes is None or len(set(np.ravel(sizes))) != 1:
                        raise ValueError(f"SplitV {node.name}: only equal "
                                         "splits supported")
                    axis = self.resolve(node.inputs[2])
                    value_ref = node.inputs[0]
                    num = len(np.ravel(sizes))
                if axis is None:
                    raise ValueError(f"{op} {node.name}: non-constant axis")
                split = add_module(
                    _TFSplit(int(np.ravel(axis)[0]), int(num)), {},
                    [node_out(value_ref)])
                multi_out[node.name] = split
                return split
            if op in _BINOPS:
                return binary(node)
            if op == "ConcatV2":
                # last input is the axis (round-1 advisor: it was ignored);
                # TF frozen graphs and our runtime are both NHWC, so the
                # axis carries over directly
                ax = self.resolve(node.inputs[-1])
                if ax is None:
                    raise ValueError(f"ConcatV2 {node.name}: non-constant "
                                     "axis")
                return add_module(nn.JoinTable(int(np.ravel(ax)[0])), {},
                                  [node_out(i) for i in node.inputs[:-1]])
            if not self.permissive:
                raise ValueError(
                    f"tf op {op!r} ({node.name}) unsupported; pass "
                    "permissive=True to map it to Identity (reference "
                    "fails on unmatched patterns too, "
                    "TensorflowToBigDL.scala)")
            logger.warning("tf op %s (%s) unsupported; identity",
                           op, node.name)
            return add_module(nn.Identity(), {},
                              [node_out(node.inputs[0])])

        # choose the output: explicit, else last non-consumed non-const node
        if output_name is None:
            cands = [n for n in self.nodes
                     if n.op not in ("Const", "Identity", "NoOp")
                     and n.name not in consumed]
            output_name = cands[-1].name
        out_node = self.by_name[output_name]
        if out_node.op == "BiasAdd":  # fused into its producer
            output_name = out_node.inputs[0]
        out = node_out(output_name)

        graph = Graph(inputs if len(inputs) > 1 else inputs[0], out)
        import jax
        init_params, init_state = graph.init(jax.random.key(0))
        by_id = {id(m): (p, st) for m, p, st in
                 zip(modules, params, state_overrides)}
        for i, m in enumerate(graph.modules):
            loaded, st = by_id.get(id(m), (None, None))
            if loaded:
                for k, v in loaded.items():
                    want = np.asarray(init_params[i][k]).shape
                    if v.shape != want:
                        raise ValueError(
                            f"tf node param {k}: {v.shape} vs {want}")
                    init_params[i][k] = v.astype(
                        np.asarray(init_params[i][k]).dtype)
            if st:
                for k, v in st.items():
                    init_state[i][k] = v.astype(
                        np.asarray(init_state[i][k]).dtype)
        graph.attach(init_params, init_state)
        return graph, init_params


# ------------------------------------------------------------------ saving

def _tensor_proto(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = DT_FLOAT if arr.dtype.kind == "f" else DT_INT32
    arr = arr.astype(np.float32 if dt == DT_FLOAT else np.int32)
    shape = b"".join(
        pbwire.field_bytes(2, pbwire.field_varint(1, int(d)))
        for d in arr.shape)
    return (pbwire.field_varint(1, dt) +
            pbwire.field_bytes(2, shape) +
            pbwire.field_bytes(4, arr.tobytes()))


def _attr(key: str, value: bytes) -> bytes:
    return pbwire.field_bytes(
        5, pbwire.field_string(1, key) + pbwire.field_bytes(2, value))


def _node_def(name: str, op: str, inputs: List[str],
              attrs: Dict[str, bytes] = None) -> bytes:
    body = pbwire.field_string(1, name) + pbwire.field_string(2, op)
    for i in inputs:
        body += pbwire.field_string(3, i)
    for k, v in (attrs or {}).items():
        body += _attr(k, v)
    return pbwire.field_bytes(1, body)


def _const_node(name: str, arr: np.ndarray, dt: int = DT_FLOAT) -> bytes:
    return _node_def(name, "Const", [], {
        "dtype": pbwire.field_varint(6, dt),
        "value": pbwire.field_bytes(8, _tensor_proto(arr))})


def _t_attr(extra: Dict[str, bytes] = None) -> Dict[str, bytes]:
    """Required dtype attrs for float ops: real TF refuses to import a
    NodeDef missing a no-default attr like Conv2D's T (caught by the
    execute-in-tensorflow oracle, tests/test_interop.py)."""
    d = {"T": pbwire.field_varint(6, DT_FLOAT)}
    if extra:
        d.update(extra)
    return d


class TensorflowSaver:
    """Emit a frozen GraphDef for a Sequential of supported layers
    (reference: TensorflowSaver/BigDLToTensorflow.scala)."""

    @classmethod
    def save(cls, model, params, path: str, state=None):
        from .. import nn

        if state is None:
            state = getattr(model, "state", None)
        out = bytearray()
        out += _node_def("input", "Placeholder", [],
                         {"dtype": pbwire.field_varint(6, DT_FLOAT)})
        prev = "input"
        flat = _flatten_seq(model, params, state)
        for i, (mod, p, s) in enumerate(flat):
            name = f"{type(mod).__name__.lower()}_{i}"
            if isinstance(mod, nn.Linear):
                wname, bname = name + "/weight", name + "/bias"
                out += _const_node(wname,
                                   np.asarray(p["weight"], np.float32).T)
                out += _node_def(name, "MatMul", [prev, wname], _t_attr())
                prev = name
                if "bias" in p:
                    out += _const_node(bname,
                                       np.asarray(p["bias"], np.float32))
                    out += _node_def(name + "/badd", "BiasAdd",
                                     [name, bname], _t_attr())
                    prev = name + "/badd"
            elif isinstance(mod, nn.SpatialConvolution):
                wname = name + "/weight"
                out += _const_node(wname, np.asarray(p["weight"],
                                                     np.float32))
                sh, sw = mod.stride
                strides = pbwire.field_bytes(
                    1, pbwire.field_packed_varints(3, [1, sh, sw, 1]))
                # TF only has SAME/VALID; explicit symmetric half-kernel
                # padding at stride 1 is exactly SAME
                kh, kw = mod.kernel
                ph, pw = mod.pad
                if ph == -1 or pw == -1 or (
                        (sh, sw) == (1, 1) and (ph, pw) == (kh // 2, kw // 2)
                        and kh % 2 == 1 and kw % 2 == 1):
                    pad = b"SAME"
                elif (ph, pw) == (0, 0):
                    pad = b"VALID"
                else:
                    raise ValueError(
                        f"TensorflowSaver: conv padding {mod.pad} with "
                        f"stride {mod.stride} has no SAME/VALID equivalent")
                out += _node_def(name, "Conv2D", [prev, wname], _t_attr({
                    "strides": strides,
                    "padding": pbwire.field_bytes(2, pad)}))
                prev = name
                if "bias" in p:
                    bname = name + "/bias"
                    out += _const_node(bname,
                                       np.asarray(p["bias"], np.float32))
                    out += _node_def(name + "/badd", "BiasAdd",
                                     [name, bname], _t_attr())
                    prev = name + "/badd"
            elif isinstance(mod, nn.BatchNormalization):
                if s is None:
                    raise ValueError("TensorflowSaver: BatchNormalization "
                                     "needs running stats (pass state=)")
                c = mod.n_output
                gamma = (np.asarray(p["weight"], np.float32) if mod.affine
                         else np.ones(c, np.float32))
                beta = (np.asarray(p["bias"], np.float32) if mod.affine
                        else np.zeros(c, np.float32))
                out += _const_node(name + "/gamma", gamma)
                out += _const_node(name + "/beta", beta)
                out += _const_node(name + "/mean",
                                   np.asarray(s["running_mean"], np.float32))
                out += _const_node(name + "/var",
                                   np.asarray(s["running_var"], np.float32))
                out += _node_def(name, "FusedBatchNormV3",
                                 [prev, name + "/gamma", name + "/beta",
                                  name + "/mean", name + "/var"],
                                 _t_attr({
                                     "U": pbwire.field_varint(6, DT_FLOAT),
                                     "epsilon": pbwire.field_float(
                                         4, mod.eps),
                                     "is_training": pbwire.field_varint(
                                         5, 0)}))
                prev = name
            elif isinstance(mod, nn.ReLU):
                out += _node_def(name, "Relu", [prev], _t_attr())
                prev = name
            elif isinstance(mod, nn.Tanh):
                out += _node_def(name, "Tanh", [prev], _t_attr())
                prev = name
            elif isinstance(mod, nn.Sigmoid):
                out += _node_def(name, "Sigmoid", [prev], _t_attr())
                prev = name
            elif isinstance(mod, nn.LogSoftMax):
                out += _node_def(name, "LogSoftmax", [prev], _t_attr())
                prev = name
            elif isinstance(mod, (nn.SoftMax,)):
                out += _node_def(name, "Softmax", [prev], _t_attr())
                prev = name
            elif isinstance(mod, nn.Dropout):
                pass  # inference graph: dropout is identity when frozen
            elif isinstance(mod, (nn.SpatialMaxPooling,
                                  nn.SpatialAveragePooling)):
                kh, kw = mod.kernel
                sh, sw = mod.stride
                pad = b"SAME" if -1 in mod.pad else b"VALID"
                op_name = ("MaxPool" if isinstance(mod, nn.SpatialMaxPooling)
                           else "AvgPool")
                out += _node_def(name, op_name, [prev], _t_attr({
                    "ksize": pbwire.field_bytes(
                        1, pbwire.field_packed_varints(3, [1, kh, kw, 1])),
                    "strides": pbwire.field_bytes(
                        1, pbwire.field_packed_varints(3, [1, sh, sw, 1])),
                    "padding": pbwire.field_bytes(2, pad)}))
                prev = name
            elif isinstance(mod, (nn.Reshape, nn.InferReshape, nn.View)):
                # our Reshape sizes are per-sample; TF shapes carry the
                # batch dim, so prepend -1 (loader maps it back to a
                # copy-batch-dim 0)
                shp = getattr(mod, "size", (-1,))
                sname = name + "/shape"
                out += _const_node(sname, np.array(
                    [-1] + [int(s_) for s_ in shp], np.int32), DT_INT32)
                out += _node_def(name, "Reshape", [prev, sname], _t_attr({"Tshape": pbwire.field_varint(6, DT_INT32)}))
                prev = name
            else:
                raise ValueError(
                    f"TensorflowSaver: unsupported {type(mod).__name__}")
        with open(path, "wb") as f:
            f.write(out)
        return path


def _flatten_seq(model, params, state=None):
    from ..nn.containers import Sequential
    from ..nn.graph import Graph, _InputModule

    def rec(mod, p, s, acc):
        if isinstance(mod, Sequential):
            for i, m in enumerate(mod.modules):
                rec(m, p[i], s[i] if s is not None else None, acc)
        elif isinstance(mod, _InputModule):
            pass
        else:
            acc.append((mod, p, s))

    acc = []
    if isinstance(model, Graph):
        for i, m in enumerate(model.modules):
            if not isinstance(m, _InputModule):
                acc.append((m, params[i],
                            state[i] if state is not None else None))
        return acc
    rec(model, params, state, acc)
    return acc


def load_tf(path: str, inputs=None, outputs=None, permissive: bool = False):
    """(reference: Module.loadTF, nn/Module.scala:63)."""
    return TensorflowLoader(path, permissive=permissive).build(inputs,
                                                               outputs)


def save_tf(model, params, path: str, state=None):
    """(reference: Module.saveTF)."""
    return TensorflowSaver.save(model, params, path, state=state)
