"""Read scalars/histograms back out of TensorBoard event files.

Reference: visualization/tensorboard/FileReader.scala — used by the specs and
by TrainSummary.readScalar."""

from __future__ import annotations

import glob
import os
import struct
from typing import Dict, Iterator, List, Tuple

from . import proto

__all__ = ["list_events", "read_scalar"]


def _iter_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            f.read(4)  # header crc (verified by the record tests; skip here)
            (length,) = struct.unpack("<Q", header)
            payload = f.read(length)
            if len(payload) < length:
                return
            f.read(4)  # payload crc
            yield payload


def list_events(log_dir: str) -> Iterator[Dict]:
    """All events in a log dir, file-order then record-order."""
    for path in sorted(glob.glob(os.path.join(log_dir,
                                              "events.out.tfevents.*"))):
        for rec in _iter_records(path):
            yield proto.parse_event(rec)


def read_scalar(log_dir: str, tag: str) -> List[Tuple[int, float, float]]:
    """[(step, value, wall_time)] for one scalar tag
    (reference: TrainSummary.readScalar -> FileReader.readScalar)."""
    out = []
    for ev in list_events(log_dir):
        for v in ev["values"]:
            if v["tag"] == tag and v["simple_value"] is not None:
                out.append((ev["step"], v["simple_value"], ev["wall_time"]))
    return out
