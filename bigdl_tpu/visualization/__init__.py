"""Visualization: TensorBoard-compatible training summaries.

Reference: visualization/{Summary,TrainSummary,ValidationSummary}.scala —
`TrainSummary(logDir, appName)` writes scalars {Loss, Throughput,
LearningRate} (+ optional per-parameter histograms) to
`<logDir>/<appName>/train`, `ValidationSummary` to `.../validation`; hooked
from the driver loop at optim/DistriOptimizer.scala:345-363,426-456.  Event
files are standard TensorBoard TFRecord files, so `tensorboard --logdir`
works unchanged."""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import proto
from .reader import read_scalar
from .writer import FileWriter

__all__ = ["Summary", "TrainSummary", "ValidationSummary",
           "FileWriter", "proto", "read_scalar"]


class Summary:
    """Common machinery of Train/ValidationSummary (Summary.scala:40-90)."""

    _subdir = ""

    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = log_dir
        self.app_name = app_name
        self.summary_dir = os.path.join(log_dir, app_name, self._subdir)
        self._writer: Optional[FileWriter] = None

    @property
    def writer(self) -> FileWriter:
        if self._writer is None:
            self._writer = FileWriter(self.summary_dir)
        return self._writer

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self.writer.add_summary(proto.scalar_summary(tag, value), step)
        return self

    def add_histogram(self, tag: str, values: np.ndarray,
                      step: int) -> "Summary":
        self.writer.add_summary(proto.histogram_summary(tag, values), step)
        return self

    def read_scalar(self, tag: str) -> List[Tuple[int, float, float]]:
        """(reference: Summary.readScalar)"""
        self.flush()
        return read_scalar(self.summary_dir, tag)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class TrainSummary(Summary):
    """Training-side summary with per-tag triggers
    (TrainSummary.scala:32; setSummaryTrigger restricted to the same four
    tags as the reference)."""

    _subdir = "train"
    _allowed_triggers = ("LearningRate", "Loss", "Throughput", "Parameters")

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name)
        self._triggers: Dict[str, object] = {}

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        if name not in self._allowed_triggers:
            raise ValueError(
                f"Only {self._allowed_triggers} triggers are supported")
        self._triggers[name] = trigger
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)


class ValidationSummary(Summary):
    """Validation metrics (ValidationSummary.scala)."""

    _subdir = "validation"
