"""TensorBoard event-file writers.

Reference: visualization/tensorboard/{FileWriter,EventWriter,RecordWriter}.scala
— a FileWriter owns an EventWriter (background thread draining a queue every
`flushMillis`), which frames Event protos as TFRecords with masked CRC32C
(RecordWriter.scala:44-57, netty/Crc32c.java).  Same structure here; the CRC
comes from the native C++ library when built (csrc/crc32c.cc)."""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
from typing import Optional

from ..utils.recordio import masked_crc32c
from . import proto

import struct

__all__ = ["RecordWriter", "EventWriter", "FileWriter"]


class RecordWriter:
    """TFRecord framing of serialized Event protos onto an open file."""

    def __init__(self, f):
        self._f = f

    def write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", masked_crc32c(payload)))

    def flush(self) -> None:
        self._f.flush()


_file_counter = [0]
_counter_lock = threading.Lock()


class EventWriter:
    """Queue + background flusher thread (EventWriter.scala).  All record
    writes happen under one lock, so `flush()` can drain synchronously
    without racing the background thread."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        os.makedirs(log_dir, exist_ok=True)
        with _counter_lock:
            _file_counter[0] += 1
            uniq = _file_counter[0]
        # pid + per-process counter keep same-second writers from
        # truncating each other
        fname = "events.out.tfevents.%d.%s.%d.%d" % (
            int(time.time()), socket.gethostname(), os.getpid(), uniq)
        self.path = os.path.join(log_dir, fname)
        self._file = open(self.path, "wb")
        self._writer = RecordWriter(self._file)
        self._queue: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._flush_secs = flush_secs
        self._write_lock = threading.Lock()
        self._closed = False
        # version record first, as TF does (EventWriter.scala init)
        self._writer.write(proto.event_bytes(
            time.time(), file_version="brain.Event:2"))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def add_event(self, event: bytes) -> None:
        self._queue.put(event)

    def _drain(self) -> bool:
        """Write queued events; returns False once the poison pill is seen."""
        alive = True
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return alive
            with self._write_lock:
                if item is None:
                    alive = False
                elif not self._closed:
                    self._writer.write(item)

    def _run(self) -> None:
        while self._drain():
            with self._write_lock:
                self._writer.flush()
            time.sleep(self._flush_secs)
        with self._write_lock:
            if not self._closed:
                self._writer.flush()

    def close(self) -> None:
        self.flush()
        self._queue.put(None)
        self._thread.join(timeout=30)
        with self._write_lock:
            self._closed = True
            self._file.close()

    def flush(self) -> None:
        # synchronous: drain the queue ourselves under the write lock
        self._drain()
        with self._write_lock:
            if not self._closed:
                self._writer.flush()


class FileWriter:
    """Public writer facade (FileWriter.scala)."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        self.log_dir = log_dir
        self._events = EventWriter(log_dir, flush_secs)

    def add_summary(self, summary: bytes, global_step: int = 0) -> "FileWriter":
        self._events.add_event(
            proto.event_bytes(time.time(), step=global_step, summary=summary))
        return self

    def flush(self) -> None:
        self._events.flush()

    def close(self) -> None:
        self._events.close()
