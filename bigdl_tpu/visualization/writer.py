"""TensorBoard event-file writers.

Reference: visualization/tensorboard/{FileWriter,EventWriter,RecordWriter}.scala
— a FileWriter owns an EventWriter (background thread draining a queue every
`flushMillis`), which frames Event protos as TFRecords with masked CRC32C
(RecordWriter.scala:44-57, netty/Crc32c.java).  Same structure here; the CRC
comes from the native C++ library when built (csrc/crc32c.cc)."""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
from typing import Optional

from ..utils.recordio import masked_crc32c
from . import proto

import struct

__all__ = ["RecordWriter", "EventWriter", "FileWriter"]


class RecordWriter:
    """TFRecord framing of serialized Event protos onto an open file."""

    def __init__(self, f):
        self._f = f

    def write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", masked_crc32c(payload)))

    def flush(self) -> None:
        self._f.flush()


_file_counter = [0]
_counter_lock = threading.Lock()


class EventWriter:
    """Queue + background flusher thread (EventWriter.scala).  All record
    writes happen under one lock, so `flush()` can drain synchronously
    without racing the background thread."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        os.makedirs(log_dir, exist_ok=True)
        with _counter_lock:
            _file_counter[0] += 1
            uniq = _file_counter[0]
        # pid + per-process counter keep same-second writers from
        # truncating each other
        fname = "events.out.tfevents.%d.%s.%d.%d" % (
            int(time.time()), socket.gethostname(), os.getpid(), uniq)
        self.path = os.path.join(log_dir, fname)
        self._file = open(self.path, "wb")
        self._writer = RecordWriter(self._file)
        self._queue: "queue.Queue[bytes]" = queue.Queue()
        self._flush_secs = flush_secs
        self._write_lock = threading.Lock()
        self._closed = False
        # out-of-band shutdown flag: an in-band queue sentinel could be
        # consumed by a concurrent flush() and leak the thread
        self._stop = threading.Event()
        # version record first, as TF does (EventWriter.scala init)
        self._writer.write(proto.event_bytes(
            time.time(), file_version="brain.Event:2"))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def add_event(self, event: bytes) -> None:
        self._queue.put(event)

    def _drain(self) -> None:
        """Write everything currently queued, then flush the file."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            with self._write_lock:
                if not self._closed:
                    self._writer.write(item)
        with self._write_lock:
            if not self._closed:
                self._writer.flush()

    def _run(self) -> None:
        while not self._stop.wait(self._flush_secs):
            self._drain()
        self._drain()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
        self._drain()
        with self._write_lock:
            self._closed = True
            self._file.close()

    def flush(self) -> None:
        # synchronous: drain the queue ourselves under the write lock
        self._drain()


class FileWriter:
    """Public writer facade (FileWriter.scala)."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        self.log_dir = log_dir
        self._events = EventWriter(log_dir, flush_secs)

    def add_summary(self, summary: bytes, global_step: int = 0) -> "FileWriter":
        self._events.add_event(
            proto.event_bytes(time.time(), step=global_step, summary=summary))
        return self

    def flush(self) -> None:
        self._events.flush()

    def close(self) -> None:
        self._events.close()
