"""TensorBoard Event/Summary messages, hand-encoded over the generic
protobuf wire codec (utils/pbwire.py).

Reference: BigDL ships protoc-generated Java for these protos and builds
messages in visualization/Summary.scala:95-172.

Field numbers (public tensorflow/core/util/event.proto and
tensorflow/core/framework/summary.proto):
    Event:   wall_time=1 (double), step=2 (int64), file_version=3 (string),
             summary=5 (message)
    Summary: value=1 (repeated message)
    Summary.Value: tag=1 (string), simple_value=2 (float), histo=5 (message)
    HistogramProto: min=1 max=2 num=3 sum=4 sum_squares=5 (double),
             bucket_limit=6 bucket=7 (repeated double, packed)
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..utils.pbwire import (Fields, decode_varint, encode_varint,
                            field_bytes, field_double, field_float,
                            field_packed_doubles, field_string, field_varint)

__all__ = ["encode_varint", "decode_varint", "scalar_summary",
           "histogram_summary", "event_bytes", "parse_event"]


def scalar_summary(tag: str, value: float) -> bytes:
    """Summary{value {tag, simple_value}} (Summary.scala:95-104)."""
    v = field_string(1, tag) + field_float(2, float(value))
    return field_bytes(1, v)


# TensorBoard's standard exponential bucket boundaries: +/- 1e-12 * 1.1^k
# (reference builds the identical table in Summary.scala:120-146).
def _default_bucket_limits() -> List[float]:
    pos = []
    v = 1e-12
    while v < 1e20:
        pos.append(v)
        v *= 1.1
    return [-x for x in reversed(pos)] + [0.0] + pos + [float("inf")]


_BUCKETS: List[float] = _default_bucket_limits()
_EDGES = np.array([-np.inf] + _BUCKETS[:-1] + [np.inf])


def histogram_summary(tag: str, values: np.ndarray) -> bytes:
    """Summary{value {tag, histo}} with TF exponential buckets; only buckets
    up to the last non-empty one are emitted, as TF does."""
    x = np.asarray(values, dtype=np.float64).ravel()
    if x.size == 0:
        x = np.zeros(1)
    counts, _ = np.histogram(x, bins=_EDGES)
    last = int(np.nonzero(counts)[0].max()) if counts.any() else 0
    histo = (field_double(1, float(x.min())) +
             field_double(2, float(x.max())) +
             field_double(3, float(x.size)) +
             field_double(4, float(x.sum())) +
             field_double(5, float(np.square(x).sum())) +
             field_packed_doubles(6, _BUCKETS[:last + 1]) +
             field_packed_doubles(7, counts[:last + 1].tolist()))
    v = field_string(1, tag) + field_bytes(5, histo)
    return field_bytes(1, v)


def event_bytes(wall_time: float, step: int = 0,
                summary: bytes | None = None,
                file_version: str | None = None) -> bytes:
    out = field_double(1, wall_time)
    if step:
        out += field_varint(2, step)
    if file_version is not None:
        out += field_string(3, file_version)
    if summary is not None:
        out += field_bytes(5, summary)
    return out


def parse_event(buf: bytes) -> Dict:
    """Decode an Event record into {wall_time, step, file_version,
    values: [{tag, simple_value | histo}]} — the read-back path used by
    FileReader (reference: visualization/tensorboard/FileReader.scala)."""
    f = Fields(buf)
    ev = {"wall_time": f.float(1), "step": f.int(2),
          "file_version": f.str(3) or None, "values": []}
    if f.has(5):
        for v in f.sub(5).subs(1):
            value = {"tag": v.str(1) or None,
                     "simple_value": v.float(2) if v.has(2) else None,
                     "histo": _parse_histo(v.sub(5)) if v.has(5) else None}
            ev["values"].append(value)
    return ev


def _parse_histo(f: Fields) -> Dict:
    return {"min": f.float(1), "max": f.float(2), "num": f.float(3),
            "sum": f.float(4), "sum_squares": f.float(5),
            "bucket_limit": f.doubles(6), "bucket": f.doubles(7)}
