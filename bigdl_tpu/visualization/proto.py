"""Minimal protobuf wire-format codec + the TensorBoard Event/Summary
messages, hand-encoded.

Reference: BigDL ships protoc-generated Java for the TensorFlow `Summary`/
`Event` protos and builds messages in visualization/Summary.scala:95-172.
Rebuild: TensorBoard only needs a handful of fields, so we encode the wire
format directly (varint/fixed64/length-delimited) with no protobuf runtime —
the same no-dependency spirit as the vendored netty/Crc32c.java.

Field numbers (public tensorflow/core/util/event.proto and
tensorflow/core/framework/summary.proto):
    Event:   wall_time=1 (double), step=2 (int64), file_version=3 (string),
             summary=5 (message)
    Summary: value=1 (repeated message)
    Summary.Value: tag=1 (string), simple_value=2 (float), histo=5 (message)
    HistogramProto: min=1 max=2 num=3 sum=4 sum_squares=5 (double),
             bucket_limit=6 bucket=7 (repeated double, packed)
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Sequence, Tuple

import numpy as np

__all__ = ["encode_varint", "decode_varint", "scalar_summary",
           "histogram_summary", "event_bytes", "parse_event"]


# ---------------------------------------------------------------- encoding

def encode_varint(value: int) -> bytes:
    out = bytearray()
    value &= (1 << 64) - 1  # two's-complement for negative int64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return encode_varint((field << 3) | wire)


def _field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + encode_varint(value)


def _field_double(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", value)


def _field_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def _field_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + encode_varint(len(value)) + value


def _field_packed_doubles(field: int, values: Sequence[float]) -> bytes:
    payload = struct.pack(f"<{len(values)}d", *values)
    return _field_bytes(field, payload)


# ---------------------------------------------------- summaries and events

def scalar_summary(tag: str, value: float) -> bytes:
    """Summary{value {tag, simple_value}} (Summary.scala:95-104)."""
    v = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    return _field_bytes(1, v)


# TensorBoard's standard exponential bucket boundaries: +/- 1e-12 * 1.1^k
# (reference builds the identical table in Summary.scala:120-146).
def _default_bucket_limits() -> List[float]:
    pos = []
    v = 1e-12
    while v < 1e20:
        pos.append(v)
        v *= 1.1
    return [-x for x in reversed(pos)] + [0.0] + pos + [float("inf")]


_BUCKETS: List[float] = _default_bucket_limits()
_EDGES = np.array([-np.inf] + _BUCKETS[:-1] + [np.inf])


def histogram_summary(tag: str, values: np.ndarray) -> bytes:
    """Summary{value {tag, histo}} with TF exponential buckets; only buckets
    up to the last non-empty one are emitted, as TF does."""
    x = np.asarray(values, dtype=np.float64).ravel()
    if x.size == 0:
        x = np.zeros(1)
    counts, _ = np.histogram(x, bins=_EDGES)
    last = int(np.nonzero(counts)[0].max()) if counts.any() else 0
    histo = (_field_double(1, float(x.min())) +
             _field_double(2, float(x.max())) +
             _field_double(3, float(x.size)) +
             _field_double(4, float(x.sum())) +
             _field_double(5, float(np.square(x).sum())) +
             _field_packed_doubles(6, _BUCKETS[:last + 1]) +
             _field_packed_doubles(7, counts[:last + 1].tolist()))
    v = _field_bytes(1, tag.encode()) + _field_bytes(5, histo)
    return _field_bytes(1, v)


def event_bytes(wall_time: float, step: int = 0,
                summary: bytes | None = None,
                file_version: str | None = None) -> bytes:
    out = _field_double(1, wall_time)
    if step:
        out += _field_varint(2, step)
    if file_version is not None:
        out += _field_bytes(3, file_version.encode())
    if summary is not None:
        out += _field_bytes(5, summary)
    return out


# ---------------------------------------------------------------- decoding

def decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    pos = 0
    while pos < len(buf):
        key, pos = decode_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = decode_varint(buf, pos)
        elif wire == 1:
            val = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif wire == 2:
            n, pos = decode_varint(buf, pos)
            val = buf[pos:pos + n]
            pos += n
        elif wire == 5:
            val = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def parse_event(buf: bytes) -> Dict:
    """Decode an Event record into {wall_time, step, file_version,
    values: [{tag, simple_value | histo}]} — the read-back path used by
    FileReader (reference: visualization/tensorboard/FileReader.scala)."""
    ev = {"wall_time": 0.0, "step": 0, "file_version": None, "values": []}
    for field, _wire, val in _iter_fields(buf):
        if field == 1:
            ev["wall_time"] = val
        elif field == 2:
            ev["step"] = val
        elif field == 3:
            ev["file_version"] = bytes(val).decode()
        elif field == 5:
            for f2, _w2, v2 in _iter_fields(bytes(val)):
                if f2 != 1:
                    continue
                value = {"tag": None, "simple_value": None, "histo": None}
                for f3, _w3, v3 in _iter_fields(bytes(v2)):
                    if f3 == 1:
                        value["tag"] = bytes(v3).decode()
                    elif f3 == 2:
                        value["simple_value"] = v3
                    elif f3 == 5:
                        value["histo"] = _parse_histo(bytes(v3))
                ev["values"].append(value)
    return ev


def _parse_histo(buf: bytes) -> Dict:
    h = {"min": 0.0, "max": 0.0, "num": 0.0, "sum": 0.0, "sum_squares": 0.0,
         "bucket_limit": [], "bucket": []}
    names = {1: "min", 2: "max", 3: "num", 4: "sum", 5: "sum_squares"}
    for field, wire, val in _iter_fields(buf):
        if field in names:
            h[names[field]] = val
        elif field in (6, 7):
            key = "bucket_limit" if field == 6 else "bucket"
            if wire == 2:  # packed
                n = len(val) // 8
                h[key] = list(struct.unpack(f"<{n}d", val))
            else:
                h[key].append(val)
    return h
