"""Continuous train->serve deployment: release lineage + deploy controller.

The BigDL papers' headline claim is the "end-to-end AI pipeline" —
training and serving as ONE integrated system, not two programs a human
glues together (BigDL, arXiv:1804.05839; BigDL 2.0, arXiv:2204.01715).
Every piece of that loop exists in this runtime — CRC-verified checkpoint
lineage (utils/file_io.py), zero-drop hot swap + canary auto-rollback
(serve/server.py + serve/control.py), elastic multi-host training
(parallel/elastic.py) — but until this module a human still drove it:
nothing watched the lineage, nothing decided when a fresh snapshot went
live.  This module closes the optimizer -> canary loop:

- :class:`ReleasePublisher` — the TRAINING side.  The Optimizer's
  checkpoint path (``set_checkpoint(..., publish=True)``) emits one
  *release entry* per published snapshot: a small CRC-framed blob
  ``release.<id>`` (monotonic id) carrying epoch/iteration, training
  metrics, the snapshot path and the snapshot's own frame fingerprint
  (``file_io.frame_fingerprint``).  Entries ride any file_io scheme
  (local, ``memory://``, fsspec remotes), so a training run on one host
  is a model FEED for servers on another — they share only a directory.

- :class:`DeployController` — the SERVING side.  Watches the release
  lineage with ``file_io.watch_lineage`` (retried IO, no ad-hoc loops),
  CRC-verifies every new entry BEFORE deploying — a corrupt or
  partially-written entry (or one whose snapshot was rewritten after
  publication: fingerprint mismatch) is quarantined ``.corrupt`` and
  skipped with a typed :class:`ReleaseRejected` in the timeline; the
  next good entry still deploys.  A verified release is canaried into
  the live server via ``swap(snapshot, canary_fraction=f)`` and the
  serve control plane's comparator (serve/control.CanaryController)
  promotes or rolls it back; the controller waits the verdict out
  before consuming the next release.  Consecutive rollbacks are
  BOUNDED: past ``rollback_budget`` the controller FREEZES (flagged
  unhealthy in ``stats()["deploy"]`` / ``/v1/stats``, a ``frozen``
  timeline event) instead of flapping a broken trainer into production
  forever.  The full model-version timeline — deployed / promoted /
  rolled_back / rejected / frozen, with release ids and canary verdicts
  — is kept in memory (``versions()``, the ``/v1/versions`` endpoint),
  mirrored into ``stats()["deploy"]``, and emitted as the ``deploy``
  telemetry counter track + instants so a merged trace shows training
  steps, publishes, and promotions on one timeline
  (tools/trace_report.py promotes it to its own report section).

Chaos drill (utils/chaos.py): ``deploy.publish`` fires once per release
entry write and a ``corrupt@N`` schedule mutates the FRAMED bytes — the
controller must skip the entry typed and deploy the next good one.
``tools/continuous_smoke.py`` drills the whole loop (corrupt publish,
host loss mid-train, canary regression) exit-coded as runbook cpu-smoke
stage 2o.

Knobs (utils/config tier; constructor args override):

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_DEPLOY_CANARY_FRACTION`` | canary batch fraction per release; 0 = plain full swaps | 0.25 |
| ``BIGDL_TPU_DEPLOY_ROLLBACK_BUDGET`` | consecutive canary rollbacks before the controller freezes | 2 |
| ``BIGDL_TPU_DEPLOY_POLL_S`` | lineage poll cadence, seconds | 0.25 |
| ``BIGDL_TPU_DEPLOY_DECISION_TIMEOUT`` | seconds to wait a canary verdict out; past it the controller freezes (0 = wait forever) | 0 |
| ``BIGDL_TPU_DEPLOY_MAX_UNAVAILABLE`` | fleet mode: members concurrently in-swap during the rolling fan-out (serve/fleetfront.py) | 1 |

See docs/continuous.md for the architecture, the release-entry schema
and the promote/rollback/freeze decision tree.
"""

from __future__ import annotations

import logging
import os
import pickle
import re
import threading
import time
from typing import Dict, List, Optional

from ..utils import chaos, config, file_io, telemetry
from .batcher import ServeError

logger = logging.getLogger("bigdl_tpu")

__all__ = ["ReleaseRejected", "ReleasePublisher", "DeployController",
           "RELEASE_PATTERN", "RELEASE_FORMAT", "read_release"]

#: release entry file names: ``release.<monotonic id>``
RELEASE_PATTERN = r"release\.(\d+)"
RELEASE_FORMAT = "bigdl_tpu-release-v1"


class ReleaseRejected(ServeError):
    """A lineage release entry failed verification before deployment —
    corrupt/truncated entry bytes, a missing or CRC-failing snapshot, or
    a snapshot whose frame fingerprint no longer matches the one recorded
    at publication (rewritten after publish).  The controller quarantines
    the entry, records the typed rejection in the timeline, and moves on
    to the next release — a bad publish never reaches traffic and never
    stops the feed."""

    def __init__(self, message: str, release_id: Optional[int] = None):
        super().__init__(message)
        self.release_id = release_id


# ---------------------------------------------------------------------------
# the training side: release publication
# ---------------------------------------------------------------------------


class ReleasePublisher:
    """Emit release entries into a lineage directory (any file_io scheme).

    One entry per :meth:`publish`: ``release.<id>`` with a monotonic id
    resumed from the directory contents (quarantined ids are never
    reused), CRC-framed exactly like checkpoints so the consumer's
    ``file_io.load`` verifies it for free.  The write goes through the
    scheme's own atomicity (local tmp+rename, retried remote ops) — a
    watcher can never list a half-written entry under its final name."""

    def __init__(self, lineage_dir: str, clock=None):
        self.dir = file_io._strip_file_scheme(str(lineage_dir))
        self.clock = clock or time.time
        self._lock = threading.Lock()
        self._next = self._scan_next()
        self.published = 0

    def _scan_next(self) -> int:
        fs = file_io.get_filesystem(self.dir)
        try:
            names = fs.listdir(self.dir) if fs.isdir(self.dir) else []
        except Exception:  # noqa: BLE001 — an empty/unreachable dir just
            # starts the id sequence; the first write surfaces real errors
            names = []
        newest = 0
        for n in names:
            m = re.fullmatch(RELEASE_PATTERN + r"(?:\.corrupt)?", n)
            if m:
                newest = max(newest, int(m.group(1)))
        return newest + 1

    def publish(self, model_path: str, *, neval: int,
                epoch: Optional[int] = None,
                iteration: Optional[int] = None,
                metrics: Optional[dict] = None) -> int:
        """Write one release entry for the snapshot at `model_path`;
        returns the release id.  The snapshot must already be on storage
        — its frame fingerprint is read here and pinned into the entry so
        the consumer can prove it serves the bytes that were published."""
        model_path = file_io._strip_file_scheme(str(model_path))
        try:
            fingerprint = file_io.frame_fingerprint(model_path)
        except Exception as e:  # noqa: BLE001 — refuse to publish a
            # snapshot we cannot even read: the entry would be dead on
            # arrival at the controller
            raise ReleaseRejected(
                f"publish: cannot fingerprint snapshot {model_path} "
                f"({type(e).__name__}: {e})") from e
        with self._lock:
            rid = self._next
            self._next += 1
        entry = {"format": RELEASE_FORMAT, "release_id": rid,
                 "neval": int(neval),
                 "epoch": None if epoch is None else int(epoch),
                 "iteration": int(neval if iteration is None else iteration),
                 "metrics": dict(metrics or {}),
                 "model_path": model_path,
                 "model_name": os.path.basename(model_path),
                 "fingerprint": fingerprint,
                 "wall_time": self.clock()}
        payload = pickle.dumps(entry, protocol=pickle.HIGHEST_PROTOCOL)
        # the chaos point mutates the FRAMED bytes: a corrupt@N schedule
        # lands an entry whose CRC verification must fail at the consumer
        data = chaos.transform("deploy.publish",
                               file_io.frame_bytes(payload))
        fs = file_io.get_filesystem(self.dir)
        fs.makedirs(self.dir)
        fs.write_bytes(file_io._join(self.dir, f"release.{rid}"), data)
        with self._lock:
            self.published += 1
            published = self.published
        telemetry.instant("deploy.publish", cat="deploy", release=rid,
                          neval=int(neval))
        telemetry.counter("deploy", published=published)
        logger.info("release %d published -> %s (snapshot %s, neval %d)",
                    rid, self.dir, entry["model_name"], int(neval))
        return rid


def read_release(path: str) -> dict:
    """Load + verify one release entry; raises
    :class:`~bigdl_tpu.utils.file_io.CorruptCheckpoint` on frame/payload
    corruption and :class:`ReleaseRejected` on a well-formed blob that is
    not a release entry."""
    blob = file_io.load(path)
    if not isinstance(blob, dict) or blob.get("format") != RELEASE_FORMAT:
        got = (blob.get("format") if isinstance(blob, dict)
               else type(blob).__name__)
        raise ReleaseRejected(f"{path}: not a release entry "
                              f"(format {got!r})")
    return blob


# ---------------------------------------------------------------------------
# the serving side: the deployment controller
# ---------------------------------------------------------------------------


class DeployController:
    """Watch a release lineage and drive a live server's swap/canary path
    (see module docstring).

    ``server`` needs ``swap(source, canary_fraction=)`` + ``stats()``
    (InferenceServer; a stub suffices in tests).  All public state
    (counters, timeline, frozen flag) is lock-guarded; the watch loop
    runs on one daemon thread started by :meth:`start`."""

    def __init__(self, server, lineage_dir: str, *,
                 canary_fraction: Optional[float] = None,
                 rollback_budget: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 decision_timeout: Optional[float] = None,
                 max_unavailable: Optional[int] = None,
                 since: int = 0, clock=None,
                 timeline_limit: int = 256):
        self.server = server
        #: fleet mode: a serving target declaring ``fleet = True``
        #: (serve/fleetfront.FleetFront) gets releases fanned out
        #: member-by-member — canary on member 0, then rolling swaps
        #: with at most `max_unavailable` members in-swap at a time
        self.fleet_mode = bool(getattr(server, "fleet", False))
        self.max_unavailable = max(1, int(
            max_unavailable if max_unavailable is not None
            else config.get_int("DEPLOY_MAX_UNAVAILABLE", 1)))
        self.dir = file_io._strip_file_scheme(str(lineage_dir))
        f = (canary_fraction if canary_fraction is not None
             else config.get_float("DEPLOY_CANARY_FRACTION", 0.25))
        # outside (0, 1) means plain full swaps — no canary phase
        self.canary_fraction = float(f) if 0.0 < float(f) < 1.0 else None
        self.rollback_budget = int(
            rollback_budget if rollback_budget is not None
            else config.get_int("DEPLOY_ROLLBACK_BUDGET", 2))
        self.poll_s = float(poll_s if poll_s is not None
                            else config.get_float("DEPLOY_POLL_S", 0.25))
        self.decision_timeout = float(
            decision_timeout if decision_timeout is not None
            else config.get_float("DEPLOY_DECISION_TIMEOUT", 0.0))
        self.clock = clock or time.monotonic
        self.since = int(since)
        self.timeline_limit = int(timeline_limit)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.counts: Dict[str, int] = {
            "seen": 0, "deployed": 0, "promoted": 0, "rolled_back": 0,
            "rejected": 0}
        self.consecutive_rollbacks = 0
        self.frozen: Optional[str] = None   # freeze reason, None = healthy
        self.last_release: Optional[int] = None
        self.timeline: List[dict] = []

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "DeployController":
        if self._thread is not None:
            return self
        attach = getattr(self.server, "attach_deploy", None)
        if attach is not None:
            attach(self)   # stats()["deploy"] / /v1/stats integration
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bigdl-deploy-controller")
        self._thread.start()
        logger.info("deploy: controller watching %s (canary_fraction=%s, "
                    "rollback_budget=%d)", self.dir,
                    self.canary_fraction, self.rollback_budget)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
        self._thread = None

    def healthy(self) -> bool:
        """False once frozen (rollback budget spent, decision timeout, or
        a controller crash) — the outer orchestrator's replace-me signal,
        surfaced in ``/v1/stats`` and ``/v1/versions``."""
        return self.frozen is None

    # -- the watch loop -------------------------------------------------

    def _loop(self) -> None:
        telemetry.thread_name("deploy controller")
        stop = lambda: self._stop.is_set() or self.frozen is not None  # noqa: E731
        try:
            for rid, path in file_io.watch_lineage(
                    self.dir, since=self.since, pattern=RELEASE_PATTERN,
                    poll=self.poll_s, clock=self.clock,
                    sleep=lambda s: self._stop.wait(s), stop=stop):
                self._handle(rid, path)
        except Exception as e:  # noqa: BLE001 — a crashed controller must
            # flag itself unhealthy, not die silently while the operator
            # believes deployments still flow
            logger.exception("deploy: controller loop crashed")
            self._freeze(self.last_release,
                         f"controller error: {type(e).__name__}: {e}")

    def _handle(self, rid: int, path: str) -> None:
        with self._lock:
            self.counts["seen"] += 1
            self.last_release = rid
        try:
            entry = self._verify(rid, path)
        except ReleaseRejected as e:
            self._quarantine(path)
            self._record("rejected", rid, reason=e)
            return
        try:
            self._deploy(rid, entry)
        except Exception as e:  # noqa: BLE001 — a release whose swap
            # fails (unbuildable module, engine error) is rejected typed;
            # the feed keeps flowing
            self._record("rejected", rid, reason=e)

    def _verify(self, rid: int, path: str) -> dict:
        """CRC-verify the entry AND the snapshot it points at before any
        of it goes near traffic; raises :class:`ReleaseRejected`."""
        try:
            entry = read_release(path)
        except (file_io.CorruptCheckpoint, OSError) as e:
            raise ReleaseRejected(
                f"release {rid}: unreadable entry "
                f"({type(e).__name__}: {e})", rid) from e
        model_path = entry.get("model_path") or ""
        fs = file_io.get_filesystem(model_path or self.dir)
        if not model_path or not fs.exists(model_path):
            # trainer and server may mount the lineage at different
            # paths: fall back to the snapshot's basename beside the dir
            alt = file_io._join(self.dir, entry.get("model_name") or "")
            if entry.get("model_name") and \
                    file_io.get_filesystem(alt).exists(alt):
                model_path = alt
            else:
                raise ReleaseRejected(
                    f"release {rid}: snapshot {model_path or '<none>'} "
                    "does not exist (pruned or quarantined after "
                    "publication)", rid)
        try:
            file_io.verify(model_path)
        except (file_io.CorruptCheckpoint, OSError) as e:
            raise ReleaseRejected(
                f"release {rid}: snapshot {model_path} failed "
                f"verification ({type(e).__name__}: {e})", rid) from e
        want = entry.get("fingerprint")
        if want is not None:
            got = file_io.frame_fingerprint(model_path)
            if got is None or tuple(got) != tuple(want):
                raise ReleaseRejected(
                    f"release {rid}: snapshot {model_path} fingerprint "
                    f"{got} != published {tuple(want)} (rewritten after "
                    "publication)", rid)
        entry["_model_path"] = model_path
        return entry

    def _quarantine(self, path: str) -> None:
        """Rename a rejected entry aside (``.corrupt``): it drops out of
        every future lineage walk but stays on storage for forensics —
        same contract as checkpoint quarantine."""
        fs = file_io.get_filesystem(path)
        try:
            if fs.exists(path):
                fs.rename(path, path + ".corrupt")
                logger.warning("deploy: quarantined release entry %s -> "
                               "%s.corrupt", path, path)
        except Exception as e:  # noqa: BLE001 — best-effort: the feed
            # must keep moving even when the store refuses the rename
            logger.warning("deploy: could not quarantine %s: %s", path, e)

    def _deploy(self, rid: int, entry: dict) -> None:
        fraction = self.canary_fraction
        kwargs = {"canary_fraction": fraction}
        if self.fleet_mode:
            # FleetFront.swap canaries member 0, waits the member's own
            # comparator out, then rolls the rest with this bound — the
            # verdict lands in stats()["canary"] for _await_decision
            kwargs["max_unavailable"] = self.max_unavailable
        vid = self.server.swap(entry["_model_path"], **kwargs)
        self._record("deployed", rid, version=vid,
                     neval=entry.get("neval"),
                     **({"fleet": True} if self.fleet_mode else {}))
        if fraction is None:
            # plain full swap: live immediately, nothing to observe
            with self._lock:
                self.consecutive_rollbacks = 0
            self._record("promoted", rid, version=vid,
                         neval=entry.get("neval"), verdict="full_swap")
            return
        verdict = self._await_decision(vid)
        if verdict is None:
            return  # stopping — leave the in-flight canary to the server
        state = verdict.get("state")
        if state == "promoted":
            with self._lock:
                self.consecutive_rollbacks = 0
            self._record("promoted", rid, version=vid,
                         neval=entry.get("neval"), verdict=verdict)
        elif state == "rolled_back":
            with self._lock:
                self.consecutive_rollbacks += 1
                over = self.consecutive_rollbacks > self.rollback_budget
            self._record("rolled_back", rid, version=vid,
                         neval=entry.get("neval"), verdict=verdict)
            if over:
                self._freeze(rid, f"{self.consecutive_rollbacks} "
                             "consecutive canary rollbacks (budget "
                             f"{self.rollback_budget}) — the release "
                             "feed looks systematically bad")
        else:
            # an undecided canary past the deadline: proceeding would
            # stack canaries; freeze and flag instead of guessing
            self._freeze(rid, f"canary v{vid} (release {rid}) undecided "
                         f"after {self.decision_timeout:g}s")

    def _await_decision(self, vid: int) -> Optional[dict]:
        """Poll the server's canary summary until version `vid` resolves
        (promoted/rolled_back), the decision deadline passes, or stop()
        is requested (returns None)."""
        t0 = self.clock()
        while not self._stop.is_set():
            try:
                summary = (self.server.stats() or {}).get("canary") or {}
            except Exception:  # noqa: BLE001 — a stats hiccup is not a
                # verdict; keep waiting
                summary = {}
            if summary.get("version") == vid and \
                    summary.get("state") in ("promoted", "rolled_back"):
                return dict(summary)
            if 0 < self.decision_timeout < self.clock() - t0:
                return {"state": "timeout"}
            self._stop.wait(0.02)
        return None

    # -- timeline / stats -----------------------------------------------

    def _record(self, action: str, rid: int, *, version=None, neval=None,
                reason=None, verdict=None, fleet=None) -> None:
        ev = {"release": int(rid), "action": action,
              "time": round(time.time(), 3)}
        if version is not None:
            ev["version"] = int(version)
        if fleet:
            ev["fleet"] = True
        if neval is not None:
            ev["neval"] = int(neval)
        if reason is not None:
            ev["reason"] = str(reason)
            ev["reason_type"] = type(reason).__name__
        if isinstance(verdict, dict):
            ev["verdict"] = {k: verdict[k] for k in
                             ("state", "reason", "reason_type", "routed",
                              "total") if k in verdict}
        elif verdict is not None:
            ev["verdict"] = str(verdict)
        with self._lock:
            if action in self.counts:
                self.counts[action] += 1
            self.timeline.append(ev)
            del self.timeline[:-self.timeline_limit]
            snap = dict(self.counts)
            consecutive = self.consecutive_rollbacks
            frozen = self.frozen is not None
        telemetry.instant(f"deploy.{action}", cat="deploy", release=rid,
                          **({"reason": str(reason)} if reason else {}))
        telemetry.counter("deploy", deployed=snap["deployed"],
                          promoted=snap["promoted"],
                          rolled_back=snap["rolled_back"],
                          rejected=snap["rejected"],
                          consecutive_rollbacks=consecutive,
                          frozen=int(frozen))
        log = logger.error if action in ("rejected", "rolled_back",
                                         "frozen") else logger.info
        log("deploy: release %d %s%s", rid, action,
            f" — {reason}" if reason else
            (f" (version {version})" if version is not None else ""))

    def _freeze(self, rid, reason: str) -> None:
        with self._lock:
            if self.frozen is not None:
                return
            self.frozen = reason
        telemetry.instant("deploy.frozen", cat="deploy", reason=reason)
        self._record("frozen", rid if rid is not None else -1,
                     reason=ReleaseRejected(reason))
        logger.error("deploy: controller FROZEN — %s; no further "
                     "releases will deploy until it is restarted", reason)

    def stats(self) -> dict:
        """The ``stats()["deploy"]`` blob (bounded timeline tail)."""
        with self._lock:
            out = {"watching": self.dir,
                   "healthy": self.frozen is None,
                   "frozen": self.frozen is not None,
                   "frozen_reason": self.frozen,
                   "canary_fraction": self.canary_fraction,
                   "rollback_budget": self.rollback_budget,
                   "consecutive_rollbacks": self.consecutive_rollbacks,
                   "last_release": self.last_release}
            out.update(self.counts)
            out["timeline"] = [dict(e) for e in self.timeline[-16:]]
        return out

    def versions(self) -> dict:
        """The FULL model-version timeline (``/v1/versions``)."""
        with self._lock:
            return {"healthy": self.frozen is None,
                    "frozen": self.frozen is not None,
                    "frozen_reason": self.frozen,
                    "last_release": self.last_release,
                    "timeline": [dict(e) for e in self.timeline]}
