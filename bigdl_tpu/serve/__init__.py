"""Online inference serving: dynamic batching, replica pool, hot swap.

The training stack's online counterpart (ROADMAP north star: "serves
heavy traffic"): concurrent single requests are coalesced into padded
fixed-shape batches (serve/batcher.py) and drained by a pool of replica
worker threads running the same mesh-sharded forward as bulk
`Predictor.predict` (serve/server.py).  Bounded queue + per-request
deadlines give typed load shedding (`ServerOverloaded`,
`RequestTimeout`) instead of latency collapse; `swap()` hot-loads a new
checkpoint version (optionally int8-quantized) with zero dropped
requests.  The control plane (serve/control.py) makes the pool
self-healing: dead/silent replicas restart within a bounded budget,
`swap(canary_fraction=...)` auto-promotes or auto-rolls-back a canary
on a rolling p99/error comparison, and admission is tenant/priority
aware (token-bucket quotas, shed-lowest-priority-first).  The
scale-out layer makes the pool elastic and placement topology-aware:
a queue-wait-driven autoscaler (serve/autoscale.py) grows/shrinks the
pool between bounds with AOT-warm spawn, a `TopologyRouter`
(serve/router.py) places mesh-sharded replicas on disjoint device
subsets and routes by (bucket, per-replica queue depth), and recorded
request traces (serve/tracefile.py) replay at 10-100x in `bench.py
--serve --replay` reporting per-tenant SLO attainment.  The continuous
deployment layer (serve/continuous.py) closes the optimizer->canary
loop: the trainer's checkpoint path publishes CRC-framed release
entries and a `DeployController` watches the lineage, verifies each
entry, canaries it into the live server and promotes or rolls back on
the control plane's comparator — with a bounded consecutive-rollback
budget and a full model-version timeline (docs/continuous.md).  The
fleet layer (serve/fleet.py + serve/fleetfront.py) lifts the replica
state machine to OS PROCESSES: worker processes
(tools/serve_worker.py) register CRC-framed member records + liveness
heartbeats into a shared fleet dir (the same file_io plumbing elastic
training trusts), a `FleetSupervisor` condemns silent members by
generation bump and respawns them warm through the shared AOT cache
within a restart budget, and a `FleetFront` routes by (bucket, member
queue depth) over HTTP with bounded retry-on-next-member and rolling
`swap` fan-out for the DeployController's fleet mode.  The generative
layer (serve/decode.py) brings continuous-batching autoregressive
decode to the same stack: a `DecodeEngine` runs a persistent step loop
over fixed KV-cache slots (prefill/decode as separate AOT-cached
executables on a (slots, cache-page) bucket ladder), sequences join
and leave per step, and admission rides a per-sequence `DecodeQueue`
(deadline = time-to-last-token, tenant quotas, priority eviction).
See docs/serving.md.
"""

from .autoscale import AutoScaler
from .batcher import (DecodeQueue, DynamicBatcher, PendingRequest,
                      RequestTimeout, ServeError, ServerClosed,
                      ServerOverloaded, default_buckets, fit_bucket,
                      pad_rows, pad_tail, predict_in_fixed_batches)
from .decode import DecodeEngine, SlotFault, page_ladder
from .continuous import (DeployController, ReleasePublisher,
                         ReleaseRejected, read_release)
from .control import (CanaryController, CanaryRejected, QuotaExceeded,
                      ReplicaLostError, ReplicaMonitor, TenantQuotas)
from .fleet import FleetSupervisor, MemberLostError
from .fleetfront import FleetFront
from .router import PlacementError, TopologyRouter, plan_subsets
from .server import InferenceServer, ModelVersion
from .tracefile import (TraceEvent, TraceFormatError, TraceRecorder,
                        read_trace, replay, resolve_outcomes, slo_report,
                        write_trace)

__all__ = ["InferenceServer", "ModelVersion", "DynamicBatcher",
           "PendingRequest", "ServeError", "ServerOverloaded",
           "ServerClosed", "RequestTimeout", "ReplicaLostError",
           "CanaryRejected", "QuotaExceeded", "TenantQuotas",
           "CanaryController", "ReplicaMonitor", "default_buckets",
           "pad_rows", "pad_tail", "fit_bucket", "predict_in_fixed_batches",
           "AutoScaler", "TopologyRouter", "PlacementError",
           "plan_subsets", "TraceEvent", "TraceFormatError",
           "TraceRecorder", "read_trace", "write_trace", "replay",
           "resolve_outcomes", "slo_report",
           "DeployController", "ReleasePublisher", "ReleaseRejected",
           "read_release",
           "FleetSupervisor", "FleetFront", "MemberLostError",
           "DecodeEngine", "DecodeQueue", "SlotFault", "page_ladder"]
