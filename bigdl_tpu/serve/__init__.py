"""Online inference serving: dynamic batching, replica pool, hot swap.

The training stack's online counterpart (ROADMAP north star: "serves
heavy traffic"): concurrent single requests are coalesced into padded
fixed-shape batches (serve/batcher.py) and drained by a pool of replica
worker threads running the same mesh-sharded forward as bulk
`Predictor.predict` (serve/server.py).  Bounded queue + per-request
deadlines give typed load shedding (`ServerOverloaded`,
`RequestTimeout`) instead of latency collapse; `swap()` hot-loads a new
checkpoint version (optionally int8-quantized) with zero dropped
requests.  The control plane (serve/control.py) makes the pool
self-healing: dead/silent replicas restart within a bounded budget,
`swap(canary_fraction=...)` auto-promotes or auto-rolls-back a canary
on a rolling p99/error comparison, and admission is tenant/priority
aware (token-bucket quotas, shed-lowest-priority-first).  See
docs/serving.md.
"""

from .batcher import (DynamicBatcher, PendingRequest, RequestTimeout,
                      ServeError, ServerClosed, ServerOverloaded,
                      default_buckets, pad_rows, predict_in_fixed_batches)
from .control import (CanaryController, CanaryRejected, QuotaExceeded,
                      ReplicaLostError, ReplicaMonitor, TenantQuotas)
from .server import InferenceServer, ModelVersion

__all__ = ["InferenceServer", "ModelVersion", "DynamicBatcher",
           "PendingRequest", "ServeError", "ServerOverloaded",
           "ServerClosed", "RequestTimeout", "ReplicaLostError",
           "CanaryRejected", "QuotaExceeded", "TenantQuotas",
           "CanaryController", "ReplicaMonitor", "default_buckets",
           "pad_rows", "predict_in_fixed_batches"]
