"""Fleet front tier: HTTP routing over supervised worker processes.

The in-process :class:`TopologyRouter` picks a member by
(bucket, per-replica queue depth) over direct queue handles; this front
tier keeps exactly that dispatch decision but the members are separate
PROCESSES found through the serve/fleet registry, reached over their
stdlib HTTP endpoints (tools/serve_http.py's wire format):

- **liveness**: the member set is the registry filtered by heartbeat
  publication freshness (``fleet.member_alive``) — a stale registry
  entry (record without a live heartbeat, or a condemned generation)
  can never attract traffic.  Refreshes are cached for ``refresh_s`` so
  the hot path does not list the fleet dir per request.
- **routing**: the router's key, computed over the front's LOCAL
  in-flight counters (the exact queue depth lives in another process;
  in-flight-per-member is its unbiased local estimate): fewest pending
  full buckets first (``inflight // max_batch``), then prefer joining a
  partial batch already coalescing, then raw in-flight, then index.
- **failure**: per-member HTTP timeout; a connection failure or 5xx
  from one member retries on the NEXT member, bounded — safe because
  predicts are idempotent (same row, same weights, same answer; a
  retried row costs duplicate compute, never a duplicate effect).
  Typed member errors map back to the typed serve exceptions
  (429 -> ServerOverloaded, 504 -> RequestTimeout); when no live member
  remains the front raises :class:`MemberLostError` — which the HTTP
  front end maps to 503 + Retry-After, so a fleet-wide outage
  propagates as back-off, not as a stack trace.
- **capture/replay**: ``record_trace``/``stop_trace`` note offered
  traffic exactly like the router, so ``serve/tracefile.py`` replay
  (and its zero-accepted-loss accounting) applies unchanged.
- **rolling deploy**: :meth:`swap` is the fleet mode the
  DeployController drives — canary on member 0 via the member's own
  comparator, wait the verdict out over its ``/v1/stats``, then roll
  the release member-by-member with at most ``max_unavailable``
  members in-swap at a time.  The verdict is mirrored into
  ``stats()["canary"]`` so ``DeployController._await_decision`` works
  against a fleet exactly as against one server.

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_FLEET_TIMEOUT_S`` | per-member HTTP request timeout, seconds | 60 |
| ``BIGDL_TPU_FLEET_RETRIES`` | retry-on-next-member attempts after the first | 2 |
| ``BIGDL_TPU_FLEET_REFRESH_S`` | registry cache refresh interval, seconds | 0.25 |
| ``BIGDL_TPU_FLEET_MAX_UNAVAILABLE`` | members concurrently in-swap during a rolling deploy | 1 |
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

import numpy as np

from ..utils import config, metrics_export, telemetry
from . import fleet
from .batcher import (RequestTimeout, ServeError, ServerClosed,
                      ServerOverloaded)
from .fleet import MemberLostError

logger = logging.getLogger("bigdl_tpu")

__all__ = ["FleetFront"]


class _FleetHandle:
    """PendingRequest-shaped future over one dispatched request —
    ``result(timeout)`` / ``latency_s`` / ``version`` are what replay
    resolution (serve/tracefile.resolve_outcomes) consumes."""

    __slots__ = ("_future", "latency_s", "version")

    def __init__(self, future):
        self._future = future
        self.latency_s = None
        self.version = None

    def result(self, timeout: Optional[float] = None):
        out, version, latency_s = self._future.result(timeout)
        self.version = version
        self.latency_s = latency_s
        return out


class FleetFront:
    """Route requests over the fleet registry (see module docstring).

    Duck-type compatible with :class:`InferenceServer` where the deploy
    controller and the replay tooling need it: ``submit`` / ``predict``
    / ``swap`` / ``stats`` / ``healthy`` / ``record_trace`` /
    ``stop_trace``."""

    #: continuous.DeployController switches to rolling fleet fan-out
    #: when the serving target declares itself a fleet
    fleet = True

    def __init__(self, fleet_dir: str, *, timeout_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 refresh_s: Optional[float] = None,
                 lost_after_s: Optional[float] = None,
                 max_unavailable: Optional[int] = None,
                 decision_timeout: float = 60.0,
                 max_workers: int = 32, clock=None):
        self.fleet_dir = str(fleet_dir)
        self.timeout_s = (config.get_float("FLEET_TIMEOUT_S", 60.0)
                          if timeout_s is None else float(timeout_s))
        self.retries = (config.get_int("FLEET_RETRIES", 2)
                        if retries is None else int(retries))
        self.refresh_s = (config.get_float("FLEET_REFRESH_S", 0.25)
                          if refresh_s is None else float(refresh_s))
        self.lost_after_s = (fleet.lost_after_seconds()
                             if lost_after_s is None else float(lost_after_s))
        self.max_unavailable = max(
            1, config.get_int("FLEET_MAX_UNAVAILABLE", 1)
            if max_unavailable is None else int(max_unavailable))
        self.decision_timeout = float(decision_timeout)
        self.clock = clock or time.monotonic
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="bigdl-fleet")
        self._lock = threading.Lock()
        self._inflight: Dict[int, int] = {}
        self._routed: Dict[int, int] = {}
        self._retried = 0
        self._deploying: set = set()
        self._deploy_stats = {"rolled": 0, "max_concurrent": 0}
        self._registry: Dict[int, dict] = {}
        self._registry_at = float("-inf")
        self._last_canary: Optional[dict] = None
        self._recorder = None
        self._closed = False

    # -- registry / liveness --------------------------------------------

    def _refresh(self, force: bool = False) -> Dict[int, dict]:
        now = self.clock()
        with self._lock:
            if not force and now - self._registry_at < self.refresh_s:
                return self._registry
        registry = fleet.read_registry(self.fleet_dir)
        live = {}
        for idx, record in registry.items():
            if fleet.member_alive(self.fleet_dir, idx,
                                  generation=record.get("generation"),
                                  lost_after=self.lost_after_s):
                live[idx] = record
        with self._lock:
            self._registry = live
            self._registry_at = now
        return live

    def members(self) -> Dict[int, dict]:
        """Current LIVE member records (index -> record)."""
        return dict(self._refresh())

    def healthy(self) -> bool:
        return bool(self._refresh(force=True))

    # -- routing --------------------------------------------------------

    def _pick(self, exclude=()) -> Optional[int]:
        """The TopologyRouter dispatch key over local in-flight counts:
        (pending full buckets, no-partial-coalescing, in-flight, index).
        Members currently in a rolling swap are deprioritized (not
        excluded — with one survivor, a deploying member still beats a
        503)."""
        live = self._refresh()
        best = best_key = None
        with self._lock:
            for i, record in live.items():
                if i in exclude:
                    continue
                d = self._inflight.get(i, 0)
                mb = int(record.get("max_batch") or 8)
                key = (1 if i in self._deploying else 0,
                       d // mb, 0 if d % mb else 1, d, i)
                if best_key is None or key < best_key:
                    best, best_key = i, key
        return best

    def _url(self, record: dict, route: str) -> str:
        return (f"http://{record.get('host', '127.0.0.1')}:"
                f"{record['port']}{route}")

    def _post(self, record: dict, route: str, body: dict,
              timeout: Optional[float] = None,
              request_id: Optional[str] = None):
        """POST JSON to one member; returns (status, parsed body).
        Raises URLError/OSError on transport failure (the caller's
        retry-on-next-member signal).  ``request_id`` rides the
        ``X-BigDL-Request-Id`` header so the member joins the request's
        flow (and echoes the id back)."""
        headers = {"Content-Type": "application/json"}
        if request_id is not None:
            headers[telemetry.REQUEST_ID_HEADER] = request_id
        req = urllib.request.Request(
            self._url(record, route), data=json.dumps(body).encode(),
            headers=headers, method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout_s) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            # a TYPED member answer (429/504/...) — not a transport
            # failure; surface the body for the error mapping
            try:
                return e.code, json.loads(e.read().decode())
            except Exception:  # noqa: BLE001 — unparseable error body
                return e.code, {"error": str(e)}

    def _get(self, record: dict, route: str,
             timeout: Optional[float] = None) -> dict:
        with urllib.request.urlopen(self._url(record, route),
                                    timeout=timeout or self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _get_text(self, record: dict, route: str,
                  timeout: Optional[float] = None) -> str:
        with urllib.request.urlopen(self._url(record, route),
                                    timeout=timeout or self.timeout_s) as r:
            return r.read().decode()

    # -- live metrics (GET /metrics on the front) ------------------------

    def metrics_text(self) -> str:
        """The front's Prometheus exposition: its own registry (request
        latency/SLO as routed callers saw them, failovers included)
        followed by the fleet-wide rollup — every live member's
        ``/metrics`` scraped and re-exported under a ``fleet_`` prefix
        with ``member`` labels plus per-series fleet sums.  One scrape of
        the front sees the whole fleet; a member whose scrape fails is
        skipped (its supervisor owns it), not fatal."""
        reg = metrics_export.registry()
        own = reg.render() if reg is not None else ""
        member_texts: Dict[str, str] = {}
        for i, record in self._refresh().items():
            try:
                member_texts[str(i)] = self._get_text(record, "/metrics")
            except Exception:  # noqa: BLE001 — scrape best-effort
                continue
        return metrics_export.render_rollup(own, member_texts)

    @staticmethod
    def _typed(status: int, body: dict):
        """One member's typed HTTP rejection -> the typed serve
        exception the caller (and the replay SLO classifier) expects."""
        msg = body.get("error") or f"member answered {status}"
        if status == 429:
            err = ServerOverloaded(msg)
            err.retry_after_s = body.get("retry_after_s")
            return err
        if status == 504:
            return RequestTimeout(msg)
        if status == 400:
            return ServeError(msg)
        return None  # 5xx/503: the caller retries on the next member

    def _no_member(self) -> MemberLostError:
        return MemberLostError(
            "fleet: no live member in the registry — every worker is "
            "lost, condemned, or degraded", retry_after_s=1.0)

    def _finish_flow(self, rid, t0, status: str) -> None:
        """Close the request's flow and feed the front's metrics (the
        front MINTED the id, so it owns the "f" phase)."""
        if rid is not None:
            telemetry.flow_finish(rid, hop="front.done", status=status)
        dt = self.clock() - t0
        if rid is not None:
            telemetry.complete("fleet.request", dt, cat="fleet",
                               status=status, req=rid)
        reg = metrics_export._REGISTRY
        if reg is not None:
            reg.observe_request(dt, status)

    def _dispatch(self, x: np.ndarray, deadline_ms, tenant, priority,
                  rid=None):
        """Runs in the pool: route, POST, retry-on-next-member (bounded,
        idempotent predicts only).  Returns (outputs, version,
        latency_s)."""
        t0 = self.clock()
        body = {"inputs": x.tolist(), "timeout_s": self.timeout_s}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        if tenant is not None:
            body["tenant"] = tenant
        if priority:
            body["priority"] = int(priority)
        tried: set = set()
        last_exc = None
        for _attempt in range(self.retries + 1):
            i = self._pick(exclude=tried)
            if i is None:
                break
            record = self._refresh().get(i)
            if record is None:
                tried.add(i)
                continue
            with self._lock:
                self._inflight[i] = self._inflight.get(i, 0) + 1
                self._routed[i] = self._routed.get(i, 0) + 1
            if rid is not None:
                telemetry.flow_step(rid, hop="front.send", member=i)
            try:
                status, resp = self._post(record, "/v1/predict", body,
                                          request_id=rid)
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                # transport failure: the member died under us (kill -9
                # drill) or never bound — try the next one
                last_exc = e
                tried.add(i)
                with self._lock:
                    self._retried += 1
                telemetry.instant("fleet.retry", cat="fleet", member=i,
                                  error=type(e).__name__)
                if rid is not None:
                    # the failover lands on this request's flow: the
                    # arrow chain shows WHICH member the request lost
                    telemetry.flow_step(rid, hop="fleet.retry", member=i,
                                        error=type(e).__name__)
                continue
            finally:
                with self._lock:
                    self._inflight[i] = max(self._inflight.get(i, 1) - 1, 0)
            if status == 200:
                out = np.asarray(resp["outputs"], np.float32)
                self._finish_flow(rid, t0, "ok")
                return (out, resp.get("version"),
                        float(resp.get("latency_ms", 0.0)) / 1e3)
            err = self._typed(status, resp)
            if err is not None:
                self._finish_flow(rid, t0, type(err).__name__)
                raise err
            # 503 / 5xx: that member is unhealthy or mid-replacement —
            # its supervisor owns it; route around
            last_exc = ServerClosed(resp.get("error") or
                                    f"member {i} answered {status}")
            tried.add(i)
            with self._lock:
                self._retried += 1
            telemetry.instant("fleet.retry", cat="fleet", member=i,
                              status=status)
            if rid is not None:
                telemetry.flow_step(rid, hop="fleet.retry", member=i,
                                    status=status)
        self._finish_flow(rid, t0, "MemberLostError")
        if last_exc is not None and not self._refresh(force=True):
            raise self._no_member()
        if last_exc is not None:
            raise MemberLostError(
                f"fleet: request failed on {len(tried)} member(s) "
                f"({type(last_exc).__name__}: {last_exc}) with retries "
                "exhausted", retry_after_s=1.0)
        raise self._no_member()

    def submit(self, x, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None, priority: int = 0,
               request_id: Optional[str] = None):
        """Admit one sample: returns a handle whose ``result()`` blocks
        on the HTTP round trip (+ bounded failover).  Raises
        :class:`MemberLostError` at ADMISSION when no member is live —
        the typed 503 the replay accounting records as a shed, never a
        silently lost accepted request.  When tracing is on, the front
        mints the request's flow id here (``request_id`` overrides — a
        caller propagating an upstream id) and every hop downstream
        links to it."""
        if self._closed:
            raise ServerClosed("fleet: front tier is closed")
        x = np.asarray(x, np.float32)
        if self._recorder is not None:
            self._recorder.note(x, tenant=tenant, priority=priority,
                                deadline_ms=deadline_ms)
        rid = request_id
        if rid is None:
            rid = telemetry.mint_request_id()  # None when tracing is off
        if rid is not None:
            telemetry.flow_start(rid, hop="front.admit")
        if self._pick() is None:
            if rid is not None:
                telemetry.flow_finish(rid, hop="front.done",
                                      status="MemberLostError")
            raise self._no_member()
        return _FleetHandle(self._pool.submit(
            self._dispatch, x, deadline_ms, tenant, priority, rid))

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    # -- rolling deploy (the DeployController's fleet mode) -------------

    def member_stats(self, index: int) -> Optional[dict]:
        record = self._refresh(force=True).get(index)
        if record is None:
            return None
        try:
            return self._get(record, "/v1/stats")
        except Exception:  # noqa: BLE001 — a stats hiccup is not a
            # verdict; the caller polls
            return None

    def _await_member_canary(self, index: int, vid: int) -> dict:
        """Poll the canary MEMBER's own comparator verdict for version
        `vid` (promoted/rolled_back), bounded by ``decision_timeout``."""
        t0 = self.clock()
        while True:
            st = self.member_stats(index) or {}
            summary = st.get("canary") or {}
            if summary.get("version") == vid and \
                    summary.get("state") in ("promoted", "rolled_back"):
                return dict(summary)
            if 0 < self.decision_timeout < self.clock() - t0:
                return {"state": "timeout", "version": vid}
            time.sleep(0.1)

    def swap(self, source, *, quantized: bool = False,
             canary_fraction: Optional[float] = None,
             max_unavailable: Optional[int] = None) -> int:
        """Fan a release over the fleet: canary on the lowest-index live
        member first (its own comparator decides under real routed
        traffic), then — only on promotion — roll the remaining members
        with at most `max_unavailable` concurrently in-swap.  Members
        keep serving THROUGH their own zero-drop swap; the bound is the
        blast-radius cap, enforced by deprioritizing in-swap members in
        ``_pick`` and by the fan-out batching here.  The verdict lands
        in ``stats()["canary"]`` for the DeployController."""
        live = self._refresh(force=True)
        if not live:
            raise self._no_member()
        order = sorted(live)
        canary_idx = order[0]
        bound = max(1, int(max_unavailable if max_unavailable is not None
                           else self.max_unavailable))
        body = {"source": source if isinstance(source, str) else None,
                "quantized": bool(quantized)}
        if body["source"] is None:
            raise ServeError("fleet: swap source must be a path (the "
                             "members load it in their own processes)")
        telemetry.instant("fleet.deploy", cat="fleet", member=canary_idx,
                          canary=canary_fraction is not None)
        with self._lock:
            self._deploying.add(canary_idx)
        try:
            status, resp = self._post(live[canary_idx], "/v1/swap",
                                      dict(body,
                                           canary_fraction=canary_fraction))
        finally:
            with self._lock:
                self._deploying.discard(canary_idx)
        if status != 200:
            raise ServeError(f"fleet: canary swap on member {canary_idx} "
                             f"failed: {resp.get('error')}")
        vid = int(resp["version"])
        if canary_fraction is not None:
            verdict = self._await_member_canary(canary_idx, vid)
            verdict["member"] = canary_idx
            with self._lock:
                self._last_canary = verdict
            if verdict.get("state") != "promoted":
                # the canary member already rolled itself back; the rest
                # of the fleet never saw the release
                telemetry.instant("fleet.deploy_rollback", cat="fleet",
                                  member=canary_idx, version=vid)
                return vid
        self._roll(source, order[1:], bound, quantized=quantized)
        with self._lock:
            if canary_fraction is None:
                self._last_canary = {"state": "promoted", "version": vid,
                                     "member": canary_idx,
                                     "reason": "full_swap"}
            else:
                self._last_canary = dict(self._last_canary or {},
                                         rolled=len(order))
        return vid

    def _roll(self, source, indices, bound: int, *,
              quantized: bool = False) -> None:
        """Plain rolling swaps over `indices`, at most `bound`
        concurrently in-swap (each member's own swap is zero-drop; the
        bound caps how much of the fleet is warming at once)."""
        body = {"source": source, "quantized": bool(quantized)}
        for start in range(0, len(indices), bound):
            group = list(indices[start:start + bound])
            with self._lock:
                self._deploying.update(group)
                self._deploy_stats["max_concurrent"] = max(
                    self._deploy_stats["max_concurrent"], len(group))
            try:
                live = self._refresh(force=True)
                futures = {i: self._pool.submit(
                    self._post, live[i], "/v1/swap", body)
                    for i in group if i in live}
                for i, f in futures.items():
                    try:
                        status, resp = f.result(timeout=self.timeout_s * 2)
                        ok = status == 200
                    except Exception as e:  # noqa: BLE001 — a member
                        # that died mid-roll is the supervisor's problem;
                        # its replacement swaps on the next release
                        ok, resp = False, {"error": str(e)}
                    telemetry.instant("fleet.deploy_member", cat="fleet",
                                      member=i, ok=ok,
                                      version=resp.get("version"))
                    with self._lock:
                        self._deploy_stats["rolled"] += 1
                    if not ok:
                        logger.warning("fleet: rolling swap on member %d "
                                       "failed: %s", i, resp.get("error"))
            finally:
                with self._lock:
                    self._deploying.difference_update(group)

    # -- traffic trace capture ------------------------------------------

    def record_trace(self, path: Optional[str] = None, *,
                     limit: Optional[int] = None):
        from .tracefile import TraceRecorder
        if self._recorder is not None and (path is None or
                                           self._recorder.path == path):
            return self._recorder
        self._recorder = TraceRecorder(clock=self.clock, limit=limit,
                                       path=path)
        return self._recorder

    def stop_trace(self, path: Optional[str] = None):
        rec, self._recorder = self._recorder, None
        if rec is None:
            return []
        if path or rec.path:
            rec.save(path)
        return rec.events()

    # -- lifecycle / introspection --------------------------------------

    def close(self) -> None:
        self._closed = True
        self._pool.shutdown(wait=False)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """InferenceServer-shaped alias (the HTTP front end calls
        ``server.stop()`` at shutdown)."""
        del drain, timeout
        self.close()

    def stats(self) -> dict:
        live = self._refresh()
        with self._lock:
            out = {
                "fleet": {
                    "dir": self.fleet_dir,
                    "live": sorted(live),
                    "members": {str(i): {
                        "generation": r.get("generation"),
                        "pid": r.get("pid"),
                        "port": r.get("port"),
                        "inflight": self._inflight.get(i, 0),
                        "routed": self._routed.get(i, 0),
                    } for i, r in live.items()},
                    "retried": self._retried,
                    "deploy": dict(self._deploy_stats),
                },
                "replicas_live": len(live),
                "healthy": bool(live),
            }
            if self._last_canary is not None:
                out["canary"] = dict(self._last_canary)
        if self._recorder is not None:
            out["trace_recording"] = self._recorder.stats()
        return out

