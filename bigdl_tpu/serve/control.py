"""Self-healing serving control plane: replica lifecycle, canary, quotas.

The training stack already closes its detect -> react -> verify loop
(supervision promotes a hang into a typed StallError, the retry loop
recovers from the checkpoint lineage, chaos drills prove it in CI —
docs/robustness.md).  Until this module, the serving side had only the
DETECT half: a wedged replica wrote a crash report and the pool silently
lost capacity forever, a bad ``swap()`` stayed live until a human
noticed, and overload shed traffic blindly with no tenant or priority
awareness.  The MLPerf-pods line of work (PAPERS.md) makes the point
this module acts on: tail-latency SLOs are won by control-plane
reactions, not just fast kernels.

Three reactions, composed from pieces the runtime already has:

- :class:`ReplicaMonitor` — **replica lifecycle**.  Every replica worker
  stamps a local heartbeat (beside its optional supervisor channel); the
  monitor promotes a replica whose beats go silent past
  ``BIGDL_TPU_SERVE_REPLICA_LOST`` — or whose thread has died — into a
  typed :class:`ReplicaLostError`, condemns the old thread (a zombie
  that wakes later hands any held batch back to the queue and exits),
  respawns a replacement, and re-warms the bucket ladder through a fresh
  engine.  With the AOT executable cache armed (utils/aot.py) the
  re-warm is N cache reads — restart is seconds, not an 800 s compile.
  Restarts per replica are bounded (``SERVE_RESTART_BUDGET``) with
  exponential backoff (``SERVE_RESTART_BACKOFF``); past the budget the
  server flips unhealthy (``/healthz`` -> 503) so an outer orchestrator
  replaces the process — self-healing never loops forever on a broken
  host.

- :class:`CanaryController` — **canary + auto-rollback** on top of the
  zero-drop hot swap.  ``swap(source, canary_fraction=f)`` routes a
  deterministic ``f`` slice of device batches to the new version while
  a rolling window compares p99 latency and error rate against the
  incumbent: a regression past ``SERVE_CANARY_LATENCY_RATIO`` /
  ``SERVE_CANARY_ERROR_MARGIN`` rolls the canary back with a typed
  :class:`CanaryRejected` reason in ``stats()``; a clean run of
  ``SERVE_CANARY_MIN_BATCHES`` promotes it.  Rollback checks run from
  the canary's second batch (fast-fail), promotion only after the full
  observation window (slow-promote) — a bad canary never serves more
  than its fraction and never becomes the incumbent.

- :class:`TenantQuotas` — **priority-aware admission**.  Requests carry
  ``tenant``/``priority``; per-tenant token buckets
  (``SERVE_TENANT_QPS``/``_BURST``) reject over-quota tenants with a
  typed :class:`QuotaExceeded` carrying ``retry_after_s`` (HTTP 429 +
  Retry-After in tools/serve_http.py), and under queue pressure the
  batcher sheds the lowest-priority queued request first instead of
  blindly refusing the arrival (serve/batcher.py).

Chaos drills (utils/chaos.py): ``serve.replica@<idx>`` fires once per
non-empty batch on replica ``idx`` (``wedge*N@c`` blocks it
uninterruptibly — the monitor must restart around it with zero accepted
requests lost; ``exit@c`` kills just that worker thread, which requeues
its held batch first); ``serve.canary`` fires once per canary batch
(``stall*S@c`` inflates its latency — the comparator must roll it
back).  ``tools/resilience_smoke.py`` runs both drills exit-coded.

See docs/serving.md "Self-healing & resilience" for the decision tree
and knob table.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, List, Optional

from ..utils import telemetry
from .batcher import ServeError, ServerOverloaded

logger = logging.getLogger("bigdl_tpu")

__all__ = ["ReplicaLostError", "CanaryRejected", "QuotaExceeded",
           "ReplicaExit", "TenantQuotas", "CanaryController",
           "ReplicaMonitor"]


class ReplicaLostError(ServeError):
    """A replica worker died or went heartbeat-silent past
    ``SERVE_REPLICA_LOST``.  The monitor restarts it (bounded budget);
    the error surfaces in ``stats()`` / queued requests only when the
    pool is beyond recovery (restart budget exhausted)."""


class CanaryRejected(ServeError):
    """The canary comparator rolled a candidate version back: its rolling
    p99 latency or error rate regressed past the configured thresholds.
    Recorded (typed) in ``stats()["canary"]`` — the canary never served
    more than its configured fraction and never became the incumbent."""


class QuotaExceeded(ServerOverloaded):
    """A tenant exceeded its token-bucket admission quota
    (``SERVE_TENANT_QPS``).  Subclasses :class:`ServerOverloaded` so the
    HTTP front end's 429 mapping applies; ``retry_after_s`` says when the
    bucket next has a token."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ReplicaExit(BaseException):
    """Internal chaos-drill signal: the ``serve.replica@<idx>`` point's
    ``exit`` action kills exactly one worker THREAD (unlike the
    process-level ``host.lost`` drill).  BaseException so the replica
    loop's broad ``except Exception`` backstop cannot swallow it; the
    worker requeues any held batch, then lets the thread die — the
    monitor detects the dead thread and respawns."""


# ---------------------------------------------------------------------------
# per-tenant token buckets
# ---------------------------------------------------------------------------


class TenantQuotas:
    """Per-tenant token-bucket admission quotas.

    Each tenant owns an independent bucket refilled at ``qps`` tokens/s
    up to ``burst``; one admission takes one token.  An empty bucket
    raises :class:`QuotaExceeded` with ``retry_after_s`` = seconds until
    the next token — typed backpressure per tenant, so one chatty tenant
    exhausts its own quota instead of the shared queue.  Clock-injectable
    (wall-clock-free under test)."""

    def __init__(self, qps: float, burst: Optional[float] = None,
                 clock=None):
        self.qps = float(qps)
        self.burst = float(burst) if burst and float(burst) > 0 \
            else max(2.0 * self.qps, 1.0)
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._buckets: Dict[str, tuple] = {}  # tenant -> (tokens, stamp)
        self.denied = 0
        self.denied_by_tenant: Dict[str, int] = {}

    def admit(self, tenant: Optional[str]) -> None:
        """Take one token from `tenant`'s bucket (created full on first
        sight); raise :class:`QuotaExceeded` when empty."""
        if self.qps <= 0:
            return
        key = tenant or "default"
        now = self.clock()
        with self._lock:
            tokens, stamp = self._buckets.get(key, (self.burst, now))
            tokens = min(self.burst, tokens + (now - stamp) * self.qps)
            if tokens >= 1.0:
                self._buckets[key] = (tokens - 1.0, now)
                return
            self._buckets[key] = (tokens, now)
            self.denied += 1
            self.denied_by_tenant[key] = \
                self.denied_by_tenant.get(key, 0) + 1
            retry = (1.0 - tokens) / self.qps
        raise QuotaExceeded(
            f"serve: tenant {key!r} over quota ({self.qps:g} req/s, "
            f"burst {self.burst:g}) — retry in {retry:.3f}s",
            retry_after_s=retry)

    def stats(self) -> dict:
        with self._lock:
            return {"qps": self.qps, "burst": self.burst,
                    "denied": self.denied,
                    "denied_by_tenant": dict(self.denied_by_tenant)}


# ---------------------------------------------------------------------------
# canary comparator
# ---------------------------------------------------------------------------


def _p99(values) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    return vs[min(int(0.99 * len(vs)), len(vs) - 1)]


class CanaryController:
    """Weighted routing + rolling p99/error comparator for one candidate
    :class:`~bigdl_tpu.serve.server.ModelVersion`.

    All methods are called under the server's data-path lock (brief):
    routing and observation are deterministic, no RNG, no internal lock.

    Routing: :meth:`route` admits the canary for batch ``k`` only while
    ``routed/total <= fraction`` stays true AFTER the admission — the
    canary can never serve more than its fraction (the acceptance bound
    ``resilience_smoke`` asserts).

    Decision: from the canary's 2nd batch every observation runs the
    ROLLBACK comparators (error rate beyond the incumbent's +
    ``error_margin``; rolling-window p99 beyond ``latency_ratio`` x the
    incumbent's).  PROMOTION needs ``min_batches`` clean canary batches
    AND an equal incumbent observation window — fast-fail, slow-promote.
    """

    def __init__(self, version, fraction: float, *, min_batches: int = 8,
                 window: int = 64, latency_ratio: float = 2.0,
                 error_margin: float = 0.05):
        if not 0.0 < float(fraction) < 1.0:
            raise ValueError(
                f"serve: canary_fraction must be in (0, 1), got {fraction} "
                "(use a plain swap() for a full cutover)")
        self.version = version
        self.fraction = float(fraction)
        self.min_batches = max(int(min_batches), 2)
        self.latency_ratio = float(latency_ratio)
        self.error_margin = float(error_margin)
        self.state = "running"        # running | promoted | rolled_back
        self.reason: Optional[CanaryRejected] = None
        self.routed = 0               # batches sent to the canary
        self.total = 0                # batches routed while running
        self._lat = {False: collections.deque(maxlen=int(window)),
                     True: collections.deque(maxlen=int(window))}
        self._batches = {False: 0, True: 0}
        self._errors = {False: 0, True: 0}

    # -- routing --------------------------------------------------------

    def route(self) -> bool:
        """True when the NEXT batch goes to the canary (deterministic
        counter-based weighting, admissible only while the realized
        fraction stays <= the configured one)."""
        self.total += 1
        if self.routed + 1 <= self.fraction * self.total:
            self.routed += 1
            return True
        return False

    # -- comparator -----------------------------------------------------

    def observe(self, is_canary: bool, dur_s: float,
                errored: bool) -> Optional[str]:
        """Record one finished batch; return ``"promote"``,
        ``"rollback"`` (with :attr:`reason` set), or None (keep
        running)."""
        self._batches[is_canary] += 1
        if errored:
            self._errors[is_canary] += 1
        else:
            self._lat[is_canary].append(float(dur_s))
        nc, nb = self._batches[True], self._batches[False]
        if nc < 2 or nb < 1:
            return None
        err_c = self._errors[True] / nc
        err_b = self._errors[False] / nb
        p99_c, p99_b = _p99(self._lat[True]), _p99(self._lat[False])
        telemetry.counter(
            "serve.canary", err_rate_canary=round(err_c, 4),
            err_rate_base=round(err_b, 4),
            p99_canary_ms=round(p99_c * 1e3, 3) if p99_c else 0.0,
            p99_base_ms=round(p99_b * 1e3, 3) if p99_b else 0.0)
        if err_c > err_b + self.error_margin:
            self.reason = CanaryRejected(
                f"canary v{self.version.id} error rate {err_c:.3f} vs "
                f"incumbent {err_b:.3f} (margin {self.error_margin}) "
                f"after {nc} canary batches")
            return "rollback"
        if (p99_c is not None and p99_b is not None and
                len(self._lat[True]) >= 2 and len(self._lat[False]) >= 2
                and p99_c > p99_b * self.latency_ratio):
            self.reason = CanaryRejected(
                f"canary v{self.version.id} p99 {p99_c * 1e3:.1f}ms vs "
                f"incumbent {p99_b * 1e3:.1f}ms (ratio bound "
                f"{self.latency_ratio}) after {nc} canary batches")
            return "rollback"
        if nc >= self.min_batches and nb >= self.min_batches:
            return "promote"
        return None

    def summary(self) -> dict:
        """The ``stats()["canary"]`` blob (also the terminal record kept
        after promotion/rollback)."""
        out = {"state": self.state, "version": self.version.id,
               "fraction": self.fraction, "routed": self.routed,
               "total": self.total,
               "batches": {"canary": self._batches[True],
                           "incumbent": self._batches[False]},
               "errors": {"canary": self._errors[True],
                          "incumbent": self._errors[False]}}
        if self.reason is not None:
            out["reason"] = str(self.reason)
            out["reason_type"] = type(self.reason).__name__
        return out


# ---------------------------------------------------------------------------
# replica lifecycle monitor
# ---------------------------------------------------------------------------


class ReplicaMonitor:
    """Background watchdog over the server's replica pool (one daemon
    thread, started by ``InferenceServer.start()`` when
    ``SERVE_REPLICA_LOST`` > 0).

    Detection: a replica whose local heartbeat stamp is silent past
    ``deadline`` seconds (a wedged device call, an uninterruptible chaos
    wedge), or whose thread is no longer alive (crashed, chaos exit
    drill).  Reaction: condemn the old generation (the server bumps the
    replica's generation so a zombie that wakes later requeues its held
    batch and exits), then — after an exponential per-replica backoff —
    respawn via ``server._restart_replica`` (fresh engine, bucket ladder
    re-warmed through the AOT cache).  Budget: more than ``budget``
    restarts of one replica marks the server unhealthy instead of
    looping forever.

    Uses the server's (injectable) batcher clock for silence/backoff
    arithmetic; the poll cadence itself is wall-clock (daemon wait)."""

    def __init__(self, server, deadline: float, *, budget: int = 3,
                 backoff: float = 0.1, poll: Optional[float] = None):
        self._server = server
        self.deadline = float(deadline)
        self.budget = int(budget)
        self.backoff = float(backoff)
        self.clock = server.batcher.clock
        self.poll = poll if poll is not None else \
            min(max(self.deadline / 4.0, 0.02), 1.0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pending: Dict[int, float] = {}   # idx -> earliest respawn
        self._counts: Dict[int, int] = {}      # idx -> restarts so far
        self.lost = 0
        self.events: List[dict] = []

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ReplicaMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bigdl-serve-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._thread = None

    # -- the monitor loop -----------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll):
            try:
                self._check()
            except Exception:  # noqa: BLE001 — the watchdog must outlive
                # any single broken respawn/warmup
                logger.exception("serve monitor error (non-fatal)")

    def _check(self) -> None:
        srv = self._server
        if srv.batcher.closed:
            return
        now = self.clock()
        for idx, st in list(srv._replica.items()):
            if idx >= srv.replicas:
                # retired by a pool shrink (serve/autoscale.py): a slot
                # the autoscaler deliberately emptied is not a lost
                # replica — healing it back would fight the controller
                # and burn restart budget
                self._pending.pop(idx, None)
                continue
            due = self._pending.get(idx)
            if due is not None:
                # condemned and waiting out its backoff: respawn when due
                if now >= due:
                    self._pending.pop(idx, None)
                    srv._restart_replica(idx)
                continue
            thread, last = st[0], st[2]
            if thread is None:
                continue
            dead = not thread.is_alive()
            silent = self.deadline > 0 and (now - last) > self.deadline
            if not dead and not silent:
                continue
            age = now - last
            err = ReplicaLostError(
                f"serve: replica {idx} "
                + ("thread died"
                   if dead else f"heartbeat silent {age:.2f}s "
                                f"(deadline {self.deadline:g}s)"))
            self.lost += 1
            self._counts[idx] = self._counts.get(idx, 0) + 1
            n = self._counts[idx]
            self.events.append(
                {"replica": idx, "dead": dead,
                 "age_seconds": round(age, 3), "restart": n,
                 "error_type": type(err).__name__, "error": str(err)})
            telemetry.instant("serve.replica_lost", cat="serve",
                              replica=idx, dead=dead,
                              age_s=round(age, 3), restart=n)
            logger.error("%s — %s", err,
                         "restart budget exhausted; flipping unhealthy"
                         if n > self.budget else
                         f"restart {n}/{self.budget} scheduled")
            srv._condemn_replica(idx)
            if n > self.budget:
                srv._mark_unhealthy(err)
                continue
            # exponential backoff: a replica that keeps dying backs off
            # 1x, 2x, 4x... the base before each respawn attempt
            self._pending[idx] = now + self.backoff * (2 ** (n - 1))

    def stats(self) -> dict:
        return {"lost": self.lost,
                "restarts": dict(self._counts),
                "budget": self.budget,
                "deadline_seconds": self.deadline,
                "events": list(self.events[-8:])}
