"""Queue-driven replica autoscaling: the serving pool tracks offered load.

PR 10 made the pool self-healing (a dead replica is replaced) and PR 6
made replica spawn cheap (the bucket ladder warms from the persistent
AOT executable cache — reads, not compiles), but the pool SIZE was still
a static knob: a diurnal peak melted a small pool into timeouts while a
trough burned a big one idle.  This module closes ROADMAP open item 3's
first leg: an :class:`AutoScaler` controller loop that grows the pool
when the estimated queue wait crosses a target and shrinks it back after
a sustained idle window — elasticity from the telemetry the server
already emits, no new measurement machinery.

Signals (all pre-existing):

- **queue depth** — ``DynamicBatcher.depth()`` (the ``serve`` counter
  track's ``queue_depth`` series);
- **EMA service rate** — seconds/row from ``DynamicBatcher.note_service``
  (the same estimate behind overload ``retry_after_s``);
- **batch activity** — the server's ``batches`` counter (idle = no depth
  AND no batches completing for the whole idle window).

Decision rule (hysteresis on both edges, cooldown between actions)::

    est_wait = depth * row_seconds_ema / live_replicas
    est_wait > target for UP_POLLS consecutive polls  -> scale UP by STEP
    idle (depth == 0, no batches) for IDLE_S seconds  -> scale DOWN by 1

Bounds compose with the PR 10 control plane: the pool never leaves
[min, max], a shrink retires the HIGHEST indices (the ReplicaMonitor
skips retired slots, so a scale-down is never "healed" back and never
burns restart budget), and an UNHEALTHY server (restart budget spent)
freezes the controller — autoscaling must not fight a broken host.

Scale-up goes through the server's existing spawn path: a plain
:class:`~bigdl_tpu.serve.server.InferenceServer` adds worker threads
over the already-warm shared ``_ShardedForward`` (zero compiles by
construction); a :class:`~bigdl_tpu.serve.router.TopologyRouter` member
builds a fresh engine on its device subset and warms its bucket ladder
through the AOT cache — cache READS, not compiles, when the cache holds
that subset's ladder (``stats()["aot"]`` shows zero fresh lowers;
``tools/scale_smoke.py`` asserts it).

Every decision is recorded: a ``serve.autoscale`` instant per action, a
``serve.autoscale`` counter track (replicas / est wait / depth) per
poll, and a bounded event list in ``stats()["autoscale"]``.

Knobs (``BIGDL_TPU_SERVE_AUTOSCALE_*``; constructor args override):

| env var | meaning | default |
|---|---|---|
| ``..._MAX`` | pool size ceiling; > 0 arms the controller | 0 (off) |
| ``..._MIN`` | pool size floor | initial replicas |
| ``..._TARGET_WAIT_MS`` | est. queue wait that triggers growth | 50 |
| ``..._UP_POLLS`` | consecutive over-target polls before growing | 2 |
| ``..._IDLE_S`` | sustained-idle seconds before one shrink step | 2.0 |
| ``..._COOLDOWN_S`` | minimum seconds between scale actions | 0.5 |
| ``..._STEP`` | replicas added per scale-up (shrink is always 1) | 1 |
| ``..._POLL_S`` | controller poll cadence seconds | 0.05 |

The decision arithmetic runs on the target's injectable clock (tests
drive :meth:`AutoScaler.check` directly with a fake clock); only the
poll cadence itself is wall-clock (daemon thread), exactly like
:class:`~bigdl_tpu.serve.control.ReplicaMonitor`.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from ..utils import config, telemetry

logger = logging.getLogger("bigdl_tpu")

__all__ = ["AutoScaler", "autoscale_knobs"]


def autoscale_knobs(initial_replicas: int, overrides: Optional[dict] = None
                    ) -> dict:
    """Resolve the ``BIGDL_TPU_SERVE_AUTOSCALE_*`` env tier into the
    AutoScaler constructor kwargs; ``overrides`` (constructor args, None
    = unset) win per key.  ``max_replicas <= 0`` means "controller off"
    — the server/router checks that before arming."""
    ov = {k: v for k, v in (overrides or {}).items() if v is not None}
    return {
        "min_replicas": int(ov.get(
            "min_replicas",
            config.get_int("SERVE_AUTOSCALE_MIN", initial_replicas))),
        "max_replicas": int(ov.get(
            "max_replicas", config.get_int("SERVE_AUTOSCALE_MAX", 0))),
        "target_wait_ms": float(ov.get(
            "target_wait_ms",
            config.get_float("SERVE_AUTOSCALE_TARGET_WAIT_MS", 50.0))),
        "up_polls": int(ov.get(
            "up_polls", config.get_int("SERVE_AUTOSCALE_UP_POLLS", 2))),
        "idle_s": float(ov.get(
            "idle_s", config.get_float("SERVE_AUTOSCALE_IDLE_S", 2.0))),
        "cooldown_s": float(ov.get(
            "cooldown_s",
            config.get_float("SERVE_AUTOSCALE_COOLDOWN_S", 0.5))),
        "step": int(ov.get(
            "step", config.get_int("SERVE_AUTOSCALE_STEP", 1))),
        "poll_s": float(ov.get(
            "poll_s", config.get_float("SERVE_AUTOSCALE_POLL_S", 0.05))),
    }


class AutoScaler:
    """Queue-wait-driven pool-size controller (see module docstring).

    ``target`` is anything with the scale protocol:

    - ``autoscale_signals() -> {"depth", "row_s_ema", "batches", "live"}``
      (queued rows, EMA seconds/row or None, cumulative served batches,
      live replica count),
    - ``scale_to(n)`` — grow/shrink the pool to ``n`` replicas,
    - ``replicas`` — the current pool target size,
    - ``healthy()`` — False freezes the controller,

    implemented by both :class:`~bigdl_tpu.serve.server.InferenceServer`
    (worker threads over one shared queue) and
    :class:`~bigdl_tpu.serve.router.TopologyRouter` (member replicas on
    device subsets, each with its own queue)."""

    def __init__(self, target, *, min_replicas: int, max_replicas: int,
                 target_wait_ms: float = 50.0, up_polls: int = 2,
                 idle_s: float = 2.0, cooldown_s: float = 0.5,
                 step: int = 1, poll_s: float = 0.05, clock=None):
        if max_replicas < min_replicas:
            raise ValueError(
                f"serve: autoscale max ({max_replicas}) < min "
                f"({min_replicas})")
        if min_replicas < 1:
            raise ValueError(f"serve: autoscale min must be >= 1, got "
                             f"{min_replicas}")
        self.target = target
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.target_wait_s = float(target_wait_ms) / 1000.0
        self.up_polls = max(int(up_polls), 1)
        self.idle_s = float(idle_s)
        self.cooldown_s = float(cooldown_s)
        self.step = max(int(step), 1)
        self.poll_s = float(poll_s)
        self.clock = clock or getattr(
            getattr(target, "batcher", None), "clock", None)
        if self.clock is None:
            import time
            self.clock = time.monotonic
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # decision state (single controller thread; check() under test)
        self._over_target = 0          # consecutive over-target polls
        self._last_action: Optional[float] = None
        self._last_busy: Optional[float] = None
        self._last_batches: Optional[int] = None
        self._last_wait_s = 0.0
        self.scale_ups = 0
        self.scale_downs = 0
        self.events: List[dict] = []

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "AutoScaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bigdl-serve-autoscaler")
        self._thread.start()
        logger.info("serve: autoscaler armed — replicas in [%d, %d], "
                    "target wait %.0fms, idle window %.1fs",
                    self.min_replicas, self.max_replicas,
                    self.target_wait_s * 1e3, self.idle_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the controller must
                # outlive any single broken poll (a member mid-teardown,
                # a telemetry sink error)
                logger.exception("serve autoscaler error (non-fatal)")

    # -- the decision step ----------------------------------------------

    def check(self, now: Optional[float] = None) -> Optional[str]:
        """One controller poll: read signals, maybe act.  Returns
        ``"up"`` / ``"down"`` when a scale action fired, else None.
        Tests drive this directly with a fake clock."""
        now = self.clock() if now is None else now
        if not self.target.healthy():
            # restart budget spent: the control plane already decided
            # this host needs replacing — resizing a broken pool would
            # only mask the signal (and burn more restart budget)
            return None
        sig = self.target.autoscale_signals()
        depth = int(sig.get("depth", 0))
        row_s = sig.get("row_s_ema") or 0.0
        live = max(int(sig.get("live", 0)), 1)
        batches = int(sig.get("batches", 0))
        cur = int(self.target.replicas)
        est_wait = depth * row_s / live
        self._last_wait_s = est_wait
        # busy = anything queued, or a batch completed since last poll
        busy = depth > 0 or (self._last_batches is not None
                             and batches != self._last_batches)
        self._last_batches = batches
        if busy or self._last_busy is None:
            self._last_busy = now
        telemetry.counter("serve.autoscale", replicas=cur,
                          est_wait_ms=round(est_wait * 1e3, 3),
                          queue_depth=depth)
        in_cooldown = (self._last_action is not None and
                       now - self._last_action < self.cooldown_s)
        # -- grow: sustained over-target queue wait ---------------------
        if est_wait > self.target_wait_s and depth > 0:
            self._over_target += 1
            if (self._over_target >= self.up_polls and cur <
                    self.max_replicas and not in_cooldown):
                n = min(cur + self.step, self.max_replicas)
                self._act(now, "up", n, est_wait, depth)
                return "up"
            return None
        self._over_target = 0
        # -- shrink: a full idle window with nothing queued or served ---
        if (not busy and cur > self.min_replicas and not in_cooldown and
                now - self._last_busy >= self.idle_s):
            n = cur - 1
            self._act(now, "down", n, est_wait, depth)
            # restart the idle window: gradual decay, one step per
            # idle_s, instead of collapsing straight to min
            self._last_busy = now
            return "down"
        return None

    def _act(self, now: float, direction: str, n: int, est_wait: float,
             depth: int) -> None:
        prev = int(self.target.replicas)
        self.target.scale_to(n)
        self._last_action = now
        self._over_target = 0
        if direction == "up":
            self.scale_ups += 1
        else:
            self.scale_downs += 1
        event = {"direction": direction, "from": prev, "to": n,
                 "est_wait_ms": round(est_wait * 1e3, 3),
                 "queue_depth": depth}
        self.events.append(event)
        del self.events[:-16]
        telemetry.instant("serve.autoscale", cat="serve", **event)
        telemetry.counter("serve.autoscale", replicas=n,
                          est_wait_ms=round(est_wait * 1e3, 3),
                          queue_depth=depth)
        logger.info("serve: autoscale %s %d -> %d (est wait %.1fms vs "
                    "target %.1fms, depth %d)", direction.upper(), prev,
                    n, est_wait * 1e3, self.target_wait_s * 1e3, depth)

    # -- introspection --------------------------------------------------

    def stats(self) -> dict:
        return {"replicas": int(self.target.replicas),
                "min": self.min_replicas, "max": self.max_replicas,
                "target_wait_ms": round(self.target_wait_s * 1e3, 3),
                "est_wait_ms": round(self._last_wait_s * 1e3, 3),
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "events": list(self.events[-8:])}
