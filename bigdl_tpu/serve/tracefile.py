"""Serving traffic traces: record real request streams, replay them 10-100x.

Every throughput number the serving stack has published so far came from
synthetic storms (``bench.py --serve``'s fixed-rate open loop and
scripted bursts).  Real traffic is nothing like that: arrivals cluster,
tenants interleave, priorities mix, deadlines vary.  This module makes
recorded traffic a first-class artifact — the BigDL papers' "production
workloads" pitch as a measurable file instead of a sentence:

- **record**: a :class:`TraceRecorder` attached to the server's
  admission path (``InferenceServer.record_trace`` /
  ``TopologyRouter.record_trace``, or the HTTP front door's
  ``X-BigDL-Record-Trace`` header) captures every OFFERED request —
  shed ones included, they are real load — as (arrival delta, payload,
  tenant, priority, deadline);
- **persist**: :func:`write_trace` / :func:`read_trace` store events in
  the repo's recordio framing (utils/recordio — u64 length + masked
  CRC32C per record, the TFRecord layout), one header record then one
  record per event, so a corrupt byte is a typed
  :class:`~bigdl_tpu.utils.recordio.CorruptRecord` with an offset, not
  a silently wrong benchmark;
- **replay**: :func:`replay` re-offers the stream with OPEN-LOOP pacing
  at ``speed`` x the recorded rate — arrival times are
  ``t0 + cumulative_dt / speed`` regardless of how the server is coping
  (a server that falls behind faces the backlog, exactly like
  production; the per-event ``lag_s`` records when the replayer itself
  could not keep pace);
- **judge**: :func:`slo_report` reduces the outcomes to per-tenant and
  per-priority-class **SLO attainment** — the fraction of OFFERED
  requests answered successfully within their own deadline — beside
  p50/p95/p99 of served latency and shed-by-cause counts
  (``overload`` / ``timeout`` / ``errors``; real failures are never
  lumped into intentional shedding).

``bench.py --serve --replay <trace> --speed K`` wraps the whole loop
into one JSON record; ``tools/scale_smoke.py`` replays a recorded
mini-trace at 10x against a fixed pool and an autoscaled one and
asserts the autoscaled pool's attainment is strictly higher.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..utils import recordio
from .batcher import RequestTimeout, ServeError, ServerOverloaded

__all__ = ["TRACE_FORMAT", "TraceEvent", "TraceFormatError",
           "TraceRecorder", "write_trace", "read_trace", "replay",
           "slo_report"]

TRACE_FORMAT = "bigdl_tpu-serve-trace-v1"

#: recorder safety valve: default cap on in-memory events
#: (BIGDL_TPU_SERVE_TRACE_LIMIT overrides) — recording must never OOM a
#: live server; past the cap events are counted as dropped, not kept
_DEFAULT_LIMIT = 100_000


class TraceFormatError(ServeError):
    """The file is framed recordio but not a serve trace (wrong/missing
    header) — typed so a mis-pointed path fails loudly, not as a weird
    replay."""


class TraceEvent:
    """One offered request: ``dt`` seconds after the PREVIOUS event (0
    for the first), the payload row, and its admission metadata.

    ``gen``: optional generation metadata for decode traces (serve/
    decode.py) — a small dict (max_tokens, eos, temperature, ...) the
    replayer hands to ``DecodeEngine.submit``.  For a generative
    sequence the payload is the prompt token row and ``deadline_ms`` is
    the time-to-LAST-token budget (the engine resolves the request at
    its final token, so recorded latency and SLO attainment are
    per-sequence by construction).  Absent on classic one-shot traces
    (``from_record`` defaults it to None — old trace files replay
    unchanged)."""

    __slots__ = ("dt", "payload", "tenant", "priority", "deadline_ms",
                 "gen")

    def __init__(self, dt: float, payload, tenant: Optional[str] = None,
                 priority: int = 0, deadline_ms: Optional[float] = None,
                 gen: Optional[dict] = None):
        self.dt = max(float(dt), 0.0)
        self.payload = payload
        self.tenant = tenant
        self.priority = int(priority)
        self.deadline_ms = (float(deadline_ms)
                            if deadline_ms is not None else None)
        self.gen = dict(gen) if gen else None

    def to_record(self) -> dict:
        rec = {"dt": self.dt, "x": np.asarray(self.payload),
               "tenant": self.tenant, "priority": self.priority,
               "deadline_ms": self.deadline_ms}
        if self.gen is not None:
            rec["gen"] = dict(self.gen)
        return rec

    @classmethod
    def from_record(cls, rec: dict) -> "TraceEvent":
        return cls(rec["dt"], rec["x"], tenant=rec.get("tenant"),
                   priority=rec.get("priority", 0),
                   deadline_ms=rec.get("deadline_ms"),
                   gen=rec.get("gen"))

    def __repr__(self):
        return (f"TraceEvent(dt={self.dt:.4f}, shape="
                f"{tuple(np.asarray(self.payload).shape)}, "
                f"tenant={self.tenant!r}, priority={self.priority}, "
                f"deadline_ms={self.deadline_ms}"
                + (f", gen={self.gen}" if self.gen else "") + ")")


class TraceRecorder:
    """Thread-safe offered-request capture (clock-injectable).

    ``note()`` is called from the server's admission path under no lock
    of its own beyond this recorder's — it must stay cheap (one stamp,
    one append) because it sits in front of every request."""

    def __init__(self, clock=None, limit: Optional[int] = None,
                 path: Optional[str] = None):
        from ..utils import config
        self.clock = clock or time.monotonic
        self.limit = int(limit) if limit is not None else \
            config.get_int("SERVE_TRACE_LIMIT", _DEFAULT_LIMIT)
        self.path = path
        self.dropped = 0
        self._lock = threading.Lock()
        self._stamps: List[float] = []
        self._events: List[TraceEvent] = []

    def note(self, payload, tenant: Optional[str] = None,
             priority: int = 0,
             deadline_ms: Optional[float] = None,
             gen: Optional[dict] = None) -> None:
        now = self.clock()
        with self._lock:
            if len(self._events) >= self.limit:
                self.dropped += 1
                return
            prev = self._stamps[-1] if self._stamps else now
            self._stamps.append(now)
            self._events.append(TraceEvent(
                now - prev, np.asarray(payload), tenant=tenant,
                priority=priority, deadline_ms=deadline_ms, gen=gen))

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self) -> List[TraceEvent]:
        with self._lock:
            return list(self._events)

    def save(self, path: Optional[str] = None,
             meta: Optional[dict] = None) -> int:
        """Write the captured stream (``path`` overrides the armed one);
        returns the event count."""
        path = path or self.path
        if not path:
            raise ValueError("serve: trace recorder has no path — pass "
                             "one to save() or record_trace()")
        events = self.events()
        write_trace(path, events, meta=meta)
        return len(events)

    def stats(self) -> dict:
        with self._lock:
            return {"events": len(self._events), "dropped": self.dropped,
                    "limit": self.limit, "path": self.path}


def write_trace(path: str, events: Sequence[TraceEvent],
                meta: Optional[dict] = None) -> None:
    """Persist a trace: one header record (format, sample shape/dtype,
    count, caller meta) then one record per event, all CRC-framed
    (utils/recordio)."""
    events = list(events)
    sample = np.asarray(events[0].payload) if events else np.zeros((0,))
    header = {"format": TRACE_FORMAT,
              "sample_shape": list(sample.shape),
              "sample_dtype": str(sample.dtype),
              "count": len(events),
              "duration_s": round(sum(e.dt for e in events), 6),
              "meta": dict(meta or {})}
    recordio.write_records(path, [header] + [e.to_record()
                                             for e in events])


def read_trace(path: str) -> tuple:
    """Load ``(header, events)``; typed :class:`TraceFormatError` when
    the file is not a serve trace, :class:`CorruptRecord` (from the
    recordio layer) on CRC/framing damage."""
    records = iter(recordio.read_records(path))
    try:
        header = next(records)
    except StopIteration:
        raise TraceFormatError(f"serve: {path!r} is empty — not a "
                               "recorded trace") from None
    if not (isinstance(header, dict)
            and header.get("format") == TRACE_FORMAT):
        raise TraceFormatError(
            f"serve: {path!r} is not a {TRACE_FORMAT} trace (header "
            f"{type(header).__name__})")
    events = [TraceEvent.from_record(r) for r in records]
    if header.get("count") is not None and header["count"] != len(events):
        raise TraceFormatError(
            f"serve: {path!r} header claims {header['count']} events, "
            f"file holds {len(events)}")
    return header, events


# ---------------------------------------------------------------------------
# replay + SLO attainment
# ---------------------------------------------------------------------------


class ReplayOutcome:
    """One replayed request's fate, filled in two phases: submit (shed at
    admission?) then resolve (served / shed / errored + latency)."""

    __slots__ = ("event", "handle", "error", "lag_s", "latency_s")

    def __init__(self, event, handle=None, error=None, lag_s=0.0):
        self.event = event
        self.handle = handle
        self.error = error        # admission or resolution error
        self.lag_s = lag_s        # replayer behind schedule at submit
        self.latency_s = None


def replay(events: Sequence[TraceEvent], submit: Callable, *,
           speed: float = 10.0, clock=None, sleep=None,
           progress: Optional[Callable] = None) -> List[ReplayOutcome]:
    """Open-loop replay: offer every event at ``recorded_time / speed``
    regardless of how the pool is coping.

    ``submit(event)`` returns a
    :class:`~bigdl_tpu.serve.batcher.PendingRequest` (or raises a typed
    admission rejection, which becomes the outcome's error).  Pacing
    never waits on results — an overloaded pool faces the backlog, like
    production.  ``lag_s`` per outcome records when the replayer itself
    fell behind schedule (a loaded host, not the server's fault: big
    sustained lag means the measurement under-offers and the record
    should say so)."""
    if speed <= 0:
        raise ValueError(f"serve: replay speed must be > 0, got {speed}")
    clock = clock or time.monotonic
    sleep = sleep or time.sleep
    outcomes: List[ReplayOutcome] = []
    t0 = clock()
    due = 0.0
    for e in events:
        due += e.dt / speed
        delay = (t0 + due) - clock()
        if delay > 0:
            sleep(delay)
        lag = max(-delay, 0.0)
        try:
            h = submit(e)
            outcomes.append(ReplayOutcome(e, handle=h, lag_s=lag))
        except Exception as exc:  # noqa: BLE001 — typed shed at
            # admission (overload/quota) or a real failure; classified
            # by slo_report
            outcomes.append(ReplayOutcome(e, error=exc, lag_s=lag))
        if progress is not None:
            progress()
    return outcomes


def resolve_outcomes(outcomes: Sequence[ReplayOutcome],
                     timeout: float = 120.0) -> None:
    """Wait for every submitted handle and record latency or the typed
    error.  Latency is the SERVER-side enqueue->resolve time
    (``PendingRequest.latency_s`` — the same clock the deadline logic
    uses), not the caller's result() wait."""
    for o in outcomes:
        if o.handle is None:
            continue
        try:
            o.handle.result(timeout)
            o.latency_s = o.handle.latency_s
        except Exception as exc:  # noqa: BLE001 — typed per-request
            o.error = exc
            o.latency_s = o.handle.latency_s


def _percentiles_ms(latencies: List[float]) -> dict:
    if not latencies:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
    xs = sorted(latencies)

    def pick(q):
        return xs[min(int(q * (len(xs) - 1) + 0.5), len(xs) - 1)]

    return {"p50_ms": round(pick(0.50) * 1e3, 2),
            "p95_ms": round(pick(0.95) * 1e3, 2),
            "p99_ms": round(pick(0.99) * 1e3, 2)}


def _classify(error) -> str:
    """Shed-by-cause bucket: intentional load shedding (overload
    eviction/refusal, deadline timeout) vs real failures — the split the
    bench's open loop historically lumped together."""
    if isinstance(error, ServerOverloaded):
        return "overload"          # includes QuotaExceeded (subclass)
    if isinstance(error, RequestTimeout):
        return "timeout"
    return "errors"


def slo_report(outcomes: Sequence[ReplayOutcome],
               default_deadline_ms: Optional[float] = None) -> dict:
    """Reduce replay outcomes to SLO attainment.

    **Attainment** = answered successfully AND within the request's own
    deadline (its recorded ``deadline_ms``, else ``default_deadline_ms``;
    a request with neither attains by being answered at all), divided by
    OFFERED — sheds and errors count against the tenant they belonged
    to.  Reported overall, by tenant, and by priority class, beside
    served-latency percentiles and shed-by-cause counts."""

    def bucket():
        return {"offered": 0, "served": 0, "attained": 0,
                "shed_overload": 0, "shed_timeout": 0, "errors": 0}

    overall = bucket()
    by_tenant: dict = {}
    by_priority: dict = {}
    latencies: List[float] = []
    max_lag = 0.0
    for o in outcomes:
        e = o.event
        tb = by_tenant.setdefault(e.tenant or "default", bucket())
        pb = by_priority.setdefault(str(e.priority), bucket())
        rows = (overall, tb, pb)
        for r in rows:
            r["offered"] += 1
        max_lag = max(max_lag, o.lag_s)
        if o.error is not None:
            key = {"overload": "shed_overload", "timeout": "shed_timeout",
                   "errors": "errors"}[_classify(o.error)]
            for r in rows:
                r[key] += 1
            continue
        lat = o.latency_s
        if lat is not None:
            latencies.append(lat)
        for r in rows:
            r["served"] += 1
        deadline = e.deadline_ms if e.deadline_ms is not None \
            else default_deadline_ms
        if deadline is None or (lat is not None
                                and lat * 1e3 <= deadline):
            for r in rows:
                r["attained"] += 1

    def finish(b):
        b["attainment"] = round(b["attained"] / b["offered"], 4) \
            if b["offered"] else None
        return b

    return {"offered": overall["offered"],
            "served": overall["served"],
            "attainment": finish(overall)["attainment"],
            "shed": {"overload": overall["shed_overload"],
                     "timeout": overall["shed_timeout"],
                     "errors": overall["errors"]},
            "per_tenant": {t: finish(b)
                           for t, b in sorted(by_tenant.items())},
            "per_priority": {p: finish(b)
                             for p, b in sorted(by_priority.items())},
            "max_replay_lag_ms": round(max_lag * 1e3, 2),
            **_percentiles_ms(latencies)}
