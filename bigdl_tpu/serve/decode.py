"""Continuous-batching generative decode serving.

Everything serve/ shipped before this module is one-shot forward: a
request is one feature row, a batch is one device call, done.  Real
serving traffic is dominated by autoregressive DECODE — and the offline
KV-cache decoder (models/decode.py ``cached_generate``) never met
``InferenceServer``.  This module closes that gap with the classic
continuous-batching design (the step BigDL 2.0's Cluster Serving never
took; PAPERS.md):

- :class:`DecodeEngine` runs a **persistent decode step loop** over a
  fixed-slot in-flight batch.  Every loop tick decodes ALL active slots
  in ONE kernel call; a sequence that emits EOS or exhausts its token
  budget leaves and frees its slot **that same tick** instead of holding
  the batch hostage (run-to-completion static batching wastes device
  steps on finished rows — the throughput gap tools/decode_smoke.py
  gates, not asserts).
- **Prefill and decode are separate jitted executables** with separate
  compile cards and AOT cache entries, keyed like the
  ``_ShardedForward`` buckets (module fingerprint + base fingerprint +
  shape dims through utils/aot.get_or_compile).  Prefill admits one new
  sequence into a free KV-cache slot: a ``fori_loop`` over the prompt
  positions inside ONE executable (traced trip count — one compile per
  (prompt-bucket, slots, cache-page), not per prompt length), reusing
  the exact per-position math of models/decode so greedy outputs
  bit-match the ``cached_generate`` oracle.
- The bucket ladder extends to **(batch-slots, cache-page)** pages:
  cache length is allocated in power-of-2 multiples of
  ``BIGDL_TPU_DECODE_PAGE`` (models/decode.init_kv_cache buffers), so a
  17-token prompt neither compiles nor pays HBM for ``max_len``.  The
  cache grows to the next page when a longer sequence is admitted and
  shrinks back when the engine drains idle.  Under a canonical layout
  mesh the cache tensors carry the ``kv_cache`` role
  (parallel/layout.py: slots over data x fsdp, heads over tp), so
  tp-sharded models serve decode through the existing mesh machinery
  unchanged.
- Admission rides :class:`~bigdl_tpu.serve.batcher.DecodeQueue`:
  bounded queue, per-sequence deadline (= time-to-LAST-token), priority
  eviction and tenant quotas all apply per-sequence; ``note_service``
  learns seconds/token so ``retry_after_s`` scales with the queued
  token budget.
- Telemetry: the ``serve.decode`` counter track emits tokens/s,
  active-slot fill, prefill-vs-decode step fractions and cache
  bytes/slot — promoted to a ``decode:`` trace_report section like
  ``aot``/``autoscale`` (utils/telemetry.phase_breakdown).
- Chaos: ``serve.decode@<slot>`` fires once per tick for every slot
  that participates (prefill or decode).  A faulted slot fails ITS
  sequence typed (:class:`SlotFault`/ChaosFault), frees the slot, and
  the other slots keep decoding with zero loss.

Config knobs (utils/config, all overridable per-engine):

=============================  =========  ================================
env var                        default    meaning
=============================  =========  ================================
BIGDL_TPU_DECODE_SLOTS         4          fixed in-flight batch slots
BIGDL_TPU_DECODE_PAGE          128        cache-page quantum (tokens);
                                          cache length is page * 2^k
BIGDL_TPU_DECODE_MAX_LEN       0          cache-length cap; 0 = the
                                          model's positional max_len
BIGDL_TPU_DECODE_QUEUE_LIMIT   64         bounded admission queue
BIGDL_TPU_DECODE_DEADLINE_MS   0          default time-to-last-token
                                          deadline; 0 = none
BIGDL_TPU_DECODE_ADMISSION     continuous 'continuous' (join per tick) or
                                          'batch' (run-to-completion —
                                          the baseline decode_smoke
                                          measures against)
BIGDL_TPU_DECODE_MIN_STEP_MS   0          per-tick pacing floor (bench /
                                          smoke determinism lever)
=============================  =========  ================================
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..models import decode as kv
from ..models.transformer_lm import PositionalEmbedding, sample_next
from ..nn.attention import MultiHeadAttention
from ..nn.containers import ConcatTable, Sequential
from ..nn.module import Container
from ..utils import aot as aot_mod
from ..utils import chaos, config, hlostats, metrics_export, telemetry
from .batcher import DecodeQueue, PendingRequest, ServeError
from .control import TenantQuotas

__all__ = ["DecodeEngine", "SlotFault", "page_ladder"]

_UNSET = object()


class SlotFault(ServeError):
    """A decode slot faulted mid-generation (the ``serve.decode@<slot>``
    chaos drill, or a per-sequence error): the sequence fails typed, the
    slot frees the same tick, the other slots keep decoding."""


def page_ladder(page: int, max_len: int) -> tuple:
    """The cache-length ladder: power-of-2 multiples of ``page`` capped
    at ``max_len`` (``max_len`` itself always included) — the cache-page
    analogue of batcher.default_buckets."""
    if page < 1:
        raise ValueError(f"page must be >= 1, got {page}")
    sizes = []
    c = int(page)
    while c < max_len:
        sizes.append(c)
        c *= 2
    sizes.append(int(max_len))
    return tuple(sizes)


# ---------------------------------------------------------------------------
# per-slot-position decode step (vmapped cache write, per-slot mask)
# ---------------------------------------------------------------------------
# models/decode._cached_attention serves ONE position shared by every
# row; continuous batching needs every slot at its OWN position.  The
# math per slot is identical (same projections, same f32 score path,
# exact-zero masked softmax weights), so greedy tokens bit-match the
# cached_generate oracle per sequence.

def _slot_attention(mha, params, x, cache, pos):
    """x: [S, 1, E], pos: [S] int32; returns ([S, 1, E], new_cache)."""
    if not mha.causal:
        raise NotImplementedError(
            "cached decoding requires causal attention "
            "(MultiHeadAttention(causal=False) found)")
    S, _, E = x.shape
    H, D = mha.num_heads, mha.head_dim
    split = lambda y: y.reshape(S, 1, H, D).transpose(0, 2, 1, 3)
    q, k, v = (split(mha._proj(params, x, n)) for n in "qkv")

    def upd(c, u, p):  # c: [H, L, D], u: [H, 1, D], p: scalar
        return jax.lax.dynamic_update_slice(c, u, (0, p, 0))

    ck = jax.vmap(upd)(cache["k"], k.astype(cache["k"].dtype), pos)
    cv = jax.vmap(upd)(cache["v"], v.astype(cache["v"].dtype), pos)
    L = ck.shape[2]
    scores = jnp.einsum("bhqd,bhld->bhql", q.astype(jnp.float32),
                        ck.astype(jnp.float32)) / (D ** 0.5)
    # per-slot causal horizon; positions past a slot's pos get EXACT
    # zero softmax weight (exp(-inf)), so stale cache rows from a
    # previous occupant of the slot contribute exactly nothing
    mask = jnp.arange(L)[None, None, None, :] <= pos[:, None, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhql,bhld->bhqd", w, cv.astype(jnp.float32))
    o = o.astype(x.dtype).transpose(0, 2, 1, 3).reshape(S, 1, E)
    return mha._proj(params, o, "o"), {"k": ck, "v": cv}


def _slot_step(module, params, state, x, caches, slot, pos):
    """models/decode._step with a per-slot position vector ``pos``."""
    if isinstance(module, MultiHeadAttention):
        y, caches[slot] = _slot_attention(module, params, x, caches[slot],
                                          pos)
        return y, slot + 1
    if isinstance(module, PositionalEmbedding):
        w = jnp.take(params["weight"], pos, axis=0)  # [S, E]
        return x + w[:, None].astype(x.dtype), slot
    if isinstance(module, Sequential):
        for m, p, s in zip(module.modules, params, state):
            x, slot = _slot_step(m, p, s, x, caches, slot, pos)
        return x, slot
    if isinstance(module, ConcatTable):
        outs = []
        for m, p, s in zip(module.modules, params, state):
            o, slot = _slot_step(m, p, s, x, caches, slot, pos)
            outs.append(o)
        return outs, slot
    if not isinstance(module, Container):
        y, _ = module.apply(params, state, x, training=False, rng=None)
        return y, slot
    raise NotImplementedError(
        f"cached decoding: unsupported container {type(module).__name__}")


def _prompt_bucket(t0: int) -> int:
    """Power-of-2 prompt padding bucket (floor 8) — one prefill
    executable per bucket, not per prompt length."""
    b = 8
    while b < t0:
        b *= 2
    return b


class _Seq:
    """Host-side state of one in-flight sequence (one slot)."""

    __slots__ = ("req", "buf", "t0", "pos", "emitted", "max_tokens",
                 "eos", "temperature", "top_k", "rng")

    def __init__(self, req: PendingRequest, prompt: np.ndarray,
                 max_tokens: int, eos, temperature: float, top_k: int,
                 rng):
        self.req = req
        self.t0 = len(prompt)
        self.buf = np.zeros(self.t0 + max_tokens, np.int32)
        self.buf[: self.t0] = prompt
        self.pos = self.t0 - 1   # last position fed to the device
        self.emitted = 0
        self.max_tokens = max_tokens
        self.eos = eos
        self.temperature = temperature
        self.top_k = top_k
        self.rng = rng


class DecodeEngine:
    """Persistent continuous-batching decode loop (module docstring)."""

    def __init__(self, model, *, slots: Optional[int] = None,
                 page: Optional[int] = None,
                 max_len: Optional[int] = None,
                 queue_limit: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 admission: Optional[str] = None,
                 eos_token: Optional[int] = None,
                 cache_dtype=None, mesh=None,
                 tenant_qps: Optional[float] = None,
                 tenant_burst: Optional[float] = None,
                 min_step_s: Optional[float] = None,
                 clock=None):
        self.model = model
        if model.params is None:
            model.build()
        self.slots = int(slots if slots is not None
                         else config.get_int("DECODE_SLOTS", 4))
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        self.page = int(page if page is not None
                        else config.get_int("DECODE_PAGE", 128))
        model_cap = min((pe.max_len for pe in kv._modules_of_type(
            model, PositionalEmbedding)), default=0)
        cap = int(max_len if max_len is not None
                  else config.get_int("DECODE_MAX_LEN", 0)) or model_cap
        if model_cap and cap > model_cap:
            raise ValueError(f"max_len {cap} > model positional "
                             f"embedding max_len {model_cap}")
        if cap < 1:
            raise ValueError("DecodeEngine needs a positive max_len "
                             "(model has no PositionalEmbedding cap)")
        self.max_len = cap
        self.ladder = page_ladder(self.page, self.max_len)
        self.admission = str(admission if admission is not None else
                             config.get_str("DECODE_ADMISSION",
                                            "continuous"))
        if self.admission not in ("continuous", "batch"):
            raise ValueError(f"admission must be 'continuous' or "
                             f"'batch', got {self.admission!r}")
        self.default_deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else config.get_float("DECODE_DEADLINE_MS", 0.0))
        self.min_step_s = float(
            min_step_s if min_step_s is not None
            else config.get_float("DECODE_MIN_STEP_MS", 0.0) / 1e3)
        self.eos_token = eos_token
        from ..common import get_policy
        self.cache_dtype = cache_dtype or get_policy().compute_dtype
        self.clock = clock or time.monotonic
        self.queue = DecodeQueue(
            int(queue_limit if queue_limit is not None
                else config.get_int("DECODE_QUEUE_LIMIT", 64)),
            clock=self.clock)
        self.quotas = TenantQuotas(tenant_qps or 0.0, burst=tenant_burst,
                                   clock=self.clock)
        self._mesh = mesh
        self._params, self._state = model.params, model.state
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel import layout as _layout
            self._params = jax.device_put(
                self._params,
                _layout.assign_shardings(model, self._params, mesh))
            rep = NamedSharding(mesh, PartitionSpec())
            self._state = jax.device_put(
                self._state, jax.tree.map(lambda _: rep, self._state))
        self._module_fp = None       # lazy (fingerprinting traces shapes)
        self._exe: dict = {}         # (kind, *dims) -> compiled
        self._slots: List[Optional[_Seq]] = [None] * self.slots
        self._caches = None
        self._cache_len = 0
        self._recorder = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # cumulative counters (stats(); serve.decode telemetry track)
        self.prefill_steps = 0
        self.decode_steps = 0
        self.tokens_out = 0
        self.seqs_done = 0
        self.seqs_failed = 0
        self.cache_grows = 0
        self._busy_s = 0.0

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "DecodeEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="bigdl-decode-engine", daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Close admissions; ``drain=True`` finishes every queued and
        in-flight sequence first."""
        self.queue.close(drain=drain)
        t = self._thread
        if t is not None:
            t.join(timeout=120.0)
            self._thread = None
        self.queue.fail_pending()

    def __enter__(self) -> "DecodeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- admission ------------------------------------------------------

    def submit(self, prompt, max_tokens: int, *,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None, priority: int = 0,
               temperature: float = 0.0, top_k: int = 0,
               eos_token=_UNSET, seed: int = 0,
               request_id: Optional[str] = None) -> PendingRequest:
        """Enqueue one sequence; returns a PendingRequest whose
        ``result()`` is the full int32 token row (prompt + generated,
        the ``cached_generate`` contract, truncated at EOS).  Typed
        rejections: ServeError (bad request), QuotaExceeded,
        ServerOverloaded, ServerClosed; RequestTimeout resolves later if
        the time-to-last-token deadline passes in the queue.
        ``request_id`` is the distributed-tracing flow id from the
        ``X-BigDL-Request-Id`` header (minted locally when absent and
        tracing is on)."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.shape[0] == 0:
            raise ServeError("decode: prompt must be a non-empty 1-D "
                             f"token row, got shape {prompt.shape}")
        max_tokens = int(max_tokens)
        if max_tokens < 1:
            raise ServeError(f"decode: max_tokens must be >= 1, got "
                             f"{max_tokens}")
        need = prompt.shape[0] + max_tokens
        if need > self.max_len:
            raise ServeError(
                f"decode: prompt ({prompt.shape[0]}) + max_tokens "
                f"({max_tokens}) exceeds max_len ({self.max_len})")
        self.quotas.admit(tenant)
        eos = self.eos_token if eos_token is _UNSET else eos_token
        dl_ms = self.default_deadline_ms \
            if deadline_ms is None else float(deadline_ms)
        deadline = self.clock() + dl_ms / 1e3 if dl_ms > 0 else None
        gen = {"max_tokens": max_tokens, "temperature": float(temperature),
               "top_k": int(top_k), "seed": int(seed)}
        if eos is not None:
            gen["eos_token"] = int(eos)
        if self._recorder is not None:
            self._recorder.note(prompt, tenant=tenant, priority=priority,
                                deadline_ms=dl_ms if dl_ms > 0 else None,
                                gen=gen)
        payload = dict(gen, prompt=prompt, eos=eos)
        return self.queue.submit(payload, deadline, tenant=tenant,
                                 priority=priority,
                                 request_id=request_id)

    def generate(self, prompt, max_tokens: int,
                 timeout: Optional[float] = 120.0, **kw) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(prompt, max_tokens, **kw).result(timeout)

    # -- trace recording (server.py contract) ---------------------------

    def record_trace(self, path: Optional[str] = None, *, limit=None):
        from .tracefile import TraceRecorder
        if self._recorder is not None and (path is None or
                                           self._recorder.path == path):
            return self._recorder
        self._recorder = TraceRecorder(clock=self.clock, limit=limit,
                                       path=path)
        return self._recorder

    def stop_trace(self, path: Optional[str] = None):
        rec, self._recorder = self._recorder, None
        if rec is not None and (path or rec.path):
            rec.save(path)
        return rec

    # -- executables (AOT-keyed like _ShardedForward buckets) -----------

    def _key_fields(self, kind: str, **dims) -> dict:
        fields = dict(aot_mod.base_fingerprint(self._mesh))
        if self._module_fp is None:
            self._module_fp = aot_mod.module_fingerprint(self.model)
        fields["module"] = self._module_fp
        fields["params"] = aot_mod.aval_fingerprint(
            (self._params, self._state))
        fields["kind"] = kind
        fields.update(dims)
        return fields

    def _cache_avals(self, cache_len: int):
        out = []
        for mha in kv._mha_modules(self.model):
            shape = (self.slots, mha.num_heads, cache_len, mha.head_dim)
            out.append({
                "k": jax.ShapeDtypeStruct(shape, self.cache_dtype),
                "v": jax.ShapeDtypeStruct(shape, self.cache_dtype)})
        return tuple(out)

    def _step_exe(self, cache_len: int):
        """The decode-step executable for the (slots, cache_len) bucket:
        ALL slots advance one position in one kernel call."""
        memo = ("step", self.slots, cache_len)
        exe = self._exe.get(memo)
        if exe is not None:
            return exe
        model, S = self.model, self.slots

        @partial(jax.jit, donate_argnums=(2,))
        def fn(params, state, caches, tok, pos):
            x = tok[:, None]          # [S, 1] token ids
            caches = list(caches)
            y, _ = _slot_step(model, params, state, x, caches, 0, pos)
            return y[:, -1], tuple(caches)

        ivec = jax.ShapeDtypeStruct((S,), jnp.int32)
        exe = aot_mod.get_or_compile(
            self._key_fields("decode.step", slots=S, cache_len=cache_len,
                             dtype=jnp.dtype(self.cache_dtype).name),
            lambda: fn.lower(self._params, self._state,
                             self._cache_avals(cache_len), ivec, ivec),
            label="decode.step",
            card_extra={"slots": S, "cache_len": cache_len})
        self._exe[memo] = exe
        return exe

    def _prefill_exe(self, prompt_bucket: int, cache_len: int):
        """The prefill executable for the (prompt_bucket, slots,
        cache_len) bucket: one new sequence enters ONE slot via a traced
        fori_loop over its prompt positions (trip count t0 is traced, so
        every prompt length in the bucket shares this compile).  Reuses
        models/decode._step per position — greedy outputs bit-match the
        cached_generate oracle by construction."""
        memo = ("prefill", prompt_bucket, self.slots, cache_len)
        exe = self._exe.get(memo)
        if exe is not None:
            return exe
        model = self.model

        @partial(jax.jit, donate_argnums=(2,))
        def fn(params, state, caches, toks, slot, t0):
            # slice this slot's [1, H, L, D] cache views out, run the
            # rows=1 incremental step over the prompt, write back — the
            # other slots' caches pass through untouched
            sub = tuple(
                {n: jax.lax.dynamic_slice_in_dim(c[n], slot, 1, axis=0)
                 for n in c} for c in caches)

            def run_pos(i, sub_t):
                sub_l = list(sub_t)
                x = toks[i][None, None]     # [1, 1]
                y, _ = kv._step(model, params, state, x, sub_l, 0, i)
                return tuple(sub_l), y[:, -1]

            sub, logits = run_pos(0, sub)
            sub, logits = jax.lax.fori_loop(
                1, t0, lambda i, c: run_pos(i, c[0]), (sub, logits))
            new = tuple(
                {n: jax.lax.dynamic_update_slice(c[n], s[n],
                                                 (slot, 0, 0, 0))
                 for n in c} for c, s in zip(caches, sub))
            return logits[0], new          # [V] logits of last position

        exe = aot_mod.get_or_compile(
            self._key_fields("decode.prefill", slots=self.slots,
                             cache_len=cache_len,
                             prompt_bucket=prompt_bucket,
                             dtype=jnp.dtype(self.cache_dtype).name),
            lambda: fn.lower(
                self._params, self._state, self._cache_avals(cache_len),
                jax.ShapeDtypeStruct((prompt_bucket,), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32)),
            label="decode.prefill",
            card_extra={"slots": self.slots, "cache_len": cache_len,
                        "prompt_bucket": prompt_bucket})
        self._exe[memo] = exe
        return exe

    # -- (slots, cache-page) ladder -------------------------------------

    def _bucket_for(self, need: int) -> int:
        for c in self.ladder:
            if c >= need:
                return c
        return self.ladder[-1]

    def _fresh_caches(self, cache_len: int):
        caches = kv.init_kv_cache(self.model, self.slots, cache_len,
                                  self.cache_dtype, mesh=self._mesh)
        return tuple(caches)

    def _ensure_cache(self, need: int, idle: bool) -> None:
        want = self._bucket_for(need)
        if self._caches is None or (idle and want != self._cache_len):
            # idle engine: re-page to exactly what the next admission
            # needs (a 17-token prompt must not pay for max_len)
            self._caches = self._fresh_caches(want)
            self._cache_len = want
            return
        if want > self._cache_len:
            # grow to the next page: pad the length axis with zeros —
            # masked positions carry exact-zero softmax weight, so the
            # in-flight slots decode on unchanged
            grown = []
            for c in self._caches:
                pad = {}
                for n, arr in c.items():
                    z = jnp.zeros(arr.shape[:2]
                                  + (want - self._cache_len,)
                                  + arr.shape[3:], arr.dtype)
                    pad[n] = jnp.concatenate([arr, z], axis=2)
                grown.append(pad)
            self._caches = tuple(grown)
            if self._mesh is not None:
                from jax.sharding import NamedSharding
                from ..parallel import layout as _layout
                lay = _layout.MeshLayout.of_mesh(self._mesh)
                self._caches = tuple(
                    {n: jax.device_put(arr, NamedSharding(
                        self._mesh, lay.spec_for("kv_cache", arr.shape,
                                                 min_size=0)))
                     for n, arr in c.items()} for c in self._caches)
            self._cache_len = want
            self.cache_grows += 1

    def cache_bytes_per_slot(self) -> int:
        if self._caches is None:
            return 0
        total = sum(int(arr.nbytes) for c in self._caches
                    for arr in c.values())
        return total // self.slots

    # -- the persistent step loop ---------------------------------------

    def _loop(self) -> None:
        telemetry.thread_name("decode engine")
        while True:
            try:
                if not self._tick():
                    return
            except Exception as e:  # noqa: BLE001 — engine must survive
                # backstop: a fault not attributable to one slot fails
                # every in-flight sequence typed rather than wedging the
                # loop (the queue keeps serving future ticks)
                now = self.clock()
                for s in range(self.slots):
                    seq = self._slots[s]
                    if seq is not None:
                        seq.req._resolve(error=e, now=now)
                        self._slots[s] = None
                        self.seqs_failed += 1

    def _fail_slot(self, s: int, err: Exception) -> None:
        seq = self._slots[s]
        if seq is not None:
            if seq.req.rid is not None:
                # the fault lands on the request's flow (failover segment)
                telemetry.flow_step(seq.req.rid, hop="decode.fault",
                                    slot=s, error=type(err).__name__)
            seq.req._resolve(error=err, now=self.clock())
            self._slots[s] = None
            self.seqs_failed += 1

    def _finish_slot(self, s: int) -> None:
        seq = self._slots[s]
        out = seq.buf[: seq.t0 + seq.emitted].copy()
        seq.req._resolve(result=out, version="decode", now=self.clock())
        reg = metrics_export._REGISTRY
        if reg is not None and seq.req.latency_s is not None:
            reg.observe("bigdl_decode_ttlt_seconds", seq.req.latency_s,
                        help="time to last token (submit to full row), "
                             "seconds")
        self._slots[s] = None
        self.seqs_done += 1

    def _sample(self, seq: _Seq, logits_row: np.ndarray) -> int:
        tok, seq.rng = sample_next(logits_row[None], seq.temperature,
                                   seq.top_k, seq.rng)
        return int(tok[0])

    def _advance(self, s: int, tok: int) -> None:
        """Record one emitted token for slot ``s``; finish the sequence
        the SAME step when it hits EOS or its budget."""
        seq = self._slots[s]
        seq.pos += 1
        seq.buf[seq.pos] = tok
        seq.emitted += 1
        self.tokens_out += 1
        if seq.req.rid is not None:
            # one flow step per emitted token: the per-token decode ticks
            # become arrows on the request's chain in Perfetto
            telemetry.flow_step(seq.req.rid, hop="decode.tick",
                                slot=s, n=seq.emitted)
        if (seq.eos is not None and tok == seq.eos) or \
                seq.emitted >= seq.max_tokens:
            self._finish_slot(s)

    def _admit(self, req: PendingRequest, s: int) -> None:
        p = req.payload
        prompt = p["prompt"]
        t0 = len(prompt)
        rng = jax.random.PRNGKey(p.get("seed", 0)) \
            if p.get("temperature", 0.0) > 0 else None
        seq = _Seq(req, prompt, p["max_tokens"], p.get("eos"),
                   p.get("temperature", 0.0), p.get("top_k", 0), rng)
        self._slots[s] = seq
        if req.rid is not None:
            telemetry.flow_step(req.rid, hop="decode.admit", slot=s,
                                prompt_len=t0)
        try:
            chaos.fire(f"serve.decode@{s}", thread_exc=SlotFault)
        except Exception as e:  # noqa: BLE001 — typed per-sequence fail
            self._fail_slot(s, e)
            return
        pb = _prompt_bucket(t0)
        toks = np.zeros(pb, np.int32)
        toks[:t0] = prompt
        exe = self._prefill_exe(pb, self._cache_len)
        try:
            logits, self._caches = exe(
                self._params, self._state, self._caches,
                jnp.asarray(toks), jnp.int32(s), jnp.int32(t0))
        except Exception as e:  # noqa: BLE001
            self._fail_slot(s, SlotFault(f"decode: prefill failed in "
                                         f"slot {s}: {e!r}"))
            return
        self.prefill_steps += 1
        self._advance(s, self._sample(seq, np.asarray(logits)))

    def _tick(self) -> bool:
        """One loop iteration: admit into free slots, decode all active
        slots in one kernel call.  Returns False when closed + drained."""
        q = self.queue
        free = [s for s in range(self.slots) if self._slots[s] is None]
        n_active = self.slots - len(free)
        incoming: List[PendingRequest] = []
        if free and (self.admission == "continuous" or n_active == 0):
            incoming = q.take(len(free))
        if n_active == 0 and not incoming:
            if q.closed and q.depth() == 0:
                return False
            q.wait_for_work(DecodeQueue._SLICE)
            return True
        t_start = self.clock()
        tokens_before = self.tokens_out
        if incoming:
            need = max(len(r.payload["prompt"]) + r.payload["max_tokens"]
                       for r in incoming)
            self._ensure_cache(need, idle=(n_active == 0))
            for r in incoming:
                self._admit(r, free.pop(0))
        # decode every still-active slot (including freshly prefilled
        # ones — their first token is already in the buffer) one
        # position forward, in ONE kernel call
        active = [s for s in range(self.slots)
                  if self._slots[s] is not None]
        for s in list(active):
            try:
                chaos.fire(f"serve.decode@{s}", thread_exc=SlotFault)
            except Exception as e:  # noqa: BLE001
                self._fail_slot(s, e)
                active.remove(s)
        if active:
            tok = np.zeros(self.slots, np.int32)
            pos = np.zeros(self.slots, np.int32)
            for s in active:
                seq = self._slots[s]
                tok[s] = seq.buf[seq.pos]
                pos[s] = seq.pos
            exe = self._step_exe(self._cache_len)
            logits, self._caches = exe(self._params, self._state,
                                       self._caches, jnp.asarray(tok),
                                       jnp.asarray(pos))
            logits = np.asarray(logits)
            self.decode_steps += 1
            for s in active:
                self._advance(s, self._sample(self._slots[s], logits[s]))
        dt = self.clock() - t_start
        if self.min_step_s > 0 and dt < self.min_step_s:
            time.sleep(self.min_step_s - dt)
            dt = self.min_step_s
        self._busy_s += dt
        q.note_service(max(self.tokens_out - tokens_before, 1), dt)
        n_active = sum(1 for s in self._slots if s is not None)
        steps = self.prefill_steps + self.decode_steps
        telemetry.counter(
            "serve.decode",
            tokens_per_s=self.tokens_out / max(self._busy_s, 1e-9),
            fill=n_active / self.slots,
            prefill_frac=self.prefill_steps / max(steps, 1),
            decode_frac=self.decode_steps / max(steps, 1),
            cache_bytes_per_slot=self.cache_bytes_per_slot(),
            cache_len=self._cache_len)
        return True

    # -- introspection --------------------------------------------------

    def tokens_per_s(self) -> float:
        return self.tokens_out / max(self._busy_s, 1e-9)

    def stats(self) -> dict:
        s = aot_mod.stats()
        out = {
            "slots": self.slots,
            "active": sum(1 for x in self._slots if x is not None),
            "admission": self.admission,
            "cache_len": self._cache_len,
            "cache_bytes_per_slot": self.cache_bytes_per_slot(),
            "cache_grows": self.cache_grows,
            "prefill_steps": self.prefill_steps,
            "decode_steps": self.decode_steps,
            "tokens_out": self.tokens_out,
            "tokens_per_s": round(self.tokens_per_s(), 3),
            "seqs_done": self.seqs_done,
            "seqs_failed": self.seqs_failed,
            "queue": self.queue.stats(),
            "quota": self.quotas.stats(),
            "aot": {k: int(s[k]) for k in ("hits", "misses", "stores",
                                           "lowers", "compiles",
                                           "corrupt")},
        }
        cards = hlostats.ledger()
        if cards:
            out["compile_cards"] = cards
        if self._recorder is not None:
            out["trace_recording"] = self._recorder.stats()
        return out
