"""Online inference server: replica pool, deadline shedding, hot model swap.

The device-side half of the serving subsystem (see serve/batcher.py for
the host-side queue/coalescing).  Composes pieces the training stack
already has into an online server:

- each **replica** is a worker thread draining the shared
  :class:`~bigdl_tpu.serve.batcher.DynamicBatcher` and running padded
  fixed-shape batches through the same mesh-sharded forward engine
  Predictor/Evaluator use (`optim.optimizer._ShardedForward`) — online
  answers are the SAME arithmetic as bulk `Predictor.predict`;
- replicas heartbeat their own supervisor **channel**
  (`utils.supervisor.Supervisor.channel`, phase ``serve``), so a wedged
  replica trips a stall with a crash report instead of hanging its
  callers silently;
- a **model version** bundles (module, params, engine); ``swap()`` loads
  a new version through the existing checkpoint-lineage/`file_io` path
  (CRC-verified, retried remote IO), optionally int8-quantizes it
  (`bigdl_tpu.quantize`), warms its batch shapes, then flips one
  reference — in-flight batches finish on the old version, queued
  requests run on the new one, zero requests dropped;
- everything is instrumented: per-batch ``serve.batch`` spans, a
  ``serve`` counter track (queue depth / batch fill), ``serve.swap``
  instants, and the ``serve.request``/``serve.batch`` chaos points for
  fault drills (a ChaosFault in a batch surfaces as a typed per-request
  error; the server keeps serving).

Knobs (utils/config tier; constructor args override):

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_SERVE_MAX_BATCH`` | max requests coalesced per device batch | 8 |
| ``BIGDL_TPU_SERVE_MAX_WAIT_MS`` | flush deadline: max ms the oldest request waits for fill | 5 |
| ``BIGDL_TPU_SERVE_QUEUE_LIMIT`` | bounded queue; admission past it -> ServerOverloaded | 64 |
| ``BIGDL_TPU_SERVE_REPLICAS`` | worker threads draining the shared queue | 1 |
| ``BIGDL_TPU_SERVE_DEADLINE_MS`` | default per-request deadline (0 = none) | 0 |
| ``BIGDL_TPU_SERVE_STALL_SECONDS`` | per-replica supervision deadline (0 = unwatched) | 0 |
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import numpy as np

from ..nn.module import Module
from ..utils import chaos, config, telemetry
from ..utils.supervisor import StallError, Supervisor
from .batcher import (DynamicBatcher, PendingRequest, ServeError,
                      default_buckets, pad_rows)

logger = logging.getLogger("bigdl_tpu")

__all__ = ["ModelVersion", "InferenceServer"]


class ModelVersion:
    """One servable (module, params, engine) bundle.  Immutable once
    built; the server flips between versions by replacing one reference."""

    def __init__(self, vid: int, module: Module, label: str,
                 strategy=None):
        from ..optim.optimizer import _ShardedForward
        if module.params is None:
            module.build()
        self.id = int(vid)
        self.label = label
        self.module = module
        self._engine = _ShardedForward(module, strategy)

    def predict(self, batch: np.ndarray) -> np.ndarray:
        """Forward one padded fixed-shape batch; returns host rows (the
        engine pads to the mesh's data-axis multiple internally — the
        same program bulk Predictor.predict runs)."""
        out, n = self._engine(batch)
        return np.asarray(out)[:len(batch)]


def _clone_with(module: Module, params, state) -> Module:
    """A structural clone of `module` serving different weights: modules
    carry no authoritative pytrees below the top (nn/module.py Container
    note), so a shallow copy + attach is a full new version while the
    original keeps serving its own params untouched."""
    import copy
    clone = copy.copy(module)
    clone.attach(params, state)
    return clone


class InferenceServer:
    """Online serving facade over a trained Module (see module docstring).

    Usage::

        server = InferenceServer(model, example=x0).start()
        y = server.predict(x)                  # blocking convenience
        h = server.submit(x, deadline_ms=50)   # async handle
        ...
        server.swap("/ckpts/run1")             # newest lineage snapshot
        server.stop()                          # graceful drain

    Also a context manager (``with InferenceServer(...) as s:``)."""

    def __init__(self, model: Module, *,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None,
                 replicas: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 example: Optional[np.ndarray] = None,
                 strategy=None,
                 supervisor: Optional[Supervisor] = None,
                 stall_seconds: Optional[float] = None,
                 report_dir: Optional[str] = None,
                 clock=None):
        self.max_batch = int(max_batch if max_batch is not None
                             else config.get_int("SERVE_MAX_BATCH", 8))
        wait_ms = (max_wait_ms if max_wait_ms is not None
                   else config.get_float("SERVE_MAX_WAIT_MS", 5.0))
        self.queue_limit = int(queue_limit if queue_limit is not None
                               else config.get_int("SERVE_QUEUE_LIMIT", 64))
        self.replicas = int(replicas if replicas is not None
                            else config.get_int("SERVE_REPLICAS", 1))
        self.default_deadline_ms = (
            deadline_ms if deadline_ms is not None
            else config.get_float("SERVE_DEADLINE_MS", 0.0))
        self._strategy = strategy
        self.batcher = DynamicBatcher(self.max_batch, wait_ms / 1000.0,
                                      self.queue_limit, buckets=buckets,
                                      clock=clock)
        self._example = None if example is None else np.asarray(example)
        self._version = ModelVersion(1, model, "initial", strategy)
        self._lock = threading.Lock()       # stats + version flip (brief)
        self._swap_lock = threading.Lock()  # serialize concurrent swaps
        self._threads: list = []
        self._stats = {"batches": 0, "batch_rows": 0, "batch_errors": 0,
                       "bucket_rows": 0, "swaps": 0}
        # supervision: an embedder-owned Supervisor, or our own from the
        # SERVE_STALL_SECONDS knob — each replica heartbeats a channel
        # under phase 'serve' so a wedged one trips a stall+crash report
        self._sup = supervisor
        self._own_sup = False
        if self._sup is None:
            d = (stall_seconds if stall_seconds is not None
                 else config.get_float("SERVE_STALL_SECONDS", 0.0))
            if d > 0:
                self._sup = Supervisor({"serve": d}, report_dir=report_dir,
                                       name="bigdl-serve-supervisor")
                self._own_sup = True

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "InferenceServer":
        if self._threads:
            return self
        if self.batcher.closed:
            raise ServeError("serve: cannot restart a stopped server")
        if self._own_sup:
            self._sup.start()
        if self._example is not None:
            self.warmup()
        for i in range(self.replicas):
            t = threading.Thread(target=self._worker, args=(i,),
                                 daemon=True,
                                 name=f"bigdl-serve-replica-{i}")
            t.start()
            self._threads.append(t)
        logger.info("serve: started %d replica(s), max_batch=%d, "
                    "buckets=%s, queue_limit=%d", self.replicas,
                    self.max_batch, self.batcher.buckets, self.queue_limit)
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Shut down.  drain=True (graceful) answers everything already
        queued before workers exit; drain=False fails queued requests
        with ServerClosed.  Idempotent; joins every replica thread."""
        # with no workers running there is nobody to drain the queue —
        # draining would strand queued requests' result() forever
        self.batcher.close(drain=drain and bool(self._threads))
        for t in self._threads:
            t.join(timeout=timeout)
        leaked = [t.name for t in self._threads if t.is_alive()]
        self._threads = []
        if self._own_sup:
            self._sup.stop()
        if leaked:
            raise ServeError(f"serve: replica thread(s) did not exit "
                             f"within {timeout}s: {leaked}")

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- request path ---------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None
               ) -> PendingRequest:
        """Enqueue one sample (NOT a batch — the batcher owns batching);
        returns a handle whose ``result()`` is the per-sample output row.
        Raises ServerOverloaded / ServerClosed at admission."""
        x = np.asarray(x)
        if self._example is None:
            # remember the sample shape so later swaps can warm up the
            # new version's batch shapes before taking traffic
            self._example = np.zeros_like(x)
        elif x.shape != self._example.shape:
            # reject shape strays at admission: one odd sample must not
            # reach np.stack inside a coalesced batch, where the failure
            # would hit its innocent batch-mates too
            raise ServeError(
                f"serve: sample shape {x.shape} does not match the "
                f"server's example shape {self._example.shape}")
        ms = (deadline_ms if deadline_ms is not None
              else self.default_deadline_ms)
        deadline = (self.batcher.clock() + ms / 1000.0) if ms and ms > 0 \
            else None
        return self.batcher.submit(x, deadline)

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit + wait."""
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    # -- replica workers ------------------------------------------------

    def _worker(self, idx: int) -> None:
        telemetry.thread_name(f"serve replica {idx}")
        chan = (self._sup.channel(f"serve-replica-{idx}", phase="serve")
                if self._sup is not None else None)
        beat = chan.beat if chan is not None else None
        try:
            while True:
                try:
                    if beat is not None:
                        beat()
                    reqs = self.batcher.collect(heartbeat=beat)
                    if reqs is None:
                        return
                    if reqs:
                        self._execute(reqs, beat)
                except StallError:
                    # the supervisor async-raised into this replica while
                    # it was between batches (a stall DURING a batch is
                    # caught by _execute and fails that batch typed);
                    # the crash report is already written — keep serving
                    logger.warning("serve: replica %d received a stall "
                                   "notice between batches; continuing",
                                   idx)
                except Exception as e:  # noqa: BLE001 — replica backstop
                    # _execute resolves its own batch's errors, so reqs
                    # dequeued by a failed iteration are already answered;
                    # anything that still escapes (collect-path surprise,
                    # telemetry sink error...) must not take the replica
                    # down with it — a dead replica silently shrinks the
                    # pool until the server stops serving
                    logger.exception(
                        "serve: replica %d loop error (%s); continuing",
                        idx, type(e).__name__)
        finally:
            if chan is not None:
                chan.close()

    def _execute(self, reqs, beat) -> None:
        version = self._version  # one snapshot: a swap mid-batch cannot
        # split the batch across versions (no misrouted requests)
        n = len(reqs)
        bucket = self.batcher.bucket_for(n)
        try:
            # batch assembly is inside the guard too: a stray payload that
            # defeats admission-time shape checks (or OOMs the stack) must
            # fail ITS batch typed, not kill the replica thread
            batch = pad_rows(np.stack([r.payload for r in reqs]), bucket)
            with telemetry.span("serve.batch", cat="serve", size=n,
                                bucket=bucket, version=version.id):
                chaos.fire("serve.batch")
                out = version.predict(batch)
        except Exception as e:  # noqa: BLE001 — typed per-request error
            # (ChaosFault, StallError, backend error...): the batch fails
            # loudly to its callers, the replica and queue survive
            now = self.batcher.clock()
            for r in reqs:
                r._resolve(error=e, now=now)
            with self._lock:
                self._stats["batch_errors"] += 1
            logger.warning("serve: batch of %d failed: %s: %s", n,
                           type(e).__name__, e)
            return
        now = self.batcher.clock()
        for i, r in enumerate(reqs):
            r._resolve(result=out[i], version=version.id, now=now)
        with self._lock:
            self._stats["batches"] += 1
            self._stats["batch_rows"] += n
            self._stats["bucket_rows"] += bucket
        telemetry.counter("serve", queue_depth=self.batcher.depth(),
                          batch_fill=n / bucket)
        if beat is not None:
            beat()

    # -- warmup ---------------------------------------------------------

    def warmup(self, example: Optional[np.ndarray] = None) -> None:
        """Compile every bucket shape on the CURRENT version before (or
        between) traffic, so steady state never recompiles.

        With the AOT executable cache armed (``BIGDL_TPU_AOT_CACHE``,
        utils/aot.py), a warm process turns the whole bucket ladder into
        N cache reads — zero fresh lowers, zero XLA compiles — so a
        swapped-in replica reaches serving-ready in seconds instead of
        minutes.  The first process to run a ladder populates the cache;
        ``stats()["aot"]`` shows the hit/miss ledger."""
        ex = np.asarray(example) if example is not None else self._example
        if ex is None:
            raise ValueError("serve: warmup needs an example sample "
                             "(pass example= here or at construction)")
        self._example = ex
        self._warm_version(self._version, ex)

    def _warm_version(self, version: ModelVersion, ex: np.ndarray) -> None:
        with telemetry.span("serve.warmup", cat="serve",
                            version=version.id):
            for b in self.batcher.buckets:
                version.predict(np.stack([ex] * b))

    # -- hot swap -------------------------------------------------------

    def swap(self, source, *, quantized: bool = False,
             state=None) -> int:
        """Install a new model version with ZERO dropped requests.

        source: a checkpoint DIRECTORY (newest lineage snapshot via
        file_io.latest_checkpoint — CRC-verified, quarantine-aware), a
        snapshot/module FILE path, a params pytree, or a built Module.
        quantized=True additionally int8-quantizes the loaded weights
        (bigdl_tpu.quantize) before serving them.

        The new version is fully built — loaded, (optionally) quantized,
        engine constructed, batch shapes warmed — BEFORE one reference
        flip makes it live: in-flight batches finish on the old version,
        every queued/new request runs on the new one."""
        # the slow build (retried remote IO, quantize, engine, warmup)
        # runs under its OWN lock: _lock guards only the reference flip
        # and per-batch stats, so replicas keep answering traffic for the
        # whole duration of a swap — serialize concurrent swaps, never
        # the data path
        with self._swap_lock:
            vid = self._version.id + 1
            module, label = self._load_module(source, state)
            if quantized:
                from ..quantize import quantize
                module = quantize(module)
                label += "+int8"
            version = ModelVersion(vid, module, label, self._strategy)
            if self._example is not None:
                self._warm_version(version, self._example)
            with self._lock:
                self._version = version  # the atomic flip
                self._stats["swaps"] += 1
        telemetry.instant("serve.swap", cat="serve", version=vid,
                          label=label)
        logger.info("serve: hot-swapped to version %d (%s)", vid, label)
        return vid

    def _load_module(self, source, state):
        from ..utils import file_io
        arch = self._version.module
        if isinstance(source, Module):
            if source.params is None:
                source.build()
            return source, f"module:{type(source).__name__}"
        if isinstance(source, str):
            latest = file_io.latest_checkpoint(source)
            if latest is not None:  # checkpoint directory: newest snapshot
                mp, _op, neval = latest
                blob = file_io.load(mp)
                return (_clone_with(arch, blob["params"], blob["state"]),
                        f"ckpt:{source}@{neval}")
            blob = file_io.load(source)
            if isinstance(blob, dict) and \
                    blob.get("format") == "bigdl_tpu-module-v1":
                m = blob["module"]
                m.attach(blob["params"], blob["state"])
                return m, f"file:{source}"
            if isinstance(blob, dict) and "params" in blob:
                return (_clone_with(arch, blob["params"],
                                    blob.get("state")), f"file:{source}")
            raise ValueError(f"serve: {source!r} is neither a checkpoint "
                             "directory, a model snapshot, nor a module "
                             "file")
        # params pytree swapped in directly (e.g. from a live Optimizer)
        return _clone_with(arch, source, state), "params"

    # -- introspection --------------------------------------------------

    @property
    def version(self) -> ModelVersion:
        return self._version

    def stats(self) -> dict:
        """One merged counter snapshot: admission/shed counts (batcher),
        batch counts/fill, swaps, current version."""
        out = self.batcher.stats()
        with self._lock:
            out.update(self._stats)
            out["version"] = self._version.id
            out["version_label"] = self._version.label
        out["batch_fill"] = (round(out["batch_rows"] /
                                   max(out["bucket_rows"], 1), 4))
        out["replicas"] = self.replicas
        from ..utils import aot
        if aot.enabled():
            # warm-start ledger: a freshly swapped/restarted replica that
            # served its ladder from the AOT cache shows hits==buckets,
            # misses==0 here (process-wide counters, utils/aot.py)
            s = aot.stats()
            out["aot"] = {k: int(s[k]) for k in
                          ("hits", "misses", "stores", "lowers",
                           "compiles", "corrupt")}
        return out
