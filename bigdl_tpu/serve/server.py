"""Online inference server: replica pool, deadline shedding, hot model swap.

The device-side half of the serving subsystem (see serve/batcher.py for
the host-side queue/coalescing).  Composes pieces the training stack
already has into an online server:

- each **replica** is a worker thread draining the shared
  :class:`~bigdl_tpu.serve.batcher.DynamicBatcher` and running padded
  fixed-shape batches through the same mesh-sharded forward engine
  Predictor/Evaluator use (`optim.optimizer._ShardedForward`) — online
  answers are the SAME arithmetic as bulk `Predictor.predict`;
- replicas heartbeat their own supervisor **channel**
  (`utils.supervisor.Supervisor.channel`, phase ``serve``), so a wedged
  replica trips a stall with a crash report instead of hanging its
  callers silently;
- a **model version** bundles (module, params, engine); ``swap()`` loads
  a new version through the existing checkpoint-lineage/`file_io` path
  (CRC-verified, retried remote IO), optionally int8-quantizes it
  (`bigdl_tpu.quantize`), warms its batch shapes, then flips one
  reference — in-flight batches finish on the old version, queued
  requests run on the new one, zero requests dropped;
- the **control plane** (serve/control.py) closes the loop the trainer
  already has: a dead/silent replica is restarted (bounded budget,
  exponential backoff, bucket ladder re-warmed through the AOT cache),
  ``swap(..., canary_fraction=f)`` routes a weighted slice of batches to
  the candidate and auto-promotes or auto-rolls-back on a rolling
  p99/error-rate comparison, and admission is tenant/priority-aware
  (token-bucket quotas, shed-lowest-priority-first) — see
  docs/serving.md "Self-healing & resilience";
- everything is instrumented: per-batch ``serve.batch`` spans, a
  ``serve`` counter track (queue depth / batch fill), ``serve.swap``/
  ``serve.replica_lost``/``serve.canary`` instants, and the
  ``serve.request``/``serve.batch``/``serve.replica@<idx>``/
  ``serve.canary`` chaos points for fault drills (a ChaosFault in a
  batch surfaces as a typed per-request error; the server keeps
  serving).

Knobs (utils/config tier; constructor args override):

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_SERVE_MAX_BATCH`` | max requests coalesced per device batch | 8 |
| ``BIGDL_TPU_SERVE_MAX_WAIT_MS`` | flush deadline: max ms the oldest request waits for fill | 5 |
| ``BIGDL_TPU_SERVE_QUEUE_LIMIT`` | bounded queue; admission past it -> ServerOverloaded | 64 |
| ``BIGDL_TPU_SERVE_REPLICAS`` | worker threads draining the shared queue | 1 |
| ``BIGDL_TPU_SERVE_DEADLINE_MS`` | default per-request deadline (0 = none) | 0 |
| ``BIGDL_TPU_SERVE_STALL_SECONDS`` | per-replica supervision deadline (0 = unwatched) | 0 |
| ``BIGDL_TPU_SERVE_REPLICA_LOST`` | replica heartbeat-silence seconds before restart (0 = monitor off) | 0 |
| ``BIGDL_TPU_SERVE_RESTART_BUDGET`` | restarts per replica before the server flips unhealthy | 3 |
| ``BIGDL_TPU_SERVE_RESTART_BACKOFF`` | base restart backoff seconds (doubles per restart) | 0.1 |
| ``BIGDL_TPU_SERVE_CANARY_MIN_BATCHES`` | clean canary batches required to promote | 8 |
| ``BIGDL_TPU_SERVE_CANARY_WINDOW`` | rolling latency-window size per arm (batches) | 64 |
| ``BIGDL_TPU_SERVE_CANARY_LATENCY_RATIO`` | rollback when canary p99 > ratio x incumbent p99 | 2.0 |
| ``BIGDL_TPU_SERVE_CANARY_ERROR_MARGIN`` | rollback when canary error rate > incumbent + margin | 0.05 |
| ``BIGDL_TPU_SERVE_TENANT_QPS`` | per-tenant token-bucket refill, req/s (0 = quotas off) | 0 |
| ``BIGDL_TPU_SERVE_TENANT_BURST`` | per-tenant bucket depth (0 = 2x qps, min 1) | 0 |
| ``BIGDL_TPU_SERVE_AUTOSCALE_MAX`` | pool ceiling; > 0 arms queue-driven autoscaling (serve/autoscale.py) | 0 |
| ``BIGDL_TPU_SERVE_AUTOSCALE_MIN`` | pool floor under autoscaling | replicas |
| ``BIGDL_TPU_SERVE_AUTOSCALE_TARGET_WAIT_MS`` | est. queue wait that triggers growth | 50 |
| ``BIGDL_TPU_SERVE_AUTOSCALE_IDLE_S`` | sustained-idle seconds before one shrink step | 2.0 |
| ``BIGDL_TPU_SERVE_AUTOSCALE_COOLDOWN_S`` | min seconds between scale actions | 0.5 |
| ``BIGDL_TPU_SERVE_AUTOSCALE_UP_POLLS`` | consecutive over-target polls before growing | 2 |
| ``BIGDL_TPU_SERVE_AUTOSCALE_STEP`` | replicas added per scale-up | 1 |
| ``BIGDL_TPU_SERVE_AUTOSCALE_POLL_S`` | controller poll cadence | 0.05 |
| ``BIGDL_TPU_SERVE_TRACE_LIMIT`` | max in-memory trace events while recording | 100000 |
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import numpy as np

from ..nn.module import Module
from ..utils import chaos, config, metrics_export, telemetry
from ..utils.supervisor import StallError, Supervisor
from . import control
from .batcher import (DynamicBatcher, PendingRequest, ServeError,
                      default_buckets, fit_bucket, pad_rows, pad_tail)

logger = logging.getLogger("bigdl_tpu")

__all__ = ["ModelVersion", "InferenceServer"]


class ModelVersion:
    """One servable (module, params, engine) bundle.  Immutable once
    built; the server flips between versions by replacing one reference.

    ``mesh`` pins the forward engine to a fixed device subset instead of
    the process-wide ``Engine.mesh()`` — the topology router
    (serve/router.py) places each replica's versions on its own disjoint
    subset this way."""

    def __init__(self, vid: int, module: Module, label: str,
                 strategy=None, mesh=None):
        from ..optim.optimizer import _ShardedForward
        if module.params is None:
            module.build()
        self.id = int(vid)
        self.label = label
        self.module = module
        self._engine = _ShardedForward(module, strategy, mesh=mesh)

    def predict(self, batch: np.ndarray) -> np.ndarray:
        """Forward one padded fixed-shape batch; returns host rows (the
        engine pads to the mesh's data-axis multiple internally — the
        same program bulk Predictor.predict runs)."""
        out, n = self._engine(batch)
        return np.asarray(out)[:len(batch)]


def _clone_with(module: Module, params, state) -> Module:
    """A structural clone of `module` serving different weights: modules
    carry no authoritative pytrees below the top (nn/module.py Container
    note), so a shallow copy + attach is a full new version while the
    original keeps serving its own params untouched."""
    import copy
    clone = copy.copy(module)
    clone.attach(params, state)
    return clone


class InferenceServer:
    """Online serving facade over a trained Module (see module docstring).

    Usage::

        server = InferenceServer(model, example=x0).start()
        y = server.predict(x)                  # blocking convenience
        h = server.submit(x, deadline_ms=50)   # async handle
        ...
        server.swap("/ckpts/run1")             # newest lineage snapshot
        server.stop()                          # graceful drain

    Also a context manager (``with InferenceServer(...) as s:``)."""

    def __init__(self, model: Module, *,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None,
                 replicas: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 example: Optional[np.ndarray] = None,
                 strategy=None,
                 supervisor: Optional[Supervisor] = None,
                 stall_seconds: Optional[float] = None,
                 report_dir: Optional[str] = None,
                 clock=None,
                 replica_lost: Optional[float] = None,
                 restart_budget: Optional[int] = None,
                 restart_backoff: Optional[float] = None,
                 tenant_qps: Optional[float] = None,
                 tenant_burst: Optional[float] = None,
                 canary_min_batches: Optional[int] = None,
                 canary_window: Optional[int] = None,
                 canary_latency_ratio: Optional[float] = None,
                 canary_error_margin: Optional[float] = None,
                 mesh=None,
                 autoscale_min: Optional[int] = None,
                 autoscale_max: Optional[int] = None,
                 autoscale_target_wait_ms: Optional[float] = None,
                 autoscale_idle_s: Optional[float] = None,
                 autoscale_cooldown_s: Optional[float] = None,
                 autoscale_up_polls: Optional[int] = None,
                 autoscale_step: Optional[int] = None,
                 autoscale_poll_s: Optional[float] = None):
        self.max_batch = int(max_batch if max_batch is not None
                             else config.get_int("SERVE_MAX_BATCH", 8))
        wait_ms = (max_wait_ms if max_wait_ms is not None
                   else config.get_float("SERVE_MAX_WAIT_MS", 5.0))
        self.queue_limit = int(queue_limit if queue_limit is not None
                               else config.get_int("SERVE_QUEUE_LIMIT", 64))
        self.replicas = int(replicas if replicas is not None
                            else config.get_int("SERVE_REPLICAS", 1))
        self.default_deadline_ms = (
            deadline_ms if deadline_ms is not None
            else config.get_float("SERVE_DEADLINE_MS", 0.0))
        self._strategy = strategy
        self._mesh = mesh                   # pinned device subset (router)
        self.batcher = DynamicBatcher(self.max_batch, wait_ms / 1000.0,
                                      self.queue_limit, buckets=buckets,
                                      clock=clock)
        # sequence-length ladder for variable-length workloads (None =
        # fixed-shape samples, byte-identical behavior).  Requests pad
        # their TRAILING axis to the smallest bucket that fits at batch
        # assembly, so the device only ever sees (batch-bucket, seq-bucket)
        # product shapes — all warmed up front — and a request's answer
        # never depends on its batch-mates' lengths (bit-match with bulk
        # Predictor at the same padded length).
        self.seq_buckets = (tuple(sorted(int(b) for b in seq_buckets))
                            if seq_buckets else None)
        self._example = None if example is None else np.asarray(example)
        self._version = ModelVersion(1, model, "initial", strategy,
                                     mesh=mesh)
        self._vid = 1                       # monotonic version ids
        self._lock = threading.Lock()       # stats + version flip (brief)
        self._swap_lock = threading.Lock()  # serialize concurrent swaps
        self._scale_lock = threading.Lock()  # serialize pool resizes
        self._threads: list = []
        # replica lifecycle state (serve/control.ReplicaMonitor): idx ->
        # [thread, generation, last local heartbeat].  The generation is
        # the condemnation mechanism — a zombie whose generation moved on
        # requeues any held batch and exits.
        self._replica: dict = {}
        self._monitor: Optional[control.ReplicaMonitor] = None
        self._unhealthy: Optional[Exception] = None
        self._canary: Optional[control.CanaryController] = None
        self._canary_last: Optional[dict] = None
        self._stats = {"batches": 0, "batch_rows": 0, "batch_errors": 0,
                       "bucket_rows": 0, "swaps": 0, "restarts": 0,
                       "canary_rollbacks": 0}
        # control-plane knobs (serve/control.py; docs/serving.md)
        self._replica_lost = float(
            replica_lost if replica_lost is not None
            else config.get_float("SERVE_REPLICA_LOST", 0.0))
        self._restart_budget = int(
            restart_budget if restart_budget is not None
            else config.get_int("SERVE_RESTART_BUDGET", 3))
        self._restart_backoff = float(
            restart_backoff if restart_backoff is not None
            else config.get_float("SERVE_RESTART_BACKOFF", 0.1))
        self._canary_cfg = {
            "min_batches": int(
                canary_min_batches if canary_min_batches is not None
                else config.get_int("SERVE_CANARY_MIN_BATCHES", 8)),
            "window": int(
                canary_window if canary_window is not None
                else config.get_int("SERVE_CANARY_WINDOW", 64)),
            "latency_ratio": float(
                canary_latency_ratio if canary_latency_ratio is not None
                else config.get_float("SERVE_CANARY_LATENCY_RATIO", 2.0)),
            "error_margin": float(
                canary_error_margin if canary_error_margin is not None
                else config.get_float("SERVE_CANARY_ERROR_MARGIN", 0.05))}
        qps = float(tenant_qps if tenant_qps is not None
                    else config.get_float("SERVE_TENANT_QPS", 0.0))
        burst = (tenant_burst if tenant_burst is not None
                 else config.get_float("SERVE_TENANT_BURST", 0.0))
        self._quotas = (control.TenantQuotas(qps, burst=burst,
                                             clock=self.batcher.clock)
                        if qps > 0 else None)
        # queue-driven autoscaling (serve/autoscale.py): _MAX > 0 arms a
        # controller that grows/shrinks the worker pool between the
        # bounds — scale-up reuses this version's already-warm engine
        # (zero compiles), shrink retires the highest replica slots
        from . import autoscale as autoscale_mod
        self._autoscale_cfg = autoscale_mod.autoscale_knobs(
            self.replicas,
            {"min_replicas": autoscale_min, "max_replicas": autoscale_max,
             "target_wait_ms": autoscale_target_wait_ms,
             "idle_s": autoscale_idle_s, "cooldown_s": autoscale_cooldown_s,
             "up_polls": autoscale_up_polls, "step": autoscale_step,
             "poll_s": autoscale_poll_s})
        self._autoscaler: Optional[autoscale_mod.AutoScaler] = None
        # offered-traffic trace capture (serve/tracefile.py), armed by
        # record_trace() / the HTTP X-BigDL-Record-Trace header
        self._recorder = None
        # continuous-deployment controller (serve/continuous.py), set by
        # DeployController.start() so its timeline rides stats()["deploy"]
        self._deploy = None
        # supervision: an embedder-owned Supervisor, or our own from the
        # SERVE_STALL_SECONDS knob — each replica heartbeats a channel
        # under phase 'serve' so a wedged one trips a stall+crash report
        self._sup = supervisor
        self._own_sup = False
        if self._sup is None:
            d = (stall_seconds if stall_seconds is not None
                 else config.get_float("SERVE_STALL_SECONDS", 0.0))
            if d > 0:
                self._sup = Supervisor({"serve": d}, report_dir=report_dir,
                                       name="bigdl-serve-supervisor")
                self._own_sup = True

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "InferenceServer":
        if self._threads:
            return self
        if self.batcher.closed:
            raise ServeError("serve: cannot restart a stopped server")
        if self._own_sup:
            self._sup.start()
        if self._example is not None:
            self.warmup()
        for i in range(self.replicas):
            self._spawn_replica(i)
        if self._replica_lost > 0:
            self._monitor = control.ReplicaMonitor(
                self, self._replica_lost, budget=self._restart_budget,
                backoff=self._restart_backoff).start()
        if self._autoscale_cfg["max_replicas"] > 0:
            from . import autoscale as autoscale_mod
            cfg = dict(self._autoscale_cfg)
            cfg["min_replicas"] = min(cfg["min_replicas"], self.replicas)
            cfg["max_replicas"] = max(cfg["max_replicas"],
                                      cfg["min_replicas"])
            poll = cfg.pop("poll_s")
            self._autoscaler = autoscale_mod.AutoScaler(
                self, poll_s=poll, clock=self.batcher.clock,
                **cfg).start()
        logger.info("serve: started %d replica(s), max_batch=%d, "
                    "buckets=%s, queue_limit=%d%s", self.replicas,
                    self.max_batch, self.batcher.buckets, self.queue_limit,
                    f", replica_lost={self._replica_lost:g}s"
                    if self._monitor is not None else "")
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Shut down.  drain=True (graceful) answers everything already
        queued before workers exit; drain=False fails queued requests
        with ServerClosed.  Idempotent; joins every replica thread.
        Whatever is STILL queued once the workers are gone — a dead
        pool, a drain the workers never finished — fails with a typed
        ServerClosed instead of leaving callers blocked on ``result()``
        forever."""
        if self._autoscaler is not None:
            # the controller must not resize a pool that is shutting down
            self._autoscaler.stop()
        if self._monitor is not None:
            # the monitor must not respawn replicas into a shutdown
            self._monitor.stop()
        if self._recorder is not None and self._recorder.path:
            try:  # flush an armed trace so recordings survive shutdown
                self._recorder.save()
            except Exception:  # noqa: BLE001 — recording is best-effort
                logger.exception("serve: trace flush failed at shutdown")
        # with no LIVE workers there is nobody to drain the queue —
        # draining would strand queued requests' result() forever
        self.batcher.close(
            drain=drain and any(t.is_alive() for t in self._threads))
        for t in self._threads:
            t.join(timeout=timeout)
        leaked = [t.name for t in self._threads if t.is_alive()]
        self._threads = []
        stranded = self.batcher.fail_pending()
        if stranded:
            logger.warning("serve: failed %d still-queued request(s) "
                           "with ServerClosed at shutdown (no worker "
                           "drained them)", stranded)
        if self._own_sup:
            self._sup.stop()
        if leaked:
            raise ServeError(f"serve: replica thread(s) did not exit "
                             f"within {timeout}s: {leaked}")

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- request path ---------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: int = 0,
               request_id: Optional[str] = None) -> PendingRequest:
        """Enqueue one sample (NOT a batch — the batcher owns batching);
        returns a handle whose ``result()`` is the per-sample output row.
        Raises ServerOverloaded / QuotaExceeded / ServerClosed at
        admission.  ``tenant`` tags the request for token-bucket quotas
        (``SERVE_TENANT_QPS``); ``priority`` (higher = more important)
        decides who is shed first under queue pressure; ``request_id``
        is the distributed-tracing flow id from the
        ``X-BigDL-Request-Id`` header (minted locally when absent and
        tracing is on)."""
        if self._unhealthy is not None and not self._pool_alive():
            # the restart budget is spent and nobody is left to serve:
            # admitting would strand the caller on result() forever
            raise control.ReplicaLostError(
                f"serve: pool unhealthy — {self._unhealthy}")
        x = np.asarray(x)
        if self._example is None:
            # remember the sample shape so later swaps can warm up the
            # new version's batch shapes before taking traffic
            self._example = np.zeros_like(x)
        elif self.seq_buckets is not None:
            # variable-length admission: leading dims fixed, trailing axis
            # may be any length that fits the sequence ladder
            if x.ndim != self._example.ndim or \
                    x.shape[:-1] != self._example.shape[:-1]:
                raise ServeError(
                    f"serve: sample shape {x.shape} does not match the "
                    f"server's example shape {self._example.shape} "
                    "(leading dims must agree under seq_buckets)")
            if fit_bucket(x.shape[-1], self.seq_buckets) is None:
                raise ServeError(
                    f"serve: sample length {x.shape[-1]} exceeds the "
                    f"largest sequence bucket {self.seq_buckets[-1]} "
                    "(refusing to truncate)")
        elif x.shape != self._example.shape:
            # reject shape strays at admission: one odd sample must not
            # reach np.stack inside a coalesced batch, where the failure
            # would hit its innocent batch-mates too
            raise ServeError(
                f"serve: sample shape {x.shape} does not match the "
                f"server's example shape {self._example.shape}")
        ms = (deadline_ms if deadline_ms is not None
              else self.default_deadline_ms)
        if self._recorder is not None:
            # record OFFERED traffic (shed requests included — they are
            # real load), after shape validation so the trace replays
            self._recorder.note(x, tenant=tenant, priority=priority,
                                deadline_ms=ms if ms and ms > 0 else None)
        if self._quotas is not None:
            try:
                self._quotas.admit(tenant)
            except Exception:
                reg = metrics_export._REGISTRY
                if reg is not None:
                    reg.shed("quota")
                raise
        deadline = (self.batcher.clock() + ms / 1000.0) if ms and ms > 0 \
            else None
        return self.batcher.submit(x, deadline, tenant=tenant,
                                   priority=priority,
                                   request_id=request_id)

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit + wait."""
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    # -- replica lifecycle (serve/control.ReplicaMonitor hooks) ---------

    def _spawn_replica(self, idx: int) -> threading.Thread:
        """Start (or re-start) the worker thread for replica slot `idx`,
        bumping its generation — any previous incarnation that wakes up
        later sees the newer generation, requeues its batch and exits."""
        st = self._replica.setdefault(
            idx, [None, 0, self.batcher.clock()])
        st[1] += 1
        st[2] = self.batcher.clock()
        t = threading.Thread(target=self._worker, args=(idx, st[1]),
                             daemon=True,
                             name=f"bigdl-serve-replica-{idx}")
        st[0] = t
        t.start()
        self._threads.append(t)
        return t

    def _condemn_replica(self, idx: int) -> None:
        """Retire the current incarnation of replica `idx` (generation
        bump, no thread kill — an uninterruptibly wedged thread cannot be
        killed; it retires itself at its next loop turn)."""
        st = self._replica.get(idx)
        if st is not None:
            st[1] += 1

    def _restart_replica(self, idx: int) -> None:
        """Respawn replica `idx` on a FRESH forward engine: the current
        version's module gets a new engine whose bucket ladder is
        re-warmed before the flip — with the AOT executable cache armed
        the whole ladder is cache reads (zero fresh lowers), so restart
        is seconds, not a cold compile.  Runs on the monitor thread; the
        old engine keeps answering until the flip."""
        if self.batcher.closed or idx >= self.replicas:
            # retired by a pool shrink: the monitor must not heal a slot
            # the autoscaler deliberately emptied
            return
        with self._lock:
            old = self._version
        try:
            version = ModelVersion(old.id, old.module, old.label,
                                   self._strategy, mesh=self._mesh)
            if self._example is not None:
                self._warm_version(version, self._example)
            with self._lock:
                if self._version is old:  # a swap may have raced us
                    self._version = version
        except Exception:  # noqa: BLE001 — a broken rebuild must not
            # stop the respawn: the old engine still works
            logger.exception("serve: replica %d engine rebuild failed; "
                             "respawning on the existing engine", idx)
        with self._lock:
            self._stats["restarts"] += 1
        reg = metrics_export._REGISTRY
        if reg is not None:
            reg.counter_inc("bigdl_serve_restarts_total", 1.0,
                            help="replica respawns by the monitor")
        self._spawn_replica(idx)
        telemetry.instant("serve.replica_restart", cat="serve",
                          replica=idx)
        logger.info("serve: replica %d restarted (bucket ladder "
                    "re-warmed)", idx)

    # -- elastic pool size (serve/autoscale.AutoScaler hooks) -----------

    def scale_to(self, n: int) -> int:
        """Resize the worker pool to ``n`` replicas (the autoscaler's
        actuator; also a manual operation).

        Growth spawns worker threads through the same path start() uses
        — they drain the shared queue through the CURRENT version's
        already-warm engine, so scale-up performs zero compiles and zero
        fresh lowers (the ladder was warmed at start/swap; with the AOT
        cache armed even THAT was cache reads — ``stats()["aot"]``).
        Shrink condemns the HIGHEST replica slots (generation bump): a
        condemned worker parked on the empty queue exits at its next
        wait slice, one holding a collected batch requeues it first —
        zero accepted-request loss — and the ReplicaMonitor skips
        retired slots so a scale-down is never "healed" back."""
        n = max(int(n), 1)
        with self._scale_lock:
            if self.batcher.closed:
                return self.replicas
            cur = self.replicas
            if n == cur:
                return cur
            if n > cur:
                for idx in range(cur, n):
                    self._spawn_replica(idx)
            else:
                for idx in range(n, cur):
                    self._condemn_replica(idx)
                # wake parked workers so condemned ones notice promptly
                with self.batcher._cond:
                    self.batcher._cond.notify_all()
            self.replicas = n
        logger.info("serve: pool scaled %d -> %d replica(s)", cur, n)
        return n

    def autoscale_signals(self) -> dict:
        """The controller's inputs (serve/autoscale.py): queued rows,
        EMA service seconds/row, cumulative served batches, and live
        worker count — all signals the server already maintained."""
        with self._lock:
            batches = self._stats["batches"]
        live = sum(1 for idx, st in self._replica.items()
                   if idx < self.replicas and st[0] is not None
                   and st[0].is_alive())
        return {"depth": self.batcher.depth(),
                "row_s_ema": self.batcher.service_row_seconds(),
                "batches": batches, "live": live}

    # -- traffic trace capture (serve/tracefile.py) ---------------------

    def record_trace(self, path: Optional[str] = None, *,
                     limit: Optional[int] = None):
        """Arm offered-traffic recording (idempotent for the same path).
        Every subsequent ``submit()`` — shed or served — is captured as
        a trace event; ``stop_trace()`` (or server stop, when a path is
        armed) writes the recordio trace file.  Returns the recorder."""
        from .tracefile import TraceRecorder
        if self._recorder is not None and (path is None or
                                           self._recorder.path == path):
            return self._recorder
        self._recorder = TraceRecorder(clock=self.batcher.clock,
                                       limit=limit, path=path)
        logger.info("serve: trace recording armed%s",
                    f" -> {path}" if path else " (in-memory)")
        return self._recorder

    def stop_trace(self, path: Optional[str] = None):
        """Disarm recording; write the trace when a path is armed (or
        given) and return the captured events."""
        rec, self._recorder = self._recorder, None
        if rec is None:
            return []
        if path or rec.path:
            n = rec.save(path)
            logger.info("serve: trace recording stopped — %d event(s) "
                        "-> %s", n, path or rec.path)
        return rec.events()

    def attach_deploy(self, controller) -> None:
        """Register a continuous-deployment controller
        (serve/continuous.DeployController) so its state surfaces in
        ``stats()["deploy"]`` / ``/v1/stats`` and the HTTP front end can
        serve ``/v1/versions`` from it."""
        self._deploy = controller

    def _mark_unhealthy(self, err: Exception) -> None:
        """The restart budget is exhausted: stop self-healing, surface it.
        ``/healthz`` flips 503 so an outer orchestrator replaces the
        process; with no live worker left, queued requests fail typed
        instead of hanging."""
        self._unhealthy = err
        telemetry.instant("serve.unhealthy", cat="serve", reason=str(err))
        logger.error("serve: UNHEALTHY — %s (restart budget %d "
                     "exhausted); /healthz now fails", err,
                     self._restart_budget)
        if not self._pool_alive():
            n = self.batcher.fail_pending(err)
            if n:
                logger.error("serve: failed %d queued request(s) with "
                             "the replica-lost error", n)

    def _pool_alive(self) -> bool:
        return any(t.is_alive() for t in self._threads)

    def healthy(self) -> bool:
        """False once the replica restart budget is exhausted — the
        ``/healthz`` signal for the outer orchestrator."""
        return self._unhealthy is None

    # -- replica workers ------------------------------------------------

    def _worker(self, idx: int, gen: int = 1) -> None:
        telemetry.thread_name(f"serve replica {idx}")
        st = self._replica.setdefault(
            idx, [None, gen, self.batcher.clock()])
        chan = (self._sup.channel(f"serve-replica-{idx}", phase="serve")
                if self._sup is not None else None)

        def beat(phase: Optional[str] = None) -> None:
            # the LOCAL stamp feeds the replica monitor (control plane);
            # the channel feeds the embedder's supervisor, when armed
            st[2] = self.batcher.clock()
            if chan is not None:
                chan.beat(phase)

        try:
            while True:
                try:
                    if st[1] != gen:
                        return  # condemned: a newer incarnation owns idx
                    beat()
                    # stop_when: a pool shrink condemns this slot while
                    # the worker is parked on an EMPTY queue — it must
                    # exit at the next wait slice, not linger until the
                    # next request arrives just to requeue it
                    reqs = self.batcher.collect(
                        heartbeat=beat, stop_when=lambda: st[1] != gen)
                    if reqs is None:
                        return
                    if st[1] != gen:
                        # condemned while collecting (e.g. woke from a
                        # wedge): zero accepted-request loss — hand the
                        # batch back for the replacement to serve
                        if telemetry.get_active() is not None:
                            for r in reqs:
                                telemetry.flow_step(r.rid,
                                                    hop="replica.lost",
                                                    replica=idx)
                        self.batcher.requeue(reqs)
                        return
                    if reqs:
                        try:
                            # replica-loss drill (serve/control.py):
                            # wedge blocks THIS thread uninterruptibly,
                            # exit kills it — after requeueing its batch
                            chaos.fire(f"serve.replica@{idx}",
                                       thread_exc=control.ReplicaExit)
                        except control.ReplicaExit as e:
                            # land the chaos kill on every held request's
                            # flow before the batch goes back to the queue
                            if telemetry.get_active() is not None:
                                for r in reqs:
                                    telemetry.flow_step(
                                        r.rid, hop="replica.lost",
                                        replica=idx)
                            self.batcher.requeue(reqs)
                            logger.error(
                                "serve: replica %d killed by chaos drill "
                                "(%s); batch of %d requeued", idx, e,
                                len(reqs))
                            return
                        self._execute(reqs, beat)
                except StallError:
                    # the supervisor async-raised into this replica while
                    # it was between batches (a stall DURING a batch is
                    # caught by _execute and fails that batch typed);
                    # the crash report is already written — keep serving
                    logger.warning("serve: replica %d received a stall "
                                   "notice between batches; continuing",
                                   idx)
                except Exception as e:  # noqa: BLE001 — replica backstop
                    # _execute resolves its own batch's errors, so reqs
                    # dequeued by a failed iteration are already answered;
                    # anything that still escapes (collect-path surprise,
                    # telemetry sink error...) must not take the replica
                    # down with it — a dead replica silently shrinks the
                    # pool until the server stops serving
                    logger.exception(
                        "serve: replica %d loop error (%s); continuing",
                        idx, type(e).__name__)
        finally:
            if chan is not None:
                chan.close()

    def _execute(self, reqs, beat) -> None:
        # one version snapshot per collect: a swap mid-batch cannot split
        # the collected requests across versions (no misrouted requests).
        # Canary routing happens here — per COLLECT, deterministic,
        # bounded by the configured fraction
        # (serve/control.CanaryController).
        with self._lock:
            version = self._version
            canary = self._canary
            is_canary = False
            if canary is not None and canary.state == "running" \
                    and canary.route():
                version = canary.version
                is_canary = True
        if self.seq_buckets is None:
            groups = [(None, reqs)]
        else:
            # variable-length workloads: each request lands on the
            # smallest sequence bucket that fits it, and each bucket is
            # its own device batch — a request's padded length is a
            # function of ITS length only, never its batch-mates'
            by: dict = {}
            for r in reqs:
                by.setdefault(fit_bucket(r.payload.shape[-1],
                                         self.seq_buckets), []).append(r)
            groups = sorted(by.items())
        for seq, group in groups:
            self._run_batch(group, version, canary, is_canary, seq)
        if beat is not None:
            beat()

    def _run_batch(self, reqs, version, canary, is_canary: bool,
                   seq: Optional[int]) -> None:
        n = len(reqs)
        bucket = self.batcher.bucket_for(n)
        seq_extra = {} if seq is None else {"seq": seq}
        t0 = self.batcher.clock()
        if telemetry.get_active() is not None:
            for r in reqs:
                telemetry.flow_step(r.rid, hop="batch.assemble", size=n,
                                    bucket=bucket, **seq_extra)
        try:
            # batch assembly is inside the guard too: a stray payload that
            # defeats admission-time shape checks (or OOMs the stack) must
            # fail ITS batch typed, not kill the replica thread
            rows = ([r.payload for r in reqs] if seq is None
                    else [pad_tail(r.payload, seq) for r in reqs])
            batch = pad_rows(np.stack(rows), bucket)
            with telemetry.span("serve.batch", cat="serve", size=n,
                                bucket=bucket, version=version.id,
                                canary=is_canary, **seq_extra):
                chaos.fire("serve.batch")
                if is_canary:
                    # canary drill point: stall*S@c inflates exactly the
                    # canary's latency, fail@c its error rate — the
                    # comparator must roll it back
                    chaos.fire("serve.canary")
                out = version.predict(batch)
        except Exception as e:  # noqa: BLE001 — typed per-request error
            # (ChaosFault, StallError, backend error...): the batch fails
            # loudly to its callers, the replica and queue survive
            now = self.batcher.clock()
            for r in reqs:
                r._resolve(error=e, now=now)
            with self._lock:
                self._stats["batch_errors"] += 1
            logger.warning("serve: batch of %d failed: %s: %s", n,
                           type(e).__name__, e)
            self._canary_observe(canary, is_canary, now - t0, True)
            return
        now = self.batcher.clock()
        for i, r in enumerate(reqs):
            r._resolve(result=out[i], version=version.id, now=now)
        with self._lock:
            self._stats["batches"] += 1
            self._stats["batch_rows"] += n
            self._stats["bucket_rows"] += bucket
        self.batcher.note_service(n, now - t0)
        telemetry.counter("serve", queue_depth=self.batcher.depth(),
                          batch_fill=n / bucket)
        self._canary_observe(canary, is_canary, now - t0, False)

    def _canary_observe(self, canary, is_canary: bool, dur_s: float,
                        errored: bool) -> None:
        """Feed one finished batch to the canary comparator and act on
        its verdict — promotion flips the reference exactly like a plain
        swap; rollback discards the candidate and records the typed
        :class:`~bigdl_tpu.serve.control.CanaryRejected` reason."""
        if canary is None:
            return
        with self._lock:
            if self._canary is not canary or canary.state != "running":
                return  # already decided (or superseded by a full swap)
            decision = canary.observe(is_canary, dur_s, errored)
            if decision is None:
                return
            if decision == "promote":
                canary.state = "promoted"
                self._version = canary.version
                self._stats["swaps"] += 1
            else:
                canary.state = "rolled_back"
                self._stats["canary_rollbacks"] += 1
            self._canary = None
            self._canary_last = canary.summary()
        telemetry.instant("serve.canary", cat="serve",
                          decision=canary.state,
                          version=canary.version.id,
                          reason=str(canary.reason or ""))
        if canary.state == "promoted":
            logger.info("serve: canary v%d promoted after %d canary "
                        "batches", canary.version.id, canary.routed)
        else:
            logger.error("serve: canary v%d ROLLED BACK — %s",
                         canary.version.id, canary.reason)

    # -- warmup ---------------------------------------------------------

    def warmup(self, example: Optional[np.ndarray] = None) -> None:
        """Compile every bucket shape on the CURRENT version before (or
        between) traffic, so steady state never recompiles.

        With the AOT executable cache armed (``BIGDL_TPU_AOT_CACHE``,
        utils/aot.py), a warm process turns the whole bucket ladder into
        N cache reads — zero fresh lowers, zero XLA compiles — so a
        swapped-in replica reaches serving-ready in seconds instead of
        minutes.  The first process to run a ladder populates the cache;
        ``stats()["aot"]`` shows the hit/miss ledger."""
        ex = np.asarray(example) if example is not None else self._example
        if ex is None:
            raise ValueError("serve: warmup needs an example sample "
                             "(pass example= here or at construction)")
        self._example = ex
        self._warm_version(self._version, ex)

    def _warm_version(self, version: ModelVersion, ex: np.ndarray) -> None:
        with telemetry.span("serve.warmup", cat="serve",
                            version=version.id):
            for b in self.batcher.buckets:
                if self.seq_buckets is None:
                    version.predict(np.stack([ex] * b))
                    continue
                # variable-length ladder: warm the full (batch x seq)
                # product so steady state never sees a fresh shape
                for length in self.seq_buckets:
                    row = pad_tail(ex[..., :length], length)
                    version.predict(np.stack([row] * b))

    # -- hot swap -------------------------------------------------------

    def swap(self, source, *, quantized: bool = False,
             state=None, canary_fraction: Optional[float] = None) -> int:
        """Install a new model version with ZERO dropped requests.

        source: a checkpoint DIRECTORY (newest lineage snapshot via
        file_io.latest_checkpoint — CRC-verified, quarantine-aware), a
        snapshot/module FILE path, a params pytree, or a built Module.
        quantized=True additionally int8-quantizes the loaded weights
        (bigdl_tpu.quantize) before serving them.

        The new version is fully built — loaded, (optionally) quantized,
        engine constructed, batch shapes warmed — BEFORE one reference
        flip makes it live: in-flight batches finish on the old version,
        every queued/new request runs on the new one.

        ``canary_fraction`` in (0, 1) installs the new version as a
        CANARY instead of flipping: that fraction of device batches
        routes to it while a rolling p99-latency/error-rate comparator
        (serve/control.CanaryController) decides — auto-promote after
        ``SERVE_CANARY_MIN_BATCHES`` clean batches, auto-rollback (typed
        ``CanaryRejected`` in ``stats()["canary"]``) on a regression.
        A later plain ``swap()`` supersedes a still-running canary."""
        # the slow build (retried remote IO, quantize, engine, warmup)
        # runs under its OWN lock: _lock guards only the reference flip
        # and per-batch stats, so replicas keep answering traffic for the
        # whole duration of a swap — serialize concurrent swaps, never
        # the data path
        with self._swap_lock:
            self._vid += 1
            vid = self._vid
            module, label = self._load_module(source, state)
            if quantized:
                from ..quantize import quantize
                module = quantize(module)
                label += "+int8"
            version = ModelVersion(vid, module, label, self._strategy,
                                   mesh=self._mesh)
            if self._example is not None:
                self._warm_version(version, self._example)
            if canary_fraction is not None:
                ctl = control.CanaryController(version, canary_fraction,
                                               **self._canary_cfg)
                with self._lock:
                    self._canary = ctl
                    self._canary_last = None
                telemetry.instant("serve.swap", cat="serve", version=vid,
                                  label=label,
                                  canary_fraction=float(canary_fraction))
                logger.info("serve: canary version %d (%s) taking %.0f%% "
                            "of batches", vid, label,
                            100.0 * float(canary_fraction))
                return vid
            with self._lock:
                self._version = version  # the atomic flip
                self._canary = None      # a full swap supersedes a canary
                self._stats["swaps"] += 1
        telemetry.instant("serve.swap", cat="serve", version=vid,
                          label=label)
        logger.info("serve: hot-swapped to version %d (%s)", vid, label)
        return vid

    def _load_module(self, source, state):
        from ..utils import file_io
        arch = self._version.module
        if isinstance(source, Module):
            if source.params is None:
                source.build()
            return source, f"module:{type(source).__name__}"
        if isinstance(source, str):
            latest = file_io.latest_checkpoint(source)
            if latest is not None:  # checkpoint directory: newest snapshot
                mp, _op, neval = latest
                blob = file_io.load(mp)
                return (_clone_with(arch, blob["params"], blob["state"]),
                        f"ckpt:{source}@{neval}")
            blob = file_io.load(source)
            if isinstance(blob, dict) and \
                    blob.get("format") == "bigdl_tpu-module-v1":
                m = blob["module"]
                m.attach(blob["params"], blob["state"])
                return m, f"file:{source}"
            if isinstance(blob, dict) and "params" in blob:
                return (_clone_with(arch, blob["params"],
                                    blob.get("state")), f"file:{source}")
            raise ValueError(f"serve: {source!r} is neither a checkpoint "
                             "directory, a model snapshot, nor a module "
                             "file")
        # params pytree swapped in directly (e.g. from a live Optimizer)
        return _clone_with(arch, source, state), "params"

    # -- introspection --------------------------------------------------

    @property
    def version(self) -> ModelVersion:
        return self._version

    def stats(self) -> dict:
        """One merged counter snapshot: admission/shed counts (batcher),
        batch counts/fill, swaps, restarts, canary/quota/health state,
        current version."""
        out = self.batcher.stats()
        with self._lock:
            out.update(self._stats)
            out["version"] = self._version.id
            out["version_label"] = self._version.label
            canary = self._canary
            canary_last = self._canary_last
        out["batch_fill"] = (round(out["batch_rows"] /
                                   max(out["bucket_rows"], 1), 4))
        out["replicas"] = self.replicas
        out["replicas_live"] = sum(
            1 for idx, st in self._replica.items()
            if idx < self.replicas and st[0] is not None
            and st[0].is_alive())
        out["healthy"] = self.healthy()
        if self._autoscaler is not None:
            out["autoscale"] = self._autoscaler.stats()
        if self._deploy is not None:
            # the deploy controller's healthy/frozen state + version
            # timeline tail (serve/continuous.py; full list: /v1/versions)
            out["deploy"] = self._deploy.stats()
        if self._recorder is not None:
            out["trace_recording"] = self._recorder.stats()
        if self._unhealthy is not None:
            out["unhealthy_reason"] = str(self._unhealthy)
            out["unhealthy_type"] = type(self._unhealthy).__name__
        if canary is not None:
            out["canary"] = canary.summary()
        elif canary_last is not None:
            out["canary"] = canary_last
        if self._monitor is not None:
            out["replica_monitor"] = self._monitor.stats()
        if self._quotas is not None:
            out["quota"] = self._quotas.stats()
        from ..utils import aot, hlostats
        if aot.enabled():
            # warm-start ledger: a freshly swapped/restarted replica that
            # served its ladder from the AOT cache shows hits==buckets,
            # misses==0 here (process-wide counters, utils/aot.py)
            s = aot.stats()
            out["aot"] = {k: int(s[k]) for k in
                          ("hits", "misses", "stores", "lowers",
                           "compiles", "corrupt")}
        if hlostats.enabled():
            # compiled-program ledger: one compile card per bucket shape
            # the ladder warmed (utils/hlostats.py — counts per label plus
            # capture/write/error totals)
            out["compile_cards"] = {"labels": hlostats.ledger(),
                                    **hlostats.stats()}
        return out
