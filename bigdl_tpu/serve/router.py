"""Topology-aware replica routing: mesh-sharded replicas on device subsets.

The PR 5/10 server is one shared queue drained by worker THREADS over
one engine on the process-wide ``Engine.mesh()`` — fine for a single
chip, wrong for a host with many: every request pays the full-mesh
padding multiple, one wedged collective stalls the only engine, and the
pool cannot grow past the thread count usefully.  This module places
REAL replicas instead:

- **placement**: the host's devices are partitioned into DISJOINT
  subsets, one per replica, each of the member layout's size
  (``MeshLayout(data, fsdp, tp)`` per member — a tp=2 member owns 2
  devices and serves its version fsdp/tp-sharded through
  ``LayoutSharding``, exactly like training).  Subsets are contiguous
  device runs (devices enumerate locality-ordered), never overlap, and
  a layout that does not fit the host raises a typed
  :class:`PlacementError` at construction, not at traffic time.
- **routing**: each member owns its own
  :class:`~bigdl_tpu.serve.batcher.DynamicBatcher` and worker; a request
  routes by **(bucket, per-replica queue depth)** instead of one shared
  queue: fewest pending full buckets first, then prefer JOINING an
  already-coalescing partial batch (it raises fill and that batch's
  flush window is already ticking) over opening a fresh window, then
  lowest depth, then index.  Answers stay bit-identical to bulk
  ``Predictor.predict`` — same ``_ShardedForward`` arithmetic, just
  pinned to the member's mesh.
- **degradation**: each member runs the PR 10 control plane on its own
  subset (heartbeat monitor, bounded restart budget).  A member whose
  budget is spent flips unhealthy and the router simply stops routing
  to it — traffic degrades to the surviving subsets; only when NO
  member survives does admission raise
  :class:`~bigdl_tpu.serve.control.ReplicaLostError`.
- **elasticity**: ``scale_to(n)`` activates/retires members;
  activation builds a fresh engine on the next free subset and warms
  its bucket ladder through the AOT executable cache — with
  ``prewarm`` (default: on whenever the cache is armed) every subset's
  ladder is compiled-and-stored once at ``start()``, so a later
  scale-up is pure cache READS: zero fresh lowers, asserted by
  ``tools/scale_smoke.py`` via ``stats()["aot"]``.  The
  :class:`~bigdl_tpu.serve.autoscale.AutoScaler` drives ``scale_to``
  through the same signal protocol the plain server implements.

Tenant quotas live at the ROUTER (one bucket per tenant across the
whole pool — members get quotas off), shed-priority stays inside each
member's queue where the eviction candidate lives.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Sequence

import numpy as np

from ..utils import config, telemetry
from . import control
from .batcher import ServeError

logger = logging.getLogger("bigdl_tpu")

__all__ = ["PlacementError", "TopologyRouter", "plan_subsets"]


class PlacementError(ServeError):
    """The requested replica layout cannot be placed: not enough devices
    for `replicas` disjoint subsets of the member layout's size.  Raised
    at construction — a placement that cannot exist must not fail at
    traffic time."""


def plan_subsets(devices: Sequence, per_replica: int,
                 replicas: int) -> List[list]:
    """Partition ``devices`` into ``replicas`` DISJOINT contiguous runs
    of ``per_replica`` devices (contiguous = locality: jax enumerates
    devices neighbor-ordered).  Typed :class:`PlacementError` when the
    host cannot hold them."""
    devices = list(devices)
    need = per_replica * replicas
    if per_replica < 1 or replicas < 1:
        raise PlacementError(
            f"serve: placement needs >= 1 device per replica and >= 1 "
            f"replica (got {per_replica} x {replicas})")
    if need > len(devices):
        raise PlacementError(
            f"serve: cannot place {replicas} replica(s) x {per_replica} "
            f"device(s) = {need} on a {len(devices)}-device host — "
            "shrink the member layout or the replica count")
    return [devices[i * per_replica:(i + 1) * per_replica]
            for i in range(replicas)]


class TopologyRouter:
    """Route requests over mesh-sharded replicas on disjoint device
    subsets (see module docstring).

    Duck-type compatible with :class:`InferenceServer` where the HTTP
    front end and the autoscaler need it: ``submit`` / ``predict`` /
    ``stats`` / ``healthy`` / ``version`` / ``swap`` / ``warmup`` /
    ``start`` / ``stop`` / ``scale_to`` / ``autoscale_signals`` /
    ``record_trace`` / ``stop_trace``."""

    def __init__(self, model, *, layout=None, replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 devices: Optional[Sequence] = None,
                 example: Optional[np.ndarray] = None,
                 prewarm: Optional[bool] = None,
                 tenant_qps: Optional[float] = None,
                 tenant_burst: Optional[float] = None,
                 autoscale_min: Optional[int] = None,
                 autoscale_max: Optional[int] = None,
                 autoscale_target_wait_ms: Optional[float] = None,
                 autoscale_idle_s: Optional[float] = None,
                 autoscale_cooldown_s: Optional[float] = None,
                 autoscale_up_polls: Optional[int] = None,
                 autoscale_step: Optional[int] = None,
                 autoscale_poll_s: Optional[float] = None,
                 clock=None, **member_kwargs):
        import jax

        from ..parallel.layout import MeshLayout
        from . import autoscale as autoscale_mod
        self.model = model
        self.layout = layout if layout is not None else MeshLayout(1, 1, 1)
        if isinstance(self.layout, str):
            self.layout = MeshLayout.parse(self.layout)
        self.replicas = int(replicas if replicas is not None
                            else config.get_int("SERVE_REPLICAS", 1))
        self._example = None if example is None else np.asarray(example)
        self._member_kwargs = dict(member_kwargs)
        self._member_kwargs.pop("replicas", None)
        self._member_kwargs.pop("mesh", None)
        # quotas are ROUTER-level (one bucket per tenant across the
        # pool); members run with quotas off
        self._member_kwargs["tenant_qps"] = 0.0
        import time as _time
        self.clock = clock or _time.monotonic
        qps = float(tenant_qps if tenant_qps is not None
                    else config.get_float("SERVE_TENANT_QPS", 0.0))
        burst = (tenant_burst if tenant_burst is not None
                 else config.get_float("SERVE_TENANT_BURST", 0.0))
        self._quotas = (control.TenantQuotas(qps, burst=burst,
                                             clock=self.clock)
                        if qps > 0 else None)
        self._autoscale_cfg = autoscale_mod.autoscale_knobs(
            self.replicas,
            {"min_replicas": autoscale_min, "max_replicas": autoscale_max,
             "target_wait_ms": autoscale_target_wait_ms,
             "idle_s": autoscale_idle_s,
             "cooldown_s": autoscale_cooldown_s,
             "up_polls": autoscale_up_polls, "step": autoscale_step,
             "poll_s": autoscale_poll_s})
        self._autoscaler = None
        self._recorder = None
        cap = max(self.replicas, self._autoscale_cfg["max_replicas"],
                  int(max_replicas or 0))
        devs = list(devices) if devices is not None else list(jax.devices())
        # every POTENTIAL member's subset is planned up front: scale-up
        # must never discover at traffic time that the host is too small
        self._subsets = plan_subsets(devs, self.layout.size, cap)
        self._meshes = [self.layout.build_mesh(s) for s in self._subsets]
        self._members: List[Optional[object]] = [None] * len(self._subsets)
        self._prewarm = prewarm
        self._lock = threading.Lock()   # member list mutations
        self._routed = [0] * len(self._subsets)
        self._started = False
        self._closed = False

    # -- members --------------------------------------------------------

    def _member_strategy(self):
        if (self.layout.fsdp, self.layout.tp) == (1, 1):
            return None  # plain data-parallel member (usually 1 device)
        from ..parallel import LayoutSharding
        return LayoutSharding(self.model)

    def _build_member(self, i: int):
        """One replica = one InferenceServer pinned to subset ``i``'s
        mesh, with its own queue, worker, and PR 10 monitor.  The warmup
        inside ``start()`` goes through the AOT cache — a subset whose
        ladder was prewarmed (or warmed by any earlier process) spawns
        with zero fresh lowers."""
        from .server import InferenceServer
        member = InferenceServer(
            self.model, example=self._example, replicas=1,
            strategy=self._member_strategy(), mesh=self._meshes[i],
            autoscale_max=0,  # one controller (the router's), not N
            **self._member_kwargs)
        return member

    def _activate(self, i: int) -> None:
        member = self._build_member(i)
        member.start()
        with self._lock:
            self._members[i] = member
        telemetry.instant("serve.router", cat="serve", action="activate",
                          member=i,
                          devices=[int(d.id) for d in self._subsets[i]])

    def _deactivate(self, i: int) -> None:
        with self._lock:
            member, self._members[i] = self._members[i], None
        if member is not None:
            # graceful: everything already queued on this member is
            # answered before its worker exits; new traffic routes to
            # the survivors the moment it leaves the member list
            member.stop(drain=True)
            telemetry.instant("serve.router", cat="serve",
                              action="retire", member=i)

    def _prewarm_subset(self, i: int) -> None:
        """Compile-and-store subset ``i``'s bucket ladder without
        activating it: one throwaway version per subset populates the
        AOT cache, so a later scale-up onto this subset is pure cache
        reads (zero fresh lowers)."""
        from .server import ModelVersion
        if self._example is None:
            return
        version = ModelVersion(0, self.model, f"prewarm:{i}",
                               self._member_strategy(),
                               mesh=self._meshes[i])
        from .batcher import default_buckets
        mb = self._member_kwargs.get("max_batch") or \
            config.get_int("SERVE_MAX_BATCH", 8)
        for b in (self._member_kwargs.get("buckets")
                  or default_buckets(int(mb))):
            version.predict(np.stack([self._example] * int(b)))

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "TopologyRouter":
        if self._started:
            return self
        if self._closed:
            raise ServeError("serve: cannot restart a stopped router")
        for i in range(self.replicas):
            self._activate(i)
        from ..utils import aot
        prewarm = self._prewarm if self._prewarm is not None \
            else aot.enabled()
        if prewarm:
            for i in range(self.replicas, len(self._subsets)):
                self._prewarm_subset(i)
        if self._autoscale_cfg["max_replicas"] > 0:
            from . import autoscale as autoscale_mod
            cfg = dict(self._autoscale_cfg)
            cfg["min_replicas"] = min(cfg["min_replicas"], self.replicas)
            cfg["max_replicas"] = min(
                max(cfg["max_replicas"], cfg["min_replicas"]),
                len(self._subsets))
            poll = cfg.pop("poll_s")
            self._autoscaler = autoscale_mod.AutoScaler(
                self, poll_s=poll, clock=self.clock, **cfg).start()
        self._started = True
        logger.info(
            "serve: router started — %d/%d replica(s) live, %d device(s) "
            "per replica (layout %s)%s", self.replicas, len(self._subsets),
            self.layout.size, self.layout.sizes,
            " [all subsets prewarmed]" if prewarm else "")
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self._closed = True
        if self._autoscaler is not None:
            self._autoscaler.stop()
        for i, m in enumerate(self._members):
            if m is not None:
                m.stop(drain=drain, timeout=timeout)
        if self._recorder is not None and self._recorder.path:
            try:
                self._recorder.save()
            except Exception:  # noqa: BLE001 — recording is best-effort
                logger.exception("serve: trace flush failed at shutdown")

    def __enter__(self) -> "TopologyRouter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- routing --------------------------------------------------------

    def _live_members(self):
        with self._lock:
            return [(i, m) for i, m in enumerate(self._members)
                    if m is not None]

    def _pick(self) -> Optional[int]:
        """The dispatch decision: (bucket, per-replica queue depth).

        Key, in order: fewest pending FULL buckets (``depth //
        max_batch`` — whole batches already owed to the device), then
        prefer a member with a PARTIAL batch coalescing (joining it
        raises fill and that batch's flush window is already ticking —
        opening a fresh window elsewhere would pay a whole
        ``max_wait`` again), then raw depth, then index (determinism).
        Unhealthy/closed members never receive traffic — replica loss
        degrades the pool to the surviving subsets."""
        best = best_key = None
        for i, m in self._live_members():
            if not m.healthy() or m.batcher.closed:
                continue
            d = m.batcher.depth()
            key = (d // m.max_batch, 0 if d % m.max_batch else 1, d, i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def submit(self, x, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None, priority: int = 0,
               request_id: Optional[str] = None):
        """Route one sample to the chosen member's queue.  Raises the
        member's typed admission errors (ServerOverloaded /
        RequestTimeout downstream), router-level QuotaExceeded, or
        ReplicaLostError when no member survives."""
        x = np.asarray(x)
        if self._recorder is not None:
            self._recorder.note(x, tenant=tenant, priority=priority,
                                deadline_ms=deadline_ms)
        if self._quotas is not None:
            self._quotas.admit(tenant)
        i = self._pick()
        if i is None:
            raise control.ReplicaLostError(
                "serve: router has no live healthy replica — every "
                "member is lost or retired")
        self._routed[i] += 1
        return self._members[i].submit(x, deadline_ms=deadline_ms,
                                       tenant=tenant, priority=priority,
                                       request_id=request_id)

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    # -- pool size (serve/autoscale.AutoScaler hooks) -------------------

    def scale_to(self, n: int) -> int:
        """Activate/retire members.  Growth builds a FRESH engine on the
        next planned subset and warms its ladder through the AOT cache
        (cache reads when prewarmed — the spawn path is deliberately the
        same one a PR 10 restart takes); shrink drains and retires the
        highest members, whose queued requests are answered before the
        worker exits."""
        n = max(min(int(n), len(self._subsets)), 1)
        cur = self.replicas
        if n == cur or self._closed:
            return cur
        if n > cur:
            for i in range(cur, n):
                self._activate(i)
        else:
            for i in range(n, cur):
                self._deactivate(i)
        self.replicas = n
        logger.info("serve: router scaled %d -> %d replica(s)", cur, n)
        return n

    def autoscale_signals(self) -> dict:
        depth = 0
        batches = 0
        emas = []
        live = 0
        for _i, m in self._live_members():
            sig = m.autoscale_signals()
            depth += sig["depth"]
            batches += sig["batches"]
            live += sig["live"]
            if sig["row_s_ema"]:
                emas.append(sig["row_s_ema"])
        return {"depth": depth,
                "row_s_ema": (sum(emas) / len(emas)) if emas else None,
                "batches": batches, "live": live}

    # -- fleet operations ----------------------------------------------

    def warmup(self, example: Optional[np.ndarray] = None) -> None:
        if example is not None:
            self._example = np.asarray(example)
        for _i, m in self._live_members():
            m.warmup(self._example)

    def swap(self, source, **kwargs) -> int:
        """Fan the swap out to every live member (each builds + warms on
        its own subset before its local flip — a multi-member swap is N
        independent zero-drop swaps; a remote `source` is fetched once
        per member, so prefer params/Module sources for big fleets)."""
        vid = None
        for _i, m in self._live_members():
            vid = m.swap(source, **kwargs)
        if vid is None:
            raise control.ReplicaLostError(
                "serve: router swap with no live member")
        return vid

    def healthy(self) -> bool:
        """True while ANY member survives — the router's whole point is
        degrading to the surviving subsets instead of dying with one."""
        return any(m.healthy() for _i, m in self._live_members())

    @property
    def version(self):
        for _i, m in self._live_members():
            return m.version
        return None

    @property
    def max_batch(self) -> int:
        for _i, m in self._live_members():
            return m.max_batch
        return int(config.get_int("SERVE_MAX_BATCH", 8))

    # -- traffic trace capture ------------------------------------------

    def record_trace(self, path: Optional[str] = None, *,
                     limit: Optional[int] = None):
        from .tracefile import TraceRecorder
        if self._recorder is not None and (path is None or
                                           self._recorder.path == path):
            return self._recorder
        self._recorder = TraceRecorder(clock=self.clock, limit=limit,
                                       path=path)
        return self._recorder

    def stop_trace(self, path: Optional[str] = None):
        rec, self._recorder = self._recorder, None
        if rec is None:
            return []
        if path or rec.path:
            rec.save(path)
        return rec.events()

    # -- introspection --------------------------------------------------

    def stats(self) -> dict:
        members = {}
        agg = {"submitted": 0, "batches": 0, "batch_rows": 0,
               "shed_overload": 0, "shed_timeout": 0, "shed_priority": 0,
               "restarts": 0}
        for i, m in self._live_members():
            st = m.stats()
            members[str(i)] = {
                "devices": [int(d.id) for d in self._subsets[i]],
                "routed": self._routed[i],
                "queue_depth": st["queue_depth"],
                "healthy": st["healthy"],
                "version": st["version"],
                "batches": st["batches"],
                "batch_fill": st["batch_fill"],
                "restarts": st["restarts"],
                "shed_overload": st["shed_overload"],
                "shed_timeout": st["shed_timeout"]}
            for k in agg:
                agg[k] += st.get(k, 0)
        out = dict(agg)
        out["router"] = {
            "layout": list(self.layout.sizes),
            "devices_per_replica": self.layout.size,
            "replicas": self.replicas,
            "replicas_planned": len(self._subsets),
            "routed": list(self._routed),
            "members": members}
        out["replicas"] = self.replicas
        out["replicas_live"] = len(members)
        out["healthy"] = self.healthy()
        v = self.version
        out["version"] = v.id if v is not None else None
        if self._autoscaler is not None:
            out["autoscale"] = self._autoscaler.stats()
        if self._quotas is not None:
            out["quota"] = self._quotas.stats()
        if self._recorder is not None:
            out["trace_recording"] = self._recorder.stats()
        from ..utils import aot
        if aot.enabled():
            s = aot.stats()
            out["aot"] = {k: int(s[k]) for k in
                          ("hits", "misses", "stores", "lowers",
                           "compiles", "corrupt")}
        return out
