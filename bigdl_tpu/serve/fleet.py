"""Cross-process serving fleet: registry, supervision, condemnation.

PR 10 made replicas self-healing *threads*, PR 14 pinned them to device
subsets, PR 15 deployed into them continuously — all inside ONE process,
so one host loss still takes the pool, the canary, and the controller
down together.  This module lifts the replica state machine one level:
each member of the fleet is a separate OS PROCESS (a thin
``tools/serve_worker.py`` wrapping an :class:`InferenceServer` pinned to
its own devices) that registers, gets supervised, and dies
independently.  No collectives, no sockets between supervisor and
member: the coordination substrate is the same file_io
heartbeat/lineage plumbing elastic training already trusts
(``parallel/elastic.py`` is the exemplar — detect by publication
silence, negotiate via CRC-verified files, any scheme).

Registry layout (one shared *fleet dir*):

- ``member.<idx>.<generation>`` — the member record, CRC-framed exactly
  like a checkpoint (``file_io.frame_bytes`` over a pickled dict:
  format/index/pid/generation/devices/buckets/max_batch/host/port/
  wall_time).  A torn or bit-rotted record fails the frame check and
  reads as absent — a consumer can never act on half a registration.
  The WRITER sweeps records from dead generations (keep the newest
  ``BIGDL_TPU_FLEET_KEEP_GENERATIONS``) so a flapping member does not
  grow the dir forever.
- ``heartbeats/heartbeat.<idx>`` — elastic-schema liveness JSON
  (``{"rank", "phase", "count", "time", "published", "generation"}``),
  restamped every worker beat.  Publication-silence (the ``published``
  stamp aging past ``BIGDL_TPU_FLEET_MEMBER_LOST``) IS the loss signal.
- ``condemn.<idx>`` — the supervisor's generation-bump verdict
  (``{"index", "generation", "time"}``): every life of member ``idx``
  with generation <= the condemned one is dead to the fleet.  A zombie
  that wakes from a wedge reads the bump in its beat loop and exits;
  the replacement spawns at generation+1 and is never confused with it.

:class:`FleetSupervisor` runs in the front-tier process: it promotes a
silent member into a typed :class:`MemberLostError`, condemns the lost
generation, best-effort kills the pid, respawns via ``subprocess`` with
exponential backoff — warm through the shared AOT cache dir, so a
rejoin does zero fresh lowers — and past a restart budget DEGRADES the
fleet to the survivors instead of flapping.  The routing half (HTTP
dispatch by (bucket, member queue depth), bounded retry-on-next-member,
rolling deploys) lives in :mod:`bigdl_tpu.serve.fleetfront`.

Knobs (utils/config tier; constructor args override):

| env var | meaning | default |
|---|---|---|
| ``BIGDL_TPU_FLEET_MEMBER_LOST`` | heartbeat publication-silence threshold, seconds | 5.0 |
| ``BIGDL_TPU_FLEET_RESTART_BUDGET`` | respawns per member before the slot degrades | 3 |
| ``BIGDL_TPU_FLEET_RESTART_BACKOFF`` | first respawn delay, seconds (doubles per consecutive restart) | 0.5 |
| ``BIGDL_TPU_FLEET_POLL`` | supervisor monitor poll cadence, seconds | 0.5 |
| ``BIGDL_TPU_FLEET_SPAWN_GRACE`` | seconds a fresh spawn may take to publish its first heartbeat | 30.0 |
| ``BIGDL_TPU_FLEET_HEARTBEAT`` | worker beat interval, seconds | 0.5 |
| ``BIGDL_TPU_FLEET_KEEP_GENERATIONS`` | member-record generations kept per index (writer-side sweep) | 4 |

Chaos (utils/chaos.py): the worker's beat loop fires
``fleet.member@<idx>`` once per turn — ``=exit@N`` kills that process
instantly (``os._exit(117)``), ``=wedge@N`` blocks the beat loop
uninterruptibly so the member goes publication-silent while its HTTP
threads still answer: the zombie the condemnation protocol exists for.
``tools/fleet_smoke.py`` drills kill -9, wedge, and a stale registry
entry in one run.  See docs/serving.md ("Fleet").
"""

from __future__ import annotations

import logging
import os
import pickle
import re
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from ..parallel.elastic import _read_json, _write_json
from ..utils import config, file_io, telemetry
from .control import ReplicaLostError

logger = logging.getLogger("bigdl_tpu")

__all__ = ["MemberLostError", "FleetSupervisor", "MEMBER_FORMAT",
           "HEARTBEAT_DIRNAME", "publish_member", "read_member",
           "read_registry", "beat", "read_heartbeat", "member_alive",
           "condemn", "condemned_generation", "default_spawner",
           "lost_after_seconds"]

#: member record format tag (same role as the checkpoint/release tags)
MEMBER_FORMAT = "bigdl_tpu-fleet-member-v1"

#: liveness subdir — same name and schema as parallel/elastic, so the
#: trace/debug tooling that reads elastic heartbeats reads fleet ones too
HEARTBEAT_DIRNAME = "heartbeats"

_MEMBER_RE = re.compile(r"member\.(\d+)\.(\d+)")


class MemberLostError(ReplicaLostError):
    """A fleet member went publication-silent (or no member is live to
    take a request).  Subclasses :class:`ReplicaLostError` so the HTTP
    front end's typed 503 + Retry-After mapping applies unchanged — the
    caller backs off while the supervisor replaces the process."""

    def __init__(self, message: str, *, index: Optional[int] = None,
                 generation: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.index = index
        self.generation = generation
        self.retry_after_s = retry_after_s


def lost_after_seconds() -> float:
    return config.get_float("FLEET_MEMBER_LOST", 5.0)


def keep_generations() -> int:
    return config.get_int("FLEET_KEEP_GENERATIONS", 4)


# ---------------------------------------------------------------------------
# registry files
# ---------------------------------------------------------------------------

def _heartbeat_dir(fleet_dir: str) -> str:
    return file_io._join(file_io._strip_file_scheme(str(fleet_dir)),
                         HEARTBEAT_DIRNAME)


def publish_member(fleet_dir: str, *, index: int, generation: int,
                   pid: int, port: int, host: str = "127.0.0.1",
                   devices: Optional[List[str]] = None,
                   buckets: Optional[List[int]] = None,
                   max_batch: Optional[int] = None,
                   wall_time: Optional[float] = None) -> str:
    """WORKER side: publish this life's CRC-framed member record and
    sweep records from dead generations (writer-side retention — the
    shared :func:`file_io.sweep_numbered` bound)."""
    base = file_io._strip_file_scheme(str(fleet_dir))
    record = {"format": MEMBER_FORMAT, "index": int(index),
              "generation": int(generation), "pid": int(pid),
              "host": str(host), "port": int(port),
              "devices": [str(d) for d in (devices or [])],
              "buckets": [int(b) for b in (buckets or [])],
              "max_batch": int(max_batch) if max_batch else None,
              "wall_time": float(wall_time if wall_time is not None
                                 else time.time())}
    fs = file_io.get_filesystem(base)
    fs.makedirs(base)
    path = file_io._join(base, f"member.{int(index)}.{int(generation)}")
    fs.write_bytes(path, file_io.frame_bytes(pickle.dumps(record)))
    file_io.sweep_numbered(base, rf"member\.{int(index)}\.(\d+)",
                           keep=keep_generations())
    return path


def read_member(path: str) -> Optional[dict]:
    """One member record, CRC-verified; None for torn/corrupt/absent
    bytes (the consumer polls — same contract as elastic's
    ``_read_json``)."""
    try:
        fs = file_io.get_filesystem(path)
        if not fs.exists(path):
            return None
        record = pickle.loads(file_io.unframe_bytes(fs.read_bytes(path)))
    except Exception:  # noqa: BLE001 — a half-written or bit-rotted
        # record reads as absent; the next publish replaces it
        return None
    if not isinstance(record, dict) or record.get("format") != MEMBER_FORMAT:
        return None
    return record


def read_registry(fleet_dir: str) -> Dict[int, dict]:
    """index -> newest VERIFIED member record whose generation survives
    condemnation.  Records from condemned generations — and records that
    fail the CRC frame — are invisible, so a stale or torn registry
    entry can never attract traffic."""
    base = file_io._strip_file_scheme(str(fleet_dir))
    fs = file_io.get_filesystem(base)
    try:
        names = fs.listdir(base) if fs.isdir(base) else []
    except Exception:  # noqa: BLE001 — dir may not exist yet
        return {}
    by_index: Dict[int, List[int]] = {}
    for name in names:
        m = _MEMBER_RE.fullmatch(name)
        if m:
            by_index.setdefault(int(m.group(1)), []).append(int(m.group(2)))
    registry = {}
    for idx, gens in by_index.items():
        floor = condemned_generation(base, idx)
        for gen in sorted(gens, reverse=True):
            if gen <= floor:
                break  # everything older is condemned too
            record = read_member(file_io._join(base, f"member.{idx}.{gen}"))
            if record is not None:
                registry[idx] = record
                break
    return registry


def beat(fleet_dir: str, index: int, generation: int, count: int, *,
         phase: str = "serve", wall_time: Optional[float] = None) -> str:
    """WORKER side: restamp this member's liveness heartbeat (elastic
    schema — ``published`` is the stamp whose age IS the loss signal)."""
    now = float(wall_time if wall_time is not None else time.time())
    return _write_json(_heartbeat_dir(fleet_dir), f"heartbeat.{int(index)}",
                       {"rank": int(index), "phase": str(phase),
                        "count": int(count), "time": now,
                        "published": now, "generation": int(generation)})


def read_heartbeat(fleet_dir: str, index: int) -> Optional[dict]:
    return _read_json(file_io._join(_heartbeat_dir(fleet_dir),
                                    f"heartbeat.{int(index)}"))


def member_alive(fleet_dir: str, index: int, *,
                 generation: Optional[int] = None,
                 lost_after: Optional[float] = None,
                 now: Optional[float] = None) -> bool:
    """Publication-freshness liveness: True when member `index` has a
    heartbeat of (at least) `generation` whose ``published`` stamp is
    younger than the silence threshold.  A registry record WITHOUT a
    fresh heartbeat is a stale entry, not a member."""
    hb = read_heartbeat(fleet_dir, index)
    if hb is None:
        return False
    if generation is not None and int(hb.get("generation", 0)) < generation:
        return False
    lost_after = lost_after_seconds() if lost_after is None else lost_after
    now = time.time() if now is None else now
    return (now - float(hb.get("published", 0.0))) <= lost_after


def condemn(fleet_dir: str, index: int, generation: int) -> str:
    """SUPERVISOR side: declare every life of member `index` up to and
    including `generation` dead.  Monotonic (never lowered): a late
    verdict for an old generation cannot un-condemn a newer one."""
    base = file_io._strip_file_scheme(str(fleet_dir))
    floor = condemned_generation(base, index)
    generation = max(int(generation), floor)
    path = _write_json(base, f"condemn.{int(index)}",
                       {"index": int(index), "generation": generation,
                        "time": time.time()})
    telemetry.instant("fleet.condemn", cat="fleet", index=int(index),
                      generation=generation)
    return path


def condemned_generation(fleet_dir: str, index: int) -> int:
    """Newest condemned generation for member `index` (0 when none)."""
    doc = _read_json(file_io._join(
        file_io._strip_file_scheme(str(fleet_dir)), f"condemn.{int(index)}"))
    return int(doc.get("generation", 0)) if doc else 0


# ---------------------------------------------------------------------------
# spawning
# ---------------------------------------------------------------------------

def default_spawner(fleet_dir: str, *, model: str = "linear",
                    extra_args: tuple = (), env: Optional[dict] = None,
                    python: Optional[str] = None) -> Callable:
    """A ``spawn(index, generation) -> Popen`` building the stock
    ``tools/serve_worker.py`` command line.  Smokes/tests inject their
    own spawner (per-member chaos env, virtual devices); this is the
    production default: inherit the environment — the shared
    ``BIGDL_TPU_AOT_CACHE`` dir rides along, which is what makes a
    respawn warm."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    worker = os.path.join(repo_root, "tools", "serve_worker.py")

    def spawn(index: int, generation: int):
        cmd = [python or sys.executable, worker,
               "--fleet-dir", str(fleet_dir),
               "--index", str(int(index)),
               "--generation", str(int(generation)),
               "--model", model] + list(extra_args)
        child_env = dict(env if env is not None else os.environ)
        child_env.setdefault("PYTHONPATH", repo_root)
        return subprocess.Popen(cmd, env=child_env)

    return spawn


# ---------------------------------------------------------------------------
# supervision
# ---------------------------------------------------------------------------

class _Slot:
    """One supervised member index: its process handle and restart
    bookkeeping (the PR 10 per-replica state tuple, lifted to a
    process)."""

    __slots__ = ("proc", "generation", "restarts", "degraded",
                 "spawned_at", "respawn_at", "last_error")

    def __init__(self):
        self.proc = None
        self.generation = 0
        self.restarts = 0
        self.degraded = False
        self.spawned_at = 0.0
        self.respawn_at = None   # pending-backoff deadline, monotonic
        self.last_error = None


class FleetSupervisor:
    """Supervise N worker processes through the shared fleet dir.

    The monitor thread polls liveness (heartbeat publication silence OR
    process exit), and on loss: records a typed
    :class:`MemberLostError`, CONDEMNS the lost generation (the bump a
    waking zombie exits on), best-effort kills the pid, and schedules a
    respawn at generation+1 under exponential backoff.  Past
    ``restart_budget`` respawns the slot DEGRADES — the fleet serves
    from the survivors instead of flapping a poisoned member forever
    (exactly the PR 10 replica budget, one level up)."""

    def __init__(self, fleet_dir: str, spawn: Optional[Callable] = None, *,
                 members: int = 3, lost_after_s: Optional[float] = None,
                 restart_budget: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 clock=None, wall=None):
        self.fleet_dir = file_io._strip_file_scheme(str(fleet_dir))
        self.spawn = spawn or default_spawner(self.fleet_dir)
        self.members = int(members)
        self.lost_after_s = (lost_after_seconds() if lost_after_s is None
                             else float(lost_after_s))
        self.restart_budget = (config.get_int("FLEET_RESTART_BUDGET", 3)
                               if restart_budget is None
                               else int(restart_budget))
        self.backoff_s = (config.get_float("FLEET_RESTART_BACKOFF", 0.5)
                          if backoff_s is None else float(backoff_s))
        self.grace_s = (config.get_float("FLEET_SPAWN_GRACE", 30.0)
                        if grace_s is None else float(grace_s))
        self.poll_s = (config.get_float("FLEET_POLL", 0.5)
                       if poll_s is None else float(poll_s))
        self.clock = clock or time.monotonic
        self.wall = wall or time.time
        self._slots = [_Slot() for _ in range(self.members)]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.last_error: Optional[MemberLostError] = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        if self._thread is not None:
            return self
        for i in range(self.members):
            self._spawn(i)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bigdl-fleet-supervisor")
        self._thread.start()
        logger.info("fleet: supervising %d member(s) in %s (silence "
                    "threshold %.1fs, restart budget %d)", self.members,
                    self.fleet_dir, self.lost_after_s, self.restart_budget)
        return self

    def stop(self, terminate: bool = True, timeout: float = 15.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self.poll_s * 4, 2.0))
        if not terminate:
            return
        procs = []
        with self._lock:
            for i, slot in enumerate(self._slots):
                if slot.proc is not None and slot.proc.poll() is None:
                    # condemn so a worker that misses the signal still
                    # exits on its next beat
                    condemn(self.fleet_dir, i, slot.generation)
                    try:
                        slot.proc.terminate()
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                    procs.append(slot.proc)
        deadline = self.clock() + timeout
        for p in procs:
            try:
                p.wait(timeout=max(deadline - self.clock(), 0.1))
            except Exception:  # noqa: BLE001 — a straggler gets the axe
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass

    def __enter__(self) -> "FleetSupervisor":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- spawning -------------------------------------------------------

    def _next_generation(self, index: int) -> int:
        """Past every condemned life AND past any frozen heartbeat a
        previous run left behind (the elastic announce_join rule: a
        returning life must outrank its ghost)."""
        floor = condemned_generation(self.fleet_dir, index)
        hb = read_heartbeat(self.fleet_dir, index)
        if hb:
            floor = max(floor, int(hb.get("generation", 0)))
        return floor + 1

    def _spawn(self, index: int) -> None:
        generation = self._next_generation(index)
        proc = self.spawn(index, generation)
        with self._lock:
            slot = self._slots[index]
            slot.proc = proc
            slot.generation = generation
            slot.spawned_at = self.clock()
            slot.respawn_at = None
        telemetry.instant("fleet.spawn", cat="fleet", index=index,
                          generation=generation,
                          pid=getattr(proc, "pid", None))
        logger.info("fleet: spawned member %d generation %d (pid %s)",
                    index, generation, getattr(proc, "pid", None))

    # -- monitoring -----------------------------------------------------

    def _slot_alive(self, index: int, slot: _Slot) -> bool:
        if slot.proc is not None and slot.proc.poll() is not None:
            return False  # the process itself is gone: no grace needed
        if member_alive(self.fleet_dir, index, generation=slot.generation,
                        lost_after=self.lost_after_s, now=self.wall()):
            return True
        # a fresh spawn gets a grace window to import/compile/bind
        # before silence counts — but only until its FIRST heartbeat
        hb = read_heartbeat(self.fleet_dir, index)
        in_grace = self.clock() - slot.spawned_at < self.grace_s
        not_yet_beating = (hb is None or
                           int(hb.get("generation", 0)) < slot.generation)
        return in_grace and not_yet_beating

    def _handle_loss(self, index: int) -> None:
        with self._lock:
            slot = self._slots[index]
            slot.restarts += 1
            restarts = slot.restarts
            generation = slot.generation
            proc = slot.proc
            err = MemberLostError(
                f"fleet: member {index} (generation {generation}) went "
                f"publication-silent past {self.lost_after_s:.1f}s",
                index=index, generation=generation,
                retry_after_s=self.backoff_s * (2 ** max(restarts - 1, 0)))
            slot.last_error = err
            self.last_error = err
        telemetry.instant("fleet.lost", cat="fleet", index=index,
                          generation=generation, restarts=restarts)
        logger.warning("%s (restart %d/%d)", err, restarts,
                       self.restart_budget)
        # condemn FIRST: a zombie that wakes after the kill misses must
        # still see the bump and exit before the replacement registers
        condemn(self.fleet_dir, index, generation)
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except Exception:  # noqa: BLE001 — already reaped
                pass
        with self._lock:
            if restarts > self.restart_budget:
                slot.degraded = True
                slot.respawn_at = None
            else:
                backoff = self.backoff_s * (2 ** max(restarts - 1, 0))
                slot.respawn_at = self.clock() + backoff
        if restarts > self.restart_budget:
            telemetry.instant("fleet.degraded", cat="fleet", index=index,
                              restarts=restarts)
            logger.error("fleet: member %d past restart budget %d — "
                         "slot DEGRADED, serving from survivors", index,
                         self.restart_budget)

    def _loop(self) -> None:
        telemetry.thread_name("fleet supervisor")
        while not self._stop.is_set():
            now = self.clock()
            for i in range(self.members):
                with self._lock:
                    slot = self._slots[i]
                    degraded = slot.degraded
                    respawn_at = slot.respawn_at
                if degraded:
                    continue
                if respawn_at is not None:
                    if now >= respawn_at:
                        self._spawn(i)
                        telemetry.instant("fleet.respawn", cat="fleet",
                                          index=i)
                    continue
                if not self._slot_alive(i, slot):
                    self._handle_loss(i)
            st = self.stats()
            telemetry.counter("fleet", live=st["live"],
                              restarts=st["restarts"],
                              degraded=st["degraded"])
            self._stop.wait(self.poll_s)

    # -- introspection --------------------------------------------------

    def live_count(self) -> int:
        return sum(1 for i in range(self.members)
                   if not self._slots[i].degraded
                   and member_alive(self.fleet_dir, i,
                                    generation=self._slots[i].generation,
                                    lost_after=self.lost_after_s,
                                    now=self.wall()))

    def healthy(self) -> bool:
        """True while ANY supervised member is live — degradation to
        survivors, not death with one (the router contract, lifted)."""
        return self.live_count() > 0

    def stats(self) -> dict:
        with self._lock:
            slots = {str(i): {
                "generation": s.generation,
                "pid": getattr(s.proc, "pid", None),
                "restarts": s.restarts,
                "degraded": s.degraded,
                "respawn_pending": s.respawn_at is not None,
                "last_error": str(s.last_error) if s.last_error else None,
            } for i, s in enumerate(self._slots)}
            restarts = sum(s.restarts for s in self._slots)
            degraded = sum(1 for s in self._slots if s.degraded)
        return {"members": self.members, "live": self.live_count(),
                "restarts": restarts, "degraded": degraded,
                "slots": slots,
                "last_error": (str(self.last_error)
                               if self.last_error else None)}
