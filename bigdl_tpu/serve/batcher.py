"""Dynamic request batching: coalesce single requests into fixed shapes.

Reference gap this closes: the reference serves models either as bulk
Spark jobs (optim/Predictor.scala — whole-RDD inference) or as one
synchronous UDF call per query (example/udfpredictor/); neither shape
survives online traffic on an XLA backend, where every distinct batch
shape is a fresh compile and every single-row forward wastes the MXU.
The MLPerf TPU-pod work (arXiv:1909.09756) shows the discipline that
keeps compiled accelerators saturated: a small, fixed set of padded
batch shapes, filled as full as latency allows.

This module is the host-side half of the serving subsystem
(bigdl_tpu/serve): a bounded request queue plus the coalescing policy.

- :class:`DynamicBatcher` — concurrent producers ``submit()`` single
  samples; replica workers ``collect()`` batches.  A batch flushes when
  ``max_batch`` requests are waiting OR the oldest request has waited
  ``max_wait_s`` (the latency-vs-fill knob).  Batch sizes are drawn from
  a fixed ``buckets`` ladder (default: powers of two up to ``max_batch``)
  and padded up to the bucket, so the device only ever sees shapes it
  has already compiled (warmed up at server start).
- **Backpressure**: the queue is bounded (``queue_limit``); admission
  past the bound raises :class:`ServerOverloaded` immediately — typed
  rejection instead of unbounded latency collapse.
- **Deadlines**: a request carries an optional absolute deadline; one
  dequeued past it is shed with :class:`RequestTimeout` and never
  reaches the device (a request already executing completes normally).
- The trailing-chunk padding trick UDFPredictor (serving.py) uses for
  bulk DataFrame calls lives here too (:func:`pad_rows`,
  :func:`predict_in_fixed_batches`) — one padding implementation for
  offline UDFs and online requests.

Everything is clock-injectable and wall-clock-free under test.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..utils import chaos, telemetry

__all__ = ["ServeError", "ServerOverloaded", "ServerClosed",
           "RequestTimeout", "PendingRequest", "DynamicBatcher",
           "default_buckets", "pad_rows", "predict_in_fixed_batches"]


class ServeError(RuntimeError):
    """Base class for typed serving rejections."""


class ServerOverloaded(ServeError):
    """Admission rejected: the bounded request queue is full.  The caller
    should back off / retry against another replica pool — queueing more
    would only grow everyone's latency (docs/serving.md decision tree)."""


class RequestTimeout(ServeError, TimeoutError):
    """The request's deadline passed while it was still queued; it was
    shed before reaching the device.  Distinct from ServerOverloaded:
    admission succeeded but service was too slow — raise the deadline or
    add replicas, not queue depth."""


class ServerClosed(ServeError):
    """submit() after shutdown began (stop() was called)."""


class PendingRequest:
    """Future-like handle for one submitted sample.

    ``result(timeout)`` blocks until a replica resolves the request and
    returns the per-sample output row, or raises the typed error the
    server recorded (RequestTimeout / ServerOverloaded at dequeue /
    ChaosFault / StallError...)."""

    __slots__ = ("payload", "enqueued", "deadline", "version", "latency_s",
                 "_event", "_result", "_error")

    def __init__(self, payload, enqueued: float,
                 deadline: Optional[float] = None):
        self.payload = payload
        self.enqueued = enqueued
        self.deadline = deadline
        self.version = None      # model version id that answered
        self.latency_s = None    # enqueue -> resolve
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _resolve(self, result=None, error=None, version=None,
                 now: Optional[float] = None) -> None:
        if self._event.is_set():  # first resolution wins (idempotent)
            return
        self._result = result
        self._error = error
        self.version = version
        if now is not None:
            self.latency_s = max(now - self.enqueued, 0.0)
            telemetry.complete(
                "serve.request", self.latency_s, cat="serve",
                status=type(error).__name__ if error is not None else "ok")
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serve: no response within {timeout}s (request still "
                "queued or executing — not shed)")
        if self._error is not None:
            raise self._error
        return self._result


def default_buckets(max_batch: int) -> tuple:
    """The fixed batch-shape ladder: powers of two up to ``max_batch``
    (``max_batch`` itself always included).  Small enough to warm every
    shape at startup, dense enough that a half-full flush wastes at most
    half the pad rows."""
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


def pad_rows(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad the batch dim up to ``n`` rows by repeating the last row — the
    fixed-shape trick that keeps jit from ever seeing a new shape (no
    per-remainder recompiles).  Shared by the online batcher and the
    offline UDF chunker."""
    short = n - len(arr)
    if short <= 0:
        return arr
    return np.concatenate([arr, np.repeat(arr[-1:], short, axis=0)])


def predict_in_fixed_batches(forward: Callable, feats: np.ndarray,
                             batch_size: int) -> np.ndarray:
    """Chunk ``feats`` host-side into full ``batch_size`` batches (one XLA
    call per batch, never one giant buffer), padding the trailing chunk
    with :func:`pad_rows`, and concatenate the trimmed outputs.  The bulk
    (UDFPredictor) counterpart of the online batcher's bucket padding.
    Zero-row ``feats`` return a zero-row array without touching the
    device (the output's trailing shape is unknowable without a forward,
    so it mirrors the input's)."""
    feats = np.asarray(feats)
    if len(feats) == 0:
        return feats
    outs = []
    for i in range(0, len(feats), batch_size):
        chunk = feats[i:i + batch_size]
        outs.append(np.asarray(forward(pad_rows(chunk, batch_size)))
                    [:len(chunk)])
    return np.concatenate(outs, axis=0)


class DynamicBatcher:
    """Bounded request queue + coalescing policy (see module docstring).

    Thread contract: any number of producer threads call :meth:`submit`;
    any number of replica workers call :meth:`collect`.  ``close(drain=
    True)`` lets workers finish the queue before :meth:`collect` returns
    None; ``drain=False`` fails everything still queued with
    :class:`ServerClosed`."""

    #: wait-slice so idle workers keep heartbeating their supervisor
    #: channel (a parked worker must never read as a stalled one)
    _SLICE = 0.05

    def __init__(self, max_batch: int, max_wait_s: float,
                 queue_limit: int, buckets: Optional[Sequence[int]] = None,
                 clock=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self.buckets = tuple(sorted(buckets)) if buckets else \
            default_buckets(self.max_batch)
        if self.buckets[-1] < self.max_batch:
            raise ValueError(f"largest bucket {self.buckets[-1]} < "
                             f"max_batch {self.max_batch}")
        self.clock = clock or time.monotonic
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain = True
        # shed counters (read under the cond lock via stats())
        self.submitted = 0
        self.shed_overload = 0
        self.shed_timeout = 0

    # -- producers ------------------------------------------------------

    def submit(self, payload, deadline: Optional[float] = None
               ) -> PendingRequest:
        """Enqueue one sample; raises :class:`ServerOverloaded` when the
        bounded queue is full, :class:`ServerClosed` after shutdown.
        ``deadline`` is absolute (this batcher's clock)."""
        chaos.fire("serve.request")  # admission-path fault point
        with self._cond:
            if self._closed:
                raise ServerClosed("serve: server is shutting down")
            if len(self._q) >= self.queue_limit:
                self.shed_overload += 1
                raise ServerOverloaded(
                    f"serve: request queue full ({self.queue_limit} "
                    "waiting) — shedding at admission")
            req = PendingRequest(payload, self.clock(), deadline)
            self._q.append(req)
            self.submitted += 1
            depth = len(self._q)
            self._cond.notify_all()
        telemetry.counter("serve", queue_depth=depth)
        return req

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -- workers --------------------------------------------------------

    def collect(self, heartbeat: Optional[Callable] = None
                ) -> Optional[List[PendingRequest]]:
        """Block until a batch is ready, the coalesce window expires, or
        shutdown.  Returns up to ``max_batch`` live requests (may be []
        when every dequeued request had expired — the caller just loops),
        or None when the batcher is closed and (if draining) empty.
        ``heartbeat`` is called on every wait slice so the worker's
        supervisor channel stays live while parked."""
        with self._cond:
            while not self._q:
                if self._closed:
                    return None
                self._cond.wait(self._SLICE)
                if heartbeat is not None:
                    heartbeat()
            # coalesce: from the OLDEST waiting request's enqueue time,
            # hold the flush up to max_wait_s hoping to fill the batch —
            # the configurable latency-for-fill trade
            flush_at = self._q[0].enqueued + self.max_wait_s
            while len(self._q) < self.max_batch and not self._closed:
                remaining = flush_at - self.clock()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, self._SLICE))
                if heartbeat is not None:
                    heartbeat()
            reqs = [self._q.popleft()
                    for _ in range(min(len(self._q), self.max_batch))]
        # deadline shedding happens at dequeue, outside the lock: an
        # expired request never reaches the device
        now = self.clock()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                with self._cond:
                    self.shed_timeout += 1
                r._resolve(error=RequestTimeout(
                    f"serve: deadline exceeded after "
                    f"{now - r.enqueued:.3f}s in queue"), now=now)
            else:
                live.append(r)
        return live

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n is capped at max_batch by collect)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    # -- shutdown -------------------------------------------------------

    def close(self, drain: bool = True) -> None:
        """Stop admissions.  drain=True lets workers finish the queue;
        drain=False fails everything still queued with ServerClosed."""
        with self._cond:
            self._closed = True
            self._drain = drain
            pending = []
            if not drain:
                while self._q:
                    pending.append(self._q.popleft())
            self._cond.notify_all()
        now = self.clock()
        for r in pending:
            r._resolve(error=ServerClosed(
                "serve: server stopped before this request ran"), now=now)

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        with self._cond:
            return {"queue_depth": len(self._q),
                    "submitted": self.submitted,
                    "shed_overload": self.shed_overload,
                    "shed_timeout": self.shed_timeout}
