"""Dynamic request batching: coalesce single requests into fixed shapes.

Reference gap this closes: the reference serves models either as bulk
Spark jobs (optim/Predictor.scala — whole-RDD inference) or as one
synchronous UDF call per query (example/udfpredictor/); neither shape
survives online traffic on an XLA backend, where every distinct batch
shape is a fresh compile and every single-row forward wastes the MXU.
The MLPerf TPU-pod work (arXiv:1909.09756) shows the discipline that
keeps compiled accelerators saturated: a small, fixed set of padded
batch shapes, filled as full as latency allows.

This module is the host-side half of the serving subsystem
(bigdl_tpu/serve): a bounded request queue plus the coalescing policy.

- :class:`DynamicBatcher` — concurrent producers ``submit()`` single
  samples; replica workers ``collect()`` batches.  A batch flushes when
  ``max_batch`` requests are waiting OR the oldest request has waited
  ``max_wait_s`` (the latency-vs-fill knob).  Batch sizes are drawn from
  a fixed ``buckets`` ladder (default: powers of two up to ``max_batch``)
  and padded up to the bucket, so the device only ever sees shapes it
  has already compiled (warmed up at server start).
- **Backpressure**: the queue is bounded (``queue_limit``); admission
  past the bound first sweeps queued requests whose deadline already
  expired (dead slots must shed themselves, not fresh traffic), then
  sheds the LOWEST-priority queued request if the arrival outranks it,
  and only then raises :class:`ServerOverloaded` (carrying a
  ``retry_after_s`` estimate) — typed, priority-aware rejection instead
  of unbounded latency collapse.
- **Deadlines**: a request carries an optional absolute deadline; one
  dequeued past it is shed with :class:`RequestTimeout` and never
  reaches the device (a request already executing completes normally).
- **Priorities/tenants**: requests carry ``priority`` (higher = more
  important, default 0) and an optional ``tenant`` tag; per-tenant
  token-bucket quotas live one layer up (serve/control.py), the
  shed-lowest-first policy lives here where the queue is.
- The trailing-chunk padding trick UDFPredictor (serving.py) uses for
  bulk DataFrame calls lives here too (:func:`pad_rows`,
  :func:`predict_in_fixed_batches`) — one padding implementation for
  offline UDFs and online requests.

Everything is clock-injectable and wall-clock-free under test.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..utils import chaos, metrics_export, telemetry

__all__ = ["ServeError", "ServerOverloaded", "ServerClosed",
           "RequestTimeout", "PendingRequest", "DynamicBatcher",
           "DecodeQueue", "default_buckets", "fit_bucket", "pad_rows",
           "pad_tail", "predict_in_fixed_batches"]


class ServeError(RuntimeError):
    """Base class for typed serving rejections."""


class ServerOverloaded(ServeError):
    """Admission rejected: the bounded request queue is full (or this
    request was evicted from it for a higher-priority arrival).  The
    caller should back off / retry against another replica pool —
    queueing more would only grow everyone's latency (docs/serving.md
    decision tree).  ``retry_after_s``, when set, estimates when the
    queue will have drained (HTTP Retry-After in tools/serve_http.py)."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class RequestTimeout(ServeError, TimeoutError):
    """The request's deadline passed while it was still queued; it was
    shed before reaching the device.  Distinct from ServerOverloaded:
    admission succeeded but service was too slow — raise the deadline or
    add replicas, not queue depth."""


class ServerClosed(ServeError):
    """submit() after shutdown began (stop() was called)."""


class PendingRequest:
    """Future-like handle for one submitted sample.

    ``result(timeout)`` blocks until a replica resolves the request and
    returns the per-sample output row, or raises the typed error the
    server recorded (RequestTimeout / ServerOverloaded at dequeue /
    ChaosFault / StallError...)."""

    __slots__ = ("payload", "enqueued", "deadline", "tenant", "priority",
                 "version", "latency_s", "rid", "rid_owner",
                 "_event", "_result", "_error")

    def __init__(self, payload, enqueued: float,
                 deadline: Optional[float] = None,
                 tenant: Optional[str] = None, priority: int = 0,
                 rid: Optional[str] = None, rid_owner: bool = False):
        self.payload = payload
        self.enqueued = enqueued
        self.deadline = deadline
        self.tenant = tenant     # quota/accounting tag (control plane)
        self.priority = int(priority)  # higher = shed later
        self.version = None      # model version id that answered
        self.latency_s = None    # enqueue -> resolve
        self.rid = rid           # request flow id (X-BigDL-Request-Id)
        self.rid_owner = rid_owner  # this process minted it (it finishes)
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _resolve(self, result=None, error=None, version=None,
                 now: Optional[float] = None) -> None:
        if self._event.is_set():  # first resolution wins (idempotent)
            return
        self._result = result
        self._error = error
        self.version = version
        status = type(error).__name__ if error is not None else "ok"
        if now is not None:
            self.latency_s = max(now - self.enqueued, 0.0)
            if self.rid is None:
                telemetry.complete("serve.request", self.latency_s,
                                   cat="serve", status=status)
            else:
                telemetry.complete("serve.request", self.latency_s,
                                   cat="serve", status=status, req=self.rid)
            reg = metrics_export._REGISTRY
            if reg is not None:
                reg.observe_request(self.latency_s, status)
        if self.rid is not None:
            # the minter closes the flow; a fleet-arrived id gets a step
            # (the front owns the "f" for the whole cross-process chain)
            if self.rid_owner:
                telemetry.flow_finish(self.rid, hop="resolve",
                                      status=status)
            else:
                telemetry.flow_step(self.rid, hop="resolve", status=status)
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serve: no response within {timeout}s (request still "
                "queued or executing — not shed)")
        if self._error is not None:
            raise self._error
        return self._result


def _metrics_shed(cause: str) -> None:
    """Count one shed on the live-metrics plane (no-op when unarmed)."""
    reg = metrics_export._REGISTRY
    if reg is not None:
        reg.shed(cause)


def default_buckets(max_batch: int) -> tuple:
    """The fixed batch-shape ladder: powers of two up to ``max_batch``
    (``max_batch`` itself always included).  Small enough to warm every
    shape at startup, dense enough that a half-full flush wastes at most
    half the pad rows."""
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


def fit_bucket(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= ``n`` from an ascending ladder, or None when
    ``n`` overflows the largest bucket.  The sequence-length counterpart
    of :meth:`DynamicBatcher.bucket_for` (which serves the batch axis and
    clamps instead — a batch can split, a sequence cannot)."""
    for b in buckets:
        if b >= n:
            return b
    return None


def pad_tail(arr: np.ndarray, length: int) -> np.ndarray:
    """Zero-pad ONLY the trailing axis up to ``length`` — the per-request
    half of :func:`pad_rows`'s ``length=`` handling, used when requests
    must land on a deterministic per-request sequence bucket BEFORE batch
    assembly (so a request's answer never depends on its batch-mates'
    lengths).  Refuses to truncate, like pad_rows."""
    arr = np.asarray(arr)
    if arr.ndim < 1:
        raise ValueError("pad_tail: needs at least a 1-D array, got "
                         f"ndim={arr.ndim}")
    have = arr.shape[-1]
    if have > length:
        raise ValueError(f"pad_tail: trailing axis {have} exceeds "
                         f"length={length} (refusing to truncate)")
    if have == length:
        return arr
    pad = [(0, 0)] * (arr.ndim - 1) + [(0, length - have)]
    return np.pad(arr, pad, mode="constant", constant_values=0)


def pad_rows(arr: np.ndarray, n: int,
             length: Optional[int] = None) -> np.ndarray:
    """Pad the batch dim up to ``n`` rows by repeating the last row — the
    fixed-shape trick that keeps jit from ever seeing a new shape (no
    per-remainder recompiles).  Shared by the online batcher and the
    offline UDF chunker.

    ``length``, when given, additionally pads the TRAILING axis up to
    ``length`` with zeros (the generative token-batch case: ragged
    prompts ride the same (bucket, page) shape ladder as fixed feature
    batches).  Rows longer than ``length`` are an error — truncation
    would silently drop tokens.  Dtype is always preserved, including
    for zero-row inputs (which still get their trailing axis resized so
    the compiled shape is honest)."""
    arr = np.asarray(arr)
    if length is not None:
        if arr.ndim < 1:
            raise ValueError("pad_rows: length= needs at least a 1-D "
                             f"array, got ndim={arr.ndim}")
        have = arr.shape[-1]
        if have > length:
            raise ValueError(f"pad_rows: trailing axis {have} exceeds "
                             f"length={length} (refusing to truncate)")
        if have < length:
            pad = [(0, 0)] * (arr.ndim - 1) + [(0, length - have)]
            arr = np.pad(arr, pad, mode="constant", constant_values=0)
    short = n - len(arr)
    if short <= 0:
        return arr
    if len(arr) == 0 and length is not None:
        # nothing to repeat: zero rows of the (resized) shape, zeros —
        # the token-batch contract (pad token 0), dtype preserved
        return np.zeros((n,) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, np.repeat(arr[-1:], short, axis=0)])


def predict_in_fixed_batches(forward: Callable, feats: np.ndarray,
                             batch_size: int) -> np.ndarray:
    """Chunk ``feats`` host-side into full ``batch_size`` batches (one XLA
    call per batch, never one giant buffer), padding the trailing chunk
    with :func:`pad_rows`, and concatenate the trimmed outputs.  The bulk
    (UDFPredictor) counterpart of the online batcher's bucket padding.
    Zero-row ``feats`` return a zero-row array without touching the
    device (the output's trailing shape is unknowable without a forward,
    so it mirrors the input's)."""
    feats = np.asarray(feats)
    if len(feats) == 0:
        return feats
    outs = []
    for i in range(0, len(feats), batch_size):
        chunk = feats[i:i + batch_size]
        outs.append(np.asarray(forward(pad_rows(chunk, batch_size)))
                    [:len(chunk)])
    return np.concatenate(outs, axis=0)


class DynamicBatcher:
    """Bounded request queue + coalescing policy (see module docstring).

    Thread contract: any number of producer threads call :meth:`submit`;
    any number of replica workers call :meth:`collect`.  ``close(drain=
    True)`` lets workers finish the queue before :meth:`collect` returns
    None; ``drain=False`` fails everything still queued with
    :class:`ServerClosed`."""

    #: wait-slice so idle workers keep heartbeating their supervisor
    #: channel (a parked worker must never read as a stalled one)
    _SLICE = 0.05

    def __init__(self, max_batch: int, max_wait_s: float,
                 queue_limit: int, buckets: Optional[Sequence[int]] = None,
                 clock=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self.buckets = tuple(sorted(buckets)) if buckets else \
            default_buckets(self.max_batch)
        if self.buckets[-1] < self.max_batch:
            raise ValueError(f"largest bucket {self.buckets[-1]} < "
                             f"max_batch {self.max_batch}")
        self.clock = clock or time.monotonic
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain = True
        # shed counters (read under the cond lock via stats())
        self.submitted = 0
        self.shed_overload = 0
        self.shed_timeout = 0
        self.shed_priority = 0      # evicted for a higher-priority arrival
        self.shed_by_priority: dict = {}  # priority class -> total sheds
        self._row_s_ema = None      # EMA service seconds/row (retry-after)

    # -- producers ------------------------------------------------------

    def _count_shed(self, req: "PendingRequest") -> None:
        # caller holds self._cond
        self.shed_by_priority[req.priority] = \
            self.shed_by_priority.get(req.priority, 0) + 1

    def _sweep_expired_locked(self, now: float) -> List["PendingRequest"]:
        """Drop queued requests whose deadline already passed (caller
        holds the lock; resolution happens outside it).  A stale queue
        must never hold ``queue_limit`` slots against fresh traffic —
        the dead requests are shed, not the arrival."""
        live, expired = collections.deque(), []
        for r in self._q:
            if r.deadline is not None and now > r.deadline:
                expired.append(r)
                self.shed_timeout += 1
                self._count_shed(r)
                _metrics_shed("timeout")
            else:
                live.append(r)
        self._q = live
        return expired

    def retry_after_s(self) -> float:
        """Seconds a rejected caller should back off: the estimated time
        to drain a full queue (EMA service rate from note_service), never
        below the coalesce window."""
        per_row = self._row_s_ema or 0.0
        return round(max(per_row * self.queue_limit, self.max_wait_s,
                         0.05), 3)

    def note_service(self, rows: int, seconds: float) -> None:
        """Feed the service-rate EMA (the server calls this after every
        successful batch) powering the retry-after estimate."""
        per = seconds / max(rows, 1)
        self._row_s_ema = per if self._row_s_ema is None else \
            0.8 * self._row_s_ema + 0.2 * per

    def service_row_seconds(self) -> Optional[float]:
        """The EMA seconds/row (None before the first served batch) —
        the service-rate signal behind retry-after and the autoscaler's
        queue-wait estimate (serve/autoscale.py)."""
        return self._row_s_ema

    def submit(self, payload, deadline: Optional[float] = None, *,
               tenant: Optional[str] = None,
               priority: int = 0,
               request_id: Optional[str] = None) -> PendingRequest:
        """Enqueue one sample; raises :class:`ServerOverloaded` when the
        bounded queue is full, :class:`ServerClosed` after shutdown.
        ``deadline`` is absolute (this batcher's clock).  When the queue
        is full, expired-deadline entries are swept first, then the
        LOWEST-priority queued request is evicted if this arrival
        strictly outranks it (shed-lowest-first under pressure).

        ``request_id`` is the distributed-tracing flow id: pass the one
        from the ``X-BigDL-Request-Id`` header when the request arrived
        through the fleet front (its flow already started there); when
        omitted and tracing is on, one is minted here and this process
        owns (finishes) the flow."""
        chaos.fire("serve.request")  # admission-path fault point
        rid, rid_owner = request_id, False
        if rid is None:
            rid = telemetry.mint_request_id()  # None when tracing is off
            rid_owner = rid is not None
        expired: List[PendingRequest] = []
        victim: Optional[PendingRequest] = None
        with self._cond:
            if self._closed:
                raise ServerClosed("serve: server is shutting down")
            if len(self._q) >= self.queue_limit:
                expired = self._sweep_expired_locked(self.clock())
            if len(self._q) >= self.queue_limit:
                # newest of the lowest-priority queued requests: it has
                # waited least, so evicting it wastes the least work
                cand = min(reversed(self._q), key=lambda r: r.priority)
                if cand.priority < int(priority):
                    self._q.remove(cand)
                    victim = cand
                    self.shed_priority += 1
                    self._count_shed(cand)
                    _metrics_shed("priority")
                else:
                    self.shed_overload += 1
                    self.shed_by_priority[int(priority)] = \
                        self.shed_by_priority.get(int(priority), 0) + 1
                    _metrics_shed("overloaded")
                    retry = self.retry_after_s()
                    raise ServerOverloaded(
                        f"serve: request queue full ({self.queue_limit} "
                        f"waiting, none below priority {int(priority)}) "
                        f"— shedding at admission; retry in {retry}s",
                        retry_after_s=retry)
            req = PendingRequest(payload, self.clock(), deadline,
                                 tenant=tenant, priority=priority,
                                 rid=rid, rid_owner=rid_owner)
            self._q.append(req)
            self.submitted += 1
            depth = len(self._q)
            self._cond.notify_all()
        if rid is not None:
            if rid_owner:
                telemetry.flow_start(rid, hop="queue.enqueue", depth=depth)
            else:
                telemetry.flow_step(rid, hop="queue.enqueue", depth=depth)
        now = self.clock()
        for r in expired:
            r._resolve(error=RequestTimeout(
                f"serve: deadline expired after {now - r.enqueued:.3f}s "
                "in queue (swept at admission)"), now=now)
        if victim is not None:
            victim._resolve(error=ServerOverloaded(
                f"serve: shed from a full queue for a priority-"
                f"{int(priority)} arrival (this request: priority "
                f"{victim.priority}); retry in {self.retry_after_s()}s",
                retry_after_s=self.retry_after_s()), now=now)
        telemetry.counter("serve", queue_depth=depth)
        return req

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -- workers --------------------------------------------------------

    def collect(self, heartbeat: Optional[Callable] = None,
                stop_when: Optional[Callable] = None
                ) -> Optional[List[PendingRequest]]:
        """Block until a batch is ready, the coalesce window expires, or
        shutdown.  Returns up to ``max_batch`` live requests (may be []
        when every dequeued request had expired — the caller just loops),
        or None when the batcher is closed and (if draining) empty.
        ``heartbeat`` is called on every wait slice so the worker's
        supervisor channel stays live while parked.  ``stop_when`` (a
        predicate checked per wait slice) lets a caller retire a worker
        parked on an EMPTY queue without closing the batcher — the pool
        shrink path (serve/autoscale.py): a condemned replica must not
        stay parked until the next request arrives just to notice its
        condemnation."""
        with self._cond:
            while not self._q:
                if self._closed:
                    return None
                if stop_when is not None and stop_when():
                    return None
                self._cond.wait(self._SLICE)
                if heartbeat is not None:
                    heartbeat()
            # coalesce: from the OLDEST waiting request's enqueue time,
            # hold the flush up to max_wait_s hoping to fill the batch —
            # the configurable latency-for-fill trade
            flush_at = self._q[0].enqueued + self.max_wait_s
            while len(self._q) < self.max_batch and not self._closed:
                remaining = flush_at - self.clock()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, self._SLICE))
                if heartbeat is not None:
                    heartbeat()
            reqs = [self._q.popleft()
                    for _ in range(min(len(self._q), self.max_batch))]
        # deadline shedding happens at dequeue, outside the lock: an
        # expired request never reaches the device
        now = self.clock()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                with self._cond:
                    self.shed_timeout += 1
                    self._count_shed(r)
                _metrics_shed("timeout")
                r._resolve(error=RequestTimeout(
                    f"serve: deadline exceeded after "
                    f"{now - r.enqueued:.3f}s in queue"), now=now)
            else:
                live.append(r)
        return live

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n is capped at max_batch by collect)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def requeue(self, reqs: Sequence["PendingRequest"]) -> None:
        """Hand collected-but-unserved requests back to the queue HEAD in
        their original order — a condemned/dying replica (serve/control
        teardown, the ``serve.replica`` exit drill) must lose zero
        accepted requests.  After a no-drain close there is nobody left
        to serve them: they fail typed instead."""
        reqs = [r for r in reqs if not r.done()]
        if not reqs:
            return
        stranded = None
        with self._cond:
            if self._closed and not self._drain:
                stranded = reqs
            else:
                for r in reversed(reqs):
                    self._q.appendleft(r)
                self._cond.notify_all()
        if stranded:
            now = self.clock()
            for r in stranded:
                r._resolve(error=ServerClosed(
                    "serve: server stopped before this request ran"),
                    now=now)

    def fail_pending(self, error: Optional[Exception] = None) -> int:
        """Resolve everything still queued with a typed error (default
        :class:`ServerClosed`) and return how many there were — the final
        shutdown sweep for queues nobody is left to drain (dead replica
        pool, drain interrupted), so no caller ever blocks on
        ``result()`` forever."""
        with self._cond:
            pending = [r for r in self._q if not r.done()]
            self._q.clear()
        now = self.clock()
        err = error if error is not None else ServerClosed(
            "serve: server stopped before this request ran")
        for r in pending:
            r._resolve(error=err, now=now)
        return len(pending)

    # -- shutdown -------------------------------------------------------

    def close(self, drain: bool = True) -> None:
        """Stop admissions.  drain=True lets workers finish the queue;
        drain=False fails everything still queued with ServerClosed."""
        with self._cond:
            self._closed = True
            self._drain = drain
            pending = []
            if not drain:
                while self._q:
                    pending.append(self._q.popleft())
            self._cond.notify_all()
        now = self.clock()
        for r in pending:
            r._resolve(error=ServerClosed(
                "serve: server stopped before this request ran"), now=now)

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        with self._cond:
            return {"queue_depth": len(self._q),
                    "submitted": self.submitted,
                    "shed_overload": self.shed_overload,
                    "shed_timeout": self.shed_timeout,
                    "shed_priority": self.shed_priority,
                    "shed_by_priority": {str(k): v for k, v in
                                         sorted(self.shed_by_priority
                                                .items())}}


class DecodeQueue(DynamicBatcher):
    """Per-SEQUENCE admission queue for the generative decode engine
    (serve/decode.py).

    Same bounded queue, deadlines, priority eviction and shed policy as
    :class:`DynamicBatcher` — a queued item is one *sequence* (prompt +
    generation budget), not one feature row, and the consumer is the
    engine's persistent step loop rather than a replica pool:

    - :meth:`take` pops up to ``n`` live sequences WITHOUT blocking or
      coalescing — the step loop admits into whatever slots just freed
      and must never park while other slots are still decoding.
    - :meth:`note_service` is fed (tokens, seconds), so the EMA learns
      seconds/TOKEN; ``retry_after_s`` therefore scales with the queue's
      total outstanding token budget, not its request count.
    """

    def __init__(self, queue_limit: int, max_wait_s: float = 0.0,
                 clock=None):
        # max_batch/buckets are meaningless per-sequence: slots and the
        # (slots, cache-page) ladder live in the engine
        super().__init__(max_batch=1, max_wait_s=max_wait_s,
                         queue_limit=queue_limit, buckets=(1,),
                         clock=clock)
        self._pending_tokens = 0  # queued generation budget (retry-after)

    def submit(self, payload, deadline: Optional[float] = None, *,
               tenant: Optional[str] = None,
               priority: int = 0,
               request_id: Optional[str] = None) -> PendingRequest:
        req = super().submit(payload, deadline, tenant=tenant,
                             priority=priority, request_id=request_id)
        with self._cond:
            self._pending_tokens += int(payload.get("max_tokens", 1)) \
                if isinstance(payload, dict) else 1
        return req

    def retry_after_s(self) -> float:
        """Back-off estimate for a rejected sequence: EMA seconds/token
        times the *queued token budget* (a queue of 8 sequences at 256
        tokens each is 2048 steps of work, not 8)."""
        per_tok = self._row_s_ema or 0.0
        return round(max(per_tok * max(self._pending_tokens, 1),
                         self.max_wait_s, 0.05), 3)

    def take(self, n: int) -> List[PendingRequest]:
        """Pop up to ``n`` live sequences, non-blocking.  Expired
        deadlines shed at dequeue exactly like :meth:`collect` (a
        sequence whose time-to-last-token deadline already passed must
        never occupy a slot).  Returns [] when the queue is empty."""
        if n <= 0:
            return []
        with self._cond:
            reqs = [self._q.popleft()
                    for _ in range(min(len(self._q), n))]
            for r in reqs:
                if isinstance(r.payload, dict):
                    self._pending_tokens = max(
                        0, self._pending_tokens
                        - int(r.payload.get("max_tokens", 1)))
        now = self.clock()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                with self._cond:
                    self.shed_timeout += 1
                    self._count_shed(r)
                _metrics_shed("timeout")
                r._resolve(error=RequestTimeout(
                    f"serve: deadline exceeded after "
                    f"{now - r.enqueued:.3f}s in queue (decode "
                    "admission)"), now=now)
            else:
                live.append(r)
        return live

    def wait_for_work(self, timeout: float) -> bool:
        """Park the step loop (briefly) until a sequence is queued or the
        queue closes.  Returns True when there may be work."""
        with self._cond:
            if self._q or self._closed:
                return True
            self._cond.wait(timeout)
            return bool(self._q) or self._closed
