"""bigdl_tpu: a TPU-native distributed deep-learning framework with the
capabilities of the original BigDL (reference: dgur1n/BigDL, surveyed in
SURVEY.md), rebuilt from scratch on JAX/XLA/pjit/Pallas.

Layer map (SURVEY.md §1 -> here):
  tensor/TensorNumeric + MKL JNI  -> jax.Array + XLA (common.py dtype policy)
  nn/ (Torch-style modules)       -> bigdl_tpu.nn (pure-functional core +
                                     stateful facade)
  dataset/                        -> bigdl_tpu.dataset
  optim/ + parameters/ (Spark PS) -> bigdl_tpu.optim (pjit step, psum over ICI)
  utils/Engine (topology)         -> bigdl_tpu.utils.Engine (jax.sharding.Mesh)
  visualization/                  -> bigdl_tpu.visualization
  models/                         -> bigdl_tpu.models
  parallel (net-new: TP/SP/PP)    -> bigdl_tpu.parallel
"""

__version__ = "0.1.0"

from . import common
from .common import DTypePolicy, get_policy, set_policy, set_seed
from .utils import Engine, Table, T, RandomGenerator, RNG
from . import nn
from . import optim
from . import dataset
from . import models
from . import parallel
from . import quantize as quantization
from .quantize import quantize
from . import serve
from .serve import InferenceServer
