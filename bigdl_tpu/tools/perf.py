"""Throughput micro-benchmark CLI.

Reference: models/utils/DistriOptimizerPerf.scala (:91-95 — inception_v1/v2,
vgg16/19 at batch x 3 x 224 x 224, synthetic data, no loading) and
LocalOptimizerPerf.scala.  Same role here: time the compiled train step on
synthetic batches per model, print records/s.

Usage:
    python -m bigdl_tpu.tools.perf --model inception_v1 --batch-size 32 \
        [--iters 20] [--warmup 3]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.timing import fetch_scalar, measure_step_seconds

# zoo names, resolved through models/run._build_model so the benched step
# uses the SAME model/criterion pairing as real training (LogSoftMax heads
# pair with ClassNLL, logits heads with CrossEntropy)
_MODELS = {"inception_v1": ("inception", 1000),
           "inception_v2": ("inception_v2", 1000),
           "vgg16": ("vgg16", 1000),
           "vgg19": ("vgg19", 1000), "resnet50": ("resnet50", 1000),
           "alexnet": ("alexnet", 1000), "lenet": ("lenet", 10),
           "transformer": ("transformer", 32000)}


def run(model_name: str, batch_size: int, iters: int = 20, warmup: int = 3,
        profile_dir: str = None, num_experts: int = 0):
    from ..models.run import _build_model, build_criterion
    from ..optim import SGD, Optimizer, Trigger
    from ..utils.engine import Engine
    from ..utils.platform import enable_compilation_cache

    enable_compilation_cache()
    Engine.reset()
    Engine.init()
    mesh = Engine.mesh()
    zoo_name, classes = _MODELS[model_name]
    if num_experts and zoo_name != "transformer":
        raise ValueError(f"--num-experts applies to the transformer only; "
                         f"{model_name} would silently bench the dense "
                         "model")
    model, input_hw, crit = _build_model(zoo_name, classes, num_experts)
    criterion = build_criterion(crit)
    model.build(jax.random.key(0))
    opt = Optimizer(model, dataset=None, criterion=criterion,
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(learning_rate=0.01, momentum=0.9))
    step, param_sh, data_sh = opt._build_step(mesh)

    params = jax.device_put(model.params, param_sh)
    net_state = model.state
    opt_state = opt.optim_method.init_state(params)
    if input_hw and input_hw[0] == "tokens":  # LM: int token sequences
        _, seq, vocab = input_hw
        r = np.random.default_rng(0)
        inp = jnp.asarray(r.integers(0, vocab, (batch_size, seq)), jnp.int32)
        tgt = jnp.asarray(r.integers(0, vocab, (batch_size, seq)), jnp.int32)
    else:
        inp = jnp.asarray(np.random.default_rng(0).standard_normal(
            (batch_size,) + input_hw), jnp.float32)
        tgt = jnp.asarray(np.random.default_rng(1).integers(
            0, classes, batch_size), jnp.float32)
    rng = jax.random.key(1)

    def one():
        nonlocal params, net_state, opt_state
        params, net_state, opt_state, loss = step(
            params, net_state, opt_state, inp, tgt, jnp.float32(0.01), rng)
        return loss

    # fetch-synced timing (utils/timing.py): block_until_ready does not
    # actually synchronize on this image's tunneled TPU backend
    t0 = time.perf_counter()
    fetch_scalar(one())
    compile_s = time.perf_counter() - t0
    for _ in range(max(warmup - 1, 0)):
        one()
    fetch_scalar(one())
    dt, detail = measure_step_seconds(one, n2=max(iters, 8))
    out = {"model": model_name,
        **({"num_experts": num_experts} if num_experts else {}), "batch_size": batch_size,
           "step_seconds": dt, "records_per_second": batch_size / dt,
           "compile_seconds": compile_s, "timing": detail,
           "device": str(jax.devices()[0])}
    if profile_dir:
        # xplane trace of the real compiled step (SURVEY.md §7.6)
        from ..utils.profiling import trace_steps
        out["profile_dir"] = trace_steps(one, max(iters // 2, 3), profile_dir)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description="train-step throughput bench "
                                 "(reference: DistriOptimizerPerf)")
    ap.add_argument("--model", default="inception_v1",
                    choices=sorted(_MODELS))
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler xplane trace of the step here")
    ap.add_argument("--num-experts", type=int, default=0,
                    help="transformer only: bench the Switch-style MoE "
                         "variant (parallel/expert.MoEFFN)")
    args = ap.parse_args(argv)
    print(json.dumps(run(args.model, args.batch_size, args.iters,
                         args.warmup, profile_dir=args.profile_dir,
                         num_experts=args.num_experts)))


if __name__ == "__main__":
    main()
