"""BatchNorm stat-computation experiment for the ResNet-50 MFU push.

Measured round 3 (real v5e chip, batch 256 bf16): fwd-eval hits 0.61 MFU
and eval-mode grad 0.45, but training-mode BN batch-stats machinery costs
~27ms of the 108ms step, capping train MFU at ~0.34 vs the 0.45 target
(BASELINE.md).  This tool times stat-computation variants through the whole
resnet50 grad so the winner can be promoted into nn/normalization.py with
evidence.  Run ON A REAL TPU (the tunnel was down for the second half of
round 3, so the variants were never measured):

    python -m bigdl_tpu.tools.bn_experiment [baseline dtype_arg]

Variants:
  baseline   — astype(f32) then two fused reductions (current nn code)
  dtype_arg  — jnp.mean(..., dtype=f32) accumulation without the explicit
               upcast (tests whether XLA materializes the f32 copy)
  custom_vjp — hand-written fused BN backward (2 read passes + 1 write:
               the canonical dx = scale*(dy - mean(dy) - xhat*mean(dy*xhat))
               formula) instead of autodiff through the stat graph
  remat_conv — baseline BN + selective rematerialization: save only conv
               outputs + BN stats across fwd/bwd, recompute all elementwise
               (BN normalize, ReLU, adds) in the backward pass — trades
               cheap recompute FLOPs for HBM writes of BN/ReLU activations
  vjp_remat  — custom_vjp and remat_conv combined
  pallas     — the fully fused Pallas kernel (ops/batchnorm.bn_train):
               2 reads + 1 write per direction, stats resident in VMEM
  stat<k>    — ghost-batch statistics from the first k rows only
               (BIGDL_TPU_BN_STAT_ROWS=k), e.g. stat64
  conv_epilogue — nn.fuse_conv_bn model rewrite: BN stats accumulated in
               the producing 1x1 conv's matmul epilogue (ops/convbn.py),
               deleting the separate stat read; non-1x1 convs' BNs run
               the baseline path
  <any>_remat — the above combined with the conv_out remat policy
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.config import get_int

PEAK = 197e12  # v5e table peak; see utils/timing.measure_roofline
# BIGDL_TPU_BN_BATCH overrides (the round-3 "MFU falls as batch grows"
# anomaly — 256:0.333, 512:0.317, 1024:0.273 — needs per-variant batch
# sweeps to localize; bench.py's step is identical, only stats vary)
BATCH = get_int("BN_BATCH", 256)


_PRISTINE_APPLY = None  # BatchNormalization.apply before any variant patch


def _variant_apply(kind):
    import os

    for var in ("BIGDL_TPU_BN_FUSED_VJP", "BIGDL_TPU_BN_IMPL",
                "BIGDL_TPU_BN_STAT_ROWS"):
        os.environ.pop(var, None)
    if kind == "custom_vjp":
        # the library implementation behind BIGDL_TPU_BN_FUSED_VJP
        # (nn/normalization._fused_bn_train) — benchmark THAT, not a copy
        os.environ["BIGDL_TPU_BN_FUSED_VJP"] = "1"
        return _PRISTINE_APPLY
    if kind == "pallas":
        # the Pallas BN kernels (ops/batchnorm).  Single device routes to
        # the fused two-phase kernel; multi-device routes through the
        # shard_map+psum sync path IF a data-only Engine mesh exists and
        # the batch divides over it — otherwise the library would silently
        # benchmark the baseline under this label, so fail loud.
        import jax

        if jax.device_count() > 1:
            from ..utils.engine import Engine

            if Engine._mesh is None:
                Engine.init()  # data-only mesh over all visible devices
            mesh = Engine.mesh()
            from ..nn.normalization import BatchNormalization as _BN

            if not _BN.shardmap_route_engages(mesh, BATCH):
                raise RuntimeError(
                    f"pallas BN variant needs a data-only mesh dividing "
                    f"batch {BATCH} (mesh: {dict(mesh.shape)}): the "
                    "library would fall back to the baseline path and "
                    "mislabel the measurement")
        os.environ["BIGDL_TPU_BN_IMPL"] = "pallas"
        return _PRISTINE_APPLY
    if kind == "conv_epilogue":
        # model-level rewrite (bench_variant applies nn.fuse_conv_bn before
        # build); the BN class itself stays pristine.  ConvBN only engages
        # its fused kernel single-device — fail loud rather than silently
        # benchmark the unfused fallback under this label.
        import jax

        from ..utils.platform import backend_kind

        if jax.device_count() != 1 or backend_kind() != "tpu":
            raise RuntimeError(
                f"conv_epilogue needs exactly 1 TPU device (have "
                f"{jax.device_count()} x {backend_kind()}): ConvBN would "
                "fall back to the unfused path and mislabel the "
                "measurement")
        return _PRISTINE_APPLY
    if kind.startswith("stat") and kind[len("stat"):].isdigit():
        # ghost-batch statistics from the first k rows (BN_STAT_ROWS)
        os.environ["BIGDL_TPU_BN_STAT_ROWS"] = kind[len("stat"):]
        return _PRISTINE_APPLY
    if kind not in ("baseline", "dtype_arg"):
        # unknown names must not silently benchmark the baseline under a
        # wrong label — mislabeled numbers would enter the bench provenance
        raise ValueError(f"unknown BN variant: {kind!r}")

    def apply(self, params, state, x, *, training=False, rng=None):
        axes = tuple(range(x.ndim - 1))
        if training:
            if kind == "baseline":
                xf = x.astype(jnp.float32)
                mean = jnp.mean(xf, axis=axes)
                var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
            else:  # dtype_arg
                mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
                var = (jnp.mean(jnp.square(x.astype(jnp.float32)),
                                axis=axes) - jnp.square(mean))
            m = self.momentum
            n = 1
            for ax in axes:
                n *= x.shape[ax]
            unbiased = var * (n / max(n - 1, 1))
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        if self.affine:
            scale = params["weight"] * inv
            shift = params["bias"] - mean * scale
        else:
            scale, shift = inv, -mean * inv
        y = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        return y, new_state

    return apply


def bench_variant(kind: str) -> None:
    global _PRISTINE_APPLY
    from ..common import DTypePolicy, set_policy
    from ..nn import CrossEntropyCriterion
    from ..nn.normalization import BatchNormalization
    from ..utils.flops import jaxpr_flops
    from ..utils.timing import measure_step_seconds

    if _PRISTINE_APPLY is None:
        _PRISTINE_APPLY = BatchNormalization.apply
    # conv outputs are checkpoint_name-tagged by nn/conv itself, so the
    # remat variants only need the jax.checkpoint policy below
    remat = kind.endswith("_remat") or kind in ("remat_conv", "vjp_remat")
    base = {"remat_conv": "baseline", "vjp_remat": "custom_vjp"}.get(kind)
    if base is None:
        base = kind[:-len("_remat")] if kind.endswith("_remat") else kind
    BatchNormalization.apply = _variant_apply(base)
    set_policy(DTypePolicy(compute_dtype=jnp.bfloat16))
    from ..models.resnet import ResNet
    model = ResNet(50, class_num=1000, dataset="imagenet")
    if base == "conv_epilogue":
        from ..nn import fuse_conv_bn
        fuse_conv_bn(model)  # before build: the rewrite re-nests params
    model.build(jax.random.key(0))
    crit = CrossEntropyCriterion()
    x = jnp.zeros((BATCH, 224, 224, 3), jnp.float32)
    y = jnp.ones((BATCH,), jnp.int32)

    def loss(p):
        out, _ = model.apply(p, model.state, x, training=True,
                             rng=jax.random.key(2))
        return crit.forward(out, y)

    if remat:
        loss = jax.checkpoint(
            loss, policy=jax.checkpoint_policies.save_only_these_names(
                "conv_out"))

    def g(p):
        gr = jax.grad(loss)(p)
        return sum(jnp.sum(l.astype(jnp.float32))
                   for l in jax.tree.leaves(gr))

    flops = jaxpr_flops(jax.make_jaxpr(g)(model.params))
    compiled = jax.jit(g).lower(model.params).compile()
    compiled(model.params)
    dt, _ = measure_step_seconds(lambda: compiled(model.params))
    print(f"bn[{kind:9s}] dt={dt * 1e3:8.2f}ms "
          f"mfu={flops / dt / PEAK:.4f}", flush=True)


def main(argv=None):
    for kind in (argv or sys.argv[1:]) or ["baseline", "dtype_arg",
                                           "custom_vjp", "remat_conv",
                                           "vjp_remat", "pallas",
                                           "pallas_remat", "stat64",
                                           "stat64_remat", "conv_epilogue",
                                           "conv_epilogue_remat"]:
        try:
            bench_variant(kind)
        except Exception as e:  # noqa: BLE001 — report and continue
            print(f"bn[{kind}] FAILED {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
