"""Scaling-efficiency measurement on a virtual device mesh.

BASELINE.md's scaling target ("linear, 8 -> 64 chips") cannot be measured on
this image (one real chip), so this tool produces the best available
evidence (round-2 verdict demand #4):

1. **Collective introspection** — compile the real distributed train step
   (Optimizer._build_step) over an n-device mesh and count the XLA
   collectives in the optimized HLO.  Sync data-parallel SGD must lower to
   gradient all-reduce(s) riding the mesh (the in-XLA form of the
   reference's reduce-scatter + lazy allgather over the Spark block manager,
   parameters/AllReduceParameter.scala:53-60) — and must NOT contain
   host transfers.
2. **Virtual throughput ratio** — per-device throughput with the same
   per-device batch on a 1-device vs an n-device CPU mesh.  On virtual CPU
   devices all n "chips" share the host's cores, so this UNDERSTATES real
   efficiency (ICI is free of core contention); it is a smoke check that
   per-step overhead does not explode with mesh width, not a TPU number.

Usage:  python -m bigdl_tpu.tools.scaling [--devices 8] [--batch-per-device 64]
Prints one JSON object.
"""

from __future__ import annotations

import argparse
import json
import re
import sys


_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def collective_counts(hlo_text: str) -> dict:
    """Count collective ops in optimized HLO text."""
    counts = {}
    for name in _COLLECTIVES:
        # match op instructions like '%all-reduce.3 = ' or 'all-reduce-start'
        n = len(re.findall(rf"= \S* ?{name}[.\-(]", hlo_text)) or \
            len(re.findall(rf"{name}[.\d]* =", hlo_text))
        if n:
            counts[name] = n
    return counts


def _build(n_devices: int, batch_per_device: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ..models.lenet import LeNet5
    from ..nn import ClassNLLCriterion
    from ..optim import Optimizer, SGD, Trigger

    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices, (
        f"need {n_devices} devices, have {len(devices)} — launch with "
        f"JAX_PLATFORMS=cpu (fresh process) so the virtual-device config "
        f"can take effect")
    mesh = Mesh(np.asarray(devices).reshape(n_devices), ("data",))
    model = LeNet5(10).build(jax.random.key(0))
    opt = Optimizer(model, dataset=None, criterion=ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(learning_rate=0.05, momentum=0.9))
    step, param_sh, data_sh = opt._build_step(mesh)

    batch = batch_per_device * n_devices
    params = jax.device_put(model.params, param_sh)
    opt_state = opt.optim_method.init_state(params)
    inp = jax.device_put(jnp.zeros((batch, 28, 28, 1), jnp.float32), data_sh)
    tgt = jax.device_put(jnp.ones((batch,), jnp.int32), data_sh)
    lr, rng = jnp.float32(0.05), jax.random.key(1)

    lowered = step.lower(params, model.state, opt_state, inp, tgt, lr, rng)
    compiled = lowered.compile()

    box = {"p": params, "s": model.state, "o": opt_state}

    def run():
        box["p"], box["s"], box["o"], loss = compiled(
            box["p"], box["s"], box["o"], inp, tgt, lr, rng)
        return loss

    return run, compiled, batch


def measure(n_devices: int, batch_per_device: int = 64) -> dict:
    from ..utils.timing import measure_step_seconds

    run1, compiled1, batch1 = _build(1, batch_per_device)
    dt1, _ = measure_step_seconds(run1, n1=2, n2=8, reps=2)
    runn, compiledn, batchn = _build(n_devices, batch_per_device)
    dtn, _ = measure_step_seconds(runn, n1=2, n2=8, reps=2)

    thr1 = batch1 / dt1            # records/s on 1 device
    thrn = batchn / dtn            # records/s on n devices
    per_dev_eff = (thrn / n_devices) / thr1

    hlo = compiledn.as_text()
    colls = collective_counts(hlo)
    return {
        "n_devices": n_devices,
        "batch_per_device": batch_per_device,
        "throughput_1dev_records_s": round(thr1, 1),
        "throughput_ndev_records_s": round(thrn, 1),
        "per_device_efficiency": round(per_dev_eff, 3),
        "note": ("virtual CPU mesh: all devices share host cores, so "
                 "efficiency here is a contention-bound LOWER bound; "
                 "collectives confirm the compiled step is genuinely "
                 "distributed"),
        "collectives_ndev_step": colls,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--batch-per-device", type=int, default=64)
    args = ap.parse_args(argv)

    from ..utils.platform import force_cpu
    force_cpu(args.devices)
    print(json.dumps(measure(args.devices, args.batch_per_device)))


if __name__ == "__main__":
    main()
