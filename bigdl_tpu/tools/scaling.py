"""Scaling-efficiency measurement on a virtual device mesh.

BASELINE.md's scaling target ("linear, 8 -> 64 chips") cannot be measured on
this image (one real chip), so this tool produces the best available
evidence (round-2 verdict demand #4):

1. **Collective introspection** — compile the real distributed train step
   (Optimizer._build_step) over an n-device mesh and count the XLA
   collectives in the optimized HLO.  Sync data-parallel SGD must lower to
   gradient all-reduce(s) riding the mesh (the in-XLA form of the
   reference's reduce-scatter + lazy allgather over the Spark block manager,
   parameters/AllReduceParameter.scala:53-60) — and must NOT contain
   host transfers.
2. **Virtual throughput ratio** — per-device throughput with the same
   per-device batch on a 1-device vs an n-device CPU mesh.  On virtual CPU
   devices all n "chips" share the host's cores, so this UNDERSTATES real
   efficiency (ICI is free of core contention); it is a smoke check that
   per-step overhead does not explode with mesh width, not a TPU number.

Usage:  python -m bigdl_tpu.tools.scaling [--devices 8] [--batch-per-device 64]
Prints one JSON object.
"""

from __future__ import annotations

import argparse
import json
import re
import sys


_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def collective_counts(hlo_text: str) -> dict:
    """Count collective ops in optimized HLO text."""
    counts = {}
    for name in _COLLECTIVES:
        # match op instructions like '%all-reduce.3 = ' or 'all-reduce-start'
        n = len(re.findall(rf"= \S* ?{name}[.\-(]", hlo_text)) or \
            len(re.findall(rf"{name}[.\d]* =", hlo_text))
        if n:
            counts[name] = n
    return counts


def _devices(n: int):
    import jax

    devices = jax.devices()[:n]
    assert len(devices) == n, (
        f"need {n} devices, have {len(devices)} — launch with "
        f"JAX_PLATFORMS=cpu (fresh process) so the virtual-device config "
        f"can take effect")
    return devices


def _build(n_devices: int, batch_per_device: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ..models.lenet import LeNet5
    from ..nn import ClassNLLCriterion
    from ..optim import Optimizer, SGD, Trigger

    devices = _devices(n_devices)
    mesh = Mesh(np.asarray(devices).reshape(n_devices), ("data",))
    model = LeNet5(10).build(jax.random.key(0))
    opt = Optimizer(model, dataset=None, criterion=ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(learning_rate=0.05, momentum=0.9))
    step, param_sh, data_sh = opt._build_step(mesh)

    batch = batch_per_device * n_devices
    params = jax.device_put(model.params, param_sh)
    opt_state = opt.optim_method.init_state(params)
    inp = jax.device_put(jnp.zeros((batch, 28, 28, 1), jnp.float32), data_sh)
    tgt = jax.device_put(jnp.ones((batch,), jnp.int32), data_sh)
    lr, rng = jnp.float32(0.05), jax.random.key(1)

    lowered = step.lower(params, model.state, opt_state, inp, tgt, lr, rng)
    compiled = lowered.compile()

    box = {"p": params, "s": model.state, "o": opt_state}

    def run():
        box["p"], box["s"], box["o"], loss = compiled(
            box["p"], box["s"], box["o"], inp, tgt, lr, rng)
        return loss

    return run, compiled, batch


def measure(n_devices: int, batch_per_device: int = 64) -> dict:
    from ..utils.timing import measure_step_seconds

    run1, compiled1, batch1 = _build(1, batch_per_device)
    dt1, _ = measure_step_seconds(run1, n1=2, n2=8, reps=2)
    runn, compiledn, batchn = _build(n_devices, batch_per_device)
    dtn, _ = measure_step_seconds(runn, n1=2, n2=8, reps=2)

    thr1 = batch1 / dt1            # records/s on 1 device
    thrn = batchn / dtn            # records/s on n devices
    per_dev_eff = (thrn / n_devices) / thr1

    hlo = compiledn.as_text()
    colls = collective_counts(hlo)
    return {
        "n_devices": n_devices,
        "batch_per_device": batch_per_device,
        "throughput_1dev_records_s": round(thr1, 1),
        "throughput_ndev_records_s": round(thrn, 1),
        "per_device_efficiency": round(per_dev_eff, 3),
        "note": ("virtual CPU mesh: all devices share host cores, so "
                 "efficiency here is a contention-bound LOWER bound; "
                 "collectives confirm the compiled step is genuinely "
                 "distributed"),
        "collectives_ndev_step": colls,
    }


def strategy_signatures(n_devices: int) -> dict:
    """Collective signature of every parallelism strategy, compiled on the
    virtual mesh: evidence that each strategy lowers to the expected ICI
    collectives (not a Python-side simulation of them).

    Expected shapes — DP: gradient all-reduce; ZeRO/ShardedDP:
    reduce-scatter (or windowed all-reduce) + all-gather of sharded
    params/opt-state; DP x TP: all-reduces on both the gradient and the
    activation path; ring SP: collective-permute chain (the shard_map
    ppermute ring); Ulysses SP: all-to-alls re-sharding heads<->sequence."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ..models.lenet import LeNet5
    from ..nn import ClassNLLCriterion
    from ..optim import Optimizer, SGD, Trigger
    from ..parallel.ring_attention import ring_attention, ulysses_attention
    from ..parallel.sharding import (DataParallel, ShardedDataParallel,
                                     TensorParallel)

    devices = _devices(n_devices)
    out = {}

    def train_step_hlo(mesh, strategy):
        model = LeNet5(10).build(jax.random.key(0))
        opt = Optimizer(model, dataset=None, criterion=ClassNLLCriterion(),
                        end_trigger=Trigger.max_iteration(1),
                        strategy=strategy)
        opt.set_optim_method(SGD(learning_rate=0.05, momentum=0.9))
        step, param_sh, data_sh = opt._build_step(mesh)
        batch = 8 * mesh.devices.size
        args = (jax.device_put(model.params, param_sh), model.state,
                opt.optim_method.init_state(model.params),
                jax.device_put(jnp.zeros((batch, 28, 28, 1), jnp.float32),
                               data_sh),
                jax.device_put(jnp.ones((batch,), jnp.int32), data_sh),
                jnp.float32(0.05), jax.random.key(1))
        return step.lower(*args).compile().as_text()

    mesh1d = Mesh(np.asarray(devices).reshape(n_devices), ("data",))
    out[f"dp{n_devices}"] = collective_counts(
        train_step_hlo(mesh1d, DataParallel()))
    out[f"zero{n_devices}"] = collective_counts(
        train_step_hlo(mesh1d, ShardedDataParallel(min_size=1)))
    if n_devices % 2 == 0:
        mesh2d = Mesh(np.asarray(devices).reshape(n_devices // 2, 2),
                      ("data", "model"))

        def tp_rule(path, leaf):
            # shard every even last axis: TensorParallel's default rule has
            # a 2^16-element floor that (correctly) leaves LeNet's small
            # weights replicated, which would make this signature a plain
            # DP one — the point here is the ENGAGED-TP collective shape
            from jax.sharding import PartitionSpec as P
            if leaf.ndim >= 2 and leaf.shape[-1] % 2 == 0:
                return P(*([None] * (leaf.ndim - 1) + ["model"]))
            return P()

        out[f"dp{n_devices // 2}xtp2"] = collective_counts(
            train_step_hlo(mesh2d, TensorParallel(rule=tp_rule)))

    seq_mesh = Mesh(np.asarray(devices).reshape(n_devices), ("seq",))
    B, H, T, D = 2, n_devices, 4 * n_devices, 8
    q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.float32)
               for kk in jax.random.split(jax.random.key(2), 3))
    out[f"ring_sp{n_devices}"] = collective_counts(
        jax.jit(lambda a, b, c: ring_attention(
            a, b, c, mesh=seq_mesh, causal=True, batch_axis=None)
        ).lower(q, k, v).compile().as_text())
    out[f"ulysses_sp{n_devices}"] = collective_counts(
        jax.jit(lambda a, b, c: ulysses_attention(
            a, b, c, mesh=seq_mesh, causal=True, batch_axis=None)
        ).lower(q, k, v).compile().as_text())
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--batch-per-device", type=int, default=64)
    ap.add_argument("--no-strategies", action="store_true",
                    help="skip the per-strategy collective signatures")
    args = ap.parse_args(argv)

    from ..utils.platform import force_cpu
    force_cpu(args.devices)
    result = measure(args.devices, args.batch_per_device)
    if not args.no_strategies:
        result["strategy_collectives"] = strategy_signatures(args.devices)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
