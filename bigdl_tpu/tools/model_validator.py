"""ModelValidator: load a pretrained model in any supported format and
evaluate it.

Reference: example/loadmodel/{ModelValidator,AlexNet}.scala — a CLI that
loads Caffe/Torch/BigDL models and reports top-1/top-5 on a validation set.
Formats here: bigdl (Module.save), caffe (.caffemodel), torch (.t7), tf
(frozen GraphDef) — all via interop/.

Usage:
    python -m bigdl_tpu.tools.model_validator \
        --model-type caffe --model /m.caffemodel \
        --data /data/val.bdr --batch-size 128
"""

from __future__ import annotations

import argparse
import json


def load_model(model_type: str, path: str):
    if model_type == "bigdl":
        # "bigdl" covers BOTH native formats: a file written by actual BigDL
        # is a Java object-serialization stream (magic 0xACED — the
        # reference's Module.save, utils/File.scala:25); a file written by
        # THIS framework's Module.save is a weight-detached pickle.  Sniff
        # through file_io so gs://-style remote paths keep working.
        from ..utils import file_io
        data = file_io.get_filesystem(path).read_bytes(path)
        if data[:2] == b"\xac\xed":
            from ..interop import bigdl as bigdl_fmt
            return bigdl_fmt.load_bytes(data)
        from ..nn.module import Module
        return Module.load(path)
    if model_type == "caffe":
        from ..interop import load_caffe
        return load_caffe(path)[0]
    if model_type == "torch":
        from ..interop import load_torch_module
        return load_torch_module(path)[0]
    if model_type == "tf":
        from ..interop import load_tf
        return load_tf(path)[0]
    raise ValueError(f"unknown model type {model_type!r}")


def validate(model_type: str, model_path: str, data_path: str,
             batch_size: int = 128):
    from ..dataset import DataSet
    from ..models.run import _load_samples
    from ..optim import Evaluator, Top1Accuracy, Top5Accuracy
    from ..utils.engine import Engine

    Engine.init()
    model = load_model(model_type, model_path)
    samples = _load_samples(data_path, None)
    results = Evaluator(model).test(DataSet.array(samples),
                                    [Top1Accuracy(), Top5Accuracy()],
                                    batch_size=batch_size)
    out = {}
    for method, res in results:
        acc, n = res.result()
        out[method.name] = {"accuracy": acc, "count": n}
        print(f"{method.name}: {res}")
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="load + evaluate a pretrained model "
                    "(reference: example/loadmodel/ModelValidator.scala)")
    ap.add_argument("--model-type", required=True,
                    choices=("bigdl", "caffe", "torch", "tf"))
    ap.add_argument("--model", required=True)
    ap.add_argument("--data", required=True, help="BDRecord path/glob")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line instead of text")
    args = ap.parse_args(argv)
    out = validate(args.model_type, args.model, args.data, args.batch_size)
    if args.json:
        print(json.dumps(out))


if __name__ == "__main__":
    main()
