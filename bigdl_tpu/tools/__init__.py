"""Command-line tools (reference: the models/utils CLIs —
ImageNetSeqFileGenerator, DistriOptimizerPerf/LocalOptimizerPerf)."""
