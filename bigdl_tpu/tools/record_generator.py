"""Offline ETL: image-folder tree -> sharded BDRecord files.

Reference: models/utils/ImageNetSeqFileGenerator.scala — the CLI that turns
the raw ImageNet folder layout into the Hadoop SequenceFiles BigDL trains
from.  Here the target is the BDRecord format (utils/recordio.py; TFRecord
framing, native C++ reader), sharded so each TPU host process reads its own
subset of shards.

Usage:
    python -m bigdl_tpu.tools.record_generator \
        --folder /data/imagenet/train --output /data/bdr/train \
        --shards 64 [--scale 256] [--parallel 8]
"""

from __future__ import annotations

import argparse
import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np


def convert(folder: str, output: str, shards: int = 8, scale: int = -1,
            parallel: int = os.cpu_count() or 1, quiet: bool = False):
    from ..dataset.image import _decode_image, _resize_shorter
    from ..utils.recordio import write_records

    classes = sorted(d for d in os.listdir(folder)
                     if os.path.isdir(os.path.join(folder, d)))
    if not classes:
        raise ValueError(f"no class directories under {folder!r}")
    jobs = []
    for label, cls in enumerate(classes):
        cdir = os.path.join(folder, cls)
        for fname in sorted(os.listdir(cdir)):
            jobs.append((os.path.join(cdir, fname), float(label)))

    os.makedirs(os.path.dirname(output) or ".", exist_ok=True)

    def prepare(job):
        path, label = job
        img = _decode_image(path)  # float32 in [0, 1]
        if scale > 0:
            img = _resize_shorter(img, scale)
        # store compact uint8 pixels; loaders rescale by dtype
        data = np.clip(np.round(img * 255.0), 0, 255).astype(np.uint8)
        return {"data": data, "label": label}

    n = 0

    def records():
        nonlocal n
        with ThreadPoolExecutor(max_workers=parallel) as pool:
            for rec in pool.map(prepare, jobs, chunksize=16):
                n += 1
                if not quiet and n % 1000 == 0:
                    print(f"{n}/{len(jobs)} records")
                yield rec

    # decode in the thread pool; sharded framing/atomic-rename is
    # write_records' job (utils/recordio.py)
    paths = write_records(output, records(), shards=shards)
    if not quiet:
        print(f"wrote {n} records over {shards} shards -> {output}-*")
    return paths, n


def convert_seq(folder: str, output: str, shards: int = 8,
                class_num: int = None, quiet: bool = False):
    """Hadoop SequenceFile shards (reference ImageNetSeqFileGenerator
    format) -> BDRecord shards: the re-ETL-free import path for datasets
    prepared for the reference (dataset/seqfile.py does the parsing)."""
    from ..dataset.seqfile import find_seq_files, read_byte_records
    from ..utils.recordio import write_records

    paths = find_seq_files(folder)
    n = 0

    def records():
        nonlocal n
        for p in paths:
            for rec in read_byte_records(p, class_num):
                n += 1
                yield rec

    out = write_records(output, records(), shards=shards)
    if not quiet:
        print(f"imported {n} records from {len(paths)} .seq files "
              f"-> {output}-*")
    return out, n


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="image folder (or reference .seq shards, --from-seq) "
                    "-> sharded BDRecord files")
    ap.add_argument("--folder", required=True,
                    help="directory-per-class image tree, or a folder of "
                         "*.seq files with --from-seq")
    ap.add_argument("--output", required=True, help="output shard base path")
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--scale", type=int, default=-1,
                    help="resize shorter side to this (like LocalImgReader)")
    ap.add_argument("--parallel", type=int, default=os.cpu_count() or 1)
    ap.add_argument("--from-seq", action="store_true",
                    help="input is Hadoop SequenceFile shards written by "
                         "the reference's ImageNetSeqFileGenerator")
    ap.add_argument("--class-num", type=int, default=None,
                    help="with --from-seq: keep labels <= this")
    args = ap.parse_args(argv)
    if args.from_seq:
        if args.scale != -1 or args.parallel != (os.cpu_count() or 1):
            ap.error("--scale/--parallel apply only to the image-folder "
                     "path; --from-seq copies records as stored")
        convert_seq(args.folder, args.output, args.shards, args.class_num)
    else:
        if args.class_num is not None:
            ap.error("--class-num requires --from-seq")
        convert(args.folder, args.output, args.shards, args.scale,
                args.parallel)


if __name__ == "__main__":
    main()
