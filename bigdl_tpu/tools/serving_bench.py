"""Serving microbench: KV-cache decode vs full re-forward, float vs int8.

Run on the real chip (one JSON line per config, bench.py conventions):

    python -m bigdl_tpu.tools.serving_bench [--d-model 512 --num-layers 8
        --max-len 1024 --batch 8 --num-tokens 64]

Measures tokens/sec for:
  full_fwd   — transformer_lm.greedy_generate (full [B, L] forward/token)
  kv_cache   — models/decode.cached_generate ([B, 1] step + cache)
  kv_int8    — cached decode on the quantize()-d model

The interesting ratios: kv_cache/full_fwd (the O(L) vs O(L^2) win) and
kv_int8/kv_cache (weight-bandwidth relief in the memory-bound regime).
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--num-layers", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--max-len", type=int, default=1024)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--num-tokens", type=int, default=64)
    p.add_argument("--skip-full", action="store_true",
                   help="full re-forward is O(L^2)/token — skip when slow")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from ..common import DTypePolicy, set_policy
    from ..models import TransformerLM, cached_generate
    from ..models.transformer_lm import greedy_generate
    from ..quantize import quantize

    import jax.numpy as jnp
    from ..common import get_policy
    prev_policy = get_policy()
    set_policy(DTypePolicy(compute_dtype=jnp.bfloat16))
    try:
        model = TransformerLM(
            vocab_size=args.vocab, max_len=args.max_len,
            d_model=args.d_model, num_heads=args.num_heads,
            num_layers=args.num_layers).build(jax.random.key(0))
        # 1-token prompt: the KV paths then run exactly num_tokens steps,
        # matching full_fwd's loop count — otherwise prompt prefill would
        # be charged against generated tokens and skew the ratio
        prompt = np.ones((args.batch, 1), np.int32)

        def bench(name, fn):
            fn()  # compile + warm
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            dt = min(times)  # bench.py convention: best of N, noise-robust
            toks = args.batch * args.num_tokens
            return {"path": name, "tokens_per_sec": round(toks / dt, 1),
                    "seconds": round(dt, 4)}

        results = []
        if not args.skip_full:
            results.append(bench("full_fwd", lambda: greedy_generate(
                model, prompt, args.num_tokens, args.max_len)))
        results.append(bench("kv_cache", lambda: cached_generate(
            model, prompt, args.num_tokens, args.max_len)))
        qmodel = quantize(model)
        results.append(bench("kv_int8", lambda: cached_generate(
            qmodel, prompt, args.num_tokens, args.max_len)))
    finally:
        set_policy(prev_policy)

    out = {"metric": "serving_decode_tokens_per_sec",
           "config": {k: getattr(args, k)
                      for k in ("d_model", "num_heads", "num_layers",
                                "vocab", "max_len", "batch", "num_tokens")},
           "device": jax.devices()[0].device_kind,
           "results": results}
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
