"""Post-training weight quantization (int8, per-output-channel symmetric).

Net-new vs the 2017 reference (no quantization anywhere in BigDL v0.3;
SURVEY.md §2 inventory) — on TPU this is a serving lever: weights stay int8
in HBM (half of bf16, quarter of f32) and XLA fuses the int8→compute-dtype
convert into the matmul/conv read, so memory-bound inference (small batch,
big weights — the LLM decode regime served by models/decode.py) gains
roughly the storage ratio in weight bandwidth.

Design: `quantize(model)` rebuilds the module tree, swapping the
matmul-bearing leaves for quantized twins that store `{q: int8, scale:
f32[per-out-channel]}` and apply `matmul(x, q.astype(compute)) * scale`
— scales commute with the contraction because both are linear per output
channel.  Everything else (BN folded stats, LayerNorm, activations,
containers) is structurally copied.  The result is a normal Module:
`forward`, `Module.save/load`, `Predictor`, and `cached_generate` all work
unchanged.

Accuracy contract: symmetric per-channel int8 on weights only (activations
stay bf16/f32), the configuration that is near-lossless for the zoo models
(tests assert trained-model parity).
"""

from __future__ import annotations

import copy

import jax
import jax.numpy as jnp

from .common import get_policy
from .nn.attention import MultiHeadAttention
from .nn.conv import SpatialConvolution
from .nn.dropout import LookupTable
from .nn.linear import Linear
from .nn.module import Container, Module

__all__ = ["quantize", "quantize_array", "QuantLinear",
           "QuantSpatialConvolution", "QuantMultiHeadAttention",
           "QuantLookupTable"]


class _NoReinit:
    """Mixin: quantized params come only from from_float; a re-build would
    silently replace int8 weights with float keys and crash later."""

    def _init(self, rng):
        raise RuntimeError(
            f"{type(self).__name__} cannot be (re)initialized — quantized "
            "modules get their params from quantize()/from_float only")


def quantize_array(w, channel_axis: int):
    """Symmetric per-channel int8: returns (q int8, scale f32 [channels])."""
    w = jnp.asarray(w, jnp.float32)
    axes = tuple(a for a in range(w.ndim) if a != channel_axis)
    amax = jnp.max(jnp.abs(w), axis=axes)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    shape = [1] * w.ndim
    shape[channel_axis] = -1
    q = jnp.clip(jnp.round(w / scale.reshape(shape)), -127, 127)
    return q.astype(jnp.int8), scale


class QuantLinear(_NoReinit, Module):
    """int8 twin of nn.Linear (weight (out, in), per-out-row scale)."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias

    @classmethod
    def from_float(cls, mod: Linear, params):
        m = cls(mod.input_size, mod.output_size, mod.with_bias)
        q, scale = quantize_array(params["weight"], channel_axis=0)
        p = {"q": q, "scale": scale}
        if mod.with_bias:
            p["bias"] = jnp.asarray(params["bias"])
        return m, p

    def _apply(self, params, x):
        c = get_policy().compute_dtype
        y = jax.lax.dot_general(
            x.astype(c), params["q"].astype(c),
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        y = (y * params["scale"]).astype(c)
        if self.with_bias:
            y = y + params["bias"].astype(c)
        return y


class QuantSpatialConvolution(_NoReinit, Module):
    """int8 twin of nn.SpatialConvolution (HWIO weight, per-O scale).

    Keeps the float layer's geometry by delegating to a carried
    SpatialConvolution instance's `_conv` (stride/pad/group handling) with
    the int8 weight cast to compute dtype; the per-channel scale is applied
    to the conv OUTPUT, which is exact because convolution is linear per
    output channel."""

    def __init__(self, conv: SpatialConvolution):
        super().__init__()
        self.conv = conv
        self.with_bias = conv.with_bias

    @classmethod
    def from_float(cls, mod: SpatialConvolution, params):
        geom = copy.copy(mod)
        geom.params = geom.state = None  # geometry only — no float weights
        m = cls(geom)
        q, scale = quantize_array(params["weight"], channel_axis=3)  # HWIO
        p = {"q": q, "scale": scale}
        if mod.with_bias:
            p["bias"] = jnp.asarray(params["bias"])
        return m, p

    def _apply(self, params, x):
        c = get_policy().compute_dtype
        y = self.conv._conv(x, params["q"])
        y = (y.astype(jnp.float32) * params["scale"]).astype(c)
        if self.with_bias:
            y = y + params["bias"].astype(c)
        return y


class QuantMultiHeadAttention(_NoReinit, MultiHeadAttention):
    """MHA with int8 q/k/v/o projection weights (per-out-column scale).

    Inherits the attention math (flash/ring path selection) and overrides
    only the projections, so cached decoding (models/decode.py) quantizes
    for free — _cached_attention calls _proj."""

    @classmethod
    def from_float(cls, mod: MultiHeadAttention, params):
        m = cls(mod.embed_dim, mod.num_heads, causal=mod.causal,
                seq_parallel=mod.seq_parallel, seq_axis=mod.seq_axis,
                with_bias=mod.with_bias)
        p = {}
        for n in "qkvo":
            q, scale = quantize_array(params["w" + n], channel_axis=1)
            p["w" + n + "_q"] = q
            p["s" + n] = scale
            if mod.with_bias:
                p["b" + n] = jnp.asarray(params["b" + n])
        return m, p

    def _proj(self, params, x, name):
        c = get_policy().compute_dtype
        y = jax.lax.dot_general(
            x.astype(c), params["w" + name + "_q"].astype(c),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        y = (y * params["s" + name]).astype(c)
        if self.with_bias:
            y = y + params["b" + name].astype(c)
        return y


class QuantLookupTable(_NoReinit, Module):
    """int8 embedding table with per-ROW scale (rows are the channels)."""

    def __init__(self, lut: LookupTable):
        super().__init__()
        self.lut = lut

    @classmethod
    def from_float(cls, mod: LookupTable, params):
        table = copy.copy(mod)
        table.params = table.state = None  # config only — no float weights
        q, scale = quantize_array(params["weight"], channel_axis=0)
        return cls(table), {"q": q, "scale": scale}

    def _apply(self, params, x):
        c = get_policy().compute_dtype
        idx = jnp.asarray(x, jnp.int32)
        if self.lut.one_based:
            idx = idx - 1
        rows = jnp.take(params["q"], idx, axis=0).astype(jnp.float32)
        scale = jnp.take(params["scale"], idx, axis=0)
        return (rows * scale[..., None]).astype(c)


_LEAF_RULES = [
    (MultiHeadAttention, QuantMultiHeadAttention),  # before generic checks
    (Linear, QuantLinear),
    (SpatialConvolution, QuantSpatialConvolution),
    (LookupTable, QuantLookupTable),
]


def _quantize_node(module, params, state):
    """Returns (new_module, new_params, new_state).

    Child modules deliberately carry NO params/state — the module system's
    contract is that the top-level module owns the authoritative pytrees
    (nn/module.py Container note); attaching copies to every node would
    make Module.save embed each weight twice."""
    if isinstance(module, Container):
        clone = copy.copy(module)
        clone.modules = []
        clone.params = clone.state = None
        new_p, new_s = [], []
        for m, p, s in zip(module.modules, params, state):
            qm, qp, qs = _quantize_node(m, p, s)
            clone.modules.append(qm)
            new_p.append(qp)
            new_s.append(qs)
        return clone, new_p, new_s
    for float_cls, quant_cls in _LEAF_RULES:
        # exact-class dispatch would miss aliases (SpatialShareConvolution);
        # subclass dispatch must not re-quantize an already-quantized twin
        if isinstance(module, float_cls) and \
                not isinstance(module, (QuantLinear, QuantSpatialConvolution,
                                        QuantMultiHeadAttention,
                                        QuantLookupTable)):
            if isinstance(module, SpatialConvolution) and \
                    type(module).__name__ not in ("SpatialConvolution",
                                                  "SpatialShareConvolution"):
                break  # dilated/full/map conv geometries stay float
            if isinstance(module, LookupTable) and \
                    module.max_norm is not None:
                break  # lookup-time renorm is not representable in int8 rows
            qm, qp = quant_cls.from_float(module, params)
            return qm, qp, state
    clone = copy.copy(module)
    clone.params = clone.state = None
    return clone, params, state


def quantize(model: Module) -> Module:
    """Weight-only int8 post-training quantization.

    Returns a NEW module tree (the float model is untouched) whose
    matmul-bearing leaves store int8 weights + per-channel scales; use it
    exactly like the float model for inference (training a quantized model
    is not supported — gradients through rounded weights are meaningless
    here)."""
    if model.params is None:
        raise ValueError("quantize: build/train the model first "
                         "(params is None)")
    qm, qp, qs = _quantize_node(model, model.params, model.state)
    qm.params, qm.state = qp, qs
    return qm
