"""Tree-structured LSTMs.

Reference: nn/TreeLSTM.scala (base) and nn/BinaryTreeLSTM.scala — a
constituency-tree LSTM (Tai et al. 2015) used by the treeLSTMSentiment
example: leaves embed word vectors through a leaf module; internal nodes
compose their two children's (h, c) states with gated composition.  The
reference walks the tree object graph recursively per example.

TPU-native re-design: trees are encoded as static-shape arrays in
topological (children-before-parent) order, and the recursion becomes ONE
`lax.scan` over node slots carrying an (n_nodes, hidden) state buffer —
compiled once for a given tree size, vmap-batched over examples.  Encoding
per example (pad nodes with -1 rows to a fixed n_nodes):

    children: (n_nodes, 2) int32 — indices of left/right child node slots,
              or -1 for leaves
    leaf_ids: (n_nodes,) int32 — index into the input sequence for leaves,
              -1 for internal nodes

Input to BinaryTreeLSTM.apply: (inputs, children, leaf_ids) with
inputs (batch, seq, in_dim), children (batch, n_nodes, 2),
leaf_ids (batch, n_nodes).  Output: (batch, n_nodes, hidden) node hiddens
(padded slots zero) — the reference likewise emits per-node hidden states.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..common import get_policy
from .module import Module

__all__ = ["TreeLSTM", "BinaryTreeLSTM"]


def _uniform(rng, shape, stdv):
    return jax.random.uniform(rng, shape, get_policy().param_dtype,
                              -stdv, stdv)


class TreeLSTM(Module):
    """Base holding sizes (reference: nn/TreeLSTM.scala)."""

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size


class BinaryTreeLSTM(TreeLSTM):

    PARAM_ROLES = {"leaf_c": "kernel_in", "leaf_o": "kernel_in",
                   "comp_w": "kernel_in", "leaf_cb": "bias",
                   "leaf_ob": "bias", "comp_b": "bias"}
    """Binary constituency TreeLSTM (reference: nn/BinaryTreeLSTM.scala).

    Leaf:      c = W_leaf x,            h = o * tanh(c), o = sigm(O_leaf x)
    Internal:  gates from [h_l, h_r]:   i, f_l, f_r, o, g
               c = i*g + f_l*c_l + f_r*c_r,   h = o * tanh(c)
    (the gate structure of the reference's composer module, built there out
    of Linear/CAddTable graph nodes.)
    """

    def __init__(self, input_size: int, hidden_size: int,
                 gate_output: bool = True):
        super().__init__(input_size, hidden_size)
        self.gate_output = gate_output

    def _init(self, rng):
        k1, k2, k3, k4, k5, k6 = jax.random.split(rng, 6)
        stdv = 1.0 / (self.hidden_size ** 0.5)
        h = self.hidden_size
        return {
            # the reference leaf Linears carry biases
            # (BinaryTreeLSTM.scala:61-63) — kept for wire-format parity
            "leaf_c": _uniform(k1, (self.input_size, h), stdv),
            "leaf_cb": _uniform(k5, (h,), stdv),
            "leaf_o": _uniform(k2, (self.input_size, h), stdv),
            "leaf_ob": _uniform(k6, (h,), stdv),
            # composer: [h_l, h_r] -> 5 gates (i, f_l, f_r, o, g)
            "comp_w": _uniform(k3, (2 * h, 5 * h), stdv),
            "comp_b": _uniform(k4, (5 * h,), stdv),
        }

    def _leaf(self, params, x):
        cd = get_policy().compute_dtype
        c = x.astype(cd) @ params["leaf_c"].astype(cd) + params["leaf_cb"]
        if self.gate_output:
            o = jax.nn.sigmoid(
                x.astype(cd) @ params["leaf_o"].astype(cd)
                + params["leaf_ob"])
            h = o * jnp.tanh(c)
        else:
            h = jnp.tanh(c)
        return h, c

    def _compose(self, params, h_l, c_l, h_r, c_r):
        cd = get_policy().compute_dtype
        z = jnp.concatenate([h_l, h_r], axis=-1).astype(cd)
        gates = z @ params["comp_w"].astype(cd) + params["comp_b"]
        i, f_l, f_r, o, g = jnp.split(gates, 5, axis=-1)
        i, f_l, f_r = (jax.nn.sigmoid(i), jax.nn.sigmoid(f_l),
                       jax.nn.sigmoid(f_r))
        c = i * jnp.tanh(g) + f_l * c_l + f_r * c_r
        h = (jax.nn.sigmoid(o) if self.gate_output else 1.0) * jnp.tanh(c)
        return h, c

    def _run_tree(self, params, inputs, children, leaf_ids):
        """One example: inputs (seq, in), children (n_nodes, 2),
        leaf_ids (n_nodes,) -> (n_nodes, hidden)."""
        n_nodes = children.shape[0]
        hdim = self.hidden_size
        h_buf = jnp.zeros((n_nodes, hdim), jnp.float32)
        c_buf = jnp.zeros((n_nodes, hdim), jnp.float32)

        def step(carry, node):
            h_buf, c_buf = carry
            idx, (l, r), leaf_id = node
            is_leaf = l < 0
            # leaf path: gather the word vector (index 0 when padded/internal)
            x = inputs[jnp.maximum(leaf_id, 0)]
            h_leaf, c_leaf = self._leaf(params, x)
            # internal path: compose children (index 0 when leaf/padded)
            h_int, c_int = self._compose(
                params, h_buf[jnp.maximum(l, 0)], c_buf[jnp.maximum(l, 0)],
                h_buf[jnp.maximum(r, 0)], c_buf[jnp.maximum(r, 0)])
            valid = (leaf_id >= 0) | (l >= 0)
            h = jnp.where(valid,
                          jnp.where(is_leaf, h_leaf, h_int), 0.0)
            c = jnp.where(valid,
                          jnp.where(is_leaf, c_leaf, c_int), 0.0)
            h_buf = lax.dynamic_update_slice(h_buf, h[None].astype(jnp.float32),
                                             (idx, 0))
            c_buf = lax.dynamic_update_slice(c_buf, c[None].astype(jnp.float32),
                                             (idx, 0))
            return (h_buf, c_buf), None

        nodes = (jnp.arange(n_nodes), (children[:, 0], children[:, 1]),
                 leaf_ids)
        (h_buf, _), _ = lax.scan(step, (h_buf, c_buf), nodes)
        return h_buf

    def _apply(self, params, inp):
        inputs, children, leaf_ids = inp
        children = jnp.asarray(children, jnp.int32)
        leaf_ids = jnp.asarray(leaf_ids, jnp.int32)
        run = lambda x, ch, lf: self._run_tree(params, x, ch, lf)
        return jax.vmap(run)(inputs, children, leaf_ids).astype(inputs.dtype)
