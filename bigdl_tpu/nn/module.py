"""Module system: the TPU-native re-design of BigDL's AbstractModule.

Reference: `nn/abstractnn/AbstractModule.scala:54` defines a *stateful* Torch-style
module: mutable `output`/`gradInput` caches (:62,67), `forward` = timed
`updateOutput` (:213), `backward` = `updateGradInput` + `accGradParameters` (:231),
`parameters()` exposing weight/gradient tensor pairs, and `getParameters()` (:284)
flattening everything into ONE contiguous weight vector + ONE gradient vector — the
contract BigDL's whole distributed design hangs off.

TPU-native re-design
--------------------
The mutable-module style cannot live inside `jax.jit` (tracing requires pure
functions), so each Module here is two things at once:

1. **A pure functional core** — `init(rng) -> (params, state)` and
   `apply(params, state, input, training, rng) -> (output, new_state)` where
   `params`/`state` are pytrees.  This is what the Optimizer jits/pjits: a whole
   train step (forward + loss + backward + update + psum) compiles to one XLA
   program, where BigDL dispatched each op separately to MKL via JNI
   (tensor/TensorNumeric.scala:195-312).

2. **A thin stateful facade** for API parity and interactive use — `forward`,
   `backward`, `zero_grad_parameters`, `update_parameters`, `parameters`,
   `get_parameters` behave like the reference (backward computes gradInput via
   `jax.vjp` and *accumulates* parameter gradients, matching accGradParameters
   semantics).

`Activity` (Tensor ∨ Table union, nn/abstractnn/Activity.scala) needs no machinery:
any pytree (array, list, dict, Table) is a valid input/output.
"""

from __future__ import annotations

import itertools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import get_policy, next_rng_key

__all__ = ["Module", "Container", "Criterion"]

_uid_counter = itertools.count()


#: bumped by every set_scale_w/set_scale_b anywhere — lets cached
#: grad-scale trees (facade) and compiled steps (Optimizer) detect scale
#: changes without parent/child cache-invalidation plumbing
_SCALE_EPOCH = [0]


def scale_epoch() -> int:
    return _SCALE_EPOCH[0]


def _tree_add(a, b):
    return jax.tree.map(jnp.add, a, b)


def _tree_zeros_like(a):
    return jax.tree.map(jnp.zeros_like, a)


class Module:
    """Base class for all layers (BigDL: AbstractModule, abstractnn/AbstractModule.scala:54)."""

    #: parameter-name -> role string for the mesh-layout assigner
    #: (parallel/layout.py): modules declare WHAT each parameter is
    #: ("kernel_out", "embedding_row", "bias", ...) and the canonical
    #: role table decides how it shards over the data/fsdp/tp mesh.
    #: None (the default) = unannotated — the assigner fails loudly on
    #: such leaves instead of silently replicating them.  "*" is a
    #: wildcard entry covering every remaining name.
    PARAM_ROLES = None

    def __init__(self):
        self.name = f"{type(self).__name__}_{next(_uid_counter)}"
        self.training_mode: bool = True
        # facade state
        self.params = None   # pytree of parameters (None until build())
        self.state = None    # pytree of non-trained state (e.g. BN running stats)
        self.grads = None    # accumulated parameter gradients (accGradParameters)
        self.output = None
        self.grad_input = None
        self._last_rng = None
        # per-module gradient scaling (AbstractModule.scala:73 scaleW/scaleB);
        # property-backed so even direct assignment bumps the scale epoch
        self._scale_w: float = 1.0
        self._scale_b: float = 1.0
        # initializer overrides (nn/abstractnn/Initializable.scala:23)
        self.weight_initializer = None
        self.bias_initializer = None

    # scale_w/scale_b are properties so that DIRECT attribute assignment
    # (m.scale_w = 2.0) also bumps the scale epoch — otherwise a cached
    # grad-scale tree or an already-compiled step would keep applying the
    # stale scale with no error.  set_scale_w/set_scale_b remain the
    # container-propagating API.
    @property
    def scale_w(self) -> float:
        return self._scale_w

    @scale_w.setter
    def scale_w(self, s: float):
        self._scale_w = s
        _SCALE_EPOCH[0] += 1

    @property
    def scale_b(self) -> float:
        return self._scale_b

    @scale_b.setter
    def scale_b(self, s: float):
        self._scale_b = s
        _SCALE_EPOCH[0] += 1

    # ------------------------------------------------------------------
    # pure functional core — override _init / _apply (stateless layers) or
    # init / apply (layers with state or randomness)
    # ------------------------------------------------------------------

    def init(self, rng):
        """Create (params, state) pytrees."""
        return self._init(rng), self._init_state()

    def _init(self, rng):
        return {}

    def _init_state(self):
        return {}

    def apply(self, params, state, input, *, training: bool = False, rng=None):
        """Pure forward. Returns (output, new_state)."""
        return self._apply(params, input), state

    def _apply(self, params, input):
        raise NotImplementedError(
            f"{type(self).__name__} must implement _apply or apply")

    def has_params(self) -> bool:
        return len(jax.tree.leaves(self.init(jax.random.key(0))[0])) > 0

    def param_roles(self):
        """name -> role map for THIS module's own parameters (see
        PARAM_ROLES; containers are never asked — the layout assigner
        recurses into their children instead, and parameter-free
        modules have no leaves to resolve).  None = unannotated."""
        return self.PARAM_ROLES

    # ------------------------------------------------------------------
    # stateful facade (Torch-style API parity)
    # ------------------------------------------------------------------

    def build(self, rng=None):
        """Materialize parameters (lazy; called automatically on first forward)."""
        if rng is None:
            rng = next_rng_key()
        self.params, self.state = self.init(rng)
        self.grads = _tree_zeros_like(self.params)
        return self

    def set_init_method(self, weight_init=None, bias_init=None):
        """BigDL: Initializable.setInitMethod (abstractnn/Initializable.scala:29)."""
        self.weight_initializer = weight_init
        self.bias_initializer = bias_init
        if self.params is not None:
            self.build()
        return self

    def forward(self, input):
        """BigDL: AbstractModule.forward (AbstractModule.scala:213)."""
        if self.params is None:
            self.build()
        rng = next_rng_key()
        self._last_rng = rng
        out, new_state = self.apply(self.params, self.state, input,
                                    training=self.training_mode, rng=rng)
        self.state = new_state
        self.output = out
        return out

    __call__ = forward

    def backward(self, input, grad_output):
        """gradInput + accumulated parameter grads (AbstractModule.scala:231-236)."""
        if self.params is None:
            raise RuntimeError("backward before forward")

        def f(p, x):
            y, _ = self.apply(p, self.state, x, training=self.training_mode,
                              rng=self._last_rng)
            return y

        _, vjp = jax.vjp(f, self.params, input)
        gp, gx = vjp(grad_output)
        gp = self._scale_param_grads(gp)
        self.grads = _tree_add(self.grads, gp)
        self.grad_input = gx
        return gx

    def update_grad_input(self, input, grad_output):
        """BigDL: updateGradInput — gradInput only, no param-grad accumulation."""
        def f(x):
            y, _ = self.apply(self.params, self.state, x,
                              training=self.training_mode, rng=self._last_rng)
            return y
        _, vjp = jax.vjp(f, input)
        (gx,) = vjp(grad_output)
        self.grad_input = gx
        return gx

    def acc_grad_parameters(self, input, grad_output):
        """BigDL: accGradParameters — accumulate dL/dParams only."""
        def f(p):
            y, _ = self.apply(p, self.state, input,
                              training=self.training_mode, rng=self._last_rng)
            return y
        _, vjp = jax.vjp(f, self.params)
        (gp,) = vjp(grad_output)
        self.grads = _tree_add(self.grads, self._scale_param_grads(gp))

    def _scale_param_grads(self, gp):
        """Facade-path scaling: same tree the compiled step uses, so the
        two paths cannot diverge."""
        st = self._grad_scale_tree()
        if st is None:
            return gp
        return jax.tree.map(lambda g, s: g * s, gp, st)

    def _grad_scale_tree(self, params=None):
        """Per-leaf gradient scale factors matching the params tree
        (scaleW/scaleB, AbstractModule.scala:73; the reference applies them
        inside accGradParameters so layer-wise LR scaling reaches the
        DISTRIBUTED update too — DistriOptimizer.scala:729
        isLayerwiseScaled).  Container-level scales reach leaves because
        Container.set_scale_w/b PROPAGATE to children (the reference's
        Container.setScaleW semantics) — set scales through the setters,
        not by attribute assignment.  Returns None when every module's
        scales are 1 so the compiled step skips the multiply entirely."""
        if params is None:
            if self.params is None:
                self.build()
            params = self.params
            # static between set_scale calls — cache per scale epoch so the
            # facade backward's common all-ones case costs one int compare
            cached = getattr(self, "_scale_tree_cache", None)
            if cached is not None and cached[0] == _SCALE_EPOCH[0]:
                return cached[1]
        tree = None
        if not all(m.scale_w == 1.0 and m.scale_b == 1.0
                   for m in self.unique_modules()):
            tree = self._walk_scales(self, params)
        if params is self.params:
            self._scale_tree_cache = (_SCALE_EPOCH[0], tree)
        return tree

    @staticmethod
    def _walk_scales(root, params):
        def walk(mod, p):
            if hasattr(mod, "modules") and isinstance(p, list):
                return [walk(c, cp) for c, cp in zip(mod.modules, p)]

            def f(path, leaf):
                key = path[-1].key if hasattr(path[-1], "key") else ""
                return float(mod.scale_b if key == "bias" else mod.scale_w)

            return jax.tree_util.tree_map_with_path(f, p)

        return walk(root, params)

    # -- parameter access ----------------------------------------------

    def parameters(self):
        """(weights, gradWeights) leaf lists (BigDL: AbstractModule.parameters)."""
        if self.params is None:
            self.build()
        return jax.tree.leaves(self.params), jax.tree.leaves(self.grads)

    def get_parameters(self):
        """ONE flat weight vector + ONE flat gradient vector.

        BigDL contract: AbstractModule.getParameters (AbstractModule.scala:284)
        flattens all parameters into a single contiguous tensor pair; the
        distributed optimizer slices that flat vector across nodes.  JAX arrays
        are immutable so these are copies, not views — the compiled train step
        never uses this path (it maps pytrees directly); it exists for API parity,
        checkpoint compactness, and tests.
        """
        ws, gs = self.parameters()
        if not ws:
            return jnp.zeros((0,)), jnp.zeros((0,))
        return (jnp.concatenate([w.reshape(-1) for w in ws]),
                jnp.concatenate([g.reshape(-1) for g in gs]))

    def set_flat_parameters(self, flat):
        """Inverse of get_parameters()[0]: scatter a flat vector back."""
        leaves, treedef = jax.tree.flatten(self.params)
        out, off = [], 0
        for leaf in leaves:
            n = leaf.size
            out.append(jnp.asarray(flat[off:off + n]).reshape(leaf.shape).astype(leaf.dtype))
            off += n
        self.params = jax.tree.unflatten(treedef, out)
        return self

    def zero_grad_parameters(self):
        if self.grads is not None:
            self.grads = _tree_zeros_like(self.grads)

    def update_parameters(self, learning_rate: float):
        """w -= lr * gradW (BigDL: AbstractModule.updateParameters)."""
        self.params = jax.tree.map(
            lambda w, g: w - learning_rate * g, self.params, self.grads)

    def get_parameters_table(self):
        """name -> params dict (BigDL: getParametersTable, used by summaries)."""
        return {self.name: self.params}

    def summary(self, print_fn=print) -> str:
        """Keras/torchsummary-style parameter table (net-new ergonomics vs
        the reference, whose closest analog is the bare __repr__ tree):
        one row per leaf module with its parameter count and dtypes, plus
        totals.  Returns the rendered string (also sent to print_fn)."""
        if self.params is None:
            self.build()
        rows = []

        def count(p):
            leaves = jax.tree.leaves(p)
            return (sum(l.size for l in leaves),
                    ",".join(sorted({str(l.dtype) for l in leaves})) or "-")

        def walk(module, params, depth):
            n, dt = count(params)
            label = "  " * depth + type(module).__name__
            rows.append((label, n, dt))
            # Container AND Graph (which subclasses Module directly) both
            # keep child params list-aligned with .modules — recurse on the
            # structural property so imported Caffe/TF Graphs break down too
            children = getattr(module, "modules", None)
            if children is not None and isinstance(params, list) and \
                    len(children) == len(params):
                for m, p in zip(children, params):
                    walk(m, p, depth + 1)

        walk(self, self.params, 0)
        width = max(len(r[0]) for r in rows) + 2
        total = rows[0][1]  # the root row already counted everything
        body = [f"{lbl:<{width}}{n:>12,}  {dt}" for lbl, n, dt in rows]
        header = f"{'Layer':<{width}}{'Params':>12}  Dtypes"
        rule = "-" * max(len(header), max(len(b) for b in body))
        lines = ([header, rule] + body
                 + [rule, f"{'Total':<{width}}{total:>12,}"])
        text = "\n".join(lines)
        if print_fn is not None:
            print_fn(text)
        return text

    # -- native-format persistence ------------------------------------
    # (reference: Module.save/Module.load, nn/Module.scala:41 over JVM
    # serialization in utils/File.scala; here: pickle of the module with
    # weights detached — the same strip trick ModelBroadcast uses,
    # models/utils/ModelBroadcast.scala:66)

    def save(self, path: str, overwrite: bool = True):
        import numpy as _np

        from ..utils import file_io
        to_np = lambda t: jax.tree.map(_np.asarray, t) if t is not None \
            else None
        detached = (self.params, self.state, self.grads, self.output,
                    self.grad_input)
        self.params = self.state = self.grads = None
        self.output = self.grad_input = None
        try:
            blob = {"format": "bigdl_tpu-module-v1", "module": self,
                    "params": to_np(detached[0]), "state": to_np(detached[1])}
            file_io.save(blob, path, overwrite=overwrite)
        finally:
            (self.params, self.state, self.grads, self.output,
             self.grad_input) = detached
        return self

    @staticmethod
    def load(path: str) -> "Module":
        from ..utils import file_io
        blob = file_io.load(path)
        if not (isinstance(blob, dict) and
                blob.get("format") == "bigdl_tpu-module-v1"):
            raise ValueError(f"{path!r} is not a bigdl_tpu module file")
        m = blob["module"]
        m.attach(blob["params"], blob["state"])
        return m

    def attach(self, params, state=None):
        """Install externally-produced params (checkpoint/interop load) into
        the stateful facade, keeping grads consistent with build()."""
        self.params = params
        if state is not None:
            self.state = state
        elif self.state is None:
            _, self.state = self.init(jax.random.key(0))
        self.grads = (_tree_zeros_like(params)
                      if params is not None else None)
        return self

    # -- modes ---------------------------------------------------------

    def training(self):
        self.training_mode = True
        return self

    def evaluate(self, dataset=None, methods=None, batch_size=None):
        """No args: switch to eval mode (Torch semantics).  With a dataset
        and validation methods: bulk mesh-sharded evaluation — the
        reference's `model.evaluate(rdd, vMethods, batchSize)` overload
        (AbstractModule.scala:571 -> Evaluator, SURVEY.md §3.4)."""
        if dataset is None:
            self.training_mode = False
            return self
        if not methods:
            raise ValueError(
                "evaluate(dataset, ...) needs validation methods, e.g. "
                "[Top1Accuracy()] (AbstractModule.evaluate vMethods)")
        from ..optim.optimizer import Evaluator
        self.training_mode = False
        # list coercion + batch-size defaulting live in Evaluator.test so
        # every entry point (this facade, Evaluator, Validator) accepts the
        # same inputs
        return Evaluator(self).test(dataset, methods, batch_size=batch_size)

    def is_training(self) -> bool:
        return self.training_mode

    # -- misc parity helpers ------------------------------------------

    def get_times(self):
        """(module, forward_seconds, backward_seconds) triples for this module
        tree, populated by the most recent utils.profiling.ModuleProfiler run
        (reference: AbstractModule.getTimes, abstractnn/AbstractModule.scala:197
        — always-on there; opt-in here because per-layer timers cannot live
        inside one fused XLA program)."""
        return [(m, *getattr(m, "_profile_times", (0.0, 0.0)))
                for m in self.unique_modules()]

    def reset_times(self):
        """Clear profiling counters (AbstractModule.resetTimes:204)."""
        for m in self.unique_modules():
            if hasattr(m, "_profile_times"):
                del m._profile_times

    def unique_modules(self):
        """Pre-order walk of the module tree, visiting each INSTANCE once —
        shared (weight-tied) submodules appear a single time.  Shared by
        get_times/reset_times and utils.profiling.ModuleProfiler."""
        seen = set()

        def walk(m):
            if id(m) in seen:
                return
            seen.add(id(m))
            yield m
            for c in getattr(m, "modules", []):
                yield from walk(c)

        # note: the inner generator must be consumed, not returned, so the
        # seen-set is shared across recursion
        yield from walk(self)

    # -- facade parity: weight interchange, prediction, interop savers ---
    # (AbstractModule.scala's public surface beyond the training core)

    def update_output(self, input):
        """Alias of forward for reference-API parity (updateOutput is the
        compute half of AbstractModule.forward; this facade never separates
        them because timing lives in get_times' profiler instead)."""
        return self.forward(input)

    def get_scale_w(self) -> float:
        return self.scale_w

    def get_scale_b(self) -> float:
        return self.scale_b

    def inputs(self, *nodes):
        """Graph-building parity (`layer.inputs(node...)`,
        AbstractModule.scala / nn/Graph.scala): identical to calling the
        module on node(s) — returns the ModuleNode wired to `nodes`."""
        from .graph import _node
        return _node(self, list(nodes) if len(nodes) != 1 else nodes[0])

    def clear_state(self):
        """Drop cached activations (AbstractModule.clearState) — slims the
        facade before serialization or cloning; parameters are untouched."""
        self.output = None
        self.grad_input = None
        return self

    def copy_status(self, src: "Module"):
        """Copy cached output/gradInput (+ running state) from `src`
        (AbstractModule.copyStatus)."""
        self.output = src.output
        self.grad_input = src.grad_input
        if src.state is not None:
            self.state = src.state
        return self

    def get_weights_bias(self):
        """Parameter leaves in deterministic tree order
        (AbstractModule.getWeightsBias: Array[Tensor])."""
        if self.params is None:
            self.build()
        return [np.asarray(leaf) for leaf in jax.tree.leaves(self.params)]

    def set_weights_bias(self, arrays):
        """Install leaves produced by get_weights_bias (or any same-shaped
        sequence) back into the parameter tree
        (AbstractModule.setWeightsBias)."""
        if self.params is None:
            self.build()
        leaves, treedef = jax.tree.flatten(self.params)
        if len(arrays) != len(leaves):
            raise ValueError(f"expected {len(leaves)} arrays, "
                             f"got {len(arrays)}")
        new = []
        for i, (a, leaf) in enumerate(zip(arrays, leaves)):
            a = jnp.asarray(a, leaf.dtype)
            if a.shape != leaf.shape:
                # no silent reshape: a same-element-count array in the
                # wrong layout (e.g. a transposed Linear weight from
                # another framework) would install scrambled weights
                raise ValueError(
                    f"set_weights_bias: array {i} has shape {a.shape}, "
                    f"parameter expects {leaf.shape}")
            new.append(a)
        self.attach(jax.tree.unflatten(treedef, new), self.state)
        return self

    def save_weights(self, path: str, overwrite: bool = True):
        """Weights-only snapshot (AbstractModule.saveWeights) — loadable
        into any architecture-identical module via load_weights."""
        from ..utils import file_io
        file_io.save({"format": "bigdl_tpu-weights-v1",
                      "weights": self.get_weights_bias()},
                     path, overwrite=overwrite)
        return self

    def load_weights(self, path: str):
        """(AbstractModule.loadWeights)"""
        from ..utils import file_io
        blob = file_io.load(path)
        if not (isinstance(blob, dict) and
                blob.get("format") == "bigdl_tpu-weights-v1"):
            raise ValueError(f"{path!r} is not a bigdl_tpu weights file")
        return self.set_weights_bias(blob["weights"])

    def load_model_weights(self, src: "Module"):
        """Copy another (architecture-identical) module's weights
        (AbstractModule.loadModelWeights / copyWeights)."""
        if src.params is None:
            src.build()
        # device arrays pass straight through set_weights_bias — no
        # host round trip
        return self.set_weights_bias(jax.tree.leaves(src.params))

    copy_weights = load_model_weights

    def predict(self, dataset, batch_size: int = 128):
        """Bulk inference over a dataset or raw Sample list
        (AbstractModule.predict -> Predictor, SURVEY.md §3.4)."""
        from ..optim.optimizer import Predictor
        self.training_mode = False
        return Predictor(self, batch_size=batch_size).predict(dataset)

    def predict_class(self, dataset, batch_size: int = 128):
        """(AbstractModule.predictClass)"""
        from ..optim.optimizer import Predictor
        self.training_mode = False
        return Predictor(self, batch_size=batch_size).predict_class(dataset)

    def save_caffe(self, prototxt_path: str, model_path: str = None):
        """(AbstractModule.saveCaffe(prototxtPath, modelPath) ->
        CaffePersister).  Two-arg form writes the text net definition to
        `prototxt_path` AND the binary caffemodel to `model_path`; one-arg
        form writes only the binary caffemodel to the given path."""
        from ..interop.caffe import save_caffe
        if self.params is None:
            self.build()
        if model_path is None:
            save_caffe(self, self.params, prototxt_path, state=self.state)
        else:
            save_caffe(self, self.params, model_path, state=self.state,
                       prototxt_path=prototxt_path)
        return self

    def save_tf(self, path: str):
        """(AbstractModule.saveTF -> TensorflowSaver)"""
        from ..interop.tensorflow import save_tf
        if self.params is None:
            self.build()
        save_tf(self, self.params, path, state=self.state)
        return self

    def save_torch(self, path: str):
        """(AbstractModule.saveTorch -> TorchFile)"""
        from ..interop.torchfile import save_torch_module
        if self.params is None:
            self.build()
        save_torch_module(self, self.params, path)
        return self

    def set_name(self, name: str):
        self.name = name
        return self

    def get_name(self) -> str:
        return self.name

    def set_scale_w(self, s: float):
        """Layer-wise weight-gradient scale (AbstractModule.scala:73).
        Propagates to children when this module has any (`self.modules`):
        the reference's Container.setScaleW semantics, and Graph/MapTable
        get the same behavior for free."""
        self.scale_w = s
        for m in getattr(self, "modules", ()):
            m.set_scale_w(s)
        _SCALE_EPOCH[0] += 1
        return self

    def set_scale_b(self, s: float):
        """(AbstractModule.setScaleB; propagation as set_scale_w)."""
        self.scale_b = s
        for m in getattr(self, "modules", ()):
            m.set_scale_b(s)
        _SCALE_EPOCH[0] += 1
        return self

    def clone_module(self) -> "Module":
        """Deep copy (BigDL: cloneModule via serialization, AbstractModule.scala:353)."""
        import copy
        return copy.deepcopy(self)

    def reset(self):
        """Re-randomize parameters (BigDL: AbstractModule.reset)."""
        self.build()
        return self

    def __repr__(self):
        return self.name


class Container(Module):
    """Base for composite modules (BigDL: nn/Container.scala:40).

    Child params/state are list-pytrees in child order.
    """

    def __init__(self, *modules: Module):
        super().__init__()
        self.modules: list = list(modules)

    def add(self, module: Module):
        """BigDL: Container.add (nn/Container.scala:54)."""
        self.modules.append(module)
        return self

    def __len__(self):
        return len(self.modules)

    def __getitem__(self, i):
        return self.modules[i]

    def init(self, rng):
        keys = jax.random.split(rng, max(len(self.modules), 1))
        ps, ss = [], []
        for m, k in zip(self.modules, keys):
            p, s = m.init(k)
            ps.append(p)
            ss.append(s)
        return ps, ss

    def _split_rng(self, rng):
        if rng is None:
            return [None] * len(self.modules)
        return list(jax.random.split(rng, max(len(self.modules), 1)))

    # facade conveniences: keep children's own facade params in sync is NOT done;
    # the container owns the authoritative (params, state) pytrees.

    def __repr__(self):
        inner = "\n  ".join(repr(m).replace("\n", "\n  ") for m in self.modules)
        return f"{self.name} {{\n  {inner}\n}}"


class Criterion:
    """Loss base (BigDL: nn/abstractnn/AbstractCriterion.scala).

    Pure core: `loss(output, target) -> scalar` (mean-reduced over batch by
    default, matching BigDL's sizeAverage=true convention).  Facade: forward /
    backward mirroring AbstractCriterion.
    """

    def __init__(self):
        self.output = None
        self.grad_input = None

    def loss(self, output, target):
        raise NotImplementedError

    def forward(self, output, target):
        self.output = self.loss(output, target)
        return self.output

    __call__ = forward

    def backward(self, output, target):
        self.grad_input = jax.grad(lambda o: self.loss(o, target))(output)
        return self.grad_input
