"""Simple elementwise / reduction math layers.

Reference: one file each under BigDL `nn/`: Power.scala, Sqrt.scala, Square.scala,
Clamp.scala, Max.scala, Min.scala, Mean.scala, Sum.scala, Exp.scala, Log.scala,
Abs.scala, Scale.scala, MM.scala, MV.scala, Cosine.scala, Euclidean.scala,
DotProduct.scala, PairwiseDistance.scala, CosineDistance.scala.

All trivial XLA-fusable ops; axes are 0-based.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common import get_policy
from .module import Module

__all__ = ["Power", "Sqrt", "Square", "Clamp", "Max", "Min", "Mean", "Sum",
           "Exp", "Log", "Abs", "Scale", "MM", "MV", "Cosine", "Euclidean",
           "DotProduct", "PairwiseDistance", "CosineDistance"]


class Power(Module):
    """(shift + scale * x) ^ power (nn/Power.scala)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power, self.scale, self.shift = power, scale, shift

    def _apply(self, params, x):
        return (self.shift + self.scale * x) ** self.power


class Sqrt(Module):
    def _apply(self, params, x):
        return jnp.sqrt(x)


class Square(Module):
    def _apply(self, params, x):
        return jnp.square(x)


class Clamp(Module):
    def __init__(self, min_value: float, max_value: float):
        super().__init__()
        self.min_value, self.max_value = min_value, max_value

    def _apply(self, params, x):
        return jnp.clip(x, self.min_value, self.max_value)


class Max(Module):
    """Max along `dim` (nn/Max.scala); returns values only (the reference also
    tracks indices internally for backward — autodiff handles that here)."""

    def __init__(self, dim: int = -1, num_input_dims: int = None):
        super().__init__()
        self.dim = dim

    def _apply(self, params, x):
        return jnp.max(x, axis=self.dim)


class Min(Module):
    def __init__(self, dim: int = -1, num_input_dims: int = None):
        super().__init__()
        self.dim = dim

    def _apply(self, params, x):
        return jnp.min(x, axis=self.dim)


class Mean(Module):
    def __init__(self, dimension: int = 0, n_input_dims: int = -1,
                 squeeze: bool = True):
        super().__init__()
        self.dimension, self.squeeze = dimension, squeeze

    def _apply(self, params, x):
        return jnp.mean(x, axis=self.dimension, keepdims=not self.squeeze)


class Sum(Module):
    def __init__(self, dimension: int = 0, n_input_dims: int = -1,
                 size_average: bool = False, squeeze: bool = True):
        super().__init__()
        self.dimension, self.size_average, self.squeeze = \
            dimension, size_average, squeeze

    def _apply(self, params, x):
        if self.size_average:
            return jnp.mean(x, axis=self.dimension, keepdims=not self.squeeze)
        return jnp.sum(x, axis=self.dimension, keepdims=not self.squeeze)


class Exp(Module):
    def _apply(self, params, x):
        return jnp.exp(x)


class Log(Module):
    def _apply(self, params, x):
        return jnp.log(x)


class Abs(Module):
    def _apply(self, params, x):
        return jnp.abs(x)


class Scale(Module):

    PARAM_ROLES = {"weight": "elementwise", "bias": "elementwise"}
    """CMul then CAdd with learnable per-channel weight/bias (nn/Scale.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)

    def _init(self, rng):
        return {"weight": jnp.ones(self.size, jnp.float32),
                "bias": jnp.zeros(self.size, jnp.float32)}

    def _apply(self, params, x):
        return x * params["weight"] + params["bias"]


class MM(Module):
    """Batch/plain matrix-matrix product of a two-tensor input (nn/MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a, self.trans_b = trans_a, trans_b

    def _apply(self, params, inputs):
        a, b = inputs[0], inputs[1]
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)


class MV(Module):
    """Matrix-vector product of a two-tensor input (nn/MV.scala)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def _apply(self, params, inputs):
        m, v = inputs[0], inputs[1]
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class Cosine(Module):

    PARAM_ROLES = {"weight": "kernel_out"}
    """Cosine similarity of input rows to each of `output_size` learned anchors
    (nn/Cosine.scala)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size

    def _init(self, rng):
        stdv = 1.0 / (self.input_size ** 0.5)
        return {"weight": jax.random.uniform(
            rng, (self.output_size, self.input_size), jnp.float32, -stdv, stdv)}

    def _apply(self, params, x):
        w = params["weight"]
        xn = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
        wn = w / (jnp.linalg.norm(w, axis=-1, keepdims=True) + 1e-12)
        return xn @ wn.T


class Euclidean(Module):

    PARAM_ROLES = {"weight": "kernel_out"}
    """Euclidean distance of input rows to learned centers (nn/Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int,
                 fast_backward: bool = True):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size

    def _init(self, rng):
        stdv = 1.0 / (self.input_size ** 0.5)
        return {"weight": jax.random.uniform(
            rng, (self.output_size, self.input_size), jnp.float32, -stdv, stdv)}

    def _apply(self, params, x):
        diff = x[:, None, :] - params["weight"][None, :, :]
        return jnp.sqrt(jnp.sum(jnp.square(diff), axis=-1) + 1e-12)


class DotProduct(Module):
    """Row-wise dot product of a two-tensor input (nn/DotProduct.scala)."""

    def _apply(self, params, inputs):
        a, b = inputs[0], inputs[1]
        return jnp.sum(a * b, axis=-1)


class PairwiseDistance(Module):
    """Row-wise L_p distance of a two-tensor input (nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def _apply(self, params, inputs):
        d = inputs[0] - inputs[1]
        return jnp.sum(jnp.abs(d) ** self.norm, axis=-1) ** (1.0 / self.norm)


class CosineDistance(Module):
    """Row-wise cosine similarity of a two-tensor input (nn/CosineDistance.scala)."""

    def _apply(self, params, inputs):
        a, b = inputs[0], inputs[1]
        an = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + 1e-12)
        bn = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + 1e-12)
        return jnp.sum(an * bn, axis=-1)
